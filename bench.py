"""Benchmark: SHA-256d hashes/sec/chip + time-to-block at difficulty 20.

The driver contract (run on the ambient platform — the real TPU chip when
available): print ONE JSON line with the headline metric and the speedup
over the CPU baseline.  Metrics per BASELINE.json:2 — "SHA-256d
hashes/sec/chip; time-to-block at difficulty 20" — measured, not estimated;
the ≥10x north-star target is BASELINE.json:5.

Extra keys carry the sub-measurements (cpu baseline rate, per-config
detail); the required keys stay exactly {metric, value, unit, vs_baseline}.
"""

from __future__ import annotations

import json
import statistics
import sys
import time

# Difficulty no hash can meet: keeps throughput runs scanning the whole range.
IMPOSSIBLE = 255


def _throughput(backend, prefix: bytes, count: int, repeats: int = 3) -> float:
    """Best-of-N hashes/sec scanning ``count`` nonces with no hits."""
    backend.search(prefix, 0, min(count, 1 << 16), IMPOSSIBLE)  # warmup/compile
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = backend.search(prefix, 0, count, IMPOSSIBLE)
        dt = time.perf_counter() - t0
        best = max(best, res.hashes_done / dt)
    return best


def _time_to_block(miner, difficulty: int, blocks: int = 5) -> float:
    """Median wall-clock seconds to seal a block at ``difficulty``."""
    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.core.header import BlockHeader

    tip = make_genesis(difficulty)
    times = []
    prev = tip.block_hash()
    for height in range(1, blocks + 1):
        header = BlockHeader(
            1, prev, bytes(32), tip.header.timestamp + 60 * height, difficulty, 0
        )
        t0 = time.perf_counter()
        sealed = miner.search_nonce(header)
        times.append(time.perf_counter() - t0)
        assert sealed is not None
        prev = sealed.block_hash()
    return statistics.median(times)


def main() -> None:
    import jax

    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    from p1_tpu.hashx.jax_backend import is_tpu_platform

    platform = jax.default_backend()
    on_tpu = is_tpu_platform(platform)
    prefix = make_genesis(20).header.mining_prefix()

    # CPU baseline (the graded ratio's denominator): best-of-3 over a ≥2 s
    # window each.  Round 3 used one 0.7 s shot and the recorded ratio
    # swung 933x -> 1998x on scheduler noise alone (VERDICT r3 weak #1).
    cpu_hps = _throughput(get_backend("cpu"), prefix, 1 << 21, repeats=3)

    # Flagship: the Pallas kernel ("tpu") on real hardware; it needs Mosaic,
    # so anywhere else the XLA backend carries the headline instead (the
    # interpreted kernel is a correctness tool, not a benchmark).
    if on_tpu:
        device = get_backend("tpu")
        count = 1 << 29
    else:
        device = get_backend("jax")
        count = 1 << 21
    device_hps = _throughput(device, prefix, count)

    # The relay occasionally degrades ~25x for a few minutes (observed
    # 2026-07-30: 30 MH/s vs the usual ~750 on identical code; host-side
    # rates unaffected).  If the measurement is far below the recorded
    # healthy figure — ONE constant shared with docs/PERF.md, not a local
    # magic number (p1_tpu/hashx/perf_record.py) — wait out the window a
    # few times and re-measure; the FINAL measurement is reported either
    # way, with the retry count, so a genuinely slower chip still reports
    # honestly.  On such a platform, set P1_BENCH_HEALTHY_HPS (0 disables
    # the guard) to skip the pointless waits (ADVICE r3).
    import os

    from p1_tpu.hashx.perf_record import DEGRADED_FRACTION, RECORDED_V5E_PALLAS_HPS

    healthy_hps = float(
        os.environ.get("P1_BENCH_HEALTHY_HPS", RECORDED_V5E_PALLAS_HPS)
    )
    degraded_retries = 0
    while (
        on_tpu
        and device_hps < DEGRADED_FRACTION * healthy_hps
        and degraded_retries < 3
    ):
        degraded_retries += 1
        time.sleep(60)
        device_hps = _throughput(device, prefix, count)

    extra = {}
    if degraded_retries:
        extra["degraded_retries"] = degraded_retries
    if on_tpu:
        # The pure-XLA formulation, for the Pallas-vs-XLA record
        # (docs/PERF.md): same chip, same session.
        extra["xla_hps"] = round(_throughput(get_backend("jax"), prefix, 1 << 28))
    from p1_tpu.hashx.native_build import NativeBuildError

    try:
        # The C++ host tier (SHA-NI when available); skipped cleanly when
        # no toolchain exists on the bench host — anything else is a real
        # regression and should crash the bench loudly.
        native = get_backend("native")
    except (NativeBuildError, OSError):
        native = None
    if native is not None:
        extra["native_hps"] = round(_throughput(native, prefix, 1 << 22, repeats=1))
        extra["native_shani"] = native.has_shani

    # Host-load context for BOTH ratios below: the live cpu denominator
    # collapses up to ~3.6x under co-tenant load (rounds 2-5 record),
    # and these figures are what lets a reader see a degraded
    # denominator instead of inferring it from a suspicious ratio.
    try:
        load_1m, load_5m, _ = os.getloadavg()
    except OSError:
        load_1m = load_5m = None

    # Peak RSS of the bench process itself: the memory-side context
    # field the governor work reads against — an unexplained jump here
    # flags a resident-set regression the throughput numbers can't see
    # (docs/PERF.md "Memory-bounded operation").  VmHWM, not ru_maxrss:
    # the latter survives fork+exec on Linux, so a bench spawned from a
    # fat driver would report the DRIVER's high-water mark
    # (benchmarks/memory_bound.py measured exactly that failure mode).
    peak_rss_bytes = None
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmHWM:"):
                    peak_rss_bytes = int(line.split()[1]) * 1024
                    break
    except OSError:
        pass
    if peak_rss_bytes is None:
        import resource

        peak_rss_bytes = (
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        )

    ttb = _time_to_block(Miner(backend=device), difficulty=20)

    # Host ingest plane (the serialization-side headline,
    # benchmarks/host_ingest.py): a quick same-session measurement,
    # reported against the ONE recorded constant so a regression in the
    # zero-repack pipeline surfaces in the bench JSON — same convention
    # as the TPU degradation guard above.
    from p1_tpu.hashx.perf_record import (
        HOST_INGEST_DEGRADED_FRACTION,
        RECORDED_HOST_INGEST_BPS,
    )

    try:
        from benchmarks.host_ingest import bench_ingest, build_blocks

        chain, raws = build_blocks(300, 2, difficulty=1)
        for blk in chain.main_chain():
            for tx in blk.txs:
                tx.verify_signature()  # warm the memo, as ingest meets it
        ingest_bps = bench_ingest(raws, 1, repeats=3)
        extra["host_ingest_bps"] = round(ingest_bps)
        extra["host_ingest_vs_recorded"] = round(
            ingest_bps / RECORDED_HOST_INGEST_BPS, 2
        )
        if ingest_bps < HOST_INGEST_DEGRADED_FRACTION * RECORDED_HOST_INGEST_BPS:
            extra["host_ingest_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Staged node (round 19): the same ingest shape through the
    # pipeline driver (node/pipeline.py) at 1 worker, against ITS
    # recorded constant — staged ingest pays cold-cache signature math
    # and fsynced appends, so it has its own denominator; the unstaged
    # same-driver control rides along so the staging overhead is a
    # measured per-session number (docs/PERF.md "Staged node").
    from p1_tpu.hashx.perf_record import (
        RECORDED_STAGED_INGEST_BPS,
        STAGED_INGEST_DEGRADED_FRACTION,
    )

    try:
        import tempfile

        from benchmarks.host_ingest import bench_staged_ingest

        with tempfile.TemporaryDirectory() as _staged_tmp:
            rungs = bench_staged_ingest(
                raws, 1, [1], repeats=2, tmpdir=_staged_tmp
            )
        staged_bps = rungs[1]
        extra["staged_ingest_bps"] = round(staged_bps)
        extra["staged_ingest_vs_recorded"] = round(
            staged_bps / RECORDED_STAGED_INGEST_BPS, 2
        )
        if rungs[0] > 0:
            extra["staged_overhead_pct"] = round(
                (rungs[0] - staged_bps) / rungs[0] * 100.0, 1
            )
        if staged_bps < (
            STAGED_INGEST_DEGRADED_FRACTION * RECORDED_STAGED_INGEST_BPS
        ):
            extra["staged_ingest_degraded"] = True
    except (ImportError, NameError):
        pass  # bare package, or the ingest fixtures above didn't build

    # Telemetry plane (round 14): what the stage spans cost the same
    # ingest pipeline — blocks/s through the node's dispatch front door
    # with telemetry on vs off (benchmarks/telemetry_overhead.py), the
    # with-telemetry rate reported against the SAME recorded host-ingest
    # constant so a creeping observability tax shows up in the bench
    # JSON like any other regression.
    try:
        from benchmarks.telemetry_overhead import bench_quick as tel_quick

        to = tel_quick(blocks=300, repeats=3)
        extra["ingest_with_telemetry_bps"] = to["ingest_telemetry_bps"]
        extra["ingest_with_telemetry_vs_recorded"] = round(
            to["ingest_telemetry_bps"] / RECORDED_HOST_INGEST_BPS, 2
        )
        extra["telemetry_overhead_pct"] = to["overhead_pct"]
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Untrusted-path validation (round 8): quick same-session
    # revalidation measurement — serial vs batched signature lane on a
    # small store — reported against the ONE recorded constant
    # (perf_record.py RECORDED_REVALIDATE_BPS), the same
    # denominator-pinning convention as the ratios above.
    from p1_tpu.hashx.perf_record import (
        RECORDED_REVALIDATE_BPS,
        REVALIDATE_DEGRADED_FRACTION,
    )

    try:
        from benchmarks.sig_verify import bench_revalidate

        reval = bench_revalidate(400, repeats=3)
        extra["revalidate_bps"] = reval["revalidate_bps"]
        extra["revalidate_speedup"] = reval["revalidate_speedup"]
        extra["revalidate_vs_recorded"] = round(
            reval["revalidate_bps"] / RECORDED_REVALIDATE_BPS, 2
        )
        if (
            reval["revalidate_bps"]
            < REVALIDATE_DEGRADED_FRACTION * RECORDED_REVALIDATE_BPS
        ):
            extra["revalidate_degraded"] = True
        from p1_tpu.core import keys as _keys

        extra["sig_backend"] = _keys.backend()
    except ImportError:
        pass

    # Native + device Ed25519 (round 15): per-signature cost of the
    # native C++ batch engine against its recorded pin, and — only when
    # P1_BENCH_DEVICE is set, because every mesh shape pays a
    # multi-minute XLA compile on a small host — the device-sharded MSM
    # (benchmarks/sig_verify.py has the full per-backend + scaling
    # harness).  LOWER is better for both ratios.
    from p1_tpu.hashx.perf_record import (
        RECORDED_SIG_DEVICE_MS,
        RECORDED_SIG_NATIVE_MS,
        SIG_DEGRADED_FACTOR,
    )

    try:
        from p1_tpu.core import _ed25519_native

        if _ed25519_native.available():
            from benchmarks.sig_verify import _make_triples, _rate
            from p1_tpu.core.keys import Keypair

            kps = [Keypair.from_seed_text(f"bench-nat-{i}") for i in range(8)]
            tr = _make_triples(1024, kps)
            native_ms = 1e3 / _rate(
                lambda: _ed25519_native.verify_batch(tr), 1024
            )
            extra["sig_native_ms"] = round(native_ms, 4)
            extra["sig_native_vs_recorded"] = round(
                native_ms / RECORDED_SIG_NATIVE_MS, 2
            )
            if native_ms > SIG_DEGRADED_FACTOR * RECORDED_SIG_NATIVE_MS:
                extra["sig_native_degraded"] = True
        import os as _os

        if _os.environ.get("P1_BENCH_DEVICE"):
            from benchmarks.sig_verify import bench_device

            dv = bench_device(batch=256, device_counts=(8,), repeats=2)
            if dv.get("device_us_per_sig"):
                device_ms = dv["device_us_per_sig"] / 1e3
                extra["sig_device_ms"] = round(device_ms, 2)
                extra["sig_device_vs_recorded"] = round(
                    device_ms / RECORDED_SIG_DEVICE_MS, 2
                )
                if device_ms > SIG_DEGRADED_FACTOR * RECORDED_SIG_DEVICE_MS:
                    extra["sig_device_degraded"] = True
    except ImportError:
        pass  # bare install without the benchmarks/ tree

    # Query serving plane (round 9): quick same-session measurement of
    # cached proofs/s (benchmarks/query_plane.py), with the serial
    # baseline from the SAME run so the speedup is never a cross-session
    # artifact — reported against the ONE recorded constant
    # (perf_record.py RECORDED_QUERY_QPS), same convention as above.
    from p1_tpu.hashx.perf_record import (
        QUERY_DEGRADED_FRACTION,
        RECORDED_QUERY_QPS,
    )

    try:
        from benchmarks.query_plane import bench_quick

        qp = bench_quick(repeats=3)
        extra["query_qps"] = qp["proof_cached_qps"]
        extra["query_serial_qps"] = qp["proof_serial_qps"]
        extra["query_batched_qps"] = qp["proof_batched_qps"]
        extra["query_vs_recorded"] = round(
            qp["proof_cached_qps"] / RECORDED_QUERY_QPS, 2
        )
        if qp["proof_cached_qps"] < QUERY_DEGRADED_FRACTION * RECORDED_QUERY_QPS:
            extra["query_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Wallet push plane (round 21): live subscriptions held and p95
    # per-block notify latency on the shared-decode push path
    # (benchmarks/wallet_plane.py bench_quick, 20k sessions — the 100k
    # acceptance run is the benchmark's main()).  LOWER is better for
    # the p95, so notify_vs_recorded > 1 means slower than the record
    # (perf_record.py RECORDED_NOTIFY_P95_MS).
    from p1_tpu.hashx.perf_record import (
        NOTIFY_DEGRADED_FACTOR,
        RECORDED_NOTIFY_P95_MS,
        RECORDED_WALLET_SUBS,
    )

    try:
        from benchmarks.wallet_plane import bench_quick as wallet_quick

        wp = wallet_quick()
        extra["wallet_subs"] = wp["wallet_subs"]
        extra["notify_p95_ms"] = wp["notify_p95_ms"]
        extra["notify_events_per_sec"] = wp["notify_events_per_sec"]
        extra["notify_vs_recorded"] = round(
            wp["notify_p95_ms"] / RECORDED_NOTIFY_P95_MS, 2
        )
        if wp["wallet_subs"] < RECORDED_WALLET_SUBS or (
            wp["notify_p95_ms"]
            > NOTIFY_DEGRADED_FACTOR * RECORDED_NOTIFY_P95_MS
        ):
            extra["notify_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Fleet provisioning (round 22): snapshot cold-start seconds for a
    # `p1 serve --bootstrap` replica and the kill-one-replica notify
    # p95 (benchmarks/wallet_plane.py bench_fleet_quick — 3 replicas x
    # 24 spread sessions, most-loaded replica killed mid-push).  LOWER
    # is better for both; fleet_missed must be 0 regardless of load
    # (a missed confirmation is a bug, not a regression).
    from p1_tpu.hashx.perf_record import (
        FLEET_DEGRADED_FACTOR,
        RECORDED_FLEET_COLD_START_S,
        RECORDED_FLEET_NOTIFY_P95_MS,
    )

    try:
        from benchmarks.wallet_plane import bench_fleet_quick

        fp = bench_fleet_quick()
        extra["fleet_cold_start_s"] = fp["fleet_cold_start_s"]
        extra["fleet_notify_p95_ms"] = fp["fleet_notify_p95_ms"]
        extra["fleet_failovers"] = fp["fleet_failovers"]
        extra["fleet_missed"] = fp["fleet_missed"]
        extra["fleet_cold_start_vs_recorded"] = round(
            fp["fleet_cold_start_s"] / RECORDED_FLEET_COLD_START_S, 2
        )
        extra["fleet_notify_vs_recorded"] = round(
            fp["fleet_notify_p95_ms"] / RECORDED_FLEET_NOTIFY_P95_MS, 2
        )
        if (
            fp["fleet_missed"] > 0
            or fp["fleet_cold_start_s"]
            > FLEET_DEGRADED_FACTOR * RECORDED_FLEET_COLD_START_S
            or fp["fleet_notify_p95_ms"]
            > FLEET_DEGRADED_FACTOR * RECORDED_FLEET_NOTIFY_P95_MS
        ):
            extra["fleet_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Deterministic network simulator (round 10): node-seconds of
    # simulated mesh per wall second on a quick 100-node partition-heal
    # (benchmarks/netsim_scale.py scales linearly enough that the small
    # run tracks the pinned 200-node figure within the guard band) —
    # reported against the ONE recorded constant (perf_record.py
    # RECORDED_SIM_RATE), same convention as above.
    from p1_tpu.hashx.perf_record import (
        RECORDED_SIM_RATE,
        SIM_DEGRADED_FRACTION,
    )

    try:
        from benchmarks.netsim_scale import bench_sim

        sim = bench_sim(nodes=100, seed=0)
        extra["sim_nodes_per_sec"] = sim["sim_nodes_per_sec"]
        extra["sim_events_per_sec"] = sim["events_per_wall_s"]
        extra["sim_ok"] = sim["ok"]
        extra["sim_vs_recorded"] = round(
            sim["sim_nodes_per_sec"] / RECORDED_SIM_RATE, 2
        )
        if sim["sim_nodes_per_sec"] < SIM_DEGRADED_FRACTION * RECORDED_SIM_RATE:
            extra["sim_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Sharded far field (round 17): node-seconds per wall second on a
    # quick 2,000-node far-field run at 2 process shards
    # (benchmarks/netsim_scale.py bench_far_field; the full 10k ladder
    # is the --far table).  Header-only node-seconds — read the figure
    # against RECORDED_SIM_SHARDED_RATE, never against the full-node
    # sim rate above (docs/PERF.md spells out what the far-field model
    # omits).
    from p1_tpu.hashx.perf_record import (
        RECORDED_SIM_SHARDED_RATE,
        SIM_SHARDED_DEGRADED_FRACTION,
    )

    try:
        from benchmarks.netsim_scale import bench_far_field

        far = bench_far_field(nodes=2000, shards=2, seed=0)
        extra["sim_sharded_nodes_per_sec"] = far["sim_sharded_nodes_per_sec"]
        extra["sim_sharded_ok"] = far["ok"]
        extra["sim_sharded_vs_recorded"] = round(
            far["sim_sharded_nodes_per_sec"] / RECORDED_SIM_SHARDED_RATE, 2
        )
        if (
            far["sim_sharded_nodes_per_sec"]
            < SIM_SHARDED_DEGRADED_FRACTION * RECORDED_SIM_SHARDED_RATE
        ):
            extra["sim_sharded_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Relay bandwidth budget (round 23): tx-plane bytes per delivered
    # tx and submit-to-everywhere p95 on the reconciliation arm of a
    # quick flood-vs-recon A/B over shaped 64 kbps uplinks
    # (benchmarks/netsim_scale.py bench_relay; the 16-node acceptance
    # run is `p1 sim relay-budget`).  Both figures are virtual-time
    # deterministic — drift past the band is a protocol regression
    # (duplicate serves, capacity under-estimates, demotion floods),
    # not host noise.  LOWER is better for both.
    from p1_tpu.hashx.perf_record import (
        RECORDED_RELAY_BYTES_PER_TX,
        RECORDED_TX_PROP_P95_MS,
        RELAY_DEGRADED_FACTOR,
    )

    try:
        from benchmarks.netsim_scale import bench_relay

        rl = bench_relay(
            nodes=10,
            senders=4,
            txs_per_sender=24,
            storm_vs=10.0,
            min_reduction=3.0,
        )
        extra["relay_bytes_per_tx"] = rl["relay_bytes_per_tx"]
        extra["tx_prop_p95_ms"] = rl["tx_prop_p95_ms"]
        extra["relay_reduction"] = rl["reduction"]
        extra["relay_ok"] = rl["ok"]
        extra["relay_bytes_vs_recorded"] = round(
            rl["relay_bytes_per_tx"] / RECORDED_RELAY_BYTES_PER_TX, 2
        )
        extra["tx_prop_vs_recorded"] = round(
            rl["tx_prop_p95_ms"] / RECORDED_TX_PROP_P95_MS, 2
        )
        if (
            rl["relay_bytes_per_tx"]
            > RELAY_DEGRADED_FACTOR * RECORDED_RELAY_BYTES_PER_TX
            or rl["tx_prop_p95_ms"]
            > RELAY_DEGRADED_FACTOR * RECORDED_TX_PROP_P95_MS
        ):
            extra["relay_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Chaos plane (round 11): combined-fault schedules per wall second
    # (benchmarks/chaos_rate.py) against the ONE recorded constant
    # (perf_record.py RECORDED_CHAOS_RATE), same convention as above.
    # A quick 5-schedule probe: it tracks the pinned 10-schedule figure
    # within the guard band at half the bench cost.
    from p1_tpu.hashx.perf_record import (
        CHAOS_DEGRADED_FRACTION,
        RECORDED_CHAOS_RATE,
    )

    try:
        from benchmarks.chaos_rate import bench_chaos

        ch = bench_chaos(schedules=5)
        extra["chaos_schedules_per_sec"] = ch["chaos_schedules_per_sec"]
        extra["chaos_virtual_per_wall"] = ch["virtual_per_wall"]
        extra["chaos_ok"] = ch["ok"]
        extra["chaos_vs_recorded"] = round(
            ch["chaos_schedules_per_sec"] / RECORDED_CHAOS_RATE, 2
        )
        if (
            ch["chaos_schedules_per_sec"]
            < CHAOS_DEGRADED_FRACTION * RECORDED_CHAOS_RATE
        ):
            extra["chaos_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Untrusted snapshot sync (round 12): seconds from a cold snapshot
    # file to serving queries (benchmarks/snapshot_boot.py), with the
    # batched-revalidation baseline from the SAME run — reported
    # against the ONE recorded constant (perf_record.py
    # RECORDED_SNAPSHOT_BOOT_S; LOWER is better, so vs_recorded > 1
    # means slower than the record).
    from p1_tpu.hashx.perf_record import (
        RECORDED_SNAPSHOT_BOOT_S,
        SNAPSHOT_DEGRADED_FACTOR,
    )

    try:
        from benchmarks.snapshot_boot import bench_quick as snap_quick

        sb = snap_quick(blocks=800, repeats=3)
        extra["snapshot_boot_s"] = sb["snapshot_boot_s"]
        extra["snapshot_revalidate_s"] = sb["revalidate_boot_s"]
        extra["snapshot_vs_recorded"] = round(
            sb["snapshot_boot_s"] / RECORDED_SNAPSHOT_BOOT_S, 2
        )
        if sb["snapshot_boot_s"] > SNAPSHOT_DEGRADED_FACTOR * (
            RECORDED_SNAPSHOT_BOOT_S
        ):
            extra["snapshot_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Archive scale (round 18, chain/segstore.py + headerplane.py):
    # the synthetic segmented-archive probe — whole-archive
    # packed-header resume rate and the boot-to-serving peak RSS
    # (benchmarks/archive_scale.py).  The default probe is the 100k
    # shape (seconds); ``P1_BENCH_ARCHIVE=1`` runs the full 10M
    # acceptance shape instead (minutes of build + a ~3 GB scratch
    # store) — the slow ladder docs/PERF.md "Archive scale" records.
    from p1_tpu.hashx.perf_record import (
        ARCHIVE_BOOT_RSS_DEGRADED_FACTOR,
        ARCHIVE_RESUME_DEGRADED_FRACTION,
        RECORDED_ARCHIVE_BOOT_RSS_MB,
        RECORDED_ARCHIVE_RESUME_BPS,
    )

    try:
        from benchmarks.archive_scale import bench_quick as arch_quick

        ar = arch_quick(
            blocks=10_000_000
            if os.environ.get("P1_BENCH_ARCHIVE")
            else 100_000
        )
        extra["archive_blocks"] = ar["blocks"]
        extra["archive_resume_bps"] = ar["archive_resume_bps"]
        extra["archive_boot_s"] = ar["archive_boot_s"]
        extra["archive_boot_rss_mb"] = ar["archive_boot_rss_mb"]
        extra["archive_query_qps"] = ar["archive_query_qps"]
        extra["archive_resume_vs_recorded"] = round(
            ar["archive_resume_bps"] / RECORDED_ARCHIVE_RESUME_BPS, 2
        )
        extra["archive_rss_vs_recorded"] = round(
            ar["archive_boot_rss_mb"] / RECORDED_ARCHIVE_BOOT_RSS_MB, 2
        )
        if (
            ar["archive_resume_bps"]
            < ARCHIVE_RESUME_DEGRADED_FRACTION * RECORDED_ARCHIVE_RESUME_BPS
            or ar["archive_boot_rss_mb"]
            > ARCHIVE_BOOT_RSS_DEGRADED_FACTOR * RECORDED_ARCHIVE_BOOT_RSS_MB
        ):
            extra["archive_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Always-on maintenance plane (round 20, benchmarks/
    # maintenance_cadence.py): incremental snapshot rebuilds/sec (the
    # continuous-publication cadence headroom, with the same-session
    # full-rebuild speedup alongside) and the in-RAM live-rebase
    # latency, against the pinned records (perf_record.py
    # RECORDED_SNAPSHOT_CADENCE_BPS / RECORDED_REBASE_MS; the rebase
    # figure is lower-is-better, so vs_recorded > 1 means slower).
    from p1_tpu.hashx.perf_record import (
        REBASE_DEGRADED_FACTOR,
        RECORDED_REBASE_MS,
        RECORDED_SNAPSHOT_CADENCE_BPS,
        SNAPSHOT_CADENCE_DEGRADED_FRACTION,
    )

    try:
        from benchmarks.maintenance_cadence import (
            bench_quick as cadence_quick,
        )

        mc = cadence_quick()
        extra["snapshot_incr_builds_per_sec"] = mc[
            "snapshot_incr_builds_per_sec"
        ]
        extra["snapshot_cadence_speedup"] = mc["snapshot_cadence_speedup"]
        extra["rebase_ms"] = mc["rebase_ms"]
        extra["snapshot_cadence_vs_recorded"] = round(
            mc["snapshot_incr_builds_per_sec"]
            / RECORDED_SNAPSHOT_CADENCE_BPS,
            2,
        )
        extra["rebase_vs_recorded"] = round(
            mc["rebase_ms"] / RECORDED_REBASE_MS, 2
        )
        if (
            mc["snapshot_incr_builds_per_sec"]
            < SNAPSHOT_CADENCE_DEGRADED_FRACTION
            * RECORDED_SNAPSHOT_CADENCE_BPS
            or mc["rebase_ms"] > REBASE_DEGRADED_FACTOR * RECORDED_REBASE_MS
        ):
            extra["maintenance_degraded"] = True
    except ImportError:
        pass  # installed as a bare package without the benchmarks/ tree

    # Static analysis plane (round 13, p1_tpu/analysis): unsettled
    # finding count (unallowlisted + stale grants — tier-1 holds it at
    # zero, so ANY nonzero here is drift the round record must show)
    # and the whole-package pass's wall time (the acceptance budget is
    # ~5 s on this 1-vCPU host; creeping past it would push `p1 lint`
    # out of the edit loop).
    try:
        from p1_tpu.analysis import run_analysis

        t0 = time.perf_counter()
        lint = run_analysis()
        extra["lint_wall_s"] = round(time.perf_counter() - t0, 3)
        extra["lint_findings"] = (
            len(lint.violations) + len(lint.stale) + len(lint.parse_errors)
        )
        extra["lint_granted"] = len(lint.granted)
        extra["lint_rules"] = len(lint.rules)
        # Round 16: the interprocedural layer's size — node/edge growth
        # is the leading indicator of wall-time creep (the fixed point
        # and the per-node summaries are both linear in these).
        extra["lint_callgraph_nodes"] = lint.callgraph_nodes
        extra["lint_callgraph_edges"] = lint.callgraph_edges
    except ImportError:
        pass  # installed as a bare package without the analysis tree

    from p1_tpu.hashx.perf_record import RECORDED_CPU_BASELINE_HPS

    print(
        json.dumps(
            {
                "metric": "sha256d_hashes_per_sec_per_chip",
                "value": round(device_hps),
                "unit": "H/s",
                # Two ratios, one kernel (VERDICT r5 weak #2): the live
                # same-session denominator moves with host load (up to
                # ~3.6x across rounds), so round-over-round comparisons
                # use vs_recorded — the pinned healthy CPU rate in
                # hashx/perf_record.py — while vs_baseline stays the
                # honest same-box-same-moment measurement.  docs/PERF.md
                # "Which ratio to trust" spells out when each applies.
                "vs_baseline": round(device_hps / cpu_hps, 1),
                "vs_recorded": round(
                    device_hps / RECORDED_CPU_BASELINE_HPS, 1
                ),
                "recorded_cpu_baseline_hps": round(
                    RECORDED_CPU_BASELINE_HPS
                ),
                "platform": platform,
                "backend": device.name,
                "cpu_baseline_hps": round(cpu_hps),
                "load_avg_1m": load_1m,
                "load_avg_5m": load_5m,
                "cpu_count": os.cpu_count(),
                "peak_rss_bytes": peak_rss_bytes,
                "time_to_block_d20_s": round(ttb, 3),
                "batch": device.batch,
                **extra,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
