"""Core types: header serialization round-trip, target math, merkle, genesis."""

import hashlib

import pytest

from p1_tpu.core import (
    HEADER_SIZE,
    NONCE_OFFSET,
    Block,
    BlockHeader,
    Transaction,
    make_genesis,
    meets_target,
    merkle_root,
    target_from_difficulty,
    target_to_words,
)


def _header(**kw) -> BlockHeader:
    base = dict(
        version=1,
        prev_hash=bytes(range(32)),
        merkle_root=bytes(reversed(range(32))),
        timestamp=1735689700,
        difficulty=16,
        nonce=0xDEADBEEF,
    )
    base.update(kw)
    return BlockHeader(**base)


class TestHeader:
    def test_serialize_size_and_roundtrip(self):
        h = _header()
        raw = h.serialize()
        assert len(raw) == HEADER_SIZE == 80
        assert BlockHeader.deserialize(raw) == h

    def test_nonce_is_last_word_big_endian(self):
        raw = _header(nonce=0x01020304).serialize()
        assert raw[NONCE_OFFSET:] == bytes([1, 2, 3, 4])

    def test_mining_prefix_excludes_nonce(self):
        a, b = _header(nonce=0), _header(nonce=0xFFFFFFFF)
        assert a.mining_prefix() == b.mining_prefix()
        assert len(a.mining_prefix()) == NONCE_OFFSET

    def test_field_validation(self):
        with pytest.raises(ValueError):
            _header(prev_hash=b"short")
        with pytest.raises(ValueError):
            _header(nonce=1 << 32)
        with pytest.raises(ValueError):
            _header(difficulty=256)

    def test_block_hash_is_sha256d_of_serialization(self):
        h = _header()
        expect = hashlib.sha256(hashlib.sha256(h.serialize()).digest()).digest()
        assert h.block_hash() == expect


class TestTarget:
    def test_target_values(self):
        assert target_from_difficulty(0) == 1 << 256
        assert target_from_difficulty(16) == 1 << 240
        assert target_from_difficulty(255) == 2

    def test_words_roundtrip(self):
        for d in (1, 16, 20, 28, 31, 32, 33, 64, 200, 255):
            words = target_to_words(target_from_difficulty(d))
            assert len(words) == 8
            value = 0
            for w in words:
                value = (value << 32) | w
            assert value == target_from_difficulty(d)
        # difficulty 0 clamps to all-ones
        assert target_to_words(target_from_difficulty(0)) == (0xFFFFFFFF,) * 8

    def test_meets_target_boundary(self):
        # exactly d leading zero bits: first set bit at position d
        for d in (8, 16, 20):
            just_under = (1 << (256 - d - 1)).to_bytes(32, "big")
            just_over = (1 << (256 - d)).to_bytes(32, "big")
            assert meets_target(just_under, d)
            assert not meets_target(just_over, d)
        assert meets_target(b"\xff" * 32, 0)


class TestTx:
    def test_roundtrip(self):
        tx = Transaction("alice", "bob", 100, 2, 7)
        assert Transaction.deserialize(tx.serialize()) == tx

    def test_txid_deterministic_and_distinct(self):
        a = Transaction("alice", "bob", 100, 2, 7)
        b = Transaction("alice", "bob", 100, 2, 8)
        assert a.txid() == Transaction("alice", "bob", 100, 2, 7).txid()
        assert a.txid() != b.txid()

    def test_validation(self):
        with pytest.raises(ValueError):
            Transaction("", "bob", 1, 0, 0)
        with pytest.raises(ValueError):
            Transaction("a", "b", -1, 0, 0)


class TestBlockMerkle:
    def test_empty_merkle_is_zeros(self):
        assert merkle_root([]) == bytes(32)

    def test_single_leaf_is_itself(self):
        leaf = bytes(range(32))
        assert merkle_root([leaf]) == leaf

    def test_odd_duplicates_last(self):
        l1, l2, l3 = (bytes([i]) * 32 for i in (1, 2, 3))
        assert merkle_root([l1, l2, l3]) == merkle_root([l1, l2, l3, l3])

    def test_order_sensitivity(self):
        l1, l2 = bytes([1]) * 32, bytes([2]) * 32
        assert merkle_root([l1, l2]) != merkle_root([l2, l1])

    def test_block_roundtrip_and_merkle_ok(self):
        txs = (
            Transaction("alice", "bob", 5, 1, 0),
            Transaction("bob", "carol", 3, 1, 0),
        )
        header = _header(merkle_root=merkle_root([t.txid() for t in txs]))
        block = Block(header, txs)
        assert block.merkle_ok()
        assert Block.deserialize(block.serialize()) == block

    def test_merkle_mismatch_detected(self):
        block = Block(_header(), (Transaction("a", "b", 1, 0, 0),))
        assert not block.merkle_ok()


class TestGenesis:
    def test_deterministic(self):
        g1, g2 = make_genesis(16), make_genesis(16)
        assert g1.block_hash() == g2.block_hash()

    def test_difficulty_changes_identity(self):
        assert make_genesis(16).block_hash() != make_genesis(20).block_hash()

    def test_shape(self):
        g = make_genesis(16)
        assert g.header.prev_hash == bytes(32)
        assert g.txs == ()
        assert g.merkle_ok()
