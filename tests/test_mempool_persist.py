"""Mempool persistence (Bitcoin's mempool.dat analog, VERDICT r4 #4):
pending transactions survive a node restart, reload passes FULL
re-validation so downtime-invalidated entries drop, and restored ages
keep the TTL clock honest across the restart.
"""

import asyncio

from txutil import account, stx

from test_node import CHUNK, DIFF, _config, fund, run, wait_until

from p1_tpu.chain import AddStatus, Chain
from p1_tpu.core import Transaction
from p1_tpu.core.genesis import genesis_hash, make_genesis
from p1_tpu.mempool import Mempool, load_mempool, save_mempool
from p1_tpu.node import Node

TAG = genesis_hash(8)


def _pool(chain: Chain | None = None) -> Mempool:
    if chain is None:
        return Mempool(chain_tag=TAG)
    return Mempool(
        balance_of=chain.balance,
        nonce_of=chain.nonce,
        chain_tag=chain.genesis.block_hash(),
    )


class TestSaveLoad:
    def test_round_trip_preserves_txs_and_ages(self, tmp_path):
        pool = _pool()
        txs = [stx("alice", account("bob"), i + 1, 2, i) for i in range(5)]
        for tx in txs:
            assert pool.add(tx)
        # Backdate one admission so the saved age is meaningfully large.
        import time

        old = txs[0].txid()
        pool._admitted_at[old] = time.monotonic() - 500.0
        path = tmp_path / "pool.mempool"
        assert save_mempool(pool, path) == 5

        fresh = _pool()
        restored, dropped = load_mempool(fresh, path)
        assert (restored, dropped) == (5, 0)
        assert {t.txid() for t, _ in fresh.snapshot()} == {
            t.txid() for t in txs
        }
        ages = dict((t.txid(), age) for t, age in fresh.snapshot())
        assert ages[old] >= 499.0  # age carried over, not reset

    def test_ttl_clock_honest_across_restart(self, tmp_path):
        pool = _pool()
        tx = stx("alice", account("bob"), 1, 1, 0)
        assert pool.restore(tx, age_s=3600.0)
        # An hour-old transfer against a 30-minute TTL expires on the
        # first housekeeping pass after the restart — no fresh lease.
        assert pool.expire(1800.0) == 1
        assert len(pool) == 0

    def test_invalid_on_reload_dropped(self, tmp_path):
        from test_consensus import _funded_chain, _mine_child

        chain, b1 = _funded_chain("alice")
        pool = Mempool(
            balance_of=chain.balance,
            nonce_of=chain.nonce,
            chain_tag=chain.genesis.block_hash(),
        )
        keep = stx("alice", account("bob"), 5, 1, 0)
        assert pool.add(keep)
        path = tmp_path / "pool.mempool"
        assert save_mempool(pool, path) == 1
        # While "down", the same slot confirms on-chain: seq 0 is now a
        # definite replay and must not re-enter.
        spend = stx("alice", account("carol"), 3, 1, 0)
        b2 = _mine_child(b1, txs=(Transaction.coinbase("m", 2), spend))
        assert chain.add_block(b2).status is AddStatus.ACCEPTED
        fresh = Mempool(
            balance_of=chain.balance,
            nonce_of=chain.nonce,
            chain_tag=chain.genesis.block_hash(),
        )
        restored, dropped = load_mempool(fresh, path)
        assert (restored, dropped) == (0, 1)
        assert len(fresh) == 0

    def test_corrupt_file_restores_prefix(self, tmp_path):
        pool = _pool()
        txs = [stx("alice", account("bob"), i + 1, 2, i) for i in range(3)]
        for tx in txs:
            assert pool.add(tx)
        path = tmp_path / "pool.mempool"
        save_mempool(pool, path)
        raw = path.read_bytes()
        path.write_bytes(raw[: len(raw) - 7])  # torn tail
        fresh = _pool()
        restored, dropped = load_mempool(fresh, path)
        assert restored == 2 and dropped == 0  # prefix kept, tail gone
        # Garbage files restore nothing and raise nothing.
        path.write_bytes(b"not a mempool at all")
        assert load_mempool(_pool(), path) == (0, 0)


class TestAtomicWrite:
    def test_fsync_data_before_replace_and_dir_after(
        self, tmp_path, monkeypatch
    ):
        """Power-loss ordering (ISSUE r7 satellite): the tmp file's DATA
        must be fsynced before the rename publishes it (or the journal
        can commit a completed rename pointing at an empty/torn file),
        and the DIRECTORY after (or the rename itself can vanish)."""
        import os
        import stat

        from p1_tpu.mempool import write_mempool_file

        events = []
        real_fsync, real_replace = os.fsync, os.replace

        def spy_fsync(fd):
            kind = "dir" if stat.S_ISDIR(os.fstat(fd).st_mode) else "file"
            events.append(("fsync", kind))
            return real_fsync(fd)

        def spy_replace(src, dst):
            events.append(("replace", None))
            return real_replace(src, dst)

        monkeypatch.setattr(os, "fsync", spy_fsync)
        monkeypatch.setattr(os, "replace", spy_replace)
        path = tmp_path / "pool.mempool"
        write_mempool_file(b"payload-bytes", path)
        assert events == [
            ("fsync", "file"),
            ("replace", None),
            ("fsync", "dir"),
        ]
        assert path.read_bytes() == b"payload-bytes"
        assert not path.with_suffix(".mempool.tmp").exists()


class TestNodeRestart:
    def test_pending_txs_survive_restart(self, tmp_path):
        async def scenario():
            store = str(tmp_path / "chain.log")
            node = Node(_config(store_path=store))
            await node.start()
            await fund(node, "alice", blocks=2)
            height = node.chain.height
            spends = [
                stx("alice", account("bob"), 5, 2, 0, difficulty=DIFF),
                stx("alice", account("bob"), 5, 2, 1, difficulty=DIFF),
            ]
            for tx in spends:
                await node.submit_tx(tx)
            assert len(node.mempool) == 2
            await node.stop()

            revived = Node(_config(store_path=store))
            await revived.start()
            try:
                assert revived.chain.height == height
                assert len(revived.mempool) == 2
                assert {t.txid() for t, _ in revived.mempool.snapshot()} == {
                    t.txid() for t in spends
                }
                # And they are still mineable: one block confirms both.
                revived.miner_id = account("miner2")
                revived.start_mining()
                assert await wait_until(
                    lambda: revived.chain.height > height
                )
                await revived.stop_mining()
                assert revived.chain.nonce(account("alice")) == 2
            finally:
                await revived.stop()

        run(scenario())
