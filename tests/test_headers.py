"""Headers-first light-client sync: wire codec, the client fetch loop,
local verification, and proof anchoring against a verified header chain.
"""

import asyncio

import pytest

from txutil import account, stx

from test_node import _config, fund, wait_until

from p1_tpu.chain import replay_host
from p1_tpu.core import BlockHeader, RetargetRule, make_genesis
from p1_tpu.node import Node, protocol
from p1_tpu.node.client import get_headers, get_proof
from p1_tpu.node.protocol import MsgType

DIFF = 12


class TestWire:
    def test_round_trips(self):
        locator = [bytes([i]) * 32 for i in range(3)]
        mtype, got = protocol.decode(protocol.encode_getheaders(locator))
        assert mtype is MsgType.GETHEADERS and got == locator
        headers = [make_genesis(d).header for d in (8, 9, 10)]
        mtype, got = protocol.decode(protocol.encode_headers(headers))
        assert mtype is MsgType.HEADERS and got == headers
        mtype, got = protocol.decode(protocol.encode_headers([]))
        assert got == []

    @pytest.mark.parametrize(
        "payload",
        [
            bytes([MsgType.GETHEADERS]) + b"\x00",  # short count
            bytes([MsgType.GETHEADERS]) + b"\x00\x02" + b"\x00" * 32,
            bytes([MsgType.HEADERS]) + b"\x00",  # short count
            bytes([MsgType.HEADERS]) + b"\x00\x01" + b"\x00" * 79,  # short hdr
            bytes([MsgType.HEADERS]) + b"\x00\x01" + b"\x00" * 81,  # long
        ],
    )
    def test_malformed_rejected(self, payload):
        with pytest.raises(ValueError):
            protocol.decode(payload)


class TestLightClientSync:
    def test_fetch_matches_chain_and_verifies(self):
        async def scenario():
            node = Node(_config(mine=True))
            await node.start()
            try:
                assert await wait_until(lambda: node.chain.height >= 15)
                await node.stop_mining()
                headers = await get_headers(
                    "127.0.0.1", node.port, DIFF
                )
                assert len(headers) == node.chain.height + 1
                assert (
                    headers[-1].block_hash() == node.chain.tip_hash
                )
                # The client verifies — PoW, linkage — locally.
                assert replay_host(headers).valid
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_fetch_spans_multiple_batches(self):
        # Force several GETHEADERS round trips by shrinking the batch.
        from p1_tpu.node import node as node_mod

        async def scenario():
            node = Node(_config(mine=True))
            await node.start()
            try:
                assert await wait_until(lambda: node.chain.height >= 13)
                await node.stop_mining()
                old = node_mod.HEADERS_BATCH
                node_mod.HEADERS_BATCH = 4
                try:
                    headers = await get_headers("127.0.0.1", node.port, DIFF)
                finally:
                    node_mod.HEADERS_BATCH = old
                assert len(headers) == node.chain.height + 1
                assert replay_host(headers).valid
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_retargeting_chain_verifies_with_rule(self):
        rule = RetargetRule(window=5, spacing=50)

        async def scenario():
            node = Node(
                _config(
                    difficulty=10,
                    mine=True,
                    retarget_window=5,
                    target_spacing=50,
                )
            )
            await node.start()
            try:
                assert await wait_until(lambda: node.chain.height >= 12)
                await node.stop_mining()
                headers = await get_headers(
                    "127.0.0.1", node.port, 10, retarget=rule
                )
                assert len(headers) == node.chain.height + 1
                report = replay_host(headers, retarget=rule)
                assert report.valid, report.first_invalid
                # The schedule moved (genesis-gap retarget at height 5) and
                # the light client verified every step of it.
                assert {h.difficulty for h in headers[1:]} != {10}
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_mid_fetch_reorg_truncates_to_link_point(self):
        """A live peer can reorg between GETHEADERS batches; the client
        must splice the new branch at its link point instead of appending
        an unlinked tail that verification would blame on an honest peer.
        Scripted server: serves branch A first, then branch B (forking
        after height 1), then quiesces."""

        from p1_tpu.core.genesis import make_genesis as mg
        from p1_tpu.hashx import get_backend
        from p1_tpu.miner import Miner

        miner = Miner(backend=get_backend("cpu"))

        def _mine_on(parent: BlockHeader, ts_off: int) -> BlockHeader:
            draft = BlockHeader(
                1,
                parent.block_hash(),
                bytes(32),
                parent.timestamp + ts_off,
                DIFF,
                0,
            )
            sealed = miner.search_nonce(draft)
            assert sealed is not None
            return sealed

        genesis = mg(DIFF)
        a1 = _mine_on(genesis.header, 1)
        a2 = _mine_on(a1, 1)
        a3 = _mine_on(a2, 1)
        b2 = _mine_on(a1, 2)  # fork after a1
        b3 = _mine_on(b2, 1)
        b4 = _mine_on(b3, 1)
        replies = [[a1, a2, a3], [b2, b3, b4], []]

        async def scenario():
            async def serve(reader, writer):
                await protocol.write_frame(
                    writer,
                    protocol.encode_hello(
                        protocol.Hello(genesis.block_hash(), 4, 0)
                    ),
                )
                await protocol.read_frame(reader)  # client HELLO
                for reply in replies:
                    await protocol.read_frame(reader)  # GETHEADERS
                    await protocol.write_frame(
                        writer, protocol.encode_headers(reply)
                    )
                writer.close()

            server = await asyncio.start_server(serve, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            try:
                headers = await get_headers("127.0.0.1", port, DIFF)
            finally:
                server.close()
                await server.wait_closed()
            # Branch A's tail was spliced out at the fork point.
            assert [h.block_hash() for h in headers] == [
                h.block_hash() for h in (genesis.header, a1, b2, b3, b4)
            ]
            assert replay_host(headers).valid

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_unlinked_headers_reply_is_a_protocol_violation(self):
        from p1_tpu.core.genesis import make_genesis as mg

        genesis = mg(DIFF)
        stray = BlockHeader(1, b"\x55" * 32, bytes(32), 1_800_000_000, DIFF, 0)

        async def scenario():
            async def serve(reader, writer):
                await protocol.write_frame(
                    writer,
                    protocol.encode_hello(
                        protocol.Hello(genesis.block_hash(), 1, 0)
                    ),
                )
                await protocol.read_frame(reader)
                await protocol.read_frame(reader)
                await protocol.write_frame(
                    writer, protocol.encode_headers([stray])
                )
                writer.close()

            server = await asyncio.start_server(serve, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            try:
                with pytest.raises(ValueError, match="link"):
                    await get_headers("127.0.0.1", port, DIFF)
            finally:
                server.close()
                await server.wait_closed()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_proof_anchors_to_verified_headers(self):
        """The full light-client story in one flow: sync headers, verify
        locally, fetch a proof, anchor its block at its claimed height on
        OUR chain — no peer claim left unverified."""

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                await fund(node, "alice", blocks=1)
                spend = stx(
                    "alice", account("bob"), 5, 1, 0, difficulty=DIFF
                )
                await node.submit_tx(spend)
                node.start_mining()
                assert await wait_until(
                    lambda: node.chain.tx_proof(spend.txid()) is not None
                )
                await node.stop_mining()
                headers = await get_headers("127.0.0.1", node.port, DIFF)
                assert replay_host(headers).valid
                proof = await get_proof(
                    "127.0.0.1", node.port, spend.txid(), DIFF
                )
                assert (
                    headers[proof.height].block_hash()
                    == proof.header.block_hash()
                )
                # A height mismatch (stale/forged claim) must NOT anchor.
                assert (
                    proof.height + 1 >= len(headers)
                    or headers[proof.height + 1].block_hash()
                    != proof.header.block_hash()
                )
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))


class TestReplayFastFallback:
    def test_falls_back_to_host_without_toolchain(self, monkeypatch):
        """replay_fast must keep working on machines that cannot build
        the C++ engine — the host oracle serves, same verdicts.  The
        environment check is the separate load PROBE (ADVICE r5), so
        that is what a toolchain-less host is simulated through."""
        from p1_tpu.chain import generate_headers, replay_fast
        from p1_tpu.chain import replay as replay_mod
        from p1_tpu.hashx.native_build import NativeBuildError

        headers = generate_headers(8, 8)

        def no_native():
            raise NativeBuildError("no compiler on this host")

        monkeypatch.setattr(replay_mod, "_probe_native", no_native)
        report = replay_fast(headers)
        assert report.valid and report.method == "host"

    def test_wrapper_bug_surfaces_instead_of_degrading(self, monkeypatch):
        """The ADVICE r5 regression: a genuine bug past the load probe
        (here: an AttributeError inside replay_native itself) must crash
        loudly, not silently demote every light-client verification to
        the host path for the life of the process."""
        from p1_tpu.chain import generate_headers, replay_fast
        from p1_tpu.chain import replay as replay_mod

        def buggy_native(*a, **k):
            raise AttributeError("wrapper typo: no such attribute")

        monkeypatch.setattr(replay_mod, "replay_native", buggy_native)
        with pytest.raises(AttributeError):
            replay_fast(generate_headers(8, 8))

    def test_prefers_native_when_available(self):
        from p1_tpu.chain import generate_headers, replay_fast

        report = replay_fast(generate_headers(8, 8))
        assert report.valid and report.method == "native"
