"""Multi-chip sharded search: parity with single-device scan on the 8-device
CPU mesh (conftest forces XLA_FLAGS=--xla_force_host_platform_device_count=8),
exercising the shard_map + pmin path the driver's dryrun validates."""

import random
import struct

import pytest

from p1_tpu.core import BlockHeader, target_from_difficulty, target_to_words
from p1_tpu.hashx import get_backend
from p1_tpu.hashx import sha256_ref

jax = pytest.importorskip("jax")
jnp = jax.numpy

from p1_tpu.hashx import sharded  # noqa: E402


def _prefix(seed: int) -> bytes:
    rng = random.Random(seed)
    return BlockHeader(
        1, rng.randbytes(32), rng.randbytes(32), 1735689700, 8, 0
    ).mining_prefix()


def _arrays(prefix: bytes, difficulty: int):
    midstate = jnp.array(sha256_ref.header_midstate(prefix), dtype=jnp.uint32)
    tail = jnp.array(sha256_ref.header_tail_words(prefix), dtype=jnp.uint32)
    target = jnp.array(
        target_to_words(target_from_difficulty(difficulty)), dtype=jnp.uint32
    )
    return midstate, tail, target


class TestMesh:
    def test_make_mesh_all_devices(self):
        mesh = sharded.make_mesh()
        assert mesh.devices.size == 8
        assert mesh.axis_names == (sharded.AXIS,)

    def test_make_mesh_subset(self):
        assert sharded.make_mesh(4).devices.size == 4
        with pytest.raises(ValueError):
            sharded.make_mesh(64)


class TestShardedStep:
    def test_parity_with_cpu_scan(self):
        # The sharded step over 8x256 lanes must report the same first hit
        # as a host scan of the same 2048-nonce range.
        prefix = _prefix(30)
        difficulty = 8
        mesh = sharded.make_mesh(8)
        step = sharded.jit_sharded_step(mesh, 256)
        midstate, tail, target = _arrays(prefix, difficulty)
        got = int(step(midstate, tail, target, jnp.uint32(0)))
        truth = get_backend("cpu").search(prefix, 0, 2048, difficulty)
        if truth.nonce is None:
            assert got == 2048
        else:
            assert got == truth.nonce

    def test_hit_on_non_first_device(self):
        # Pick a difficulty/seed whose earliest hit lands past device 0's
        # block so the pmin really crosses devices.
        difficulty = 10
        for seed in range(40, 60):
            prefix = _prefix(seed)
            truth = get_backend("cpu").search(prefix, 0, 2048, difficulty)
            if truth.nonce is not None and truth.nonce >= 256:
                break
        else:
            pytest.fail("no seed with a hit past device 0's block")
        mesh = sharded.make_mesh(8)
        step = sharded.jit_sharded_step(mesh, 256)
        midstate, tail, target = _arrays(prefix, difficulty)
        assert int(step(midstate, tail, target, jnp.uint32(0))) == truth.nonce

    def test_no_hit_returns_span(self):
        prefix = _prefix(31)
        mesh = sharded.make_mesh(8)
        step = sharded.jit_sharded_step(mesh, 256)
        midstate, tail, target = _arrays(prefix, 255)
        assert int(step(midstate, tail, target, jnp.uint32(0))) == 2048

    def test_span_overflow_rejected(self):
        mesh = sharded.make_mesh(8)
        with pytest.raises(ValueError):
            sharded.jit_sharded_step(mesh, 1 << 29)


class TestShardedBackend:
    def test_registry(self):
        backend = get_backend("sharded", batch=256)
        assert backend.name == "sharded"
        assert backend.n_devices == 8
        assert backend.step_span == 8 * 256

    def test_search_parity_with_cpu(self):
        backend = get_backend("sharded", batch=256)
        prefix = _prefix(32)
        truth = get_backend("cpu").search(prefix, 0, 1 << 13, 9)
        got = backend.search(prefix, 0, 1 << 13, 9)
        assert got.nonce == truth.nonce
        if got.nonce is not None:
            assert got.hashes_done == truth.hashes_done

    def test_partial_final_step_masked(self):
        backend = get_backend("sharded", batch=256)
        prefix = _prefix(33)
        truth = get_backend("cpu").search(prefix, 0, 1 << 12, 8)
        assert truth.nonce is not None, "seed must hit within 4096"
        res = backend.search(prefix, 0, truth.nonce, 8)  # exclusive of the hit
        assert res.nonce is None
        res2 = backend.search(prefix, 0, truth.nonce + 1, 8)
        assert res2.nonce == truth.nonce

    def test_single_device_mesh_degrades(self):
        backend = get_backend("sharded", batch=256, n_devices=1)
        prefix = _prefix(34)
        truth = get_backend("cpu").search(prefix, 0, 4096, 8)
        got = backend.search(prefix, 0, 4096, 8)
        assert got.nonce == truth.nonce

    def test_mines_valid_header(self):
        from p1_tpu.core import meets_target
        from p1_tpu.miner import Miner

        backend = get_backend("sharded", batch=256)
        miner = Miner(backend=backend, chunk=1 << 13)
        header = BlockHeader(1, bytes(32), bytes(32), 1735689700, 10, 0)
        sealed = miner.search_nonce(header)
        assert sealed is not None
        assert meets_target(sealed.block_hash(), 10)
        digest = sha256_ref.sha256d(
            sealed.mining_prefix() + struct.pack(">I", sealed.nonce)
        )
        assert digest == sealed.block_hash()


class TestPallasInMesh:
    def test_pallas_kernel_inside_shard_map(self):
        # The Mosaic kernel composed into the mesh program (interpret mode
        # on the CPU test mesh): first-hit parity with the host scan across
        # a 2-device span, exercising the pcast + pmin plumbing around the
        # pallas_call.
        backend = get_backend(
            "sharded", batch=2048, n_devices=2, kernel="pallas"
        )
        assert backend.kernel == "pallas"
        prefix = _prefix(35)
        truth = get_backend("cpu").search(prefix, 0, 4096, 8)
        got = backend.search(prefix, 0, 4096, 8)
        assert got.nonce == truth.nonce

    def test_pallas_kernel_on_full_8_device_mesh(self):
        # Config 5's ACTUAL program shape at 8 shards (VERDICT r5
        # Missing #1): the Mosaic kernel inside shard_map on the full
        # 8-device CPU mesh, one (sub x 128) tile per device, asserting
        # first-hit parity with the host scan — not extrapolated from
        # the 2-device case above.
        backend = get_backend(
            "sharded", batch=2048, n_devices=8, kernel="pallas"
        )
        assert backend.kernel == "pallas"
        assert backend.n_devices == 8
        span = backend.step_span
        assert span == 8 * 2048
        # Pick a seed whose earliest hit lands past device 0's block, so
        # the cross-device pmin is load-bearing, not vacuous.
        difficulty = 11
        for seed in range(60, 90):
            prefix = _prefix(seed)
            truth = get_backend("cpu").search(prefix, 0, span, difficulty)
            if truth.nonce is not None and truth.nonce >= 2048:
                break
        else:
            pytest.fail("no seed with a hit past device 0's block")
        got = backend.search(prefix, 0, span, difficulty)
        assert got.nonce == truth.nonce
        assert got.hashes_done == truth.hashes_done

    def test_cpu_mesh_defaults_to_xla_kernel(self):
        backend = get_backend("sharded", batch=256, n_devices=2)
        assert backend.kernel == "xla"

    def test_pallas_kernel_constructor_guards(self):
        with pytest.raises(ValueError, match="multiple"):
            get_backend("sharded", batch=1024, n_devices=1, kernel="pallas")
        with pytest.raises(ValueError, match="2\\*\\*31"):
            get_backend("sharded", batch=1 << 31, n_devices=1, kernel="pallas")
