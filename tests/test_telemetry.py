"""Telemetry plane (round 14, p1_tpu/node/telemetry.py).

Four contracts under test:

- **Histogram math**: the fixed-bucket percentile estimate is pinned
  against a sorted-list oracle by property — for every sample set and
  every requested percentile, oracle <= estimate <= oracle * √2 (one
  geometric bucket), with the absolute floor of the first bound.
- **NodeMetrics compatibility**: the registry migration preserves the
  attribute API (``metrics.blocks_mined += 1``) and every ``status()``
  key BYTE-FOR-BYTE (the pinned list below is the dashboard contract —
  extending it is fine, renaming or dropping is a breaking change this
  test exists to catch).
- **Observers, not participants**: the 200-node partition-heal scenario
  produces the SAME trace digest with telemetry enabled and disabled —
  twice in-process, and across processes under PYTHONHASHSEED (the
  `p1 sim --no-telemetry` flag is exactly this experiment).
- **Export surfaces**: GETMETRICS/METRICS codec, the node serving its
  registry over a simulated wire with the stage spans populated, the
  replica answering GETMETRICS, and the Prometheus/table renderers.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import os
import random
import subprocess
import sys

import pytest

from p1_tpu.node import protocol, telemetry
from p1_tpu.node.protocol import MsgType
from p1_tpu.node.telemetry import (
    LATENCY_BUCKETS,
    Histogram,
    MetricsRegistry,
    format_prometheus,
    format_table,
    merge_histograms,
)

_BUCKET_FACTOR = math.sqrt(2.0)


class TestHistogram:
    def test_empty(self):
        h = Histogram("t")
        assert h.percentile(50) is None
        s = h.summary()
        assert s["count"] == 0 and s["p95"] is None

    def test_percentile_property_vs_sorted_oracle(self):
        """For every distribution tried: the bucket estimate is an
        UPPER bound on the true percentile sample, and never more than
        one geometric bucket (√2) above it — with the absolute floor of
        the first bound for sub-microsecond samples."""
        rng = random.Random(0x7E1E)
        for _trial in range(60):
            n = rng.randrange(1, 300)
            kind = rng.randrange(3)
            if kind == 0:
                samples = [rng.uniform(0.0, 2.0) for _ in range(n)]
            elif kind == 1:
                samples = [rng.lognormvariate(-7, 3) for _ in range(n)]
            else:  # spiky mixture incl. exact zeros
                samples = [
                    rng.choice([0.0, 1e-7, 1e-3, 0.25, 30.0])
                    * rng.uniform(0.5, 1.5)
                    for _ in range(n)
                ]
            h = Histogram("t")
            for s in samples:
                h.observe(s)
            ordered = sorted(max(0.0, s) for s in samples)
            for p in (50, 95, 99):
                oracle = ordered[max(0, math.ceil(p / 100 * n) - 1)]
                est = h.percentile(p)
                assert est >= oracle - 1e-12, (p, oracle, est)
                bound = max(oracle * _BUCKET_FACTOR, LATENCY_BUCKETS[0])
                assert est <= bound + 1e-12, (p, oracle, est)

    def test_negative_observations_clamp_to_zero(self):
        h = Histogram("t")
        h.observe(-5.0)
        assert h.vmin == 0.0 and h.count == 1 and h.percentile(99) == 0.0

    def test_merge_matches_single_stream(self):
        rng = random.Random(99)
        a, b, one = Histogram("t"), Histogram("t"), Histogram("t")
        for i in range(500):
            v = rng.lognormvariate(-6, 2)
            (a if i % 2 else b).observe(v)
            one.observe(v)
        merged = merge_histograms([a, b])
        assert merged.counts == one.counts
        assert merged.count == one.count
        assert merged.vmin == one.vmin and merged.vmax == one.vmax
        for p in (50, 95, 99):
            assert merged.percentile(p) == one.percentile(p)
        assert merge_histograms([]) is None

    def test_recent_window_is_bounded(self):
        h = Histogram("t")
        for i in range(10_000):
            h.observe(i * 1e-6)
        assert len(h.recent) == telemetry.RECENT_WINDOW
        assert h.count == 10_000  # the buckets never forget

    def test_snapshot_buckets_are_sparse_and_cumulative(self):
        h = Histogram("t")
        for v in (1e-5, 1e-5, 0.5, 1e9):  # 1e9 = overflow bucket
            h.observe(v)
        snap = h.snapshot()
        assert snap["buckets"][-1] == ["+Inf", 4]
        cums = [c for _le, c in snap["buckets"]]
        assert cums == sorted(cums)  # cumulative, ascending
        assert len(snap["buckets"]) <= 4  # sparse: only touched les


class TestRegistry:
    def test_get_or_create_is_idempotent(self):
        reg = MetricsRegistry()
        assert reg.counter("a") is reg.counter("a")
        assert reg.histogram("h") is reg.histogram("h")
        assert reg.gauge("g") is reg.gauge("g")

    def test_span_records_clock_delta(self):
        t = [100.0]
        reg = MetricsRegistry(clock=lambda: t[0])
        with reg.span("x_s"):
            t[0] += 2.5
        h = reg.histograms["x_s"]
        assert h.count == 1 and h.vmax == 2.5

    def test_disabled_registry_reads_no_clock_and_records_nothing(self):
        """The determinism pair's mechanism: disabling removes every
        telemetry clock read, and counters stay live regardless."""
        reads = [0]

        def clock():
            reads[0] += 1
            return 0.0

        reg = MetricsRegistry(clock=clock, enabled=False)
        with reg.span("x_s"):
            pass
        reg.observe("y_s", 1.0)
        assert reads[0] == 0
        assert not reg.histograms
        reg.counter("c").inc()
        assert reg.counters["c"].value == 1

    def test_renderers_run_on_the_snapshot(self):
        reg = MetricsRegistry(clock=lambda: 0.0)
        reg.counter("blocks_accepted").inc(3)
        reg.gauge("mine_elapsed_s").set(1.5)
        reg.observe("stage.validate_s", 0.004)
        snap = reg.snapshot()
        table = format_table(snap)
        assert "blocks_accepted" in table and "stage.validate_s" in table
        prom = format_prometheus(snap)
        assert "p1_blocks_accepted 3" in prom
        assert "# TYPE p1_stage_validate_seconds histogram" in prom
        assert "p1_stage_validate_seconds_count 1" in prom
        assert 'le="+Inf"' in prom
        # The whole snapshot (the METRICS wire payload) is JSON-safe.
        json.dumps(snap)


#: The status() surface at the round-14 migration, every nested key —
#: the dashboard/test contract.  ADDING keys is fine (append here);
#: renaming or removing any existing key breaks consumers and must not
#: happen silently.
STATUS_KEYS = [
    "banned_hosts",
    "blocks_accepted",
    "blocks_mined",
    "compact",
    "compact.bytes_saved",
    "compact.received",
    "compact.sent",
    "compact.tx_fetched",
    "compact.tx_hits",
    "hashes_per_sec",
    "height",
    "known_addrs",
    "ledger_sum",
    "liveness",
    "liveness.peers_evicted_idle",
    "liveness.pings_sent",
    "maintenance",
    "maintenance.base_height",
    "maintenance.busy",
    "maintenance.compaction_records_dropped",
    "maintenance.online_compactions",
    "maintenance.online_prunes",
    "maintenance.rebases",
    "maintenance.segments_compacted",
    "maintenance.snapshot_chunks_reused",
    "maintenance.snapshot_incremental_builds",
    "maintenance.versionbits",
    "maintenance.versionbits.deployments",
    "maintenance.versionbits.threshold",
    "maintenance.versionbits.window",
    "mempool",
    "miner_id",
    "overload",
    "overload.admission_dropped",
    "overload.admission_dropped.blocks",
    "overload.admission_dropped.queries",
    "overload.admission_dropped.txs",
    "overload.bodies_evicted",
    "overload.body_cache_blocks",
    "overload.body_refetches",
    "overload.cblock_slot_drops",
    "overload.mining_paused",
    "overload.peers_dropped_squat",
    "overload.resident_body_bytes",
    "overload.shed_drops",
    "overload.sheds",
    "overload.state",
    "overload.tracked_bytes",
    "overload.tracked_peak_bytes",
    "overload.watermark_bytes",
    "overload.write_queue_drops",
    "peers",
    "pipeline",
    "pipeline.queued_bytes",
    "pipeline.store_alive",
    "pipeline.store_depth",
    "pipeline.validate_alive",
    "pipeline.validate_depth",
    "pipeline.worker_respawns",
    "pipeline.workers",
    "propagation",
    "propagation.median_ms",
    "propagation.p95_ms",
    "propagation.samples",
    "queries",
    "queries.filter_bytes_served",
    "queries.filter_cache",
    "queries.filter_cache.built",
    "queries.filter_cache.bytes",
    "queries.filter_cache.entries",
    "queries.filter_cache.hits",
    "queries.filter_cache.misses",
    "queries.filters_served",
    "queries.proof_cache",
    "queries.proof_cache.bytes",
    "queries.proof_cache.entries",
    "queries.proof_cache.hits",
    "queries.proof_cache.invalidated",
    "queries.proof_cache.misses",
    "queries.proofs_served",
    "recon",
    "recon.active_links",
    "recon.demotions",
    "recon.enabled",
    "recon.fallbacks",
    "recon.pending",
    "recon.rounds",
    "recon.sketches_served",
    "recon.success",
    "recon.txs_reconciled",
    "reorgs",
    "snapshot",
    "snapshot.base_height",
    "snapshot.bg_height",
    "snapshot.checkpoint_interval",
    "snapshot.checkpoints",
    "snapshot.chunks_served",
    "snapshot.divergences",
    "snapshot.fallbacks",
    "snapshot.fetches",
    "snapshot.fetching",
    "snapshot.flips",
    "snapshot.revalidated_blocks",
    "snapshot.revalidating",
    "snapshot.stalls",
    "snapshot.state",
    "storage",
    "storage.blocks_deferred",
    "storage.degraded",
    "storage.errors",
    "storage.healed",
    "storage.last_error",
    "storage.pending_records",
    "storage.persistent",
    "storage.pruned",
    "storage.pruned.enabled",
    "storage.pruned.floor",
    "storage.pruned.keep_blocks",
    "storage.pruned.refusals",
    "storage.pruned.segments_pruned",
    "storage.recoveries",
    "storage.retries",
    "storage.segmented",
    "subscriptions",
    "subscriptions.cursor_rejects",
    "subscriptions.disconnects_error",
    "subscriptions.disconnects_hard",
    "subscriptions.drained_total",
    "subscriptions.events_coalesced",
    "subscriptions.events_dropped",
    "subscriptions.events_pushed",
    "subscriptions.filter_headers",
    "subscriptions.gap_events",
    "subscriptions.live",
    "subscriptions.queue_depth_bytes",
    "subscriptions.replayed",
    "subscriptions.subscribed_total",
    "sync",
    "sync.cblock_fetch_stalls",
    "sync.demotions",
    "sync.exhausted",
    "sync.failovers",
    "sync.mempool_stalls",
    "sync.stalls",
    "time_to_block_s",
    "tip",
    "txs_accepted",
    "validation",
    "validation.backend",
    "validation.backends",
    "validation.backends.cryptography",
    "validation.backends.device",
    "validation.backends.native",
    "validation.backends.pure-python",
    "validation.batched",
    "validation.batches",
    "validation.bytes",
    "validation.entries",
    "validation.hits",
    "validation.misses",
    "validation.pool_dispatches",
    "validation.serial",
    "validation.workers",
    "wire",
    "wire.bytes_received",
    "wire.bytes_sent",
    "wire.relay_bytes",
]


def _fresh_node(**cfg):
    from p1_tpu.config import NodeConfig
    from p1_tpu.node.node import Node

    cfg.setdefault("difficulty", 8)
    cfg.setdefault("mine", False)
    cfg.setdefault("mempool_ttl_s", 0.0)
    return Node(NodeConfig(**cfg))


class TestNodeMetricsCompat:
    """Satellite 1: the registry migration behind the attribute API."""

    def test_status_keys_pinned_byte_for_byte(self):
        node = _fresh_node()
        status = node.status()

        def keyset(d, prefix=""):
            out = []
            for k, v in d.items():
                out.append(prefix + k)
                if isinstance(v, dict):
                    out.extend(keyset(v, prefix + k + "."))
            return sorted(out)

        assert keyset(status) == STATUS_KEYS
        json.dumps(status)  # the wire STATUS contract: JSON-clean

    def test_attribute_api_survives_the_migration(self):
        from p1_tpu.node.node import NodeMetrics

        m = NodeMetrics()
        m.blocks_mined += 2
        m.bytes_sent += 100
        m.mine_elapsed_s += 0.5
        assert m.blocks_mined == 2 and m.bytes_sent == 100
        assert m.hashes_per_sec == 0.0
        m.hashes_done += 50
        assert m.hashes_per_sec == 100.0
        with pytest.raises(AttributeError):
            m.blocks_minedd += 1  # a typo must not mint a counter
        with pytest.raises(AttributeError):
            _ = m.no_such_counter

    def test_counters_flow_into_the_registry_snapshot(self):
        node = _fresh_node()
        node.metrics.blocks_accepted += 7
        snap = node.telemetry_snapshot()
        assert snap["counters"]["blocks_accepted"] == 7
        assert snap["role"] == "node"
        assert node.status()["blocks_accepted"] == 7  # same storage

    def test_validation_backend_gauges_exported(self):
        # Round-15 satellite: keys.STATS mirrors into registry gauges on
        # the export path, one per backend rung, fixed name set — the
        # GETMETRICS/`p1 metrics`/Prometheus view of the ladder.
        from p1_tpu.core import keys

        node = _fresh_node()
        keys.STATS.reset()
        keys.verify_batch([])  # no work — gauges still materialize
        snap = node.telemetry_snapshot()
        for name in (
            "validation.sigs_serial",
            "validation.sigs_batched",
            "validation.sigs_cached",
            "validation.backend.cryptography",
            "validation.backend.native",
            "validation.backend.pure-python",
            "validation.backend.device",
        ):
            assert name in snap["gauges"], name
        # and the mirror tracks the source of truth
        keys.STATS.backends["native"] += 3
        keys.STATS.batched += 3
        snap = node.telemetry_snapshot()
        assert snap["gauges"]["validation.backend.native"] == 3
        assert snap["gauges"]["validation.sigs_batched"] == 3
        keys.STATS.reset()


class TestLogAttribution:
    """Satellite 2: LoggerAdapter carrying node identity."""

    def test_records_carry_host_and_port(self, caplog):
        node = _fresh_node(host="10.7.7.7", port=9555)
        with caplog.at_level(logging.INFO, logger="p1_tpu.node"):
            node.log.info("hello %d", 1)
        assert caplog.records[-1].getMessage() == "[10.7.7.7:9555] hello 1"

    def test_two_nodes_disambiguate(self, caplog):
        a = _fresh_node(host="10.0.0.1", port=1111)
        b = _fresh_node(host="10.0.0.2", port=2222)
        with caplog.at_level(logging.INFO, logger="p1_tpu.node"):
            a.log.info("x")
            b.log.info("x")
        msgs = [r.getMessage() for r in caplog.records[-2:]]
        assert msgs == ["[10.0.0.1:1111] x", "[10.0.0.2:2222] x"]


class TestMetricsWire:
    """GETMETRICS/METRICS (v12): codec, admission class, shed policy,
    and a node serving its registry over a simulated link."""

    def test_codec_round_trip(self):
        mtype, body = protocol.decode(protocol.encode_getmetrics())
        assert mtype is MsgType.GETMETRICS and body is None
        snap = {"role": "node", "counters": {"a": 1}, "histograms": {}}
        mtype, decoded = protocol.decode(protocol.encode_metrics(snap))
        assert mtype is MsgType.METRICS and decoded == snap
        with pytest.raises(protocol.ProtocolError):
            protocol.decode(bytes([MsgType.GETMETRICS]) + b"x")
        with pytest.raises(protocol.ProtocolError):
            protocol.decode(bytes([MsgType.METRICS]) + b"[1]")

    def test_admission_class_and_shed_policy(self):
        from p1_tpu.node.governor import CLASS_QUERIES
        from p1_tpu.node.node import _MSG_CLASS, _SHED_DROPS

        assert _MSG_CLASS[MsgType.GETMETRICS] == CLASS_QUERIES
        assert MsgType.GETMETRICS in _SHED_DROPS
        # GETSTATUS deliberately is NOT shed: the health probe must
        # survive overload even while the latency export does not.
        assert MsgType.GETSTATUS not in _SHED_DROPS

    def test_node_serves_metrics_over_the_sim_wire(self):
        """Two simulated nodes gossip two mined blocks, then a raw sim
        client scrapes GETMETRICS: the stage spans are populated (in
        virtual time), the reply decodes, and the receiver measured
        propagation."""
        from p1_tpu.node.netsim import SimNet

        net = SimNet(seed=5, difficulty=8)

        async def main():
            a = await net.add_node()
            b = await net.add_node(peers=[net.host_name(0)])
            assert await net.run_until(
                lambda: a.peer_count() == 1, 30, wall_limit_s=60
            )
            for _ in range(2):
                await net.mine_on(a, spacing_s=1.0)
            assert await net.run_until(
                lambda: b.chain.height == 2, 60, wall_limit_s=60
            )
            reader, writer = await net.net.host("10.99.0.1").connect(
                net.host_name(0), a.port
            )
            await protocol.write_frame(
                writer,
                protocol.encode_hello(
                    protocol.Hello(a.chain.genesis.block_hash(), 0, 0, 0)
                ),
            )
            await protocol.read_frame(reader)  # node's HELLO
            await protocol.write_frame(writer, protocol.encode_getmetrics())
            while True:
                mtype, body = protocol.decode(
                    await protocol.read_frame(reader)
                )
                if mtype is MsgType.METRICS:
                    break
            writer.close()
            snap_b = b.telemetry.snapshot()
            await net.stop_all()
            return body, snap_b

        snap, snap_b = net.run(main())
        assert snap["role"] == "node" and snap["height"] == 2
        assert snap["counters"]["blocks_mined"] == 2
        hists = snap["histograms"]
        assert hists["stage.validate_s"]["count"] >= 2
        assert hists["stage.relay_s"]["count"] >= 2
        # The receiver's propagation histogram carries VIRTUAL-time
        # delays consistent with the sim's ~ms link latency.
        prop = snap_b["histograms"]["block.propagation_s"]
        assert prop["count"] >= 1
        assert 0.0 < prop["p95"] < 1.0

    def test_replica_answers_getmetrics(self, tmp_path):
        from benchmarks.host_ingest import build_blocks

        from p1_tpu.chain.store import ChainStore
        from p1_tpu.core.block import Block
        from p1_tpu.node.queryplane import QueryPlaneServer, ReplicaView

        _chain, raws = build_blocks(4, 0, 1)
        store = ChainStore(tmp_path / "r.chain", fsync=False)
        try:
            for raw in raws:
                store.append(Block.deserialize(raw))
        finally:
            store.close()
        view = ReplicaView(tmp_path / "r.chain", 1)
        try:
            server = QueryPlaneServer(view)
            reply = server._answer(MsgType.GETMETRICS, None)
            mtype, snap = protocol.decode(reply)
            assert mtype is MsgType.METRICS
            assert snap["role"] == "replica" and snap["height"] == 4
        finally:
            view.close()


class TestDeterminismPair:
    """Observers, not participants: the 200-node sim trace digest is
    byte-identical with telemetry enabled and disabled."""

    @staticmethod
    def _run(telemetry_on: bool):
        from p1_tpu.node.scenarios import partition_heal

        return partition_heal(
            nodes=200, seed=7, telemetry=telemetry_on
        )

    def test_enabled_twice_and_disabled_share_one_digest(self):
        a = self._run(True)
        b = self._run(True)
        c = self._run(False)
        assert a["ok"] and b["ok"] and c["ok"]
        assert a["trace_digest"] == b["trace_digest"] == c["trace_digest"]
        # The enabled runs DID record (the pair is not vacuous) and the
        # disabled run did not.
        assert a["telemetry"]["propagation"]["samples"] > 0
        assert c["telemetry"]["propagation"] is None

    def test_cross_process_under_pythonhashseed(self):
        """`p1 sim partition-heal` with and without --no-telemetry in
        separate interpreters: same digest — nothing hash-seed- or
        process-dependent hides in the recording path."""

        def one_run(extra):
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "sim",
                    "partition-heal", "--nodes", "200", "--seed", "7",
                    *extra,
                ],
                capture_output=True,
                text=True,
                timeout=240,
                cwd="/root/repo",
                env={**os.environ, "PYTHONHASHSEED": "0"},
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            return json.loads(proc.stdout.strip().splitlines()[-1])

        on = one_run([])
        off = one_run(["--no-telemetry"])
        assert on["ok"] and off["ok"]
        assert on["trace_digest"] == off["trace_digest"]
        assert on["telemetry"]["propagation"]["samples"] > 0
        assert off["telemetry"]["propagation"] is None


class TestScenarioTelemetrySections:
    """The sim/chaos reports' timeline sections (virtual-time
    propagation histograms) — and the wan scenario's p95 SLO."""

    def test_wan_asserts_a_p95_propagation_bound(self):
        from p1_tpu.node.scenarios import wan

        r = wan(region_nodes=3, blocks=4, seed=1)
        assert r["ok"] and r["propagation_bounded"]
        prop = r["telemetry"]["propagation"]
        assert prop["samples"] > 0
        assert prop["p95_ms"] <= r["propagation_p95_bound_ms"]
        # The bound is load-bearing: an impossible bound fails the run.
        tight = wan(
            region_nodes=3, blocks=4, seed=1,
            propagation_p95_bound_ms=0.001,
        )
        assert not tight["ok"] and not tight["propagation_bounded"]

    def test_chaos_report_carries_the_section(self):
        from p1_tpu.node.chaos import run_chaos

        r = run_chaos(seed=3, nodes=4, n_events=4)
        assert r["ok"], r["violations"]
        assert "propagation" in r["telemetry"]
