"""Peer discovery: GETADDR/ADDR wire, address-book bootstrap from one
seed, and self-connect detection via the HELLO instance nonce."""

import asyncio

import pytest

from test_node import _config, stop_all, wait_until

from p1_tpu.node import Node, protocol
from p1_tpu.node.protocol import Hello, MsgType


class TestWire:
    def test_round_trips(self):
        mtype, got = protocol.decode(protocol.encode_getaddr())
        assert mtype is MsgType.GETADDR and got is None
        addrs = [("127.0.0.1", 9444), ("node-7.example", 19444)]
        mtype, got = protocol.decode(protocol.encode_addr(addrs))
        assert mtype is MsgType.ADDR and got == addrs
        _, got = protocol.decode(protocol.encode_addr([]))
        assert got == []

    def test_hello_carries_instance_nonce(self):
        h = Hello(b"\xab" * 32, 42, 9444, nonce=0xDEADBEEF12345678)
        mtype, got = protocol.decode(protocol.encode_hello(h))
        assert mtype is MsgType.HELLO and got == h
        assert got.nonce == 0xDEADBEEF12345678

    @pytest.mark.parametrize(
        "payload",
        [
            bytes([MsgType.GETADDR]) + b"\x00",  # non-empty body
            bytes([MsgType.ADDR]) + b"\x00",  # short count
            bytes([MsgType.ADDR]) + b"\x00\x01" + b"\x00\x00\x01a",  # port 0
            bytes([MsgType.ADDR]) + b"\x00\x01" + b"\x23\x28\x00",  # empty host
            bytes([MsgType.ADDR]) + b"\x00\x02" + b"\x23\x28\x01a",  # count lies
            bytes([MsgType.ADDR]) + b"\x00\x01" + b"\x23\x28\x01ab",  # trailing
        ],
    )
    def test_malformed_rejected(self, payload):
        with pytest.raises(ValueError):
            protocol.decode(payload)


class TestBanScore:
    def test_repeat_violations_ban_then_expire(self):
        """Three malformed-frame sessions within the window get the host
        refused at accept time; the ban lapses on its own."""
        import time as _time

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                for _ in range(3):
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", node.port
                    )
                    # A framed unknown message type = protocol violation.
                    writer.write((4).to_bytes(4, "big") + b"\x63zzz")
                    await writer.drain()
                    await reader.read()  # node HELLOs, then drops us
                    writer.close()
                assert "127.0.0.1" in node._banned_until
                # Banned: the accept path closes before any HELLO.
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", node.port
                )
                assert await reader.read() == b""
                writer.close()
                # Lapse the ban: service resumes (HELLO bytes flow again).
                node._banned_until["127.0.0.1"] = _time.monotonic() - 1
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", node.port
                )
                frame = await protocol.read_frame(reader)
                mtype, _ = protocol.decode(frame)
                assert mtype is MsgType.HELLO
                writer.close()
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_oversized_length_prefix_scores_too(self):
        """A hostile length prefix (> MAX_FRAME) is the canonical
        violation the cap exists for — it must count toward a ban."""

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                for _ in range(3):
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", node.port
                    )
                    writer.write((64 << 20).to_bytes(4, "big"))
                    await writer.drain()
                    await reader.read()
                    writer.close()
                assert "127.0.0.1" in node._banned_until
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_own_refusals_never_score_the_remote(self):
        """A self-connect (our policy, not the peer's fault) must not
        creep toward a ban of the host."""

        async def scenario():
            node = Node(_config(target_peers=2))
            await node.start()
            try:
                own = ("127.0.0.1", node.port)
                node._learn_addr(own)
                assert await wait_until(
                    lambda: own not in node._known_addrs, timeout=15
                )
                assert not node._violations.get("127.0.0.1")
                assert "127.0.0.1" not in node._banned_until
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))


class TestDiscovery:
    def test_one_seed_bootstraps_a_full_mesh(self):
        """Classic bootstrap: A and B each know only the seed; discovery
        must connect A<->B through the seed's address book."""

        async def scenario():
            seed = Node(_config(target_peers=3))
            await seed.start()
            a = Node(
                _config(peers=(f"127.0.0.1:{seed.port}",), target_peers=3)
            )
            b = Node(
                _config(peers=(f"127.0.0.1:{seed.port}",), target_peers=3)
            )
            await a.start()
            await b.start()
            try:
                assert await wait_until(
                    lambda: a.peer_count() >= 2
                    and b.peer_count() >= 2
                    and seed.peer_count() >= 2,
                    timeout=20,
                )
                # Everyone's book learned everyone's listening address.
                for node, others in (
                    (a, (seed, b)),
                    (b, (seed, a)),
                    (seed, (a, b)),
                ):
                    # Connected peers are promoted to the tried bucket;
                    # the book is the union of both.
                    known_ports = {
                        p
                        for _, p in (*node._known_addrs, *node._tried_addrs)
                    }
                    assert {o.port for o in others} <= known_ports
            finally:
                await stop_all((a, b, seed))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_self_address_is_detected_and_forgotten(self):
        async def scenario():
            node = Node(_config(target_peers=2))
            await node.start()
            try:
                own = ("127.0.0.1", node.port)
                node._learn_addr(own)
                # The discovery loop dials it, the HELLO nonce comes back
                # as our own, the session dies and the address is dropped.
                assert await wait_until(
                    lambda: own not in node._known_addrs, timeout=15
                )
                assert node.peer_count() == 0
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_failed_handshake_address_is_forgotten(self):
        """An address that accepts TCP but rejects the handshake (here: a
        node on a different chain) must leave the book, or the discovery
        loop would redial the same dead end every tick and starve every
        other candidate."""

        async def scenario():
            foreign = Node(_config(difficulty=13))
            await foreign.start()
            node = Node(_config(target_peers=1))
            await node.start()
            try:
                bad = ("127.0.0.1", foreign.port)
                node._learn_addr(bad)
                assert await wait_until(
                    lambda: bad not in node._known_addrs, timeout=15
                )
                assert node.peer_count() == 0
            finally:
                await stop_all((node, foreign))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_alias_of_connected_peer_not_redialed(self):
        """An address-book alias of a live peer (hostname spelling vs the
        peername IP) must count as connected — no duplicate session."""

        async def scenario():
            seed = Node(_config())
            await seed.start()
            node = Node(
                _config(peers=(f"localhost:{seed.port}",), target_peers=2)
            )
            await node.start()
            try:
                assert await wait_until(lambda: node.peer_count() == 1)
                # The book also knows the peer under its IP spelling.
                node._learn_addr(("127.0.0.1", seed.port))
                await asyncio.sleep(3)
                assert node.peer_count() == 1  # no duplicate dial
                assert seed.peer_count() == 1
            finally:
                await stop_all((node, seed))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_address_book_survives_restart(self, tmp_path):
        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_config(store_path=store))
            await node.start()
            node._learn_addr(("10.1.2.3", 9444))
            node._learn_addr(("10.1.2.4", 9445))
            await node.stop()  # persists <store>.addrs atomically
            reborn = Node(_config(store_path=store))
            await reborn.start()
            try:
                assert ("10.1.2.3", 9444) in reborn._known_addrs
                assert ("10.1.2.4", 9445) in reborn._known_addrs
            finally:
                await reborn.stop()
            # A corrupt book is ignored, never fatal.
            (tmp_path / "chain.dat.addrs").write_text("not json{")
            third = Node(_config(store_path=store))
            await third.start()
            try:
                assert ("10.1.2.3", 9444) not in third._known_addrs
            finally:
                await third.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_discovery_off_by_default(self):
        async def scenario():
            a = Node(_config())
            await a.start()
            b = Node(_config())
            await b.start()
            try:
                # Books may learn addresses, but nothing dials without
                # --target-peers: no discovery task exists.
                a._learn_addr(("127.0.0.1", b.port))
                await asyncio.sleep(2 * 1.5)
                assert a.peer_count() == 0 and b.peer_count() == 0
            finally:
                await stop_all((a, b))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))


class TestAddrHygiene:
    """ADVICE r4: one peer repeatedly sending ADDR frames could flush the
    whole bounded book (an eclipse vector).  Tried addresses (handshake-
    verified) now live beyond gossip's reach, and unsolicited ADDR is
    budgeted per peer."""

    def test_flood_cannot_flush_tried_and_is_budgeted(self):
        async def scenario():
            from p1_tpu.core.genesis import make_genesis
            from test_node import DIFF

            b = Node(_config())
            await b.start()
            a = Node(_config(peers=[f"127.0.0.1:{b.port}"]))
            await a.start()
            try:
                assert await wait_until(lambda: a.peer_count() == 1)
                tried_before = set(a._tried_addrs)
                assert tried_before  # B's handshake promoted it
                # Raw attacker completes HELLO, then streams far more
                # ADDR entries than its budget allows.
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", a.port
                )
                gh = make_genesis(DIFF).block_hash()
                await protocol.write_frame(
                    writer, protocol.encode_hello(Hello(gh, 0, 0, 999))
                )
                for burst in range(20):
                    addrs = [
                        (f"10.9.{burst}.{i}", 7000 + i) for i in range(64)
                    ]
                    await protocol.write_frame(
                        writer, protocol.encode_addr(addrs)
                    )
                await asyncio.sleep(0.5)  # let the frames dispatch
                # Tried bucket untouched; gossip book holds at most the
                # attacker's token budget, not the full 1280 streamed.
                # Budget on localhost: the base burst (64) + the one
                # solicited grant issued to 127.0.0.1 when A dialed B —
                # the attacker shares its victim's host here, a test-
                # topology artifact; on distinct hosts it gets 64.
                assert tried_before <= set(a._tried_addrs)
                flood_learned = sum(
                    1 for (h, _p) in a._known_addrs if h.startswith("10.9.")
                )
                assert flood_learned <= 130
                writer.close()
            finally:
                await a.stop()
                await b.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))


class TestMisconfigurationIsNotHostility:
    """ADVICE r4: three wallet invocations with the wrong --difficulty
    banned 127.0.0.1 for every peer, including a whole localhost mesh.
    Wrong-chain/version HELLOs now disconnect without scoring."""

    def test_wrong_chain_hellos_never_ban(self):
        async def scenario():
            from p1_tpu.core.genesis import make_genesis
            from test_node import DIFF

            node = Node(_config())
            await node.start()
            try:
                wrong = make_genesis(DIFF + 1).block_hash()
                for _ in range(4):
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", node.port
                    )
                    await protocol.write_frame(
                        writer, protocol.encode_hello(Hello(wrong, 0, 0, 0))
                    )
                    await reader.read()  # node HELLOs then hangs up
                    writer.close()
                assert "127.0.0.1" not in node._banned_until
                assert not node._violations.get("127.0.0.1")
                # Loopback service uninterrupted for correctly configured
                # clients.
                right = make_genesis(DIFF).block_hash()
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", node.port
                )
                await protocol.write_frame(
                    writer, protocol.encode_hello(Hello(right, 0, 0, 0))
                )
                mtype, _ = protocol.decode(await protocol.read_frame(reader))
                assert mtype is MsgType.HELLO
                assert await wait_until(lambda: node.peer_count() == 1)
                writer.close()
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))


class TestAddrBudgetPerHost:
    """Review r5 hardening: the ADDR budget keys on the HOST, so
    reconnecting cannot mint fresh budgets, and inbound HELLO port
    claims never reach the tried bucket."""

    async def _hello_socket(self, port, nonce, listen_port=7777):
        from p1_tpu.core.genesis import make_genesis
        from test_node import DIFF

        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        gh = make_genesis(DIFF).block_hash()
        await protocol.write_frame(
            writer,
            protocol.encode_hello(Hello(gh, 0, listen_port, nonce)),
        )
        mtype, _ = protocol.decode(await protocol.read_frame(reader))
        assert mtype is MsgType.HELLO
        return reader, writer

    def test_reconnects_do_not_refresh_budget(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                for round_ in range(5):
                    r, w = await self._hello_socket(node.port, 400 + round_)
                    addrs = [
                        (f"10.7.{round_}.{i}", 7000 + i) for i in range(64)
                    ]
                    await protocol.write_frame(w, protocol.encode_addr(addrs))
                    await asyncio.sleep(0.1)
                    w.close()
                flood = sum(
                    1
                    for (h, _p) in node._known_addrs
                    if h.startswith("10.7.")
                )
                # One host = one budget: ~64 entries + the trickle refill
                # across the run, not 5 * 64.
                assert flood <= 70, flood
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_inbound_port_claim_never_tried(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                r, w = await self._hello_socket(node.port, 500)
                assert await wait_until(lambda: node.peer_count() == 1)
                # The claimed (127.0.0.1, 7777) lands in the gossip book
                # only; tried stays empty (we never dialed anything).
                assert ("127.0.0.1", 7777) in node._known_addrs
                assert not node._tried_addrs
                w.close()
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_hello_port_claims_are_budgeted(self):
        """A reconnect loop claiming a fresh listen port per HELLO is an
        ADDR flood spelled differently — it must draw from the same
        per-host budget."""

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                # Burn the host's token budget with one full ADDR frame.
                r, w = await self._hello_socket(node.port, 600)
                await protocol.write_frame(
                    w,
                    protocol.encode_addr(
                        [(f"10.8.0.{i}", 7000 + i) for i in range(64)]
                    ),
                )
                await asyncio.sleep(0.2)
                w.close()
                # Rotating port claims on fresh connections: each learned
                # claim costs a token the host no longer has.
                for i in range(10):
                    r, w = await self._hello_socket(
                        node.port, 601 + i, listen_port=8000 + i
                    )
                    await asyncio.sleep(0.02)
                    w.close()
                claimed = sum(
                    1
                    for (h, p) in node._known_addrs
                    if h == "127.0.0.1" and 8000 <= p < 8010
                )
                assert claimed <= 2, claimed  # refill trickle at most
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_overflow_prune_keeps_granted_solicited_credit(self, monkeypatch):
        """ADVICE r5 regression: the MAX_TRACKED_HOSTS overflow prune
        kept only buckets `fresh AND below the base cap`, which dropped
        exactly the buckets holding ABOVE-cap solicited-reply credit —
        so an address-cycling flood arriving right after our own GETADDR
        grant could reset an outbound peer's budget mid-reply and
        silently ignore part of an ADDR answer we asked for.  The prune
        must drop only stale buckets sitting at exactly the base refill
        (provably stateless) and keep grant credit intact."""
        from p1_tpu.node import node as node_mod

        monkeypatch.setattr(node_mod, "MAX_TRACKED_HOSTS", 4)
        n = Node(_config())
        # An outbound peer we just solicited: grant stacks a reply's
        # credit on top of the base bucket (above the cap).
        n._addr_budget("10.9.0.1")
        n._addr_budget("10.9.0.1", grant=True)
        granted = n._addr_budgets["10.9.0.1"][0]
        assert granted > node_mod.ADDR_TOKENS_MAX
        # Stale, untouched buckets — the prunable kind.
        import time as _time

        for i in range(3):
            n._addr_budget(f"10.9.1.{i}")
            n._addr_budgets[f"10.9.1.{i}"][1] = _time.monotonic() - 1e4
        # A new host pushes the table past the cap and triggers the prune.
        n._addr_budget("10.9.2.99")
        assert "10.9.0.1" in n._addr_budgets, "granted bucket was pruned"
        assert n._addr_budgets["10.9.0.1"][0] == granted
        assert all(
            f"10.9.1.{i}" not in n._addr_budgets for i in range(3)
        ), "stale base-cap buckets should be the ones dropped"

    def test_tried_survives_one_failed_dial_as_rumor(self):
        """A tried (handshake-verified) address whose node is briefly
        down is demoted to the gossip book on a failed dial — not erased,
        which is exactly what an eclipse attacker would want."""

        async def scenario():
            node = Node(_config(target_peers=1))
            await node.start()
            try:
                addr = ("127.0.0.1", 1)  # nothing listens there
                node._learn_addr(addr, tried=True)
                assert await wait_until(
                    lambda: addr not in node._tried_addrs, timeout=15
                )
                # Demoted to rumor status (the next failed dial may
                # forget it for good — one survival is the guarantee).
                assert addr in node._known_addrs
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))
