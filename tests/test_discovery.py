"""Peer discovery: GETADDR/ADDR wire, address-book bootstrap from one
seed, and self-connect detection via the HELLO instance nonce."""

import asyncio

import pytest

from test_node import _config, stop_all, wait_until

from p1_tpu.node import Node, protocol
from p1_tpu.node.protocol import Hello, MsgType


class TestWire:
    def test_round_trips(self):
        mtype, got = protocol.decode(protocol.encode_getaddr())
        assert mtype is MsgType.GETADDR and got is None
        addrs = [("127.0.0.1", 9444), ("node-7.example", 19444)]
        mtype, got = protocol.decode(protocol.encode_addr(addrs))
        assert mtype is MsgType.ADDR and got == addrs
        _, got = protocol.decode(protocol.encode_addr([]))
        assert got == []

    def test_hello_carries_instance_nonce(self):
        h = Hello(b"\xab" * 32, 42, 9444, nonce=0xDEADBEEF12345678)
        mtype, got = protocol.decode(protocol.encode_hello(h))
        assert mtype is MsgType.HELLO and got == h
        assert got.nonce == 0xDEADBEEF12345678

    @pytest.mark.parametrize(
        "payload",
        [
            bytes([MsgType.GETADDR]) + b"\x00",  # non-empty body
            bytes([MsgType.ADDR]) + b"\x00",  # short count
            bytes([MsgType.ADDR]) + b"\x00\x01" + b"\x00\x00\x01a",  # port 0
            bytes([MsgType.ADDR]) + b"\x00\x01" + b"\x23\x28\x00",  # empty host
            bytes([MsgType.ADDR]) + b"\x00\x02" + b"\x23\x28\x01a",  # count lies
            bytes([MsgType.ADDR]) + b"\x00\x01" + b"\x23\x28\x01ab",  # trailing
        ],
    )
    def test_malformed_rejected(self, payload):
        with pytest.raises(ValueError):
            protocol.decode(payload)


class TestBanScore:
    def test_repeat_violations_ban_then_expire(self):
        """Three malformed-frame sessions within the window get the host
        refused at accept time; the ban lapses on its own."""
        import time as _time

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                for _ in range(3):
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", node.port
                    )
                    # A framed unknown message type = protocol violation.
                    writer.write((4).to_bytes(4, "big") + b"\x63zzz")
                    await writer.drain()
                    await reader.read()  # node HELLOs, then drops us
                    writer.close()
                assert "127.0.0.1" in node._banned_until
                # Banned: the accept path closes before any HELLO.
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", node.port
                )
                assert await reader.read() == b""
                writer.close()
                # Lapse the ban: service resumes (HELLO bytes flow again).
                node._banned_until["127.0.0.1"] = _time.monotonic() - 1
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", node.port
                )
                frame = await protocol.read_frame(reader)
                mtype, _ = protocol.decode(frame)
                assert mtype is MsgType.HELLO
                writer.close()
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_oversized_length_prefix_scores_too(self):
        """A hostile length prefix (> MAX_FRAME) is the canonical
        violation the cap exists for — it must count toward a ban."""

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                for _ in range(3):
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", node.port
                    )
                    writer.write((64 << 20).to_bytes(4, "big"))
                    await writer.drain()
                    await reader.read()
                    writer.close()
                assert "127.0.0.1" in node._banned_until
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_own_refusals_never_score_the_remote(self):
        """A self-connect (our policy, not the peer's fault) must not
        creep toward a ban of the host."""

        async def scenario():
            node = Node(_config(target_peers=2))
            await node.start()
            try:
                own = ("127.0.0.1", node.port)
                node._learn_addr(own)
                assert await wait_until(
                    lambda: own not in node._known_addrs, timeout=15
                )
                assert not node._violations.get("127.0.0.1")
                assert "127.0.0.1" not in node._banned_until
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))


class TestDiscovery:
    def test_one_seed_bootstraps_a_full_mesh(self):
        """Classic bootstrap: A and B each know only the seed; discovery
        must connect A<->B through the seed's address book."""

        async def scenario():
            seed = Node(_config(target_peers=3))
            await seed.start()
            a = Node(
                _config(peers=(f"127.0.0.1:{seed.port}",), target_peers=3)
            )
            b = Node(
                _config(peers=(f"127.0.0.1:{seed.port}",), target_peers=3)
            )
            await a.start()
            await b.start()
            try:
                assert await wait_until(
                    lambda: a.peer_count() >= 2
                    and b.peer_count() >= 2
                    and seed.peer_count() >= 2,
                    timeout=20,
                )
                # Everyone's book learned everyone's listening address.
                for node, others in (
                    (a, (seed, b)),
                    (b, (seed, a)),
                    (seed, (a, b)),
                ):
                    known_ports = {p for _, p in node._known_addrs}
                    assert {o.port for o in others} <= known_ports
            finally:
                await stop_all((a, b, seed))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_self_address_is_detected_and_forgotten(self):
        async def scenario():
            node = Node(_config(target_peers=2))
            await node.start()
            try:
                own = ("127.0.0.1", node.port)
                node._learn_addr(own)
                # The discovery loop dials it, the HELLO nonce comes back
                # as our own, the session dies and the address is dropped.
                assert await wait_until(
                    lambda: own not in node._known_addrs, timeout=15
                )
                assert node.peer_count() == 0
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_failed_handshake_address_is_forgotten(self):
        """An address that accepts TCP but rejects the handshake (here: a
        node on a different chain) must leave the book, or the discovery
        loop would redial the same dead end every tick and starve every
        other candidate."""

        async def scenario():
            foreign = Node(_config(difficulty=13))
            await foreign.start()
            node = Node(_config(target_peers=1))
            await node.start()
            try:
                bad = ("127.0.0.1", foreign.port)
                node._learn_addr(bad)
                assert await wait_until(
                    lambda: bad not in node._known_addrs, timeout=15
                )
                assert node.peer_count() == 0
            finally:
                await stop_all((node, foreign))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_alias_of_connected_peer_not_redialed(self):
        """An address-book alias of a live peer (hostname spelling vs the
        peername IP) must count as connected — no duplicate session."""

        async def scenario():
            seed = Node(_config())
            await seed.start()
            node = Node(
                _config(peers=(f"localhost:{seed.port}",), target_peers=2)
            )
            await node.start()
            try:
                assert await wait_until(lambda: node.peer_count() == 1)
                # The book also knows the peer under its IP spelling.
                node._learn_addr(("127.0.0.1", seed.port))
                await asyncio.sleep(3)
                assert node.peer_count() == 1  # no duplicate dial
                assert seed.peer_count() == 1
            finally:
                await stop_all((node, seed))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_address_book_survives_restart(self, tmp_path):
        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_config(store_path=store))
            await node.start()
            node._learn_addr(("10.1.2.3", 9444))
            node._learn_addr(("10.1.2.4", 9445))
            await node.stop()  # persists <store>.addrs atomically
            reborn = Node(_config(store_path=store))
            await reborn.start()
            try:
                assert ("10.1.2.3", 9444) in reborn._known_addrs
                assert ("10.1.2.4", 9445) in reborn._known_addrs
            finally:
                await reborn.stop()
            # A corrupt book is ignored, never fatal.
            (tmp_path / "chain.dat.addrs").write_text("not json{")
            third = Node(_config(store_path=store))
            await third.start()
            try:
                assert ("10.1.2.3", 9444) not in third._known_addrs
            finally:
                await third.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_discovery_off_by_default(self):
        async def scenario():
            a = Node(_config())
            await a.start()
            b = Node(_config())
            await b.start()
            try:
                # Books may learn addresses, but nothing dials without
                # --target-peers: no discovery task exists.
                a._learn_addr(("127.0.0.1", b.port))
                await asyncio.sleep(2 * 1.5)
                assert a.peer_count() == 0 and b.peer_count() == 0
            finally:
                await stop_all((a, b))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))
