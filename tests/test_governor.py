"""Resource governor: budget primitives, SHED hysteresis, prune safety.

Unit and property tests for node/governor.py plus the node-side prune
invariants the budgets depend on (_addr_budgets/_banned_until bounded
tracking).  The network-level behavior (floods, squat, soak) lives in
tests/test_overload.py.
"""

import asyncio
import random
import time

from p1_tpu.config import NodeConfig
from p1_tpu.node import Node
from p1_tpu.node import protocol
from p1_tpu.node.governor import (
    DEFAULT_RATES,
    DROPS_PER_VIOLATION,
    OverloadState,
    PeerBudget,
    ResourceGovernor,
    TokenBucket,
)
from p1_tpu.node.protocol import MsgType


class _Clock:
    """Injectable monotonic clock."""

    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestTokenBucket:
    def test_burst_then_refill(self):
        clock = _Clock()
        b = TokenBucket(rate=2.0, burst=4.0, clock=clock)
        assert all(b.take() for _ in range(4))
        assert not b.take()  # burst spent
        clock.t += 1.0  # 2 tokens refill
        assert b.take() and b.take()
        assert not b.take()

    def test_grant_is_additive_and_capped(self):
        clock = _Clock()
        b = TokenBucket(rate=1.0, burst=4.0, clock=clock)
        b.grant(4.0)
        b.grant(100.0)
        assert b.peek() == 16.0  # grant_cap = 4 * burst

    def test_refill_never_claws_back_grant_credit(self):
        # The ADDR-budget lesson (ADVICE r5): solicited credit above the
        # burst cap must survive refill observations.
        clock = _Clock()
        b = TokenBucket(rate=1.0, burst=4.0, clock=clock)
        b.grant(8.0)
        clock.t += 100.0
        assert b.peek() == 12.0

    def test_property_randomized_clock_steps(self):
        """Invariants under arbitrary take/grant/step interleavings:
        0 <= tokens <= grant_cap always; tokens <= burst when no grant
        credit is outstanding; a stalled (or repeated-same-time) clock
        refills nothing; take() never goes negative."""
        rng = random.Random(0xB0B)
        for _ in range(200):
            clock = _Clock(rng.uniform(0, 1e6))
            rate = rng.uniform(0.1, 100.0)
            burst = rng.uniform(1.0, 50.0)
            b = TokenBucket(rate=rate, burst=burst, clock=clock)
            granted = False
            for _ in range(100):
                op = rng.randrange(4)
                if op == 0:
                    before = b.peek()
                    got = b.take(rng.uniform(0.1, 3.0))
                    if got:
                        assert b.tokens >= 0.0
                    else:
                        # A refused take spends nothing (same instant).
                        assert b.peek() == before
                elif op == 1:
                    b.grant(rng.uniform(0.0, 30.0))
                    granted = True
                elif op == 2:
                    clock.t += rng.uniform(0.0, 10.0)
                else:
                    pass  # stalled clock: same instant observed again
                tokens = b.peek()
                assert 0.0 <= tokens <= b.grant_cap + 1e-9
                if not granted:
                    assert tokens <= b.burst + 1e-9

    def test_property_refill_accrues_at_rate(self):
        rng = random.Random(7)
        for _ in range(50):
            clock = _Clock()
            rate = rng.uniform(0.5, 20.0)
            burst = 1000.0
            b = TokenBucket(rate=rate, burst=burst, clock=clock)
            assert b.take(burst)  # drain to exactly 0
            total = 0.0
            for _ in range(20):
                dt = rng.uniform(0.0, 5.0)
                clock.t += dt
                total += dt
                expected = min(burst, total * rate)
                assert abs(b.peek() - expected) < 1e-6


class TestPeerBudget:
    def test_violation_every_n_drops(self):
        clock = _Clock()
        budget = PeerBudget(clock=clock)
        burst = DEFAULT_RATES["queries"][1]
        for _ in range(int(burst)):
            assert budget.admit("queries")
        violations = 0
        for i in range(1, 3 * DROPS_PER_VIOLATION + 1):
            assert not budget.admit("queries")
            if budget.owes_violation("queries"):
                violations += 1
                assert i % DROPS_PER_VIOLATION == 0
        assert violations == 3  # one per DROPS_PER_VIOLATION, consumed

    def test_classes_are_independent(self):
        clock = _Clock()
        budget = PeerBudget(clock=clock)
        while budget.admit("blocks"):
            pass
        assert budget.admit("txs") and budget.admit("queries")


class TestGovernorHysteresis:
    def test_shed_and_recover(self):
        g = ResourceGovernor(watermark_bytes=1000, clock=_Clock())
        assert not g.observe(900) and g.state is OverloadState.NORMAL
        assert g.observe(1001) and g.state is OverloadState.SHED
        assert g.sheds == 1
        # Hysteresis: between low (800) and high, SHED holds.
        assert not g.observe(900) and g.state is OverloadState.SHED
        assert g.observe(799) and g.state is OverloadState.NORMAL
        # Peak is remembered across the round trip.
        assert g.tracked_peak_bytes == 1001

    def test_zero_watermark_never_sheds(self):
        g = ResourceGovernor(watermark_bytes=0, clock=_Clock())
        assert not g.observe(1 << 40)
        assert g.state is OverloadState.NORMAL

    def test_admission_disabled_passes_everything(self):
        g = ResourceGovernor(admission=False, clock=_Clock())
        budget = g.budget()
        assert all(g.admit(budget, "blocks") for _ in range(10_000))
        assert g.admission_drops["blocks"] == 0


def _node(**kw) -> Node:
    kw.setdefault("difficulty", 12)
    kw.setdefault("mine", False)
    return Node(NodeConfig(**kw))


class TestBoundedTrackingPrune:
    """The MAX_TRACKED_HOSTS prunes must bound memory WITHOUT evicting
    entries that still carry live state (active bans, in-window
    violation scores, spent-or-granted ADDR budgets) while stale
    entries exist to shed instead."""

    def test_banned_until_prune_keeps_active_bans(self):
        from p1_tpu.node.node import MAX_TRACKED_HOSTS

        node = _node()
        now = time.monotonic()
        active = {f"10.1.{i >> 8}.{i & 255}" for i in range(64)}
        for host in active:
            node._banned_until[host] = now + 1000.0  # far from expiry
        for i in range(MAX_TRACKED_HOSTS + 100):
            node._banned_until[f"10.9.{i >> 8}.{i & 255}"] = now - 1.0  # expired
        # One more violation burst triggers the overflow prune.
        for _ in range(3):
            node._record_violation("10.200.0.1")
        assert len(node._banned_until) <= MAX_TRACKED_HOSTS
        assert active <= set(node._banned_until)  # no active ban evicted

    def test_violations_prune_keeps_in_window_scores(self):
        import collections

        from p1_tpu.node.node import BAN_WINDOW_S, MAX_TRACKED_HOSTS

        node = _node()
        now = time.monotonic()
        active = {f"10.2.{i >> 8}.{i & 255}" for i in range(64)}
        for host in active:
            node._violations[host] = collections.deque([now])
        for i in range(MAX_TRACKED_HOSTS + 100):
            node._violations[f"10.8.{i >> 8}.{i & 255}"] = collections.deque(
                [now - BAN_WINDOW_S - 5.0]
            )
        node._record_violation("10.200.0.2")
        assert len(node._violations) <= MAX_TRACKED_HOSTS + 1
        assert active <= set(node._violations)

    def test_addr_budget_prune_keeps_live_buckets(self):
        """Stale all-default buckets are shed first; buckets carrying
        information — spent tokens mid-window, or solicited grant credit
        above the cap — survive the overflow prune (the ADVICE r5
        regression, re-proven against the bounded-tracking path)."""
        from p1_tpu.node.node import ADDR_TOKENS_MAX, MAX_TRACKED_HOSTS

        node = _node()
        now = time.monotonic()
        spent = {}
        for i in range(32):
            host = f"10.3.{i >> 8}.{i & 255}"
            bucket = node._addr_budget(host)
            bucket[0] -= 3.0  # spent budget: live state
            spent[host] = bucket[0]
        node._addr_budget("10.4.0.1")  # create at the base refill...
        granted = node._addr_budget("10.4.0.1", grant=True)  # ...then credit
        assert granted[0] > ADDR_TOKENS_MAX
        stale = now - 10_000.0
        for i in range(MAX_TRACKED_HOSTS + 50):
            node._addr_budgets[f"10.7.{i >> 8}.{i & 255}"] = [
                ADDR_TOKENS_MAX,
                stale,
            ]
        node._addr_budget("10.200.0.3")  # fresh create triggers the prune
        assert len(node._addr_budgets) <= MAX_TRACKED_HOSTS + 1
        for host, tokens in spent.items():
            assert node._addr_budgets[host][0] == tokens
        assert node._addr_budgets["10.4.0.1"][0] > ADDR_TOKENS_MAX


class TestStatusWire:
    def test_getstatus_status_roundtrip(self):
        raw = protocol.encode_getstatus()
        mtype, body = protocol.decode(raw)
        assert mtype is MsgType.GETSTATUS and body is None
        status = {"height": 7, "overload": {"state": "normal", "sheds": 0}}
        mtype, decoded = protocol.decode(protocol.encode_status(status))
        assert mtype is MsgType.STATUS and decoded == status

    def test_malformed_status_is_a_protocol_error(self):
        import pytest

        with pytest.raises(protocol.ProtocolError):
            protocol.decode(bytes([MsgType.STATUS]) + b"\xff\xfe not json")
        with pytest.raises(protocol.ProtocolError):
            protocol.decode(bytes([MsgType.STATUS]) + b"[1, 2]")
        with pytest.raises(protocol.ProtocolError):
            protocol.decode(bytes([MsgType.GETSTATUS]) + b"x")

    def test_live_status_query_carries_overload_block(self):
        from p1_tpu.node.client import get_status

        async def scenario():
            node = _node()
            await node.start()
            try:
                status = await get_status(
                    "127.0.0.1", node.port, 12, timeout=10
                )
                assert status["height"] == 0
                overload = status["overload"]
                assert overload["state"] == "normal"
                assert overload["admission_dropped"] == {
                    "blocks": 0,
                    "txs": 0,
                    "queries": 0,
                }
                assert overload["resident_body_bytes"] == 0
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))


class TestShedIntegration:
    def test_shed_pauses_mining_and_recovers(self):
        """End-to-end hysteresis on a live node: pool bytes push the
        gauge over a tiny watermark -> SHED (mining paused, tx gossip
        dropped); expiring the pool drains the gauge -> NORMAL."""
        from txutil import account, stx

        async def scenario():
            node = _node(mem_watermark_bytes=1 << 30, chunk=1 << 12)
            await node.start()
            try:
                # Fund alice so a real signed spend passes admission.
                node.miner_id = account("alice")
                node.start_mining()
                while node.chain.height < 1:
                    await asyncio.sleep(0.01)
                await node.stop_mining()
                tx = stx("alice", account("bob"), 1, 1, 0, difficulty=12)
                # Warm the verify-once cache BEFORE taking the baseline:
                # admission will record the signature there, and the
                # cache term is part of the gauge (round 8) but does not
                # drain with the pool — pre-warming keeps it inside g0
                # so the watermark round trip below stays about pool
                # bytes only.
                tx.verify_signature(cache=node.sig_cache)
                # Pin the watermark between the quiescent gauge and the
                # gauge with the pending spend: admission pushes it over,
                # expiry brings it back under the low mark — a real
                # round trip, independent of exact object sizes.
                g0 = node._memory_gauge()
                tx_len = len(tx.serialize())
                node.governor.watermark_bytes = g0 + tx_len // 2
                node.governor.low_watermark_bytes = g0 + tx_len // 4
                assert node.mempool.add(tx)
                assert node.mempool.bytes_pending > 0
                for _ in range(100):
                    if node.governor.shedding:
                        break
                    await asyncio.sleep(0.1)
                assert node.governor.shedding
                assert node.status()["overload"]["state"] == "shed"
                assert node.status()["overload"]["mining_paused"]
                # Pressure gone: the pool expires, hysteresis recovers.
                node.mempool.expire(0.0)
                for _ in range(100):
                    if not node.governor.shedding:
                        break
                    await asyncio.sleep(0.1)
                assert not node.governor.shedding
                assert node.governor.sheds == 1
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))
