"""Overload resilience: admission control, write-queue caps, flood soak.

The network half of the governor tests (unit/property cases live in
tests/test_governor.py): real GreedyPeers — protocol-valid floods the
misbehavior score cannot see — against real in-process nodes.  The slow
soak is the PR's acceptance scenario: ≥3 sustained attackers, the node
stays live and memory-bounded, an honest peer completes IBD through the
noise, and the SHED state recovers (hysteresis) once the attackers go.
"""

import asyncio
import time

import pytest

from p1_tpu.chain import ChainStore
from p1_tpu.config import NodeConfig
from p1_tpu.node import Node
from p1_tpu.node.testing import FloodPlan, GreedyPeer, make_blocks

DIFF = 8  # a few hashes per block: flood chains are cheap to mine


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


async def wait_until(cond, timeout=20.0, interval=0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return False


def _config(**kw) -> NodeConfig:
    kw.setdefault("difficulty", DIFF)
    kw.setdefault("mine", False)
    kw.setdefault("chunk", 1 << 12)
    return NodeConfig(**kw)


class TestAdmission:
    def test_query_flood_is_dropped_then_banned(self):
        async def scenario():
            blocks = make_blocks(20, difficulty=DIFF)
            node = Node(_config())
            await node.start()
            for b in blocks[1:]:
                node.chain.add_block(b)
            flooder = GreedyPeer(blocks, FloodPlan(queries=True))
            try:
                await flooder.start("127.0.0.1", node.port)
                # Budget burst spent -> drops -> violations -> the
                # existing accept-time ban refuses the reconnects.
                assert await wait_until(
                    lambda: node.governor.admission_drops["queries"] > 0
                )
                assert await wait_until(
                    lambda: node._is_banned("127.0.0.1"), timeout=30
                )
                assert await wait_until(
                    lambda: flooder.refused + flooder.disconnects > 0,
                    timeout=30,
                )
                # The node is alive and serving through it all.
                assert node.status()["height"] == 20
            finally:
                await flooder.stop()
                await node.stop()

        run(scenario())

    def test_tx_flood_is_clipped_at_the_door(self):
        from txutil import account, stx

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                # Protocol-valid, signature-valid, unaffordable spends:
                # nothing scorable about them — only the admission
                # budget stands between this flood and per-frame
                # decode+verify work (and the pool's capacity).
                from p1_tpu.node import protocol

                frames = tuple(
                    protocol.encode_tx(
                        stx("pauper", account("x"), 1, 1, seq, difficulty=DIFF)
                    )
                    for seq in range(8)
                )
                blocks = make_blocks(1, difficulty=DIFF)
                flooder = GreedyPeer(blocks, FloodPlan(tx_frames=frames))
                await flooder.start("127.0.0.1", node.port)
                assert await wait_until(
                    lambda: node.governor.admission_drops["txs"] > 0
                )
                assert len(node.mempool) == 0  # nothing hostile admitted
                await flooder.stop()
            finally:
                await node.stop()

        run(scenario())

    def test_orphan_spray_stays_bounded(self):
        from p1_tpu.chain.chain import MAX_ORPHANS

        async def scenario():
            node = Node(_config())
            await node.start()
            spray = GreedyPeer(
                make_blocks(40, difficulty=DIFF), FloodPlan(orphans=True)
            )
            try:
                await spray.start("127.0.0.1", node.port)
                assert await wait_until(lambda: spray.sent > 100)
                assert len(node.chain._orphan_hashes) <= MAX_ORPHANS
                assert node.status() is not None  # alive
            finally:
                await spray.stop()
                await node.stop()

        run(scenario())

    def test_honest_rates_never_clipped(self):
        """The false-positive control: a two-node mesh mining and
        gossiping at full localhost speed never trips admission."""

        async def scenario():
            a = Node(_config(mine=True, miner_id="a"))
            await a.start()
            b = Node(_config(peers=(f"127.0.0.1:{a.port}",)))
            await b.start()
            try:
                assert await wait_until(lambda: a.chain.height >= 15)
                await a.stop_mining()
                assert await wait_until(
                    lambda: b.chain.height == a.chain.height
                )
                for node in (a, b):
                    drops = node.governor.admission_drops
                    assert drops == {"blocks": 0, "txs": 0, "queries": 0}
                    assert node.governor.peers_dropped_squat == 0
            finally:
                await b.stop()
                await a.stop()

        run(scenario())


class TestWriteQueue:
    def test_squatting_peer_is_dropped(self):
        async def scenario():
            blocks = make_blocks(250, difficulty=DIFF)
            node = Node(_config())
            await node.start()
            for b in blocks[1:]:
                node.chain.add_block(b)
            # Tight cap so a ~40 KB sync reply backlog trips it fast.
            node.governor.write_queue_max = 16 << 10
            squatter = GreedyPeer(blocks, FloodPlan(squat=True, burst=8))
            try:
                await squatter.start("127.0.0.1", node.port)
                assert await wait_until(
                    lambda: node.governor.peers_dropped_squat > 0, timeout=30
                )
                assert node.status()["height"] == 250  # alive, serving
            finally:
                await squatter.stop()
                await node.stop()

        run(scenario())


@pytest.mark.slow
class TestFloodSoak:
    def test_three_greedy_peers_vs_honest_ibd(self, tmp_path):
        """The acceptance scenario: ≥3 sustained protocol-valid
        attackers (query flood, orphan spray, write-queue squat) against
        a node running memory-bounded (body eviction on, watermark
        armed).  Through the whole window the node must stay live and
        within a bounded factor of its watermark, an honest peer must
        complete IBD of the full chain, and no consensus-critical reply
        to it may be lost; once the attackers disconnect the governor
        must come back to NORMAL (hysteresis)."""

        async def scenario():
            blocks = make_blocks(600, difficulty=DIFF, miner_id="v")
            store = ChainStore(tmp_path / "victim.dat")
            store.acquire()
            for b in blocks[1:]:
                store.append(b)
            store.close()

            victim = Node(
                _config(
                    store_path=str(tmp_path / "victim.dat"),
                    body_cache_blocks=16,
                    mem_watermark_bytes=1,  # re-pinned below, post-resume
                )
            )
            await victim.start()
            assert victim.chain.height == 600
            assert victim.chain.bodies_evicted > 0  # bounded resume ran
            # Watermark: a little above the quiescent gauge, so attack
            # pressure (write-buffer growth above all) crosses it and
            # the hysteresis round trip is actually exercised.
            quiescent = victim._memory_gauge()
            victim.governor.watermark_bytes = quiescent + (96 << 10)
            victim.governor.low_watermark_bytes = quiescent + (48 << 10)
            # Hard squat cap low enough to fire repeatedly in the window.
            victim.governor.write_queue_max = 256 << 10

            attackers = [
                GreedyPeer(
                    blocks, FloodPlan(queries=True), source="127.0.0.61"
                ),
                GreedyPeer(
                    make_blocks(60, difficulty=DIFF, miner_id="o"),
                    FloodPlan(orphans=True),
                    source="127.0.0.62",
                ),
                GreedyPeer(
                    blocks,
                    FloodPlan(squat=True, burst=8),
                    source="127.0.0.63",
                ),
            ]
            honest = Node(
                _config(peers=(f"127.0.0.1:{victim.port}",))
            )
            rss_samples = []

            def rss_bytes() -> int:
                with open("/proc/self/statm") as fh:
                    import os

                    return int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")

            try:
                for attacker in attackers:
                    await attacker.start("127.0.0.1", victim.port)
                await asyncio.sleep(1.0)  # attackers engaged first
                await honest.start()
                deadline = time.monotonic() + 25.0
                while time.monotonic() < deadline:
                    await asyncio.sleep(0.5)
                    rss_samples.append(rss_bytes())
                    # Live through the whole window: status() answers.
                    assert victim.status()["height"] == 600
                    g = victim.governor
                    if (
                        honest.chain.height == 600
                        and g.sheds > 0
                        and (
                            sum(g.admission_drops.values()) > 0
                            or g.peers_dropped_squat > 0
                        )
                    ):
                        break
                # (1) The honest peer completed IBD under attack — no
                # consensus-critical reply to it was dropped (a dropped
                # batch would stall its supervised sync past the window).
                assert honest.chain.height == 600
                assert honest.chain.tip_hash == victim.chain.tip_hash
                # ...and the honest host was never scored or banned.
                assert not victim._is_banned("127.0.0.1")
                # (2) Memory stayed bounded: the accounted gauge within
                # a small factor of the watermark (one squat cap of
                # overshoot at most), the resident bodies at O(cache),
                # and process RSS sane for a 600-block chain + attack.
                g = victim.governor
                assert g.tracked_peak_bytes <= (
                    g.watermark_bytes + g.write_queue_max + (512 << 10)
                )
                assert victim.chain.resident_body_bytes < (256 << 10)
                assert max(rss_samples) < 2 << 30
                # (3) The attack was actually repelled, not absorbed:
                # admission dropped flood frames and/or squatters died.
                assert (
                    sum(g.admission_drops.values()) > 0
                    or g.peers_dropped_squat > 0
                )
                # (4) Overload engaged... (the squat + floods must have
                # pushed the gauge over the pinned watermark)
                assert g.sheds > 0
            finally:
                for attacker in attackers:
                    await attacker.stop()
            try:
                # (5) ...and cleared: hysteresis back to NORMAL once the
                # attackers are gone and the buffers drain.
                assert await wait_until(
                    lambda: not victim.governor.shedding, timeout=30
                )
                # Mining would resume (not paused by the governor).
                assert not victim.status()["overload"]["mining_paused"]
            finally:
                await honest.stop()
                await victim.stop()

        run(scenario(), timeout=300)
