"""Miner: sealing headers, abort semantics, timestamp roll, determinism."""

import threading

import pytest

from p1_tpu.core import BlockHeader, make_genesis, meets_target
from p1_tpu.hashx.backend import HashBackend, SearchResult
from p1_tpu.miner import Miner


def _candidate(difficulty: int, seed: int = 0) -> BlockHeader:
    genesis = make_genesis(difficulty)
    return BlockHeader(
        version=1,
        prev_hash=genesis.block_hash(),
        merkle_root=bytes(32),
        timestamp=1735689700 + seed,
        difficulty=difficulty,
        nonce=0,
    )


def _backend(name):
    if name == "jax":
        from p1_tpu.hashx import get_backend

        return get_backend("jax", batch=1024)  # keep CPU-test compiles small
    return name


@pytest.mark.parametrize("backend", ["cpu", "numpy", "jax"])
def test_mines_valid_header(backend):
    miner = Miner(backend=_backend(backend), chunk=1 << 12)
    sealed = miner.search_nonce(_candidate(8))
    assert sealed is not None
    assert meets_target(sealed.block_hash(), 8)
    assert miner.last_stats.hashes_done >= 1
    assert miner.last_stats.hashes_per_sec > 0


def test_deterministic_across_backends():
    sealed = [
        Miner(backend=_backend(b), chunk=1 << 12).search_nonce(_candidate(10, seed=3))
        for b in ("cpu", "numpy", "jax")
    ]
    nonces = {s.nonce for s in sealed}
    assert len(nonces) == 1, f"backends disagree: {nonces}"


def test_abort_before_start():
    abort = threading.Event()
    abort.set()
    miner = Miner(backend="cpu", chunk=256)
    assert miner.search_nonce(_candidate(30), abort=abort) is None
    assert miner.last_stats.aborted


def test_abort_mid_search():
    abort = threading.Event()

    class SlowBackend(HashBackend):
        """Never finds anything; sets abort after a few chunks."""

        calls = 0

        def sha256d(self, data):
            raise NotImplementedError

        def search(self, prefix, start, count, difficulty):
            SlowBackend.calls += 1
            if SlowBackend.calls >= 3:
                abort.set()
            return SearchResult(None, count)

    miner = Miner(backend=SlowBackend(), chunk=1024)
    assert miner.search_nonce(_candidate(30), abort=abort) is None
    assert miner.last_stats.aborted
    assert miner.last_stats.hashes_done == SlowBackend.calls * 1024


def test_timestamp_roll_on_exhaustion():
    class NeverHit(HashBackend):
        def sha256d(self, data):
            raise NotImplementedError

        def search(self, prefix, start, count, difficulty):
            return SearchResult(None, count)

    miner = Miner(backend=NeverHit(), chunk=1 << 31, max_timestamp_rolls=2)
    header = _candidate(30)
    assert miner.search_nonce(header) is None
    assert miner.last_stats.timestamp_rolls == 2
    # 3 full sweeps of nonce space (initial + 2 rolls)
    assert miner.last_stats.hashes_done == 3 * (1 << 32)


def test_timestamp_roll_produces_valid_header():
    class HitAfterRoll(HashBackend):
        """Refuses the original timestamp's space; hits once rolled."""

        def __init__(self, real):
            self.real = real
            self.sweeps = 0

        def sha256d(self, data):
            return self.real.sha256d(data)

        def search(self, prefix, start, count, difficulty):
            sweeps_before = self.sweeps
            if start + count >= 1 << 32:
                self.sweeps += 1
            if sweeps_before < 1:
                return SearchResult(None, count)
            return self.real.search(prefix, start, count, difficulty)

    from p1_tpu.hashx import get_backend

    miner = Miner(backend=HitAfterRoll(get_backend("cpu")), chunk=1 << 31)
    header = _candidate(8)
    sealed = miner.search_nonce(header)
    # The first full sweep is swallowed; the hit comes at timestamp+1.
    assert sealed is not None
    assert sealed.timestamp == header.timestamp + 1
    assert meets_target(sealed.block_hash(), 8)
