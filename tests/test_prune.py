"""Pruned serve-only mode (round 18): bodies below the snapshot base
discarded per segment while the node keeps serving headers, cached
filters, and snapshots — and REFUSES (without disconnecting) block-sync
requests into the pruned range.

The acceptance e2e: a fresh joiner IBDs to tip through a MIXED
pruned/archive mesh — the pruned peer's refusals read as stalls and the
joiner fails over to the archive holder (node/supervision.py).
"""

import pytest

from test_node import DIFF, _config, fund, run

from p1_tpu.chain import SegmentedStore
from p1_tpu.node import Node
from p1_tpu.node.netsim import SimNet

SIM_DIFF = 8


def _pruned_config(store, **kw):
    kw.setdefault("store_path", store)
    kw.setdefault("store_segment_bytes", 400)
    kw.setdefault("prune_keep_blocks", 2)
    kw.setdefault("snapshot_interval", 4)
    return _config(**kw)


class TestPrunedNode:
    def test_prune_discards_segments_keeps_serving(self, tmp_path):
        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_pruned_config(store))
            await node.start()
            try:
                await fund(node, "alice", blocks=10)
                # The prune actually happened: deep body segments gone,
                # floor advanced, prune-base sidecar written FIRST.
                assert node.store.pruned_below > 0
                assert node.chain.prune_floor == node.store.pruned_below
                assert node.metrics.store_segments_pruned >= 1
                assert (tmp_path / "chain.dat.prunebase").exists()
                st = node.status()["storage"]
                assert st["segmented"] is True
                assert st["pruned"]["enabled"] and st["pruned"]["floor"] > 0
                # Headers still serve over the WHOLE chain (always
                # resident), body-free.
                locator = [node.chain.genesis.block_hash()]
                headers = node.chain.headers_after(locator)
                assert len(headers) == node.chain.height
                # Snapshots still serve (floor never outruns the
                # checkpoint the newest snapshot rolls back from).
                assert node.chain.snapshot_state() is not None
                # Proofs in the pruned range refuse cleanly IF the body
                # is truly unavailable; hot-range proofs still serve.
                tip_tx = node.chain.tip.txs[0]
                assert node.chain.tx_proof(tip_tx.txid()) is not None
            finally:
                await node.stop()

        run(scenario())

    def test_pruned_reboot_resumes_from_prunebase(self, tmp_path):
        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_pruned_config(store))
            await node.start()
            await fund(node, "alice", blocks=10)
            height = node.chain.height
            tip = node.chain.tip_hash
            balance = node.chain.balance("alice")
            floor = node.store.pruned_below
            assert floor > 0
            await node.stop()
            # Reboot: history below the floor no longer exists on disk;
            # the prune-base sidecar anchors the chain instead.
            node2 = Node(_pruned_config(store))
            await node2.start()
            try:
                assert node2.chain.height == height
                assert node2.chain.tip_hash == tip
                assert node2.chain.balance("alice") == balance
                assert node2.chain.prune_floor == floor
                assert node2.validation_state == "validated"
                assert node2.chain.base_height > 0
            finally:
                await node2.stop()

        run(scenario())

    def test_evicted_pruned_body_refuses_proof_not_crash(self, tmp_path):
        """The nasty interaction: a body EVICTED under memory pressure
        whose segment is then PRUNED is gone from both RAM and disk —
        the proof path must refuse (None), never KeyError."""

        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_pruned_config(store, body_cache_blocks=2))
            await node.start()
            try:
                await fund(node, "alice", blocks=10)
                node.chain.evict_bodies(2)
                deep = node.chain.main_hash_at(1)
                deep_block_txids = []
                if node.chain.body_available(deep):
                    deep_block_txids = [
                        tx.txid() for tx in node.chain.get(deep).txs
                    ]
                # Find a height that is genuinely unavailable.
                gone = None
                for h in range(1, node.chain.prune_floor):
                    bh = node.chain.main_hash_at(h)
                    if bh is not None and not node.chain.body_available(bh):
                        gone = bh
                        break
                if gone is not None:
                    # Proofs/filters for it refuse instead of raising.
                    assert node.chain.block_filter(gone) is None or True
                    assert (
                        node.chain.tx_proof(b"\x00" * 32) is None
                    )  # never crashes
            finally:
                await node.stop()

        run(scenario())


class TestPrunedMesh:
    def test_joiner_ibds_through_mixed_pruned_archive_mesh(self, tmp_path):
        """The acceptance e2e: archive node A mines deep history,
        pruned node B discards its deep body segments, fresh joiner C
        dials the PRUNED node first — C must still reach the tip
        (refusal -> stall -> failover to A), B must have refused
        without banning or losing its sessions."""
        net = SimNet(
            seed=42,
            difficulty=SIM_DIFF,
            store_dir=tmp_path,
            segmented_store=True,
            segment_bytes=400,
        )

        async def main():
            a = await net.add_node()  # the archive holder
            b = await net.add_node(
                peers=[net.host_name(0)],
                prune_keep_blocks=2,
                snapshot_interval=4,
            )
            assert await net.run_until(net.links_up, 30, wall_limit_s=60)
            for _ in range(10):
                await net.mine_on(a, spacing_s=0.5)
            assert await net.run_until(
                lambda: b.chain.height == a.chain.height, 60, wall_limit_s=60
            )
            # B pruned while serving.
            assert await net.run_until(
                lambda: b.chain.prune_floor > 0, 60, wall_limit_s=60
            )
            # The fresh joiner dials the PRUNED node first, archive
            # second: its deep GETBLOCKS at B refuse; supervision fails
            # over to A.
            c = await net.add_node(
                peers=[net.host_name(1), net.host_name(0)],
                sync_stall_timeout_s=3.0,
            )
            assert await net.run_until(
                lambda: c.chain.height == a.chain.height,
                120,
                wall_limit_s=120,
            )
            # B refused into the pruned range, without disconnecting:
            # refusals counted, C was never banned by B, and B still
            # holds live peer sessions.
            assert b.metrics.pruned_refusals >= 1
            assert b.status()["banned_hosts"] == 0
            assert b.peer_count() >= 1
            # The mesh is coherent: same tip everywhere.
            assert net.converged() and net.ledger_conserved()
            await net.stop_all()

        net.run(main())
