"""Validation fast lane: batched Ed25519, verify-once cache, equivalence.

The round-8 contract under test: the batch/cache paths may change WHERE
signature-verification cost is paid, never WHAT is accepted — identical
accept/reject decisions and identical exception text against the serial
path for EVERY input, including crafted small-order torsion components:
the fallback batch subgroup-gates every point (batch acceptance implies
serial acceptance), and a failed batch is settled by serial
confirmation, so torsion crafts can slow validation down but never
change its verdict (the chain-split review fix, docs/ROUND8.md).
"""

import dataclasses
import random

import pytest

from txutil import account, key_for, stx

from p1_tpu.chain import AddStatus, Chain, ChainStore, ValidationError, check_block
from p1_tpu.chain import validate as validate_mod
from p1_tpu.chain.store import save_chain
from p1_tpu.chain.validate import preverify_signatures
from p1_tpu.core import Block, BlockHeader, Transaction, merkle_root
from p1_tpu.core import _ed25519, keys, sigcache
from p1_tpu.core.genesis import genesis_hash
from p1_tpu.core.sigcache import SignatureCache
from p1_tpu.hashx import get_backend
from p1_tpu.miner import Miner

DIFF = 8
_MINER = Miner(backend=get_backend("cpu"))
TAG = genesis_hash(DIFF)


def _triples(n, salt="t"):
    out = []
    for i in range(n):
        kp = key_for(f"sigbatch-{salt}-{i % 5}")
        msg = b"sigbatch-%d-%s" % (i, salt.encode())
        out.append((kp.pubkey, kp.sign(msg), msg))
    return out


def _corrupt(triple, how):
    pubkey, sig, msg = triple
    if how == "sig":
        return (pubkey, sig[:20] + bytes([sig[20] ^ 1]) + sig[21:], msg)
    if how == "msg":
        return (pubkey, sig, msg + b"!")
    if how == "key":
        return (key_for("sigbatch-other").pubkey, sig, msg)
    if how == "s_range":  # scalar ≥ group order: serial rejects pre-math
        return (pubkey, sig[:32] + _ed25519._Q.to_bytes(32, "little"), msg)
    raise AssertionError(how)


# -- small-order torsion crafts (the round-8 review fix's fixtures) ------

_T2_ENC = (_ed25519._P - 1).to_bytes(32, "little")  # (0, -1): order 2
_T4_ENC = (0).to_bytes(32, "little")  # (sqrt(-1), 0): order 4


def _torsion_sign(msg: bytes, *, cancel: bool):
    """``(pubkey, sig_or_None)`` carrying small-order torsion over ``msg``.

    cancel=True: order-2 torsion planted in BOTH A and R; with k odd the
    torsion terms cancel in the serial equation, so SERIAL verification
    ACCEPTS (sig is None when k comes out even — vary the message and
    retry).  cancel=False: honest A, order-4 torsion in R — serial
    always rejects, while the pre-fix cofactored batch accepted: the
    chain-split craft.
    """
    a, prefix = _ed25519._secret_expand(bytes(32))
    B = _ed25519._B
    T = _ed25519._pt_decompress(_T2_ENC if cancel else _T4_ENC)
    a_pt = _ed25519._pt_mul(a, B)
    pub = _ed25519._pt_compress(_ed25519._pt_add(a_pt, T) if cancel else a_pt)
    r = int.from_bytes(_ed25519._sha512(prefix + msg), "little") % _ed25519._Q
    r_enc = _ed25519._pt_compress(_ed25519._pt_add(_ed25519._pt_mul(r, B), T))
    k = int.from_bytes(_ed25519._sha512(r_enc + pub + msg), "little") % _ed25519._Q
    if cancel and k % 2 == 0:
        return pub, None
    sig = r_enc + ((r + k * a) % _ed25519._Q).to_bytes(32, "little")
    return pub, sig


def _torsion_triple(*, cancel: bool, salt: bytes = b""):
    for i in range(200):
        msg = b"torsion-%d-" % i + salt
        pub, sig = _torsion_sign(msg, cancel=cancel)
        if sig is not None:
            return pub, sig, msg
    raise AssertionError("no usable k in 200 tries")


def _torsion_tx(tag: bytes, *, cancel: bool):
    """A transfer whose ownership proof is a torsion craft (see
    ``_torsion_sign``), structurally sound for ``check_block``."""
    from p1_tpu.core import keys as _k

    pub, _ = _torsion_sign(b"probe", cancel=cancel)
    sender = _k.account_id(pub)
    for seq in range(200):
        tx = Transaction(
            sender=sender,
            recipient=account("bob"),
            amount=1,
            fee=1,
            seq=seq,
            pubkey=pub,
            sig=b"",
            chain=tag,
        )
        pub2, sig = _torsion_sign(tx.signing_bytes(), cancel=cancel)
        assert pub2 == pub
        if sig is not None:
            return dataclasses.replace(tx, sig=sig)
    raise AssertionError("no usable k in 200 sequence numbers")


class TestEd25519Batch:
    """The fallback's multi-scalar batch equation against serial truth."""

    def test_rfc8032_vector_survives_decompress_rewrite(self):
        # Guards the one-exponentiation _recover_x: RFC 8032 TEST 1.
        seed = bytes.fromhex(
            "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
        )
        pub = bytes.fromhex(
            "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
        )
        assert _ed25519.public_key(seed) == pub
        sig = _ed25519.sign(seed, b"")
        assert sig.hex() == (
            "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e0652249"
            "01555fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe2465514143"
            "8e7a100b"
        )
        assert _ed25519.verify(pub, sig, b"")
        assert not _ed25519.verify(pub, sig, b"x")

    @pytest.mark.parametrize("n", [0, 1, 2, 7, 8, 9, 33])
    def test_all_valid_accepts(self, n):
        assert _ed25519.verify_batch(_triples(n))

    def test_corruption_at_every_position_rejects(self):
        base = _triples(12, salt="pos")
        for pos in range(len(base)):
            for how in ("sig", "msg", "key", "s_range"):
                bad = list(base)
                bad[pos] = _corrupt(bad[pos], how)
                assert not _ed25519.verify_batch(bad), (pos, how)
                assert not _ed25519.verify(*bad[pos])

    def test_random_mixes_match_serial(self):
        rng = random.Random(8)
        base = _triples(20, salt="mix")
        for _ in range(10):
            batch = [
                _corrupt(t, rng.choice(("sig", "msg")))
                if rng.random() < 0.2
                else t
                for t in base
            ]
            serial = all(_ed25519.verify(*t) for t in batch)
            assert _ed25519.verify_batch(batch) == serial

    def test_malformed_points_reject(self):
        kp = key_for("sigbatch-malformed")
        msg = b"m"
        sig = kp.sign(msg)
        # Non-decodable y ≥ p in the pubkey / in R.
        bad_enc = (_ed25519._P).to_bytes(32, "little")
        assert not _ed25519.verify_batch([(bad_enc, sig, msg)])
        assert not _ed25519.verify_batch([(kp.pubkey, bad_enc + sig[32:], msg)])
        assert not _ed25519.verify_batch([(kp.pubkey[:31], sig, msg)])
        assert not _ed25519.verify_batch([(kp.pubkey, sig[:63], msg)])

    def test_first_invalid_matches_serial_order(self):
        base = _triples(30, salt="first")
        for positions in ([4], [3, 17], [0, 1, 29], [29]):
            bad = list(base)
            for p in positions:
                bad[p] = _corrupt(bad[p], "sig")
            assert keys.first_invalid(bad) == min(positions)
        assert keys.first_invalid(base) is None

    def test_subgroup_gate_is_exact(self):
        # The gate must agree with the definition ([q]P == identity) on
        # torsion points, torsioned composites, and honest points.
        B = _ed25519._B
        T4 = _ed25519._pt_decompress((0).to_bytes(32, "little"))  # order 4
        T2 = _ed25519._pt_decompress(
            (_ed25519._P - 1).to_bytes(32, "little")
        )  # order 2
        assert _ed25519._in_prime_subgroup(B)
        assert _ed25519._in_prime_subgroup(_ed25519._IDENT)
        assert not _ed25519._in_prime_subgroup(T4)
        assert not _ed25519._in_prime_subgroup(T2)
        rng = random.Random(25519)
        for _ in range(8):
            honest = _ed25519._pt_mul(rng.randrange(1, _ed25519._Q), B)
            assert _ed25519._in_prime_subgroup(honest)
            for t in (T2, T4):
                mixed = _ed25519._pt_add(honest, t)
                assert not _ed25519._in_prime_subgroup(mixed)
                assert _ed25519._in_prime_subgroup(mixed) == _ed25519._pt_equal(
                    _ed25519._pt_mul(_ed25519._Q, mixed), _ed25519._IDENT
                )

    def test_torsion_craft_cannot_split_batch_from_serial(self):
        # The review fix: batch acceptance implies serial acceptance.
        # A torsion craft that serial rejects must NEVER pass the batch.
        pub, sig, msg = _torsion_triple(cancel=False)
        assert not _ed25519.verify(pub, sig, msg)
        assert not _ed25519.verify_batch([(pub, sig, msg)] * 8)
        # ...and one that serial ACCEPTS is gate-rejected by the batch,
        # then settled (accepted) by the serial confirmation.
        pub2, sig2, msg2 = _torsion_triple(cancel=True)
        assert _ed25519.verify(pub2, sig2, msg2)
        assert not _ed25519.verify_batch([(pub2, sig2, msg2)] * 8)
        assert keys.first_invalid([(pub2, sig2, msg2)] * 8) is None

    def test_first_invalid_not_steered_by_torsion_reject(self):
        # Regression for the old bisection: a torsion gate-reject in the
        # left half used to steer the search away from a genuinely bad
        # signature in the right half, returning None for a batch the
        # serial path rejects.
        base = _triples(24, salt="steer")
        tors = _torsion_triple(cancel=True)  # serially VALID, gate-rejected
        mixed = list(base)
        mixed[2] = tors
        bad_pos = 20
        mixed[bad_pos] = _corrupt(mixed[bad_pos], "sig")
        assert not _ed25519.verify_batch(mixed)
        assert keys.first_invalid(mixed) == bad_pos
        # With no genuinely bad signature, None is the (correct) verdict
        # even though the batch as a whole fails.
        mixed[bad_pos] = base[bad_pos]
        assert not _ed25519.verify_batch(mixed)
        assert keys.first_invalid(mixed) is None


class TestVerifyBatchDispatch:
    """keys.verify_batch: thresholds, worker pool, accounting."""

    def test_small_batches_run_serial(self):
        tr = _triples(keys.BATCH_MIN - 1, salt="small")
        keys.STATS.reset()
        assert keys.verify_batch(tr)
        assert keys.STATS.serial == len(tr)

    def test_large_batches_count_batched(self):
        tr = _triples(keys.BATCH_MIN, salt="large")
        keys.STATS.reset()
        assert keys.verify_batch(tr)
        assert keys.STATS.batched == len(tr)
        assert keys.STATS.serial == 0

    def test_fallback_never_dispatches_pool(self):
        # The pure-Python backend holds the GIL for its whole MSM, so
        # fanning its chunks out to worker threads is pure overhead —
        # fallback batches must run in the calling thread even when
        # workers > 1 and the batch spans multiple chunks.  The rung is
        # FORCED (round 15): on a toolchain-equipped host the auto
        # ladder resolves native, whose chunks rightly DO pool.
        old = keys._workers
        try:
            keys.set_sig_backend("fallback")
            keys.set_verify_workers(2)
            tr = _triples(16, salt="nopool") * ((keys.BATCH_CHUNK // 16) + 1)
            keys.STATS.reset()
            assert keys.verify_batch(tr)
            assert keys.STATS.pool_dispatches == 0
            assert keys.STATS.backends["pure-python"] == len(tr)
            assert keys._executor is None  # never even built
        finally:
            keys.set_sig_backend(None)
            keys.set_verify_workers(old)
            keys.shutdown_verify_pool()

    def test_pool_path_and_shutdown_cycle(self, monkeypatch):
        # Exercises the dispatch/shutdown/rebuild machinery on every
        # backend: the wheel path hits it naturally; without the wheel,
        # _use_pool is forced so the executor lifecycle still runs.
        if not keys.HAVE_CRYPTOGRAPHY:
            monkeypatch.setattr(keys, "_use_pool", lambda n_chunks: n_chunks > 1)
        old = keys._workers
        try:
            keys.set_verify_workers(2)
            tr = _triples(16, salt="pool") * ((keys.BATCH_CHUNK // 16) + 1)
            keys.STATS.reset()
            assert keys.verify_batch(tr)  # > one chunk => pool dispatch
            assert keys.STATS.pool_dispatches == 1
            keys.shutdown_verify_pool()
            assert keys.verify_batch(tr[: keys.BATCH_MIN])  # pool rebuilt ok
            bad = list(tr)
            bad[len(bad) // 2] = _corrupt(bad[len(bad) // 2], "sig")
            assert not keys.verify_batch(bad)
        finally:
            keys.set_verify_workers(old)
            keys.shutdown_verify_pool()

    def test_fallback_warning_fires_once(self, caplog):
        # Forced onto the pure-Python rung (the auto ladder resolves a
        # faster backend wherever one exists): the one-time cost-model
        # warning must fire exactly once, name the measured slowdown,
        # and name the fastest backend story for THIS host.
        keys._fallback_warned = False
        try:
            keys.set_sig_backend("fallback")
            with caplog.at_level("WARNING", logger="p1_tpu.core.keys"):
                keys.verify_batch(_triples(keys.BATCH_MIN, salt="warn"))
                keys.verify_batch(_triples(keys.BATCH_MIN, salt="warn2"))
        finally:
            keys.set_sig_backend(None)
            keys._fallback_warned = False
        hits = [r for r in caplog.records if "pure-Python Ed25519" in r.message]
        assert len(hits) == 1
        msg = hits[0].getMessage()
        assert "ms" in msg  # names the measured slowdown
        assert "FORCED" in msg  # ...and that this rung was an explicit pin

    @pytest.mark.slow
    def test_pool_cancellation_mid_batch(self, monkeypatch):
        # The soak the conftest knob (workers=1 default) excludes from
        # tier-1: a pool torn down with futures in flight must not
        # change the batch's answer — cancelled chunks re-verify in the
        # calling thread.
        import threading

        if not keys.HAVE_CRYPTOGRAPHY:
            monkeypatch.setattr(keys, "_use_pool", lambda n_chunks: n_chunks > 1)
        old = keys._workers
        try:
            keys.set_verify_workers(3)
            tr = _triples(64, salt="cancel") * ((2 * keys.BATCH_CHUNK) // 64)
            for _ in range(5):
                keys._pool(3)  # ensure a pool exists to tear down
                t = threading.Timer(
                    0.001, keys.shutdown_verify_pool, kwargs={"cancel": True}
                )
                t.start()
                assert keys.verify_batch(tr)
                t.join()
        finally:
            keys.set_verify_workers(old)
            keys.shutdown_verify_pool()


def _mine(parent, txs, ts=1):
    header = BlockHeader(
        version=1,
        prev_hash=parent.block_hash(),
        merkle_root=merkle_root([t.txid() for t in txs]),
        timestamp=parent.header.timestamp + ts,
        difficulty=DIFF,
        nonce=0,
    )
    sealed = _MINER.search_nonce(header)
    assert sealed is not None
    return Block(sealed, tuple(txs))


def _funded_chain():
    """A chain whose 'alice' can afford many transfers (mined rewards)."""
    chain = Chain(DIFF)
    for h in range(1, 4):
        blk = _mine(chain.tip, [Transaction.coinbase(account("alice"), h)])
        assert chain.add_block(blk).status is AddStatus.ACCEPTED
    return chain


def _transfers(n, start_seq=0):
    return [
        stx("alice", account("bob"), 1, 1, start_seq + i, difficulty=DIFF)
        for i in range(n)
    ]


@pytest.fixture
def serial_lane(monkeypatch):
    """Force the pre-round-8 cost model: per-tx backend verifies, no
    batching, no pre-warm — the equivalence baseline."""
    monkeypatch.setattr(keys, "BATCH_MIN", 1 << 30)
    monkeypatch.setattr(
        validate_mod, "preverify_signatures", lambda *a, **k: 0
    )
    import p1_tpu.chain.store as store_mod

    monkeypatch.setattr(
        store_mod, "_preverify_stream", lambda blocks, tag, cache: blocks
    )


class TestCheckBlockEquivalence:
    """check_block batch path == serial path, error text included."""

    def _block_with(self, txs):
        chain = _funded_chain()
        return chain, _mine(chain.tip, txs)

    def _outcome(self, chain, block, cache):
        try:
            check_block(
                block,
                DIFF,
                chain_tag=chain.genesis.block_hash(),
                sig_cache=cache,
            )
            return None
        except ValidationError as e:
            return str(e)

    def test_valid_block_all_paths(self, monkeypatch):
        chain, block = self._block_with(
            [Transaction.coinbase(account("m"), 4), *_transfers(10)]
        )
        assert self._outcome(chain, block, SignatureCache()) is None  # batch
        monkeypatch.setattr(keys, "BATCH_MIN", 1 << 30)
        assert self._outcome(chain, block, SignatureCache()) is None  # serial
        warm = SignatureCache()
        preverify_signatures(block.txs, chain.genesis.block_hash(), warm)
        keys.STATS.reset()
        assert self._outcome(chain, block, warm) is None  # cache-hit
        assert keys.STATS.serial == 0 and keys.STATS.batched == 0

    def test_corrupted_sig_every_position_identical_error(self, monkeypatch):
        txs = _transfers(10)
        for pos in range(len(txs)):
            bad_txs = list(txs)
            bad_txs[pos] = dataclasses.replace(
                bad_txs[pos],
                sig=_corrupt(
                    (b"", bad_txs[pos].sig, b""), "sig"
                )[1],
            )
            chain, block = self._block_with(
                [Transaction.coinbase(account("m"), 4), *bad_txs]
            )
            batch_err = self._outcome(chain, block, SignatureCache())
            with monkeypatch.context() as m:
                m.setattr(keys, "BATCH_MIN", 1 << 30)
                serial_err = self._outcome(chain, block, SignatureCache())
            assert batch_err == serial_err == "bad transaction signature", pos

    def test_structural_vs_signature_precedence(self, monkeypatch):
        # Serial interleaving: an EARLIER bad signature outranks a later
        # structural failure; a structural failure before any bad
        # signature is what gets reported.  Both paths must agree.
        good = _transfers(9)
        foreign = dataclasses.replace(
            stx("alice", account("bob"), 1, 1, 50, difficulty=DIFF),
            chain=genesis_hash(DIFF + 1),
        )
        bad_sig = dataclasses.replace(
            good[2], sig=_corrupt((b"", good[2].sig, b""), "sig")[1]
        )
        cases = [
            # (txs, expected error): foreign tag after a bad signature
            ([*good[:2], bad_sig, *good[3:], foreign], "bad transaction signature"),
            # foreign tag with every signature before it valid
            ([*good[:5], foreign, *good[5:]], "transaction signed for a different chain"),
            # signed coinbase reported over a later bad signature? no —
            # the coinbase slot fails structurally FIRST serially too.
            ([dataclasses.replace(Transaction.coinbase(account("m"), 4), sig=b"x" * 64), *good[:3]], "coinbase must be unsigned"),
        ]
        for txs, expected in cases:
            chain, block = self._block_with(txs)
            batch_err = self._outcome(chain, block, SignatureCache())
            with monkeypatch.context() as m:
                m.setattr(keys, "BATCH_MIN", 1 << 30)
                serial_err = self._outcome(chain, block, SignatureCache())
            assert batch_err == serial_err == expected, txs

    def test_torsion_tx_outcomes_identical(self, monkeypatch):
        # End to end: a block carrying a torsion-crafted ownership proof
        # must land the SAME way on the batch lane (gate reject → serial
        # confirmation) as on the pure serial lane — on every node,
        # whichever backend it has.  cancel=True is serially VALID (the
        # block is accepted despite the failed batch); cancel=False is
        # the old chain-split craft (rejected everywhere, same text).
        cases = [
            (_torsion_tx(TAG, cancel=True), None),
            (_torsion_tx(TAG, cancel=False), "bad transaction signature"),
        ]
        for crafted, expected in cases:
            txs = [*_transfers(keys.BATCH_MIN), crafted]  # batch lane engages
            chain, block = self._block_with(txs)
            batch_err = self._outcome(chain, block, SignatureCache())
            with monkeypatch.context() as m:
                m.setattr(keys, "BATCH_MIN", 1 << 30)
                serial_err = self._outcome(chain, block, SignatureCache())
            assert batch_err == serial_err == expected

    def test_fingerprint_mismatch_identical(self, monkeypatch):
        victim = _transfers(9)
        forged = dataclasses.replace(
            victim[4], pubkey=key_for("sigbatch-thief").pubkey
        )
        txs = [*victim[:4], forged, *victim[5:]]
        chain, block = self._block_with(txs)
        batch_err = self._outcome(chain, block, SignatureCache())
        with monkeypatch.context() as m:
            m.setattr(keys, "BATCH_MIN", 1 << 30)
            serial_err = self._outcome(chain, block, SignatureCache())
        assert batch_err == serial_err == "bad transaction signature"


#: Every signature backend THIS host can run for the equivalence
#: matrix: the pure-Python rung always, the native C++ engine when a
#: toolchain (or cached build) exists, the wheel when installed.  The
#: device rung's matrix lives in tests/test_ed25519_device.py (slow —
#: its jit compile dwarfs the tier-1 budget).
_MATRIX_BACKENDS = ["fallback"]
if keys._native_ed25519.available():
    _MATRIX_BACKENDS.append("native")
if keys.HAVE_CRYPTOGRAPHY:
    _MATRIX_BACKENDS.append("cryptography")


@pytest.fixture(params=_MATRIX_BACKENDS)
def each_backend(request):
    """Pin one backend rung for the duration of a test."""
    keys.set_sig_backend(request.param)
    yield request.param
    keys.set_sig_backend(None)


class TestBackendEquivalenceMatrix:
    """Round-15 satellite: the SAME verdict and the SAME error text on
    every backend rung, for every input — honest, corrupted at every
    position, and torsion-crafted.  The serial lane (BATCH_MIN forced
    high) on the pure-Python rung is the consensus baseline."""

    def _outcome(self, txs, backend):
        chain = _funded_chain()
        block = _mine(chain.tip, txs)
        try:
            check_block(
                block,
                DIFF,
                chain_tag=chain.genesis.block_hash(),
                sig_cache=SignatureCache(),
            )
            return None
        except ValidationError as e:
            return str(e)

    def test_valid_block_accepts_everywhere(self, each_backend):
        txs = [Transaction.coinbase(account("m"), 4), *_transfers(10)]
        assert self._outcome(txs, each_backend) is None

    def test_corruption_at_every_position_same_error(
        self, each_backend, monkeypatch
    ):
        txs = _transfers(10)
        for pos in range(len(txs)):
            bad_txs = list(txs)
            bad_txs[pos] = dataclasses.replace(
                bad_txs[pos],
                sig=_corrupt((b"", bad_txs[pos].sig, b""), "sig")[1],
            )
            block_txs = [Transaction.coinbase(account("m"), 4), *bad_txs]
            got = self._outcome(block_txs, each_backend)
            with monkeypatch.context() as m:
                # serial pure-Python: the consensus baseline
                m.setattr(keys, "BATCH_MIN", 1 << 30)
                keys.set_sig_backend("fallback")
                try:
                    want = self._outcome(block_txs, "serial")
                finally:
                    keys.set_sig_backend(each_backend)
            assert got == want == "bad transaction signature", (
                each_backend,
                pos,
            )

    def test_torsion_fixtures_same_verdict_and_text(
        self, each_backend, monkeypatch
    ):
        cases = [
            (_torsion_tx(TAG, cancel=True), None),
            (_torsion_tx(TAG, cancel=False), "bad transaction signature"),
        ]
        for crafted, expected in cases:
            txs = [*_transfers(keys.BATCH_MIN), crafted]
            got = self._outcome(txs, each_backend)
            assert got == expected, (each_backend, expected)

    def test_first_invalid_left_first_on_every_backend(self, each_backend):
        base = _triples(24, salt="matrix-" + each_backend)
        tors = _torsion_triple(cancel=True)  # serially valid, gate-rejected
        mixed = list(base)
        mixed[2] = tors
        mixed[20] = _corrupt(mixed[20], "sig")
        assert not keys.verify_batch(mixed)
        assert keys.first_invalid(mixed) == 20
        mixed[20] = base[20]
        assert keys.first_invalid(mixed) is None


class TestPreverify:
    def test_warms_only_valid_sigs(self):
        txs = _transfers(12)
        bad = dataclasses.replace(
            txs[5], sig=_corrupt((b"", txs[5].sig, b""), "sig")[1]
        )
        foreign = dataclasses.replace(txs[7], chain=b"\x00" * 32)
        mixed = [*txs[:5], bad, txs[6], foreign, *txs[8:], Transaction.coinbase("m", 1)]
        cache = SignatureCache()
        proven = preverify_signatures(mixed, TAG, cache)
        assert proven == 10  # 12 transfers minus the corrupted + foreign
        assert cache.hit(txs[0].txid(), txs[0].pubkey, txs[0].sig)
        assert not cache.hit(bad.txid(), bad.pubkey, bad.sig)
        assert not cache.hit(foreign.txid(), foreign.pubkey, foreign.sig)

    def test_warm_then_cold_outcomes_identical(self):
        # The warmer is an accelerator, not an oracle: a block whose
        # signatures were pre-proven and one validated cold must agree.
        chain_w, chain_c = _funded_chain(), _funded_chain()
        block = _mine(
            chain_w.tip, [Transaction.coinbase(account("m"), 4), *_transfers(10)]
        )
        preverify_signatures(block.txs, chain_w.genesis.block_hash(), chain_w.sig_cache)
        assert chain_w.add_block(block).status is AddStatus.ACCEPTED
        assert chain_c.add_block(block).status is AddStatus.ACCEPTED
        assert chain_w.tip_hash == chain_c.tip_hash


class TestRevalidateEquivalence:
    def _build_store(self, tmp_path, n_blocks=24):
        chain = _funded_chain()
        seq = 0
        for h in range(4, 4 + n_blocks):
            txs = [Transaction.coinbase(account("alice"), h), *_transfers(3, seq)]
            seq += 3
            assert chain.add_block(_mine(chain.tip, txs)).status is AddStatus.ACCEPTED
        path = tmp_path / "reval.chain"
        save_chain(chain, path)
        return chain, path

    @staticmethod
    def _state(chain):
        return (
            chain.tip_hash,
            chain.height,
            chain.balances_snapshot(),
            {a: chain.nonce(a) for a in ("alice", "bob")},
        )

    def test_batch_equals_serial_revalidation(self, tmp_path, serial_lane, monkeypatch):
        built, path = self._build_store(tmp_path)
        serial = ChainStore(path).load_chain(DIFF, trusted=False, sig_cache=SignatureCache())
        monkeypatch.undo()  # restore the batch lane
        batch = ChainStore(path).load_chain(DIFF, trusted=False, sig_cache=SignatureCache())
        assert self._state(serial) == self._state(batch) == self._state(built)

    def test_corrupt_record_same_rejection_both_lanes(self, tmp_path, monkeypatch):
        built, path = self._build_store(tmp_path, n_blocks=12)
        # Corrupt ONE signature inside a mid-chain record, CRC-fixed so
        # the storage layer hands it through and VALIDATION must catch it
        # (store.py's "hostile editor, not a disk" case).
        raw = bytearray(path.read_bytes())
        target = built._main_hashes[8]
        body = built.get(target).serialize()
        off = raw.find(body)
        assert off > 0
        sig_field = built.get(target).txs[1].sig
        soff = raw.find(sig_field, off)
        raw[soff] ^= 1
        # fix the record checksum: recompute over the framed record
        import struct
        import zlib

        rec_off = off - 4
        (length,) = struct.unpack_from(">I", raw, rec_off)
        crc = zlib.crc32(raw[rec_off : rec_off + 4 + length])
        struct.pack_into(">I", raw, rec_off + 4 + length, crc)
        path.write_bytes(bytes(raw))

        def load():
            return ChainStore(path).load_chain(
                DIFF, trusted=False, sig_cache=SignatureCache()
            )

        batch_chain = load()
        with monkeypatch.context() as m:
            m.setattr(keys, "BATCH_MIN", 1 << 30)
            m.setattr(validate_mod, "preverify_signatures", lambda *a, **k: 0)
            import p1_tpu.chain.store as store_mod

            m.setattr(
                store_mod, "_preverify_stream", lambda blocks, tag, cache: blocks
            )
            serial_chain = load()
        # Both lanes reject the tampered record (and its descendants,
        # which no longer connect) at the same height.
        assert batch_chain.height == serial_chain.height == 8 - 1

    def test_trusted_resume_is_signature_free_and_unchanged(self, tmp_path):
        built, path = self._build_store(tmp_path, n_blocks=12)
        keys.STATS.reset()
        resumed = ChainStore(path).load_chain(DIFF, trusted=True)
        assert keys.STATS.serial == 0
        assert keys.STATS.batched == 0
        assert self._state(resumed) == self._state(built)


class TestSignatureCache:
    def test_lru_bound_and_counters(self):
        cache = SignatureCache(max_entries=4)
        items = [(bytes([i]) * 32, b"p" * 32, b"s" * 64) for i in range(6)]
        for it in items:
            cache.add(*it)
        assert len(cache) == 4
        assert cache.bytes_used == 4 * sigcache.ENTRY_COST
        assert not cache.hit(*items[0])  # evicted (oldest)
        assert cache.hit(*items[5])
        assert cache.snapshot()["hits"] == 1
        assert cache.snapshot()["misses"] == 1

    def test_lru_refresh_on_hit(self):
        cache = SignatureCache(max_entries=2)
        a, b, c = [(bytes([i]) * 32, b"p" * 32, b"s" * 64) for i in range(3)]
        cache.add(*a)
        cache.add(*b)
        assert cache.hit(*a)  # refresh a; b is now oldest
        cache.add(*c)
        assert not cache.hit(*b)
        assert cache.hit(*a)

    def test_salted_keys_differ_across_instances(self):
        a, b = SignatureCache(), SignatureCache()
        txid, pk, sg = b"\x01" * 32, b"p" * 32, b"s" * 64
        assert a._key(txid, pk, sg) != b._key(txid, pk, sg)

    def test_failures_never_cached(self):
        cache = SignatureCache()
        tx = stx("alice", account("bob"), 1, 1, 0, difficulty=DIFF)
        bad = dataclasses.replace(tx, sig=bytes(64))
        assert not bad.verify_signature(cache=cache)
        assert len(cache) == 0
        assert tx.verify_signature(cache=cache)
        assert len(cache) == 1


class TestNegativeVerifyCache:
    """keys.verify's bounded negative memo (round-8 review, finding 3):
    a peer replaying a known-bad signature must not buy a fresh backend
    verify every time."""

    def test_replayed_invalid_costs_one_backend_call(self):
        kp = key_for("sigbatch-negcache")
        msg = b"neg-memo"
        bad_sig = bytes(64)
        keys._neg_cache.clear()
        keys.STATS.reset()
        assert not keys.verify(kp.pubkey, bad_sig, msg)
        assert keys.STATS.serial == 1
        for _ in range(5):
            assert not keys.verify(kp.pubkey, bad_sig, msg)
        assert keys.STATS.serial == 1  # the memo absorbed the replays
        # Positive results are NOT memoized here (that's sigcache's job,
        # keyed by txid): each valid verify still reaches the backend.
        good = kp.sign(msg)
        assert keys.verify(kp.pubkey, good, msg)
        assert keys.verify(kp.pubkey, good, msg)
        assert keys.STATS.serial == 3

    def test_negative_memo_is_bounded(self, monkeypatch):
        monkeypatch.setattr(keys, "_NEG_CACHE_MAX", 4)
        keys._neg_cache.clear()
        kp = key_for("sigbatch-negbound")
        for i in range(8):
            assert not keys.verify(kp.pubkey, bytes(64), b"m%d" % i)
        assert len(keys._neg_cache) <= 4
        keys._neg_cache.clear()

    def test_memo_key_commits_to_exact_bytes(self):
        kp = key_for("sigbatch-negexact")
        keys._neg_cache.clear()
        assert not keys.verify(kp.pubkey, bytes(64), b"a")
        # Same pubkey, different message: its own verdict, not a shadow.
        msg = b"b"
        assert keys.verify(kp.pubkey, kp.sign(msg), msg)


class TestNoDoubleVerify:
    """The mempool-admission → block-connect double-verify fix."""

    def test_mempool_then_block_connect_zero_backend_calls(self):
        from p1_tpu.mempool import Mempool

        chain = _funded_chain()
        cache = SignatureCache()
        chain.sig_cache = cache
        pool = Mempool(
            balance_of=chain.balance,
            nonce_of=chain.nonce,
            chain_tag=chain.genesis.block_hash(),
            sig_cache=cache,
        )
        txs = _transfers(10)
        keys.STATS.reset()
        for tx in txs:
            assert pool.add(tx)
        admitted = keys.STATS.serial + keys.STATS.batched
        assert admitted == len(txs)  # admission paid the backend once each
        # Mine-time assembly + connect: all signatures cache-hit.
        block = _mine(
            chain.tip,
            [Transaction.coinbase(account("m"), 4), *pool.select(100)],
        )
        keys.STATS.reset()
        assert chain.add_block(block).status is AddStatus.ACCEPTED
        assert keys.STATS.serial == 0
        assert keys.STATS.batched == 0
        assert cache.hits >= len(txs)


class TestNodeValidationStatus:
    """Node-level acceptance: a fully mempool-resident block connects
    with ZERO backend Ed25519 verifies, and status() exposes the
    counters."""

    def test_mempool_resident_block_connects_backend_free(self):
        import asyncio

        from p1_tpu.config import NodeConfig
        from p1_tpu.node import Node

        async def scenario():
            node = Node(
                NodeConfig(difficulty=DIFF, mine=False, chunk=1 << 14)
            )
            await node.start()
            try:
                # Fund alice so her spends are admissible.
                import time as _time

                node.miner_id = account("alice")
                node.start_mining()
                deadline = _time.monotonic() + 20
                while node.chain.height < 3:
                    assert _time.monotonic() < deadline
                    await asyncio.sleep(0.02)
                await node.stop_mining()
                tag = node.chain.genesis.block_hash()
                for i in range(10):
                    await node.submit_tx(
                        stx("alice", account("bob"), 1, 1, i, difficulty=DIFF)
                    )
                assert len(node.mempool) == 10
                block = node._assemble()
                sealed = _MINER.search_nonce(block.header)
                block = Block(sealed, block.txs)
                keys.STATS.reset()
                hits_before = node.sig_cache.hits
                res = await node._handle_block(block)
                assert res.status is AddStatus.ACCEPTED
                assert keys.STATS.serial == 0  # zero backend verifies
                assert keys.STATS.batched == 0
                assert node.sig_cache.hits - hits_before >= 10
                validation = node.status()["validation"]
                assert validation["hits"] >= 10
                assert validation["entries"] >= 10
                assert {"misses", "batched", "serial", "backend", "workers"} <= set(
                    validation
                )
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))
