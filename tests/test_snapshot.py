"""Untrusted snapshot sync (chain/snapshot.py + the node's ASSUMED plane).

Round 12's acceptance surface:

- canonical serialization: serialize→load is byte-identical and the
  state root is stable across dict insertion orders AND across
  interpreter hash seeds (the PYTHONHASHSEED subprocess pair);
- hostile-input integrity: bad digests, reordered entries, wrong
  counts, and root mismatches all raise, file framing damage is
  detected (verdict 0/1/2 exactly like `p1 fsck`);
- the chain's checkpoint commitments: recorded at interval heights,
  re-recorded across reorgs, and the rollback materialization
  (``snapshot_state``) agrees with the incremental roots;
- ``Chain.from_snapshot``: an assumed chain serves queries immediately
  and extends exactly like the fully-validated chain it mirrors;
- the node plane, end to end in the simulator: honest boot→ASSUMED→
  flip; the LYING-snapshot divergence (quarantine, demotion, genesis
  IBD fallback, convergence to the honest tip); truncated/stalling
  snapshot servers failing over; and crash-during-download /
  crash-during-revalidation recovering through the normal resume path.
"""

import os
import random
import subprocess
import sys

import pytest

from p1_tpu.chain import snapshot as snapmod
from p1_tpu.chain.chain import Chain
from p1_tpu.core.tx import BLOCK_REWARD
from p1_tpu.chain.snapshot import SnapshotError
from p1_tpu.node.netsim import SimNet
from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

DIFF = 8


def _mk_chain(n=10, interval=4, miner_id="m1"):
    chain = Chain(DIFF)
    chain.checkpoint_interval = interval
    for b in make_blocks(n, DIFF, miner_id=miner_id)[1:]:
        res = chain.add_block(b)
        assert res.status.name == "ACCEPTED", res
    return chain


def _records(chain):
    h, block, balances, nonces, root = chain.snapshot_state()
    return h, root, snapmod.build_records(h, block, balances, nonces)


class TestCanonicalState:
    """Serialization determinism — the property the digests stand on."""

    BAL = {"alice": 7, "bob": 3, "carol": 11}
    NON = {"alice": 2, "dave": 1}

    def test_root_stable_across_insertion_orders(self):
        rng = random.Random(0)
        want = snapmod.state_root(self.BAL, self.NON)
        for _ in range(10):
            b = list(self.BAL.items())
            n = list(self.NON.items())
            rng.shuffle(b)
            rng.shuffle(n)
            assert snapmod.state_root(dict(b), dict(n)) == want

    def test_chunks_byte_identical_across_insertion_orders(self):
        rng = random.Random(1)
        want = snapmod.encode_chunks(self.BAL, self.NON)
        for _ in range(10):
            b = list(self.BAL.items())
            rng.shuffle(b)
            assert snapmod.encode_chunks(dict(b), self.NON) == want

    def test_zero_entries_never_encode(self):
        # A zero balance/nonce is the same as absence — the invariant
        # the ledger's _shift maintains, mirrored by the codec.
        assert snapmod.state_root({"a": 5, "z": 0}, {}) == snapmod.state_root(
            {"a": 5}, {}
        )

    def test_round_trip_file_and_state(self, tmp_path):
        chain = _mk_chain()
        h, root, (manifest_payload, chunks) = _records(chain)
        path = tmp_path / "snap.p1s"
        snapmod.write_snapshot(path, manifest_payload, chunks)
        snap = snapmod.load_snapshot(path)
        assert snap.height == h and snap.state_root == root
        assert snap.balances == {"m1": h * BLOCK_REWARD}
        # Writing the LOADED state back reproduces the exact file.
        again = tmp_path / "again.p1s"
        m2, c2 = snapmod.build_records(
            snap.height, snap.manifest.block, snap.balances, snap.nonces
        )
        snapmod.write_snapshot(again, m2, c2)
        assert again.read_bytes() == path.read_bytes()

    def test_root_and_file_stable_under_pythonhashseed(self, tmp_path):
        """Two fresh interpreters with different hash seeds must emit
        byte-identical snapshot files and the same state root —
        canonical means canonical."""
        script = r"""
import sys, hashlib
sys.path.insert(0, "/root/repo")
from p1_tpu.chain import snapshot as snapmod
from p1_tpu.chain.chain import Chain
from p1_tpu.node.testing import make_blocks
chain = Chain(8)
chain.checkpoint_interval = 4
for b in make_blocks(9, 8, miner_id="seed-test")[1:]:
    chain.add_block(b)
h, block, balances, nonces, root = chain.snapshot_state()
m, c = snapmod.build_records(h, block, balances, nonces)
snapmod.write_snapshot(sys.argv[1], m, c)
print(root.hex(), hashlib.sha256(open(sys.argv[1], "rb").read()).hexdigest())
"""
        outs = []
        for seed in ("0", "12345"):
            out = tmp_path / f"snap-{seed}.p1s"
            env = {
                **os.environ,
                "PYTHONHASHSEED": seed,
                "JAX_PLATFORMS": "cpu",
            }
            proc = subprocess.run(
                [sys.executable, "-c", script, str(out)],
                capture_output=True,
                text=True,
                timeout=110,
                env=env,
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            outs.append(proc.stdout.strip().splitlines()[-1])
        assert outs[0] == outs[1]


class TestHostileInput:
    """Every integrity gate refuses, loudly, with SnapshotError."""

    def test_chunk_digest_mismatch(self):
        chain = _mk_chain()
        _h, _root, (manifest_payload, chunks) = _records(chain)
        manifest = snapmod.parse_manifest(manifest_payload)
        bad = [chunks[0][:-1] + bytes([chunks[0][-1] ^ 1])]
        with pytest.raises(SnapshotError, match="digest"):
            snapmod.assemble(manifest, bad)

    def test_wrong_chunk_count(self):
        chain = _mk_chain()
        _h, _root, (manifest_payload, chunks) = _records(chain)
        manifest = snapmod.parse_manifest(manifest_payload)
        with pytest.raises(SnapshotError, match="chunks"):
            snapmod.assemble(manifest, [])

    def test_out_of_order_entries_rejected(self):
        chunks = snapmod.encode_chunks({"b": 1, "a": 2}, {})
        rows = snapmod.parse_chunk(chunks[0])
        assert [r[0] for r in rows] == ["a", "b"]  # canonical order
        # Hand-build a reversed chunk: parse accepts the layout, but
        # assemble's order gate must refuse it.
        import struct

        def entry(acct, bal, nonce):
            raw = acct.encode()
            return bytes([len(raw)]) + raw + struct.pack(">QQ", bal, nonce)

        evil = struct.pack(">I", 2) + entry("b", 1, 0) + entry("a", 2, 0)
        manifest = snapmod.Manifest(
            height=1,
            block_hash=make_blocks(1, DIFF)[1].block_hash(),
            state_root=snapmod.state_root({"b": 1, "a": 2}, {}),
            accounts=2,
            chunk_digests=(snapmod.chunk_digest(evil),),
            block=make_blocks(1, DIFF)[1],
        )
        with pytest.raises(SnapshotError, match="order"):
            snapmod.assemble(manifest, [evil])

    def test_root_mismatch_rejected(self):
        chain = _mk_chain()
        _h, _root, (manifest_payload, chunks) = _records(chain)
        manifest = snapmod.parse_manifest(manifest_payload)
        lied = snapmod.Manifest(
            manifest.height,
            manifest.block_hash,
            bytes(32),
            manifest.accounts,
            manifest.chunk_digests,
            manifest.block,
        )
        with pytest.raises(SnapshotError, match="root"):
            snapmod.assemble(lied, list(chunks))

    def test_manifest_anchor_hash_must_match(self):
        chain = _mk_chain()
        _h, _root, (manifest_payload, _chunks) = _records(chain)
        bad = bytearray(manifest_payload)
        bad[5] ^= 0x01  # a block-hash byte
        with pytest.raises(SnapshotError, match="anchor"):
            snapmod.parse_manifest(bytes(bad))

    def test_verify_file_verdicts(self, tmp_path):
        chain = _mk_chain()
        _h, _root, (manifest_payload, chunks) = _records(chain)
        path = tmp_path / "v.p1s"
        snapmod.write_snapshot(path, manifest_payload, chunks)
        assert snapmod.verify_file(path)["verdict"] == 0
        # Trailing garbage past a complete verified snapshot: verdict 1.
        with open(path, "ab") as fh:
            fh.write(b"rotten tail bytes")
        assert snapmod.verify_file(path)["verdict"] == 1
        # A flipped byte INSIDE a needed record: verdict 2 (the CRC
        # stops the scan before the chunk set completes).
        data = bytearray(path.read_bytes())
        data[len(snapmod.MAGIC) + 10] ^= 0x04
        path.write_bytes(bytes(data))
        assert snapmod.verify_file(path)["verdict"] == 2
        assert snapmod.verify_file(tmp_path / "missing.p1s")["verdict"] == 2


class TestChainCheckpoints:
    def test_roots_recorded_at_interval_heights(self):
        chain = _mk_chain(n=10, interval=4)
        assert sorted(chain.state_checkpoints) == [4, 8]

    def test_reorg_rerecords_checkpoint_roots(self):
        # Two branches diverging below a checkpoint height: the reorg
        # must replace the recorded root with the new branch's.
        base = make_blocks(3, DIFF, miner_id="base")
        a = make_blocks(5, DIFF, miner_id="side-a")  # independent chain
        chain = Chain(DIFF)
        chain.checkpoint_interval = 2
        for b in base[1:]:
            chain.add_block(b)
        root_before = chain.state_checkpoints[2]
        for b in a[1:]:
            chain.add_block(b)
        assert chain.tip_hash == a[-1].block_hash()  # reorged to longer
        assert chain.state_checkpoints[2] != root_before
        assert sorted(chain.state_checkpoints) == [2, 4]
        # The recorded root matches a from-scratch replay of the branch.
        fresh = Chain(DIFF)
        fresh.checkpoint_interval = 2
        for b in a[1:]:
            fresh.add_block(b)
        assert fresh.state_checkpoints == chain.state_checkpoints

    def test_snapshot_state_rollback_matches_incremental_root(self):
        chain = _mk_chain(n=11, interval=4)
        h, _block, balances, nonces, root = chain.snapshot_state()
        assert h == 8
        assert root == chain.state_checkpoints[8]
        assert snapmod.state_root(balances, nonces) == root
        # The live tip ledger is untouched by the materialization.
        assert chain.balance("m1") == 11 * BLOCK_REWARD

    def test_too_short_chain_serves_no_snapshot(self):
        chain = _mk_chain(n=3, interval=4)
        assert chain.snapshot_state() is None


class TestAssumedChain:
    def test_from_snapshot_serves_and_extends_identically(self):
        blocks = make_blocks(10, DIFF, miner_id="m1")
        full = Chain(DIFF)
        full.checkpoint_interval = 4
        for b in blocks[1:]:
            full.add_block(b)
        _h, _root, (manifest_payload, chunks) = _records(full)
        snap = snapmod.assemble(
            snapmod.parse_manifest(manifest_payload), list(chunks)
        )
        assumed = Chain.from_snapshot(DIFF, snap)
        assert assumed.assumed and assumed.base_height == 8
        assert assumed.balance("m1") == 8 * BLOCK_REWARD
        assert assumed.main_hash_at(8) == blocks[8].block_hash()
        assert assumed.main_hash_at(3) is None  # below the base: not held
        # Extends with the real next blocks, byte-for-byte agreeing
        # with the fully-validated chain.
        for b in blocks[9:]:
            res = assumed.add_block(b)
            assert res.status.name == "ACCEPTED", res.reason
        assert assumed.tip_hash == full.tip_hash
        assert assumed.balance("m1") == full.balance("m1")
        assert assumed.nonce("m1") == full.nonce("m1")
        # Serving surfaces: locator, blocks_after, proofs, fee stats.
        assert assumed.locator()[0] == assumed.tip_hash
        served = assumed.blocks_after([blocks[8].block_hash()])
        assert [b.block_hash() for b in served] == [
            b.block_hash() for b in blocks[9:]
        ]
        tip_tx = blocks[-1].txs[0]
        assert assumed.tx_proof(tip_tx.txid()) is not None
        assumed.fee_stats()  # anchors at the base, never walks below it

    def test_history_below_base_parks_as_orphans(self):
        blocks = make_blocks(10, DIFF, miner_id="m1")
        full = Chain(DIFF)
        full.checkpoint_interval = 4
        for b in blocks[1:]:
            full.add_block(b)
        _h, _root, (manifest_payload, chunks) = _records(full)
        snap = snapmod.assemble(
            snapmod.parse_manifest(manifest_payload), list(chunks)
        )
        assumed = Chain.from_snapshot(DIFF, snap)
        res = assumed.add_block(blocks[3])
        assert res.status.name == "ORPHAN"
        assert assumed.tip_hash == blocks[8].block_hash()  # unmoved


@pytest.mark.sim
class TestNodePlane:
    """End-to-end over the deterministic simulator: full nodes, real
    protocol, virtual time."""

    def test_honest_boot_assumed_serves_then_flips(self):
        net = SimNet(seed=11, difficulty=DIFF)

        async def main():
            a = await net.add_node(snapshot_interval=4)
            b = await net.add_node(
                peers=[net.host_name(0)], snapshot_interval=4
            )
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            for _ in range(10):
                await net.mine_on(a, spacing_s=0.5)
            assert await net.run_until(
                lambda: b.chain.height == 10, 30, wall_limit_s=30
            )
            j = await net.add_node(
                peers=[net.host_name(0)],
                snapshot_sync=True,
                snapshot_interval=4,
            )
            assert await net.run_until(
                lambda: j.validation_state == "assumed", 30, wall_limit_s=30
            )
            # Serving IMMEDIATELY from the assumed state: balances,
            # headers, proofs — before any history was replayed.
            assert j.chain.base_height == 8
            assert j.chain.balance(a.miner_id) > 0
            assert j.chain.header_of(j.chain.tip_hash) is not None
            assert (
                j.chain.tx_proof(j.chain.tip.txs[0].txid()) is not None
            )
            assert j.status()["snapshot"]["state"] == "assumed"
            assert j.status()["overload"]["mining_paused"] is True
            assert await net.run_until(
                lambda: j.validation_state == "validated"
                and j.metrics.snapshot_flips == 1,
                120,
                wall_limit_s=60,
            ), j.status()["snapshot"]
            assert j.chain.tip_hash == a.chain.tip_hash
            assert j.chain.base_height == 0  # full history now
            assert net.ledger_conserved()
            # Still follows gossip after the flip.
            await net.mine_on(a, spacing_s=0.5)
            assert await net.run_until(
                lambda: j.chain.height == 11, 30, wall_limit_s=30
            )
            await net.stop_all()

        net.run(main())

    def test_lying_snapshot_quarantined_demoted_falls_back(self):
        """THE acceptance case: one wrong balance, internally consistent
        (the root commits to the lie) — adopted, served, then CAUGHT by
        background revalidation; the node quarantines the snapshot,
        demotes the serving peer, falls back to genesis IBD, and
        converges to the honest tip."""
        net = SimNet(seed=12, difficulty=DIFF)

        async def main():
            a = await net.add_node(snapshot_interval=4)
            b = await net.add_node(
                peers=[net.host_name(0)], snapshot_interval=4
            )
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            for _ in range(3):
                await net.mine_on(a, spacing_s=0.5)
            assert await net.run_until(
                lambda: b.chain.height == 3, 30, wall_limit_s=30
            )
            liar_host = "66.6.0.1"
            liar = HostilePeer(
                make_blocks(12, DIFF, miner_id="liar"),
                plan=FaultPlan(snapshot_lie="balance"),
                transport=net.net.host(liar_host),
                host=liar_host,
                rng=random.Random(99),
            )
            await liar.start()
            j = await net.add_node(
                name="10.0.0.9",
                peers=[f"{liar_host}:{liar.port}", net.host_name(0)],
                snapshot_sync=True,
                snapshot_interval=4,
            )
            assert await net.run_until(
                lambda: j.validation_state == "assumed", 60, wall_limit_s=60
            ), j.status()["snapshot"]
            # The lie is being served (that is what ASSUMED risks)...
            assert j.chain.balance("liar") == 12 * BLOCK_REWARD + 1000
            # ...until the replayed history contradicts the root.
            assert await net.run_until(
                lambda: j.metrics.snapshot_divergences == 1
                and j.validation_state == "validated",
                240,
                wall_limit_s=120,
            ), j.status()["snapshot"]
            assert j.metrics.snapshot_flips == 0
            # Quarantined + serving peer demoted + violation scored.
            assert any(
                p.sync_demerits > 0
                for p in j._peers.values()
                if p.host == liar_host
            )
            assert liar_host in j._violations
            # Honest mesh out-mines the liar's fork; the fallen-back
            # node converges to the honest tip through ordinary IBD.
            for _ in range(12):
                await net.mine_on(a, spacing_s=0.5)
            assert await net.run_until(
                lambda: j.chain.tip_hash == a.chain.tip_hash,
                240,
                wall_limit_s=120,
            )
            assert net.ledger_conserved()
            await liar.stop()
            await net.stop_all()

        net.run(main())

    def test_root_lie_refused_before_adoption(self):
        """A corrupted state root is caught at assembly — the node never
        enters ASSUMED, scores the forger, and falls over to the honest
        peer."""
        net = SimNet(seed=13, difficulty=DIFF)

        async def main():
            a = await net.add_node(snapshot_interval=4)
            assert await net.run_until(
                lambda: True, 1, wall_limit_s=30
            )
            for _ in range(6):
                await net.mine_on(a, spacing_s=0.5)
            liar_host = "66.6.0.2"
            liar = HostilePeer(
                make_blocks(12, DIFF, miner_id="liar"),
                plan=FaultPlan(snapshot_lie="root"),
                transport=net.net.host(liar_host),
                host=liar_host,
                rng=random.Random(98),
            )
            await liar.start()
            j = await net.add_node(
                name="10.0.0.9",
                peers=[f"{liar_host}:{liar.port}", net.host_name(0)],
                snapshot_sync=True,
                snapshot_interval=4,
            )
            assert await net.run_until(
                lambda: j.validation_state == "validated"
                and j.chain.height >= 6
                and j.chain.base_height == 0
                or j.validation_state == "assumed",
                120,
                wall_limit_s=60,
            )
            # Never adopted the forged snapshot; the forger was scored.
            assert j.metrics.snapshot_divergences == 0
            assert liar_host in j._violations
            # It may have assumed the HONEST peer's snapshot instead —
            # either way it must end fully validated on the honest tip.
            assert await net.run_until(
                lambda: j.validation_state == "validated"
                and j.chain.tip_hash == a.chain.tip_hash,
                240,
                wall_limit_s=120,
            ), j.status()["snapshot"]
            await liar.stop()
            await net.stop_all()

        net.run(main())

    def test_truncated_transfer_fails_over_to_honest_peer(self):
        """A server that stalls mid-transfer (crash/truncation profile)
        costs one supervised deadline, then the fetch fails over."""
        net = SimNet(seed=14, difficulty=DIFF)

        async def main():
            a = await net.add_node(snapshot_interval=4)
            for _ in range(8):
                await net.mine_on(a, spacing_s=0.5)
            liar_host = "66.6.0.3"
            liar = HostilePeer(
                make_blocks(12, DIFF, miner_id="liar"),
                plan=FaultPlan(snapshot_chunks=0),  # manifest, no chunks
                transport=net.net.host(liar_host),
                host=liar_host,
                rng=random.Random(97),
            )
            await liar.start()
            j = await net.add_node(
                name="10.0.0.9",
                peers=[f"{liar_host}:{liar.port}", net.host_name(0)],
                snapshot_sync=True,
                snapshot_interval=4,
            )
            assert await net.run_until(
                lambda: j.validation_state == "validated"
                and j.chain.tip_hash == a.chain.tip_hash
                and j.chain.base_height == 0,
                240,
                wall_limit_s=120,
            ), j.status()["snapshot"]
            assert j.metrics.snapshot_stalls >= 1
            await liar.stop()
            await net.stop_all()

        net.run(main())

    def test_crash_during_revalidation_resumes_assumed(self, tmp_path):
        """Crash mid-ASSUMED: the sidecar + store resume the assumed
        chain through the NORMAL boot path, the background revalidation
        restarts from genesis, and the flip still lands."""
        net = SimNet(seed=15, difficulty=DIFF, store_dir=tmp_path)

        async def main():
            a = await net.add_node(snapshot_interval=4)
            b = await net.add_node(
                peers=[net.host_name(0)], snapshot_interval=4
            )
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            for _ in range(10):
                await net.mine_on(a, spacing_s=0.5)
            assert await net.run_until(
                lambda: b.chain.height == 10, 30, wall_limit_s=30
            )
            jhost = "10.0.0.9"
            j = await net.add_node(
                name=jhost,
                peers=[net.host_name(0)],
                snapshot_sync=True,
                snapshot_interval=4,
            )
            assert await net.run_until(
                lambda: j.validation_state == "assumed", 60, wall_limit_s=60
            )
            snap_sidecar = tmp_path / f"{jhost}.dat.snapshot"
            assert snap_sidecar.exists()
            await net.crash_node(jhost)
            await net.mine_on(a, spacing_s=0.5)
            j2 = await net.recover_node(jhost)
            # Resumed ASSUMED from the sidecar, at (at least) the base.
            assert j2.validation_state == "assumed"
            assert j2.chain.base_height == 8
            assert j2.chain.balance(a.miner_id) > 0
            assert await net.run_until(
                lambda: j2.validation_state == "validated"
                and j2.metrics.snapshot_flips == 1,
                240,
                wall_limit_s=120,
            ), j2.status()["snapshot"]
            assert not snap_sidecar.exists()  # flip retired the sidecar
            assert await net.run_until(
                lambda: j2.chain.tip_hash == a.chain.tip_hash,
                60,
                wall_limit_s=60,
            )
            assert net.ledger_conserved()
            await net.stop_all()

        net.run(main())

    def test_crash_during_download_restarts_clean(self, tmp_path):
        """Crash while the snapshot download is in flight: nothing was
        adopted, nothing persisted — the reboot is an ordinary fresh
        boot that simply snapshots again."""
        net = SimNet(seed=16, difficulty=DIFF, store_dir=tmp_path)

        async def main():
            a = await net.add_node(snapshot_interval=4)
            for _ in range(10):
                await net.mine_on(a, spacing_s=0.5)
            jhost = "10.0.0.9"
            j = await net.add_node(
                name=jhost,
                peers=[net.host_name(0)],
                snapshot_sync=True,
                snapshot_interval=4,
            )
            # Crash at the first possible instant: mid-handshake or
            # mid-download, before any verdict.
            await net.crash_node(jhost)
            assert jhost not in net.nodes
            j2 = await net.recover_node(jhost)
            assert await net.run_until(
                lambda: j2.validation_state == "validated"
                and j2.chain.tip_hash == a.chain.tip_hash
                and j2.chain.base_height == 0,
                240,
                wall_limit_s=120,
            ), j2.status()["snapshot"]
            assert net.ledger_conserved()
            await net.stop_all()

        net.run(main())

    def test_snapshot_join_scenario_honest_and_lying(self):
        """The corpus entry (`p1 sim snapshot-join`) holds in both
        modes at a small, tier-1-priced scale."""
        from p1_tpu.node.scenarios import run_scenario

        r = run_scenario(
            "snapshot-join", seed=0, difficulty=DIFF, nodes=6
        )
        assert r["ok"], r
        assert r["flips"] == 1 and r["samples_contradicted"] == 0
        r = run_scenario(
            "snapshot-join",
            seed=1,
            difficulty=DIFF,
            nodes=6,
            chain_blocks=4,
            lie="balance",
        )
        assert r["ok"], r
        assert r["divergences"] >= 1
