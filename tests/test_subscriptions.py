"""The wallet push plane (round 21): commitment-chained filters,
watch subscriptions, graceful degradation, and trustless failover.

Four property families anchor the tier:

- **commitment = pure function of block bytes**: the filter-header
  chain (``header[i] = H(filter_hash[i] || header[i-1])``, genesis
  anchored at zero) is derived identically by every honest holder of
  the same blocks, truncate-and-extends across reorgs, and stays
  honestly SHORT when a body is unavailable — never a guess.
- **push stream = the chain**: a SubscriptionManager delivers one
  event per connected height, gap-free, with exact txids when the body
  is at hand; slow consumers walk the coalesce → drop-to-cursor →
  disconnect ladder and a drained dropper gets ONE gap notice naming
  exactly the replay window.
- **resume = replay**: a cursor the server can prove against its
  committed chain replays the missed window before live events take
  over; a cursor it cannot prove is refused by disconnect (the
  failover signal), never guessed around.
- **lying replica = demoted replica**: a replica serving a
  self-consistent forged filter stream (the missed-confirmation
  attack) is caught by cross-check + hash-pinned adjudication,
  demoted, and the watch fails over with ZERO missed confirmations —
  the stream stays gap-free across the liar.
"""

import asyncio
import hashlib
import random

import pytest

from test_node import DIFF, fund, run, wait_until
from test_queryplane import _config, build_chain
from txutil import account, key_for

from p1_tpu.chain import save_chain
from p1_tpu.chain import filters as fmod
from p1_tpu.chain.filters import (
    GENESIS_FILTER_HEADER,
    FilterHeaderChain,
    filter_hash,
    next_filter_header,
)
from p1_tpu.core.tx import Transaction
from p1_tpu.node import Node, protocol
from p1_tpu.node.client import (
    CommitmentViolation,
    filter_scan,
    get_filter_headers,
    send_tx,
    watch,
)
from p1_tpu.node.protocol import GapEvent, MsgType
from p1_tpu.node.queryplane import serve_replica
from p1_tpu.node.subscriptions import (
    ChainSubSource,
    SubscriptionManager,
    block_items_index,
)


# -- fixtures -------------------------------------------------------------


def _fake_heights(n: int, seed: int = 0):
    """n synthetic heights: deterministic block hashes and VALID filter
    encodings (the commitment chain hashes filter bytes, it never
    decodes them — but the manager does, so stay well-formed)."""
    hashes = [
        hashlib.sha256(b"blk-%d-%d" % (seed, h)).digest() for h in range(n)
    ]
    filters = [
        fmod.encode_filter(hashes[h], {b"item-%d" % h}) for h in range(n)
    ]
    return hashes, filters


def _expected_chain(filters):
    out, prev = [], GENESIS_FILTER_HEADER
    for f in filters:
        prev = next_filter_header(filter_hash(f), prev)
        out.append(prev)
    return out


class _TipSource:
    """A ChainSubSource over a prebuilt chain with a MOVABLE tip, so a
    test connects one block at a time; ``forge`` overlays forged
    (filter, fheader, index) triples per height — the lying-server
    stand-in for manager-level tests."""

    def __init__(self, chain, tip: int = 0):
        self._chain = chain
        self.tip = tip
        self.forge: dict[int, tuple] = {}

    @property
    def tip_height(self) -> int:
        return self.tip

    def hash_at(self, height):
        if not 0 <= height <= self.tip:
            return None
        return self._chain.main_hash_at(height)

    def raw_header_at(self, height):
        bhash = self.hash_at(height)
        return None if bhash is None else self._chain.header_of(bhash).serialize()

    def filter_at(self, height):
        if height in self.forge:
            return self.forge[height][0]
        bhash = self.hash_at(height)
        return None if bhash is None else self._chain.block_filter(bhash)

    def fheader_at(self, height):
        if height in self.forge:
            return self.forge[height][1]
        if height > self.tip:
            return None
        return self._chain.filter_headers.header_at(height)

    def block_items_at(self, height):
        if height in self.forge:
            return self.forge[height][2]
        bhash = self.hash_at(height)
        return None if bhash is None else block_items_index(self._chain.get(bhash))


class _Sink:
    """One subscriber's transport stand-in: captures frames, reports a
    settable buffer depth, remembers close()."""

    def __init__(self):
        self.frames: list[bytes] = []
        self.buf = 0
        self.closed = False
        self.fail = False

    async def send(self, payload: bytes) -> None:
        if self.fail:
            raise ConnectionResetError("sink gone")
        self.frames.append(payload)

    def buffer_size(self) -> int:
        return self.buf

    def close(self) -> None:
        self.closed = True

    def events(self):
        out = []
        for fr in self.frames:
            mtype, body = protocol.decode(fr)
            assert mtype is MsgType.EVENT
            out.append(body)
        return out


def _mgr(source, **kw):
    kw.setdefault("coalesce_bytes", 100)
    kw.setdefault("drop_bytes", 1_000)
    kw.setdefault("hard_bytes", 10_000)
    return SubscriptionManager(source, **kw)


async def _sub(mgr, sink, key, items, cursor=None) -> bool:
    return await mgr.subscribe(
        key, items, cursor,
        send=sink.send, buffer_size=sink.buffer_size, close=sink.close,
    )


# -- the commitment chain -------------------------------------------------


class TestFilterHeaderChain:
    def test_genesis_anchor_and_linkage(self):
        hashes, filters = _fake_heights(6)
        fhc = FilterHeaderChain()
        changed = fhc.sync(5, hashes.__getitem__, filters.__getitem__)
        assert changed == list(range(6))
        assert fhc.tip_height == 5
        assert fhc.header_at(-1) == GENESIS_FILTER_HEADER
        want = _expected_chain(filters)
        for h in range(6):
            assert fhc.header_at(h) == want[h]
            assert fhc.hash_at(h) == hashes[h]
        # Resync with nothing new is a no-op (the O(1) common case).
        assert fhc.sync(5, hashes.__getitem__, filters.__getitem__) == []
        assert fhc.rebuilds == 0

    def test_two_sources_same_blocks_identical_chains(self):
        """The trust property, literally: the chain is a pure function
        of the block bytes — two independent syncs agree everywhere."""
        hashes, filters = _fake_heights(8)
        a, b = FilterHeaderChain(), FilterHeaderChain()
        a.sync(7, hashes.__getitem__, filters.__getitem__)
        # b syncs incrementally in three visits; same result.
        for tip in (2, 5, 7):
            b.sync(tip, hashes.__getitem__, filters.__getitem__)
        assert [a.header_at(h) for h in range(8)] == [
            b.header_at(h) for h in range(8)
        ]

    def test_reorg_truncates_and_reextends(self):
        hashes, filters = _fake_heights(8)
        fhc = FilterHeaderChain()
        fhc.sync(7, hashes.__getitem__, filters.__getitem__)
        before = [fhc.header_at(h) for h in range(8)]
        # Fork from height 5: new hashes AND new filters above.
        fork_h, fork_f = _fake_heights(8, seed=1)
        hashes[5:], filters[5:] = fork_h[5:], fork_f[5:]
        changed = fhc.sync(7, hashes.__getitem__, filters.__getitem__)
        assert changed == [5, 6, 7]
        assert fhc.rebuilds == 1
        after = [fhc.header_at(h) for h in range(8)]
        assert after[:5] == before[:5]
        assert after[5:] == _expected_chain(filters)[5:]
        assert all(a != b for a, b in zip(after[5:], before[5:]))

    def test_range_is_all_or_nothing(self):
        hashes, filters = _fake_heights(5)
        fhc = FilterHeaderChain()
        fhc.sync(4, hashes.__getitem__, filters.__getitem__)
        assert len(fhc.range(0, 5)) == 5
        assert len(fhc.range(2, 3)) == 3
        # Any uncommitted part of the span: refusal, not a partial lie.
        assert fhc.range(0, 6) == []
        assert fhc.range(3, 3) == []
        assert fhc.range(-1, 2) == []
        assert fhc.range(2, 0) == []

    def test_unavailable_filter_stays_honestly_short(self):
        hashes, filters = _fake_heights(6)

        def gappy(h):
            return None if h == 3 else filters[h]

        fhc = FilterHeaderChain()
        changed = fhc.sync(5, hashes.__getitem__, gappy)
        assert changed == [0, 1, 2]
        assert fhc.tip_height == 2
        assert fhc.header_at(3) is None
        # The body shows up (backfill/unspill): extension resumes and
        # lands on the same chain a never-gapped sync produces.
        fhc.sync(5, hashes.__getitem__, filters.__getitem__)
        assert [fhc.header_at(h) for h in range(6)] == _expected_chain(filters)


class TestSharedDecodeEquivalence:
    def test_matches_values_equals_matches_any(self):
        """The 100k-subs fast path (decode once, probe per subscriber)
        answers exactly like the reference matcher, present and absent
        items alike, across real randomized blocks."""
        rng = random.Random(7)
        chain = build_chain(6, difficulty=1, rng=rng)
        for h in range(0, chain.height + 1):
            bhash = chain.main_hash_at(h)
            fbytes = chain.block_filter(bhash)
            values = fmod.decode_value_set(fbytes)
            count = fmod.filter_count(fbytes)
            block = chain.get(bhash)
            present = list(fmod.filter_items(block))
            absent = [b"absent-%d-%d" % (h, i) for i in range(20)]
            for it in present + absent:
                assert fmod.matches_values(
                    values, count, bhash, [it]
                ) == fmod.matches_any(fbytes, bhash, [it]), (h, it)
            probe = [rng.choice(present), b"cold"] if present else [b"cold"]
            assert fmod.matches_values(
                values, count, bhash, probe
            ) == fmod.matches_any(fbytes, bhash, probe)


# -- the manager: stream shape and the degradation ladder -----------------


class TestSubscriptionManager:
    def _chain(self, n=6):
        return build_chain(n, difficulty=1, rng=random.Random(3))

    def test_push_stream_is_gap_free_and_committed(self):
        chain = self._chain()
        src = _TipSource(chain)
        mgr = _mgr(src)
        bob = account("bob").encode()
        sink = _Sink()

        async def scenario():
            assert await _sub(mgr, sink, 1, [bob])
            for tip in range(1, chain.height + 1):
                src.tip = tip
                await mgr.notify()

        run(scenario())
        evs = sink.events()
        assert [e.height for e in evs] == list(range(1, chain.height + 1))
        prev = src.fheader_at(0)
        for e in evs:
            bhash = chain.main_hash_at(e.height)
            fh = next_filter_header(filter_hash(e.filter), prev)
            assert e.filter_header == fh  # the server's own commitment
            prev = fh
            truth = block_items_index(chain.get(bhash)).get(bob, ())
            assert e.matched == bool(truth)
            assert tuple(e.txids) == tuple(dict.fromkeys(truth))
        assert mgr.events_pushed == len(evs)
        # Redundant notify with a still tip is a no-op.
        run(mgr.notify())
        assert len(sink.frames) == len(evs)

    def test_coalesce_skips_plain_but_delivers_matches(self):
        chain = self._chain()
        src = _TipSource(chain)
        mgr = _mgr(src)
        bob = account("bob").encode()
        hot, cold = _Sink(), _Sink()
        hot.buf = cold.buf = 100  # >= coalesce, < drop

        async def scenario():
            assert await _sub(mgr, hot, 1, [bob])
            assert await _sub(mgr, cold, 2, [b"nobody-ever-pays-this"])
            for tip in range(1, chain.height + 1):
                src.tip = tip
                await mgr.notify()

        run(scenario())
        touched = {
            h
            for h in range(1, chain.height + 1)
            if block_items_index(
                chain.get(chain.main_hash_at(h))
            ).get(bob)
        }
        assert touched  # the fixture pays bob somewhere
        # Matches cross the coalesce bar; plain headers do not.
        assert {e.height for e in hot.events()} == touched
        assert all(e.matched for e in hot.events())
        assert cold.frames == []  # every cold event coalesced away
        skipped = (chain.height - len(touched)) + chain.height
        assert mgr.events_coalesced == skipped
        assert mgr.gap_events == 0  # a coalesce hole is not a gap

    def test_drop_to_cursor_emits_one_gap_naming_the_window(self):
        chain = self._chain()
        src = _TipSource(chain)
        mgr = _mgr(src)
        sink = _Sink()

        async def scenario():
            assert await _sub(mgr, sink, 1, [account("bob").encode()])
            src.tip = 1
            await mgr.notify()
            sink.buf = 1_000  # over the drop threshold: stall
            for tip in (2, 3, 4):
                src.tip = tip
                await mgr.notify()
            assert mgr.events_dropped == 3
            sink.buf = 0  # drained
            src.tip = 5
            await mgr.notify()

        run(scenario())
        evs = sink.events()
        assert evs[0].height == 1
        gap = evs[1]
        assert isinstance(gap, GapEvent)
        assert (gap.start, gap.end) == (2, 4)  # exactly the missed window
        assert evs[2].height == 5
        assert mgr.gap_events == 1

    def test_hard_cap_disconnects_the_squatter(self):
        chain = self._chain(3)
        src = _TipSource(chain)
        mgr = _mgr(src)
        sink = _Sink()
        sink.buf = 10_000

        async def scenario():
            assert await _sub(mgr, sink, 1, [b"x"])
            src.tip = 1
            await mgr.notify()

        run(scenario())
        assert sink.closed
        assert len(mgr) == 0
        assert mgr.disconnects_hard == 1
        assert sink.frames == []

    def test_send_error_disconnects(self):
        chain = self._chain(3)
        src = _TipSource(chain)
        mgr = _mgr(src)
        sink = _Sink()
        sink.fail = True

        async def scenario():
            assert await _sub(mgr, sink, 1, [b"x"])
            src.tip = 1
            await mgr.notify()

        run(scenario())
        assert sink.closed
        assert len(mgr) == 0
        assert mgr.disconnects_error == 1

    def test_cursor_replay_is_gap_free_then_live_takes_over(self):
        chain = self._chain()
        src = _TipSource(chain)
        mgr = _mgr(src)
        keep = _Sink()

        async def scenario():
            # One resident keeps the manager's cursor advancing.
            assert await _sub(mgr, keep, 1, [b"resident"])
            for tip in range(1, 5):
                src.tip = tip
                await mgr.notify()
            late = _Sink()
            cursor = (2, src.fheader_at(2))
            assert await _sub(mgr, late, 2, [b"late"], cursor)
            assert [e.height for e in late.events()] == [3, 4]  # replayed
            assert mgr.replayed == 2
            src.tip = 5
            await mgr.notify()
            assert [e.height for e in late.events()] == [3, 4, 5]

        run(scenario())

    def test_unprovable_cursor_is_refused(self):
        chain = self._chain()
        src = _TipSource(chain, tip=4)
        mgr = _mgr(src)
        sink = _Sink()

        async def scenario():
            ok = await _sub(mgr, sink, 1, [b"x"], (2, b"\x55" * 32))
            assert not ok
            beyond = await _sub(mgr, sink, 2, [b"x"], (99, b"\x55" * 32))
            assert not beyond

        run(scenario())
        assert mgr.cursor_rejects == 2
        assert len(mgr) == 0

    def test_reorged_height_is_repushed(self):
        chain = self._chain()
        src = _TipSource(chain)
        mgr = _mgr(src)
        sink = _Sink()
        k = 4

        async def scenario():
            assert await _sub(mgr, sink, 1, [b"x"])
            for tip in range(1, k + 1):
                src.tip = tip
                await mgr.notify()
            # A competing branch replaces height k (forge overlays a
            # new hash by changing the filter/fheader the source
            # serves; hash_at must change too for walk-back to see it).
            alt_hash = hashlib.sha256(b"fork").digest()
            alt_filter = fmod.encode_filter(alt_hash, {b"forked"})
            alt_fh = next_filter_header(
                filter_hash(alt_filter), src.fheader_at(k - 1)
            )
            real_hash_at = src.hash_at
            real_raw = src.raw_header_at(k)
            src.hash_at = lambda h: alt_hash if h == k else real_hash_at(h)
            real_raw_at = src.raw_header_at
            src.raw_header_at = (
                lambda h: real_raw if h == k else real_raw_at(h)
            )
            src.forge[k] = (alt_filter, alt_fh, {})
            await mgr.notify()

        run(scenario())
        heights = [e.height for e in sink.events()]
        assert heights == [1, 2, 3, 4, 4]  # k re-pushed after the reorg
        last = sink.events()[-1]
        assert last.filter_header != sink.events()[-2].filter_header

    def test_empty_room_fast_forwards_no_replay_storm(self):
        chain = self._chain()
        src = _TipSource(chain, tip=chain.height)
        mgr = _mgr(src)
        sink = _Sink()

        async def scenario():
            await mgr.notify()  # nobody listening: cursor keeps up
            assert await _sub(mgr, sink, 1, [b"x"])
            await mgr.notify()

        run(scenario())
        assert sink.frames == []  # history was never promised


# -- end to end: node and replica push, the lying replica -----------------


class TestWatchEndToEnd:
    def test_node_push_submit_confirm_watch(self):
        """The SLO row's shape: a watch session on a mining node sees
        every block, gap-free and verified, and the submitted payment
        arrives as a matched event with its exact txid."""

        async def scenario():
            node = Node(_config())
            await node.start()
            gen = None
            try:
                await fund(node, "alice", blocks=2)
                gen = watch(
                    "127.0.0.1", node.port, ["push-rcpt"], DIFF,
                    max_session_failures=3,
                )
                agen = gen.__aiter__()
                node.miner_id = account("alice")
                node.start_mining()
                # First event proves the session is subscribed BEFORE
                # the payment exists — no mine-before-subscribe race.
                first = await asyncio.wait_for(agen.__anext__(), 30)
                tx = Transaction.transfer(
                    key_for("alice"), "push-rcpt", 1, 1, 0,
                    chain=node.chain.genesis.block_hash(),
                )
                await node.submit_tx(tx)
                heights = [first["height"]]
                matched = None
                while matched is None:
                    ev = await asyncio.wait_for(agen.__anext__(), 30)
                    heights.append(ev["height"])
                    if ev["matched"]:
                        matched = ev
                await node.stop_mining()
                assert heights == list(
                    range(heights[0], heights[0] + len(heights))
                )
                assert tx.txid() in matched["txids"]
                assert matched["peer"] == ("127.0.0.1", node.port)
                # The pushed commitment is the node's own chain.
                assert (
                    node.chain.filter_headers.header_at(matched["height"])
                    == matched["filter_header"]
                )
            finally:
                if gen is not None:
                    await gen.aclose()
                await node.stop()

        run(scenario())

    def test_replica_push_with_cursor_resume(self, tmp_path):
        """Watch a replica from a verified past cursor: the committed
        window replays first (gap-free), then refresh-driven live
        events continue the same stream as the node keeps mining."""
        store = str(tmp_path / "chain.dat")

        async def scenario():
            node = Node(_config(store_path=store))
            await node.start()
            srv, gen = None, None
            try:
                await fund(node, "alice", blocks=4)
                srv = await serve_replica(store, DIFF, refresh_interval_s=0.05)
                assert await wait_until(
                    lambda: srv.view.filter_headers.tip_height
                    >= node.chain.height
                )
                cursor_h = 2
                (fh,) = await get_filter_headers(
                    "127.0.0.1", srv.port, cursor_h, 1, DIFF
                )
                gen = watch(
                    "127.0.0.1", srv.port, [account("alice").encode()],
                    DIFF, cursor=(cursor_h, fh), max_session_failures=5,
                )
                agen = gen.__aiter__()
                heights = []
                for _ in range(node.chain.height - cursor_h):
                    ev = await asyncio.wait_for(agen.__anext__(), 30)
                    heights.append(ev["height"])
                    assert ev["matched"]  # every block pays alice
                assert heights == list(range(cursor_h + 1, node.chain.height + 1))
                # Live tail: mine more, the refresh loop pushes it.
                await fund(node, "alice", blocks=1)
                ev = await asyncio.wait_for(agen.__anext__(), 30)
                assert ev["height"] == heights[-1] + 1
            finally:
                if gen is not None:
                    await gen.aclose()
                if srv is not None:
                    await srv.stop()
                await node.stop()

        run(scenario())

    def _forge_replica(self, srv, from_height: int) -> None:
        """Turn a replica into a self-consistent liar: from
        ``from_height`` up, serve filters that omit every real item
        (the missed-confirmation attack) and recompute the commitment
        chain over the forged filter hashes, so linkage verifies and
        only comparison with an honest holder can catch it."""
        view = srv.view
        entries = view.filter_headers._entries
        forged: dict[int, bytes] = {}
        prev = entries[from_height - 1][1]
        for h in range(from_height, len(entries)):
            bhash = entries[h][0]
            fake = fmod.encode_filter(bhash, {b"watch-elsewhere"})
            forged[h] = fake
            prev = next_filter_header(filter_hash(fake), prev)
            entries[h] = (bhash, prev)
        real_filter_at = view.filter_at
        view.filter_at = (
            lambda h: forged[h] if h in forged else real_filter_at(h)
        )
        real_items_at = view.block_items_at
        view.block_items_at = (
            lambda h: {} if h in forged else real_items_at(h)
        )

    def test_lying_replica_demoted_failover_zero_missed(self, tmp_path):
        """The acceptance scenario, literally: one of two replicas
        forges its filter stream from height k to hide a payment.  A
        watch anchored at an honest past cursor rides the liar while it
        tells the truth, catches the forgery at k via cross-check plus
        hash-pinned adjudication (CommitmentViolation → demote), fails
        over to the honest replica, and the yielded stream is STILL
        gap-free with the hidden payment delivered — zero missed
        confirmations across the liar."""
        store = str(tmp_path / "chain.dat")
        chain = build_chain(8, difficulty=1, rng=random.Random(11))

        def paid_heights(item):
            return {
                h
                for h in range(1, chain.height + 1)
                if block_items_index(
                    chain.get(chain.main_hash_at(h))
                ).get(item)
            }

        # Pick a watched account the fixture pays late enough that the
        # forgery window can hide a real payment (the chain's tx mix
        # varies with the hash seed; the property must not).
        bob, paid, k = None, None, 0
        for label in ("bob", "carol", "dave", "alice"):
            item = account(label).encode()
            got = paid_heights(item)
            if got and max(got) >= 3:
                bob, paid, k = item, got, max(got)
                break
        assert bob is not None
        save_chain(chain, store)

        async def scenario():
            liar = await serve_replica(store, 1, refresh_interval_s=0.1)
            honest = await serve_replica(store, 1, refresh_interval_s=0.1)
            gen = None
            try:
                self._forge_replica(liar, k)
                anchor_h = 1
                (fh,) = await get_filter_headers(
                    "127.0.0.1", honest.port, anchor_h, 1, 1
                )
                gen = watch(
                    "127.0.0.1", liar.port, [bob], 1,
                    cursor=(anchor_h, fh),
                    fallback_peers=[("127.0.0.1", honest.port)],
                    cross_check_every=1,
                    reconnect_delay_s=0.05,
                    max_session_failures=10,
                )
                events = []
                async for ev in gen:
                    events.append(ev)
                    if ev["height"] == chain.height:
                        break
                heights = [e["height"] for e in events]
                assert heights == list(range(anchor_h + 1, chain.height + 1))
                # Zero missed confirmations: every bob-paying height in
                # the window is a matched event, INCLUDING the forged
                # ones — they were served by the honest replica.
                got = {e["height"] for e in events if e["matched"]}
                assert got == {h for h in paid if h > anchor_h}
                by_height = {e["height"]: e for e in events}
                assert by_height[k]["peer"] == ("127.0.0.1", honest.port)
                assert any(
                    e["peer"] == ("127.0.0.1", liar.port)
                    for e in events
                    if e["height"] < k
                )
                # The verdict stuck server-side too: the liar pushed at
                # least one event, then lost the session for good.
                assert liar.subscriptions.snapshot()["live"] == 0
                # And every yielded commitment matches the true chain.
                for e in events:
                    assert (
                        chain.filter_headers.header_at(e["height"])
                        == e["filter_header"]
                    )
            finally:
                if gen is not None:
                    await gen.aclose()
                await liar.stop()
                await honest.stop()

        run(scenario())

    def test_lone_lying_replica_fails_the_watch_loudly(self, tmp_path):
        """No fallback to adjudicate against: a filter that breaks the
        H-link from the caller's verified cursor is still caught
        LOCALLY and the watch dies with CommitmentViolation, never
        yielding the forged event as verified."""
        store = str(tmp_path / "chain.dat")
        chain = build_chain(5, difficulty=1, rng=random.Random(2))
        save_chain(chain, store)

        async def scenario():
            srv = await serve_replica(store, 1, refresh_interval_s=0.1)
            gen = None
            try:
                # Forge the filters but NOT the commitment chain: the
                # served fheader no longer extends H(fhash || prev).
                view = srv.view
                real = view.filter_at
                view.filter_at = lambda h: (
                    fmod.encode_filter(b"\x99" * 32, {b"zzz"})
                    if h >= 3
                    else real(h)
                )
                (fh,) = await get_filter_headers(
                    "127.0.0.1", srv.port, 1, 1, 1
                )
                gen = watch(
                    "127.0.0.1", srv.port, [b"whatever"], 1,
                    cursor=(1, fh), max_session_failures=3,
                )
                heights = []
                with pytest.raises(CommitmentViolation):
                    async for ev in gen:
                        heights.append(ev["height"])
                assert heights == [2]  # verified up to the forgery only
            finally:
                if gen is not None:
                    await gen.aclose()
                await srv.stop()

        run(scenario())
