"""Consensus-level transaction validity: overspend rejection + ownership.

Round-4 VERDICT items 2+3: the chain must refuse blocks whose transfers
overdraw an account (contextual validation via the incremental tip ledger)
and must refuse spends that don't prove ownership (Ed25519, covered at the
block layer in test_chain.py and at the pool layer here).  The property
test extends TestForkChoiceProperty: random DAGs now carry random
transfers, some overdrawing, and fork choice must converge to the best
*valid* tip with a ledger that always matches a from-scratch replay.
"""

import dataclasses

import pytest

from txutil import account, key_for, stx

from p1_tpu.chain import AddStatus, Chain
from p1_tpu.chain.ledger import Ledger, LedgerError, balances
from p1_tpu.core import Block, BlockHeader, Transaction, make_genesis, merkle_root
from p1_tpu.core.tx import BLOCK_REWARD
from p1_tpu.hashx import get_backend
from p1_tpu.miner import Miner

DIFF = 8
_MINER = Miner(backend=get_backend("cpu"))


def _mine_child(parent: Block, txs=(), ts_offset: int = 1) -> Block:
    header = BlockHeader(
        version=1,
        prev_hash=parent.block_hash(),
        merkle_root=merkle_root([tx.txid() for tx in txs]),
        timestamp=parent.header.timestamp + ts_offset,
        difficulty=parent.header.difficulty,
        nonce=0,
    )
    sealed = _MINER.search_nonce(header)
    assert sealed is not None
    return Block(sealed, tuple(txs))


def _funded_chain(label: str = "alice"):
    """Genesis + one block crediting ``label``'s account with the subsidy."""
    genesis = make_genesis(DIFF)
    chain = Chain(DIFF, genesis=genesis)
    b1 = _mine_child(genesis, txs=(Transaction.coinbase(account(label), 1),))
    assert chain.add_block(b1).status is AddStatus.ACCEPTED
    return chain, b1


class TestLedgerUnit:
    def test_apply_undo_round_trip(self):
        genesis = make_genesis(DIFF)
        alice = account("alice")
        b1 = _mine_child(genesis, txs=(Transaction.coinbase(alice, 1),))
        b2 = _mine_child(
            b1,
            txs=(
                Transaction.coinbase("miner", 2),
                stx("alice", "bob", 20, 2, 0),
            ),
        )
        ledger = Ledger()
        for b in (genesis, b1, b2):
            ledger.apply_block(b)
        assert ledger.balance(alice) == 28
        assert ledger.balance("miner") == 52
        ledger.undo_block(b2)
        ledger.undo_block(b1)
        assert ledger.snapshot() == {}

    def test_apply_is_transactional(self):
        # A block whose SECOND transfer overdraws must leave no trace of
        # its first.
        genesis = make_genesis(DIFF)
        alice = account("alice")
        b1 = _mine_child(genesis, txs=(Transaction.coinbase(alice, 1),))
        bad = _mine_child(
            b1,
            txs=(
                stx("alice", "bob", 10, 0, 0),
                stx("alice", "bob", 1000, 0, 1),  # overdraws
            ),
        )
        ledger = Ledger()
        ledger.apply_block(b1)
        before = ledger.snapshot()
        with pytest.raises(LedgerError, match="overdraws"):
            ledger.apply_block(bad)
        assert ledger.snapshot() == before

    def test_intra_block_credit_is_spendable(self):
        # bob spends, IN THE SAME BLOCK, coins alice sent him two txs ago
        # (in-order application, documented in ledger.py).
        genesis = make_genesis(DIFF)
        alice, bob = account("alice"), account("bob")
        b1 = _mine_child(genesis, txs=(Transaction.coinbase(alice, 1),))
        b2 = _mine_child(
            b1,
            txs=(
                stx("alice", bob, 30, 0, 0),
                stx("bob", "carol", 25, 0, 0),
            ),
        )
        ledger = Ledger()
        ledger.apply_block(b1)
        ledger.apply_block(b2)
        assert ledger.balance(bob) == 5
        assert ledger.balance("carol") == 25


class TestOverspendRejection:
    def test_overspending_block_rejected_at_tip(self):
        chain, _ = _funded_chain("alice")
        tip_before = chain.tip_hash
        bad = _mine_child(
            chain.tip, txs=(stx("alice", "bob", BLOCK_REWARD + 1, 0, 0),)
        )
        res = chain.add_block(bad)
        assert res.status is AddStatus.REJECTED
        assert "overdraws" in res.reason
        assert chain.tip_hash == tip_before
        assert chain.balance(account("alice")) == BLOCK_REWARD
        # The rejected block is not offered to persistence.
        assert res.connected == ()

    def test_spend_of_unowned_account_never_connects(self):
        # mallory cannot move alice's coins: the forged tx already fails
        # stateless validation, so it is REJECTED before ledger checks.
        from p1_tpu.core.genesis import genesis_hash

        chain, _ = _funded_chain("alice")
        mallory = key_for("mallory")
        theft = Transaction(
            account("alice"), mallory.account, 10, 0, 0, chain=genesis_hash(DIFF)
        )
        theft = dataclasses.replace(
            theft, pubkey=mallory.pubkey, sig=mallory.sign(theft.signing_bytes())
        )
        res = chain.add_block(_mine_child(chain.tip, txs=(theft,)))
        assert res.status is AddStatus.REJECTED
        assert "signature" in res.reason

    def test_exact_balance_spend_connects(self):
        chain, _ = _funded_chain("alice")
        ok = _mine_child(
            chain.tip, txs=(stx("alice", "bob", BLOCK_REWARD - 3, 3, 0),)
        )
        res = chain.add_block(ok)
        assert res.status is AddStatus.ACCEPTED
        assert chain.balance(account("alice")) == 0
        assert chain.balance("bob") == BLOCK_REWARD - 3

    def test_descendants_of_invalid_block_rejected(self):
        chain, _ = _funded_chain("alice")
        bad = _mine_child(
            chain.tip, txs=(stx("alice", "bob", 9_999, 0, 0),)
        )
        assert chain.add_block(bad).status is AddStatus.REJECTED
        child = _mine_child(bad)  # internally valid, invalid ancestry
        res = chain.add_block(child)
        assert res.status is AddStatus.REJECTED
        assert "invalid" in res.reason
        assert chain.tip_hash != child.block_hash()

    def test_heavier_invalid_branch_does_not_win(self):
        # A longer branch whose FIRST block overdraws: fork choice must
        # stay on the shorter valid chain, whole branch marked invalid.
        chain, b1 = _funded_chain("alice")
        good2 = _mine_child(chain.tip)
        assert chain.add_block(good2).status is AddStatus.ACCEPTED
        bad2 = _mine_child(b1, txs=(stx("alice", "bob", 999, 0, 0),), ts_offset=3)
        bad3 = _mine_child(bad2)
        bad4 = _mine_child(bad3)
        # Deliver the heavy invalid branch out of order (orphans first).
        assert chain.add_block(bad4).status is AddStatus.ORPHAN
        assert chain.add_block(bad3).status is AddStatus.ORPHAN
        res = chain.add_block(bad2)
        assert res.status is AddStatus.REJECTED
        assert chain.tip_hash == good2.block_hash()
        assert chain.balance(account("alice")) == BLOCK_REWARD

    def test_reorg_onto_branch_that_overdraws_midway(self):
        # Branch B beats branch A on work, but B's SECOND block overdraws.
        # The settle loop must roll the ledger back cleanly and keep A.
        chain, b1 = _funded_chain("alice")
        a2 = _mine_child(b1, txs=(Transaction.coinbase("ma", 2),))
        assert chain.add_block(a2).status is AddStatus.ACCEPTED
        # Branch B off b1: valid block, then an overdraw of alice's 50.
        b2 = _mine_child(b1, txs=(Transaction.coinbase("mb", 2),), ts_offset=5)
        b3 = _mine_child(b2, txs=(stx("alice", "bob", 51, 0, 0),))
        b4 = _mine_child(b3)
        chain.add_block(b2)  # side branch, ties resolved by hash — either tip ok
        chain.add_block(b3)
        chain.add_block(b4)
        # Whatever arrival order did, the settled tip must be a VALID chain
        # of height 2 (a2 or b2 by hash tie-break), never b3/b4's branch.
        assert chain.height == 2
        assert chain.tip_hash in (a2.block_hash(), b2.block_hash())
        # Ledger matches a from-scratch replay of the surviving main chain.
        assert chain.balances_snapshot() == {
            k: v for k, v in balances(chain.main_chain()).items() if v
        }

    def test_miner_replay_of_confirmed_tx_rejected(self):
        # THE same-chain replay: a hostile miner re-includes alice's
        # already-confirmed transfer in the next block.  The signature and
        # chain tag both verify — the strict account nonce is what kills
        # it (seq 0 is consumed; alice is at nonce 1).
        chain, _ = _funded_chain("alice")
        pay = stx("alice", "bob", 10, 1, 0)
        b2 = _mine_child(chain.tip, txs=(pay,))
        assert chain.add_block(b2).status is AddStatus.ACCEPTED
        assert chain.nonce(account("alice")) == 1
        replay = _mine_child(chain.tip, txs=(pay,))  # identical bytes
        res = chain.add_block(replay)
        assert res.status is AddStatus.REJECTED
        assert "replay or gap" in res.reason
        assert chain.balance("bob") == 10  # debited exactly once

    def test_seq_gap_rejected_at_consensus(self):
        chain, _ = _funded_chain("alice")
        gap = _mine_child(chain.tip, txs=(stx("alice", "bob", 5, 0, 7),))
        res = chain.add_block(gap)
        assert res.status is AddStatus.REJECTED
        assert "replay or gap" in res.reason

    def test_reorg_rolls_nonce_back(self):
        # alice's spend confirms on branch A; a heavier branch B (without
        # it) wins — her nonce must roll back to 0 so the SAME signed tx
        # can legitimately confirm on B.
        chain, b1 = _funded_chain("alice")
        pay = stx("alice", "bob", 10, 1, 0)
        a2 = _mine_child(b1, txs=(pay,))
        assert chain.add_block(a2).status is AddStatus.ACCEPTED
        assert chain.nonce(account("alice")) == 1
        c2 = _mine_child(b1, txs=(Transaction.coinbase("c", 2),), ts_offset=4)
        c3 = _mine_child(c2)
        chain.add_block(c2)
        assert chain.add_block(c3).status is AddStatus.ACCEPTED
        assert chain.tip_hash == c3.block_hash()
        assert chain.nonce(account("alice")) == 0  # rolled back
        c4 = _mine_child(c3, txs=(pay,))  # same authorization, new branch
        assert chain.add_block(c4).status is AddStatus.ACCEPTED
        assert chain.balance("bob") == 10

    def test_valid_reorg_moves_balances(self):
        # A clean reorg where both branches are valid: ledger must track
        # undo+apply exactly.
        chain, b1 = _funded_chain("alice")
        a2 = _mine_child(b1, txs=(stx("alice", "bob", 10, 1, 0),))
        assert chain.add_block(a2).status is AddStatus.ACCEPTED
        assert chain.balance("bob") == 10
        carol = account("carol")
        c2 = _mine_child(b1, txs=(Transaction.coinbase(carol, 2),), ts_offset=4)
        c3 = _mine_child(c2, txs=(stx("carol", "dave", 5, 0, 0),))
        chain.add_block(c2)
        res = chain.add_block(c3)
        assert res.status is AddStatus.ACCEPTED
        assert chain.tip_hash == c3.block_hash()
        # alice's spend was rolled back with branch A; carol's landed.
        assert chain.balance(account("alice")) == BLOCK_REWARD
        assert chain.balance("bob") == 0
        assert chain.balance("dave") == 5
        assert chain.balances_snapshot() == {
            k: v for k, v in balances(chain.main_chain()).items() if v
        }


class TestMempoolBalance:
    def test_admission_requires_funds(self):
        from p1_tpu.mempool import Mempool

        chain, _ = _funded_chain("alice")
        pool = Mempool(balance_of=chain.balance)
        assert not pool.add(stx("bob", "alice", 1, 0, 0))  # bob has nothing
        assert pool.add(stx("alice", "bob", 30, 1, 0))
        # Second spend must fit the REMAINING 19 net of the pending 31.
        assert not pool.add(stx("alice", "bob", 20, 0, 1))
        assert pool.add(stx("alice", "bob", 19, 0, 1))

    def test_rbf_replacement_releases_incumbent_debit(self):
        from p1_tpu.mempool import Mempool

        chain, _ = _funded_chain("alice")
        pool = Mempool(balance_of=chain.balance)
        assert pool.add(stx("alice", "bob", 45, 1, 0))  # debit 46
        # Same slot, higher fee, SAME size spend: affordable only if the
        # incumbent's 46 is released before the check.
        assert pool.add(stx("alice", "bob", 45, 2, 0))
        # ... and the tally reflects exactly one pending spend (47).
        assert not pool.add(stx("alice", "bob", 4, 0, 1))
        assert pool.add(stx("alice", "bob", 3, 0, 1))

    def test_select_skips_unaffordable_without_dropping(self):
        from p1_tpu.mempool import Mempool

        chain, _ = _funded_chain("alice")
        # Build the pool balance-blind (as if funded earlier), then select
        # against a ledger where alice can only afford part of it.
        pool = Mempool()
        rich = stx("alice", "bob", 40, 5, 0)
        poor = stx("alice", "bob", 40, 1, 1)  # together they exceed 50
        assert pool.add(rich) and pool.add(poor)
        pool.balance_of = chain.balance
        picked = pool.select(10)
        assert picked == [rich]  # higher fee wins the budget
        assert poor.txid() in pool  # skipped, not dropped

    def test_admission_requires_this_chains_tag(self):
        # Pool-level mirror of the cross-chain replay rule: a spend signed
        # for another chain (internally valid!) is refused at admission.
        from p1_tpu.core.genesis import genesis_hash
        from p1_tpu.mempool import Mempool

        chain, _ = _funded_chain("alice")
        pool = Mempool(
            balance_of=chain.balance, chain_tag=genesis_hash(DIFF)
        )
        foreign = stx("alice", "bob", 5, 1, 0, difficulty=12)
        assert foreign.verify_signature()
        assert not pool.add(foreign)
        assert pool.add(stx("alice", "bob", 5, 1, 0, difficulty=DIFF))

    def test_select_emits_gap_free_seq_runs(self):
        from p1_tpu.mempool import Mempool

        chain, _ = _funded_chain("alice")
        pool = Mempool(balance_of=chain.balance, nonce_of=chain.nonce)
        # Ascending fees over a seq run: rank order is the REVERSE of the
        # required confirmation order — the eligibility heap must still
        # emit seq 0,1,2 (and the gapped seq 9 never).
        t0 = stx("alice", "bob", 2, 1, 0)
        t1 = stx("alice", "bob", 2, 5, 1)
        t2 = stx("alice", "bob", 2, 9, 2)
        gap = stx("alice", "bob", 1, 20, 9)
        for t in (gap, t2, t1, t0):
            assert pool.add(t)
        assert [t.seq for t in pool.select(10)] == [0, 1, 2]
        # An unaffordable tx ends its sender's run (later seqs would gap).
        # Build the overweight pair balance-blind (as if funded when
        # admitted, then a reorg shrank the balance).
        pool.balance_of = None
        big = stx("alice", "bob", 40, 1, 3)  # 41 > the 29 left after 0-2
        after = stx("alice", "bob", 1, 50, 4)
        assert pool.add(big) and pool.add(after)
        pool.balance_of = chain.balance
        assert [t.seq for t in pool.select(10)] == [0, 1, 2]  # run ends at 3

    def test_custom_genesis_chain_tag(self):
        # A chain built on a custom genesis must accept transfers bound to
        # ITS genesis hash (not the default-for-difficulty one) — the tag
        # the node's HELLO and mempool advertise.
        import dataclasses as dc

        from p1_tpu.core.genesis import make_genesis

        custom = dc.replace(
            make_genesis(DIFF).header, timestamp=1_700_000_000
        )
        custom_genesis = Block(custom, ())
        chain = Chain(DIFF, genesis=custom_genesis)
        alice = key_for("alice")
        b1 = _mine_child(
            custom_genesis, txs=(Transaction.coinbase(alice.account, 1),)
        )
        assert chain.add_block(b1).status is AddStatus.ACCEPTED
        pay = Transaction.transfer(
            alice, "bob", 5, 1, 0, chain=custom_genesis.block_hash()
        )
        ok = _mine_child(b1, txs=(pay,))
        assert chain.add_block(ok).status is AddStatus.ACCEPTED
        # ... and the default-genesis tag is a DIFFERENT chain here.
        foreign = stx("alice", "carol", 5, 1, 1, difficulty=DIFF)
        bad = _mine_child(ok, txs=(foreign,))
        res = chain.add_block(bad)
        assert res.status is AddStatus.REJECTED
        assert "different chain" in res.reason

    def test_eviction_releases_debit(self):
        from p1_tpu.mempool import Mempool

        chain, b1 = _funded_chain("alice")
        pool = Mempool(balance_of=chain.balance)
        spend = stx("alice", "bob", 45, 1, 0)
        assert pool.add(spend)
        blk = _mine_child(b1, txs=(spend,))
        pool.apply_block_delta((), (blk,))
        assert pool._pending_debit == {}


class TestForkChoicePropertyWithLedger:
    """TestForkChoiceProperty extended per VERDICT r3 item 2: random DAGs
    whose blocks carry random transfers (some overdrawing), delivered in
    random order to multiple nodes.  Invariants: all nodes converge to the
    same tip; the main chain replays cleanly through a fresh ledger (no
    negative balance ever); the incremental ledger equals the from-scratch
    view; no block of the main chain overdraws."""

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_random_dag_with_ledger_converges(self, seed):
        import random as rnd

        rng = rnd.Random(seed)
        diff = 2
        genesis = make_genesis(diff)
        labels = ["u1", "u2", "u3"]
        blocks = [genesis]
        # Track per-branch balances so tx generation can aim near the
        # boundary: ~60% affordable, ~40% overdraw attempts.
        for i in range(50):
            parent = rng.choice(blocks)
            # Rebuild the parent branch's balances (test-side oracle).
            branch = []
            b = parent
            by_hash = {blk.block_hash(): blk for blk in blocks}
            while b is not genesis:
                branch.append(b)
                b = by_hash[b.header.prev_hash]
            branch_blocks = [genesis, *reversed(branch)]
            bal = balances(branch_blocks)
            miner = account(rng.choice(labels))
            txs = [Transaction.coinbase(miner, i)]
            sender = rng.choice(labels)
            have = bal.get(account(sender), 0)
            # Strict account nonces: the valid seq is the count of the
            # sender's transfers already on this branch.
            nonce = sum(
                1
                for blk in branch_blocks
                for t in blk.txs
                if t.sender == account(sender)
            )
            if rng.random() < 0.4:
                amount = have + rng.randint(1, 25)  # overdraw attempt
            else:
                amount = rng.randint(0, max(0, have - 1))
            seq = nonce if rng.random() < 0.8 else nonce + rng.randint(1, 3)
            if amount > 0:
                txs.append(
                    stx(
                        sender,
                        account(rng.choice(labels)),
                        amount,
                        1,
                        seq,
                        difficulty=diff,
                    )
                )
            child = _mine_child(parent, txs=tuple(txs), ts_offset=rng.randint(1, 9))
            blocks.append(child)

        non_genesis = blocks[1:]
        tips = set()
        for trial in range(3):
            order = non_genesis[:]
            rng.shuffle(order)
            chain = Chain(diff, genesis=genesis)
            for block in order:
                chain.add_block(block)
            main = list(chain.main_chain())
            # 1. Main chain is ledger-valid from scratch.
            fresh = Ledger()
            for b in main:
                fresh.apply_block(b)  # raises on any overdraw
            # 2. Incremental state == from-scratch state, nothing negative.
            snap = chain.balances_snapshot()
            assert snap == {k: v for k, v in balances(main).items() if v}
            assert all(v > 0 for v in snap.values())
            assert main[-1].block_hash() == chain.tip_hash
            tips.add(chain.tip_hash)
        # 3. Convergence: delivery order never changes the winner.
        assert len(tips) == 1
