"""Device-sharded Ed25519 MSM (hashx/ed25519_msm.py): parity and sharding.

Layered like the module itself:

- Tier-1: the limb-decomposed fe25519 arithmetic and batched point
  formulas against the CPython big-int oracle (core/_ed25519.py), the
  windowed MSM against a direct oracle at small window counts, and the
  host-side early rejects (malformed inputs never reach the device).
- Slow: the full ``verify_batch_device`` contract — verdict parity
  with the fallback batch on valid/corrupt/torsion inputs, the
  mesh-size invariance (1 vs 8 virtual devices, same verdicts), and
  the keys.py ``device`` backend routing.  Slow because each array
  shape pays one multi-minute XLA compile on the 1-vCPU CI host (the
  cases share one batch shape to pay it once); on real TPU hardware
  the same program compiles once per pod lifetime.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from p1_tpu.core import _ed25519 as py_ed
from p1_tpu.core import keys
from p1_tpu.hashx import ed25519_msm as dev

rng = random.Random(25519)


def _rand_fe() -> int:
    return rng.randrange(py_ed._P)


def _rand_pt():
    return py_ed._pt_mul(rng.randrange(1, py_ed._Q), py_ed._B)


def _triples(n, salt=b"d"):
    out = []
    for i in range(n):
        seed = bytes([i % 5]) * 31 + bytes([len(salt) % 256])
        msg = b"dev-%d-" % i + salt
        out.append((py_ed.public_key(seed), py_ed.sign(seed, msg), msg))
    return out


def _torsion_triple(*, cancel: bool):
    t_enc = ((py_ed._P - 1) if cancel else 0).to_bytes(32, "little")
    a, prefix = py_ed._secret_expand(bytes(32))
    torsion = py_ed._pt_decompress(t_enc)
    a_pt = py_ed._pt_mul(a, py_ed._B)
    pub = py_ed._pt_compress(py_ed._pt_add(a_pt, torsion) if cancel else a_pt)
    for i in range(200):
        msg = b"dev-torsion-%d" % i
        r = int.from_bytes(py_ed._sha512(prefix + msg), "little") % py_ed._Q
        r_enc = py_ed._pt_compress(
            py_ed._pt_add(py_ed._pt_mul(r, py_ed._B), torsion)
        )
        k = int.from_bytes(py_ed._sha512(r_enc + pub + msg), "little") % py_ed._Q
        if cancel and k % 2 == 0:
            continue
        return pub, r_enc + ((r + k * a) % py_ed._Q).to_bytes(32, "little"), msg
    raise AssertionError("no usable k")


class TestFieldArithmetic:
    """fe25519 limbs vs the big-int oracle."""

    def test_roundtrip(self):
        for x in (0, 1, 19, py_ed._P - 1, (1 << 255) - 20):
            assert dev.fe_to_int(dev.fe_from_int(x)) == x % py_ed._P

    def test_mul_sq_add_sub_fuzz(self):
        for trial in range(30):
            a, b = _rand_fe(), _rand_fe()
            fa = jnp.asarray(dev.fe_from_int(a))
            fb = jnp.asarray(dev.fe_from_int(b))
            assert dev.fe_to_int(dev.fe_mul(fa, fb)) == a * b % py_ed._P, trial
            assert dev.fe_to_int(dev.fe_sq(fa)) == a * a % py_ed._P
            assert dev.fe_to_int(dev.fe_add(fa, fb)) == (a + b) % py_ed._P
            assert dev.fe_to_int(dev.fe_sub(fa, fb)) == (a - b) % py_ed._P

    def test_composed_ops_hold_the_limb_invariant(self):
        # The historical bug class: an uncarried intermediate feeding
        # fe_sub underflowed at the top limb.  Chain every op shape.
        for trial in range(12):
            a, b, c = _rand_fe(), _rand_fe(), _rand_fe()
            fa, fb, fc = (
                jnp.asarray(dev.fe_from_int(x)) for x in (a, b, c)
            )
            got = dev.fe_sub(dev.fe_mul(fa, fb), dev.fe_sq(fc))
            assert dev.fe_to_int(got) == (a * b - c * c) % py_ed._P
            got2 = dev.fe_mul(dev.fe_sub(dev.fe_add(fa, fb), fc), fb)
            assert dev.fe_to_int(got2) == (a + b - c) * b % py_ed._P, trial

    def test_canon_edges(self):
        # canonical zero from p (≡ 0) and from 2p-shaped residue
        fp = jnp.asarray(dev.fe_from_int(py_ed._P - 1))
        one = jnp.asarray(dev.fe_from_int(1))
        # p-1 + 1 ≡ 0, p-1 + 2 ≡ 1
        assert bool(dev.fe_is_zero(dev.fe_add(fp, one)))
        two = jnp.asarray(dev.fe_from_int(2))
        assert dev.fe_to_int(dev.fe_canon(dev.fe_add(fp, two))) == 1
        assert bool(dev.fe_eq(dev.fe_add(fp, two), one))
        # a merely-carried value far above p still canonicalizes: build
        # ~2^259 via repeated doubling of limb values
        big = jnp.asarray(
            np.full(dev.FE_LIMBS, dev.LIMB_MASK, dtype=np.uint32)
        )
        want = sum(
            dev.LIMB_MASK << (dev.LIMB_BITS * i) for i in range(dev.FE_LIMBS)
        ) % py_ed._P
        assert dev.fe_to_int(dev.fe_canon(big)) == want

    def test_batched_axes(self):
        ints = [_rand_fe() for _ in range(4)]
        batch = jnp.asarray(np.stack([dev.fe_from_int(x) for x in ints]))
        prod = dev.fe_mul(batch, batch)
        for i, x in enumerate(ints):
            assert dev.fe_to_int(np.asarray(prod)[i]) == x * x % py_ed._P


class TestPointArithmetic:
    def test_add_double_parity(self):
        for trial in range(10):
            p1, p2 = _rand_pt(), _rand_pt()
            jp = jnp.asarray(dev._encode_point(p1)[None])
            jq = jnp.asarray(dev._encode_point(p2)[None])
            got = dev._decode_point(np.asarray(dev.ge_add(jp, jq))[0])
            assert py_ed._pt_equal(got, py_ed._pt_add(p1, p2)), trial
            got_d = dev._decode_point(np.asarray(dev.ge_double(jp))[0])
            assert py_ed._pt_equal(got_d, py_ed._pt_double(p1))

    def test_identity_and_torsion_points(self):
        t2 = py_ed._pt_decompress((py_ed._P - 1).to_bytes(32, "little"))
        t4 = py_ed._pt_decompress((0).to_bytes(32, "little"))
        ident = dev.ge_identity((1,))
        for pt in (py_ed._B, t2, t4, py_ed._IDENT):
            jp = jnp.asarray(dev._encode_point(pt)[None])
            got = dev._decode_point(np.asarray(dev.ge_add(jp, ident))[0])
            assert py_ed._pt_equal(got, pt)
        assert bool(dev.ge_is_identity(ident)[0])
        assert not bool(
            dev.ge_is_identity(jnp.asarray(dev._encode_point(py_ed._B)[None]))[0]
        )

    @pytest.mark.slow
    def test_msm_small_windows_vs_oracle(self):
        # _msm_tree scans whatever window rows it is given: 4-window
        # scalars keep the run shortish while exercising the gather +
        # tree-reduce + Horner machinery end to end.  Slow: even the
        # 4-window scan pays a ~35 s body compile on the 1-vCPU host.
        pts = [_rand_pt() for _ in range(4)]
        scalars = [rng.randrange(1, 16**4) for _ in range(4)]
        digit_rows = np.array(
            [
                [(s >> (4 * w)) & 15 for s in scalars]
                for w in reversed(range(4))
            ],
            dtype=np.uint32,
        )
        jpts = jnp.asarray(np.stack([dev._encode_point(p) for p in pts]))
        got = dev._decode_point(
            np.asarray(dev._msm_tree(jpts, jnp.asarray(digit_rows)))
        )
        want = py_ed._IDENT
        for s, p in zip(scalars, pts):
            want = py_ed._pt_add(want, py_ed._pt_mul(s, p))
        assert py_ed._pt_equal(got, want)


class TestHostSideRejects:
    """Malformed inputs settle on the host — no device work, no jit."""

    def test_early_falses(self):
        good = _triples(2)
        pub, sig, msg = good[0]
        cases = [
            [(pub[:31], sig, msg)],
            [(pub, sig[:63], msg)],
            [(pub, sig[:32] + py_ed._Q.to_bytes(32, "little"), msg)],
            [(py_ed._P.to_bytes(32, "little"), sig, msg)],  # bad A
            [(pub, py_ed._P.to_bytes(32, "little") + sig[32:], msg)],  # bad R
        ]
        for bad in cases:
            assert dev.verify_batch_device(bad) is False
        assert dev.verify_batch_device([]) is True

    def test_digits_roundtrip(self):
        s = rng.randrange(1 << 256)
        digs = dev._digits_of(s)
        back = 0
        for d in digs:
            back = (back << 4) | int(d)
        assert back == s


@pytest.mark.slow
class TestDeviceVerifyEndToEnd:
    """Full verdict parity — one shape shared across cases so the
    multi-minute CI compile is paid once."""

    N = 12  # with 5 unique keys => 17 points => (8 dev × 4) padded

    def test_verdict_parity_and_sharding(self):
        base = _triples(self.N, salt=b"e2e")
        assert dev.verify_batch_device(base) is True
        # corruption at every position, same shape -> no recompile
        for pos in range(self.N):
            bad = list(base)
            pub, sig, msg = bad[pos]
            bad[pos] = (pub, sig[:20] + bytes([sig[20] ^ 1]) + sig[21:], msg)
            assert dev.verify_batch_device(bad) is False, pos
            assert py_ed.verify_batch(bad) is False

    def test_torsion_fixture_parity(self):
        acc = _torsion_triple(cancel=True)
        assert py_ed.verify(*acc)
        batch = _triples(self.N - 1, salt=b"tors") + [acc]
        # gate-rejected despite serial validity — exactly the fallback
        assert dev.verify_batch_device(batch) is False
        assert py_ed.verify_batch(batch) is False
        rej = _torsion_triple(cancel=False)
        batch2 = _triples(self.N - 1, salt=b"tors2") + [rej]
        assert dev.verify_batch_device(batch2) is False

    def test_mesh_size_invariance(self):
        tr = _triples(self.N, salt=b"mesh")
        assert dev.verify_batch_device(tr, n_devices=8) is True
        assert dev.verify_batch_device(tr, n_devices=1) is True
        bad = list(tr)
        pub, sig, msg = bad[3]
        bad[3] = (pub, sig, msg + b"!")
        assert dev.verify_batch_device(bad, n_devices=8) is False
        assert dev.verify_batch_device(bad, n_devices=1) is False

    def test_keys_device_backend_routing(self):
        try:
            keys.set_sig_backend("device")
            assert keys.backend() == "device"
            tr = _triples(self.N, salt=b"route")
            keys.STATS.reset()
            assert keys.verify_batch(tr)
            assert keys.STATS.backends["device"] == len(tr)
            # serial work under a device override keeps the host ladder
            keys._neg_cache.clear()
            assert keys.verify(*tr[0])
            assert keys.STATS.backends["device"] == len(tr)
            # first_invalid settles serially: byte-identical contract
            bad = list(tr)
            pub, sig, msg = bad[7]
            bad[7] = (pub, sig[:20] + bytes([sig[20] ^ 1]) + sig[21:], msg)
            assert not keys.verify_batch(bad)
            assert keys.first_invalid(bad) == 7
        finally:
            keys.set_sig_backend(None)
