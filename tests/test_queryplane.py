"""The query serving plane (round 9): compact block filters, the
reorg-safe proof cache, and mmap read replicas.

Three property families anchor the tier:

- **filter ⊇ truth**: on randomized blocks, the compact filter's match
  set is a superset of the true match set — zero false negatives, ever
  (the light client's correctness rests on skipping non-matching blocks
  unconditionally) — while the false-positive rate stays under the
  designed bound, MEASURED on a deliberately lossy parameterization
  (the production 1/784931 rate would vacuously measure 0).
- **cached == fresh**: a proof served through the cache (template +
  serialized-payload memo + tip patch) is byte-identical to one built
  from scratch, and a reorg invalidates every cached proof for the
  orphaned blocks — never served stale.
- **replica == node**: a flock-free mmap replica serves the same
  headers/filters/proofs as the node writing the store, including for
  blocks appended AFTER the replica attached (the refresh path), and
  never takes the writer lock.
"""

import asyncio
import os
import random
import struct

import pytest

from test_node import CHUNK, DIFF, fund, run, wait_until
from txutil import account, key_for, stx

from p1_tpu.chain import Chain, ChainStore, save_chain, verify_tx_proof
from p1_tpu.chain import filters as fmod
from p1_tpu.chain.proof import ProofCache, build_block_proofs
from p1_tpu.config import NodeConfig
from p1_tpu.core.block import Block, merkle_branch, merkle_root
from p1_tpu.core.header import BlockHeader
from p1_tpu.core.tx import Transaction
from p1_tpu.node import Node, protocol
from p1_tpu.node.client import (
    CommitmentViolation,
    filter_scan,
    get_filter_headers,
    get_filters,
    get_headers,
    get_proof,
    get_status,
)
from p1_tpu.node.protocol import MsgType
from p1_tpu.node.queryplane import QueryPlaneServer, ReplicaView, serve_replica

from p1_tpu.hashx import get_backend
from p1_tpu.miner import Miner


def _config(peers=(), **kw) -> NodeConfig:
    kw.setdefault("difficulty", DIFF)
    kw.setdefault("chunk", CHUNK)
    kw.setdefault("mine", False)
    return NodeConfig(peers=tuple(peers), **kw)


def build_chain(
    n_blocks: int,
    difficulty: int = 1,
    rng: random.Random | None = None,
    labels=("alice", "bob", "carol", "dave"),
    txs_per_block: int = 3,
) -> Chain:
    """A valid chain whose blocks carry randomized signed transfers
    between the label accounts — the filter property tests' fixture."""
    rng = rng or random.Random(0)
    chain = Chain(difficulty)
    tag = chain.genesis.block_hash()
    miner = Miner(backend=get_backend("cpu"), chunk=4096)
    seqs = {label: 0 for label in labels}
    funded = set()
    for height in range(1, n_blocks + 1):
        payer = rng.choice(labels)
        txs = [Transaction.coinbase(account(payer), height)]
        for label in list(funded):
            for _ in range(rng.randrange(0, txs_per_block)):
                rcpt = rng.choice(labels + ("merchant", "exchange"))
                if chain.balance(account(label)) < 2 * txs_per_block + 2:
                    break
                txs.append(
                    Transaction.transfer(
                        key_for(label),
                        account(rcpt) if rcpt in labels else rcpt,
                        1,
                        1,
                        seqs[label],
                        chain=tag,
                    )
                )
                seqs[label] += 1
        funded.add(payer)
        parent = chain.tip
        draft = BlockHeader(
            version=1,
            prev_hash=parent.block_hash(),
            merkle_root=merkle_root([tx.txid() for tx in txs]),
            timestamp=parent.header.timestamp + 60,
            difficulty=difficulty,
            nonce=0,
        )
        sealed = miner.search_nonce(draft)
        res = chain.add_block(Block(sealed, tuple(txs)))
        assert res.status.value == "accepted", res.reason
    return chain


# -- the filter construction ---------------------------------------------


class TestFilterCodec:
    def test_round_trip_values_sorted_unique(self):
        rng = random.Random(7)
        key = bytes(range(32))
        for _ in range(30):
            items = {
                rng.randbytes(rng.randrange(1, 48))
                for _ in range(rng.randrange(0, 300))
            }
            f = fmod.encode_filter(key, items)
            vals = list(fmod.decode_values(f))
            assert vals == sorted(set(vals))
            assert fmod.filter_count(f) == len(vals)

    def test_zero_false_negatives_randomized(self):
        """EVERY encoded item matches — the guarantee the light client's
        skip decision rests on, across random item sets and keys."""
        rng = random.Random(11)
        for _ in range(40):
            key = rng.randbytes(32)
            items = {
                rng.randbytes(rng.randrange(1, 40))
                for _ in range(rng.randrange(1, 120))
            }
            f = fmod.encode_filter(key, items)
            for it in items:
                assert fmod.matches_any(f, key, [it])
            # And as one batched query set too.
            assert fmod.matches_any(f, key, list(items))

    def test_false_positive_rate_under_designed_bound(self):
        """Measured FP rate on a deliberately lossy parameterization
        (P=6, M=64 → designed 1/64 per absent item).  Deterministic
        seed; the bound allows 2x the expectation — ~4 sigma over this
        sample size, so a real regression (e.g. a biased hash map)
        trips it while statistical noise never should."""
        rng = random.Random(13)
        p, m = 6, 64
        key = bytes(32)
        fp = queries = 0
        for _ in range(300):
            items = {rng.randbytes(8) for _ in range(40)}
            f = fmod.encode_filter(key, items, p, m)
            for _ in range(20):
                probe = rng.randbytes(9)  # length 9: never a real item
                queries += 1
                if fmod.matches_any(f, key, [probe], p, m):
                    fp += 1
        assert queries == 6000
        assert fp / queries < 2.0 / m, f"fp rate {fp / queries:.4f}"
        assert fp > 0  # the lossy parameterization really is lossy

    def test_truncated_filter_raises(self):
        key = bytes(32)
        f = fmod.encode_filter(key, {b"a", b"b", b"c"})
        with pytest.raises(ValueError):
            list(fmod.decode_values(f[:2]))  # inside the count prefix
        with pytest.raises(ValueError):
            list(fmod.decode_values(f[:-1] if len(f) > 5 else f[:4]))

    def test_empty_filter_matches_nothing(self):
        f = fmod.encode_filter(bytes(32), set())
        assert fmod.filter_count(f) == 0
        assert not fmod.matches_any(f, bytes(32), [b"anything"])
        assert not fmod.matches_any(f, bytes(32), [])

    def test_block_filter_commits_txids_and_accounts(self):
        chain = build_chain(4)
        for block in list(chain.main_chain())[1:]:
            f = fmod.block_filter(block)
            bhash = block.block_hash()
            for tx in block.txs:
                assert fmod.matches_any(f, bhash, [tx.txid()])
                assert fmod.matches_any(f, bhash, [tx.sender.encode()])
                assert fmod.matches_any(f, bhash, [tx.recipient.encode()])


class TestFilterWire:
    def test_getfilters_round_trip(self):
        mtype, body = protocol.decode(protocol.encode_getfilters(17, 500))
        assert mtype is MsgType.GETFILTERS
        assert body == (17, 500)

    def test_filters_round_trip(self):
        entries = [
            (bytes([i]) * 32, bytes(range(i + 1))) for i in range(5)
        ] + [(bytes(32), b"")]
        mtype, body = protocol.decode(protocol.encode_filters(9, entries))
        assert mtype is MsgType.FILTERS
        assert body == (9, entries)
        # Empty range (height past the tip) is a valid, empty reply.
        mtype, body = protocol.decode(protocol.encode_filters(1000, []))
        assert body == (1000, [])

    def test_malformed_filters_are_violations(self):
        good = protocol.encode_filters(0, [(bytes(32), b"\x01\x02")])
        for bad in (
            bytes([MsgType.GETFILTERS]) + b"\x00" * 5,  # short
            bytes([MsgType.GETFILTERS]) + struct.pack(">IH", 0, 0),  # count 0
            good[:-1],  # truncated entry
            good + b"\x00",  # trailing bytes
        ):
            with pytest.raises(protocol.ProtocolError):
                protocol.decode(bad)

    def test_raw_encoders_match_object_encoders(self):
        chain = build_chain(3)
        blocks = list(chain.main_chain())
        assert protocol.encode_headers_raw(
            [b.header.serialize() for b in blocks]
        ) == protocol.encode_headers([b.header for b in blocks])
        assert protocol.encode_blocks_raw(
            [b.serialize() for b in blocks]
        ) == protocol.encode_blocks(blocks)
        with pytest.raises(ValueError):
            protocol.encode_headers_raw([b"short"])


class TestFilterVsFullScan:
    def test_match_set_superset_of_truth_randomized(self):
        """The acceptance property: for every watched item, the set of
        blocks whose filter matches ⊇ the set of blocks that truly
        touch it — on randomized chains, with the false-positive excess
        measured and bounded."""
        rng = random.Random(23)
        chain = build_chain(16, rng=rng, txs_per_block=4)
        blocks = list(chain.main_chain())[1:]
        watch_all = [
            account(lbl).encode() for lbl in ("alice", "bob", "carol", "dave")
        ] + [b"merchant", b"exchange", b"nobody-ever"]
        fps = 0
        for item in watch_all:
            truth = set()
            matched = set()
            for block in blocks:
                f = chain.block_filter(block.block_hash())
                touched = set()
                for tx in block.txs:
                    touched |= {
                        tx.txid(),
                        tx.sender.encode(),
                        tx.recipient.encode(),
                    }
                if item in touched:
                    truth.add(block.block_hash())
                if fmod.matches_any(f, block.block_hash(), [item]):
                    matched.add(block.block_hash())
            assert truth <= matched, f"false negative for {item!r}"
            fps += len(matched - truth)
        # Production M: designed FP ≈ items_per_block/784931 per block —
        # over this sample, any false positive at all is ~10^-3 likely.
        assert fps <= 1


# -- the proof cache ------------------------------------------------------


class TestProofCache:
    def test_batched_templates_equal_serial_proofs(self):
        chain = build_chain(8)
        for block in list(chain.main_chain())[1:]:
            height = chain.height_of(block.block_hash())
            txids = [tx.txid() for tx in block.txs]
            batch = build_block_proofs(block, height, txids)
            for i, txid in enumerate(txids):
                proof = batch[txid]
                assert proof.index == i
                assert proof.branch == merkle_branch(txids, i)
                assert proof.height == height
                assert proof.tx is block.txs[i]

    def test_chain_tx_proofs_match_singles_and_verify(self):
        chain = build_chain(10)
        tag = chain.genesis.block_hash()
        txids = [
            tx.txid()
            for b in chain.main_chain()
            for tx in b.txs
            if not tx.is_coinbase
        ]
        assert txids, "fixture must carry transfers"
        batch = chain.tx_proofs(txids)
        for txid in txids:
            single = chain.tx_proof(txid)
            assert batch[txid] == single
            verify_tx_proof(single, chain.difficulty, tag, txid=txid)
        assert chain.tx_proofs([bytes(32)]) == {bytes(32): None}

    def test_cache_hits_and_tip_stamp_advances(self):
        chain = build_chain(6)
        txid = next(
            tx.txid()
            for b in chain.main_chain()
            for tx in b.txs
            if not tx.is_coinbase
        )
        p1 = chain.tx_proof(txid)
        hits0 = chain.proof_cache.hits
        p2 = chain.tx_proof(txid)
        assert chain.proof_cache.hits > hits0
        assert p1 == p2 and p2.tip_height == chain.height

    def test_payload_memo_patch_equals_fresh_encode(self):
        """The 4-byte tip patch on the memoized wire payload must be
        byte-identical to a from-scratch encode at the current tip —
        the hot serving path's correctness in one equation."""
        chain = build_chain(6)
        txid = next(
            tx.txid()
            for b in chain.main_chain()
            for tx in b.txs
            if not tx.is_coinbase
        )
        entry = chain.tx_proof_entry(txid)
        chain.proof_cache.note_payload(
            entry, protocol.encode_proof(entry.proof)
        )
        patched = protocol.patch_proof_tip(entry.payload, chain.height)
        fresh = protocol.encode_proof(chain.tx_proof(txid))
        assert patched == fresh
        # And the decode round-trips to the same proof object.
        mtype, decoded = protocol.decode(patched)
        assert mtype is MsgType.PROOF
        assert decoded == chain.tx_proof(txid)

    def test_lru_stays_under_its_byte_budget(self):
        chain = build_chain(12, txs_per_block=4)
        chain.proof_cache = ProofCache(max_bytes=4096)
        txids = [
            tx.txid()
            for b in chain.main_chain()
            for tx in b.txs
        ]
        for txid in txids:
            chain.tx_proof(txid)
        assert chain.proof_cache.bytes_used <= 4096
        assert len(chain.proof_cache) >= 1

    def test_reorg_invalidates_orphaned_blocks_never_serves_stale(self):
        """The acceptance case: a proof cached for a block that a reorg
        orphans is (a) dropped from the cache and (b) no longer
        reachable through tx_proof — a proof served after the reorg
        names the NEW containing block or nothing."""
        miner = Miner(backend=get_backend("cpu"), chunk=4096)

        def extend(chain, parent, height, txs, ts):
            draft = BlockHeader(
                version=1,
                prev_hash=parent,
                merkle_root=merkle_root([t.txid() for t in txs]),
                timestamp=ts,
                difficulty=chain.difficulty,
                nonce=0,
            )
            sealed = miner.search_nonce(draft)
            block = Block(sealed, tuple(txs))
            res = chain.add_block(block)
            assert res.status.value in ("accepted", "orphan"), res.reason
            return block

        chain = Chain(1)
        g = chain.genesis
        # Branch A: two blocks; the second carries a transfer.
        a1 = extend(
            chain,
            g.block_hash(),
            1,
            [Transaction.coinbase(account("alice"), 1)],
            g.header.timestamp + 60,
        )
        tx = stx("alice", "bob", 3, 1, 0, difficulty=1)
        a2 = extend(
            chain,
            a1.block_hash(),
            2,
            [Transaction.coinbase(account("alice"), 2), tx],
            g.header.timestamp + 120,
        )
        proof_a = chain.tx_proof(tx.txid())
        assert proof_a is not None and proof_a.header == a2.header
        assert len(chain.proof_cache) > 0
        a2_hash = a2.block_hash()

        # Branch B: three blocks from genesis — heavier, reorgs A out.
        # (B does not carry the transfer: alice's coins exist only on A.)
        parent, ts = g.block_hash(), g.header.timestamp + 61
        for h in range(1, 4):
            b = extend(
                chain,
                parent,
                h,
                [Transaction.coinbase(account("carol"), h)],
                ts,
            )
            parent, ts = b.block_hash(), ts + 60
        assert chain.height == 3  # the reorg landed
        assert chain.tip.txs[0].recipient == account("carol")

        # (a) the cache dropped every entry for the orphaned blocks...
        assert chain.proof_cache.invalidated >= 2  # a2's coinbase + tx
        assert all(bh != a2_hash for bh, _ in chain.proof_cache._lru)
        # (b) ...and the serving path cannot produce a stale proof: the
        # transfer is unconfirmed on the new main chain.
        assert chain.tx_proof(tx.txid()) is None
        # A block that SURVIVED on the new chain serves fresh proofs.
        cb = chain.tip.txs[0]
        proof = chain.tx_proof(cb.txid())
        verify_tx_proof(
            proof, 1, chain.genesis.block_hash(), txid=cb.txid()
        )
        assert proof.tip_height == 3


class TestFilterRebuildUnderReorg:
    """The round-9 FilterIndex's rebuild-from-store path under reorg —
    previously only the happy path (build at connect, serve from LRU)
    was exercised.  Here filters are LRU-evicted AND the bodies they
    would rebuild from are evicted to the store, then a reorg moves the
    main chain: every filter served afterwards must be rebuilt through
    ``Chain._block_at``'s store refetch and be byte-identical to a
    fresh construction from the block — for the new main chain and for
    the orphaned branch alike."""

    def _extend(self, chain, store, parent, height, txs, ts):
        miner = Miner(backend=get_backend("cpu"), chunk=4096)
        draft = BlockHeader(
            version=1,
            prev_hash=parent,
            merkle_root=merkle_root([t.txid() for t in txs]),
            timestamp=ts,
            difficulty=chain.difficulty,
            nonce=0,
        )
        sealed = miner.search_nonce(draft)
        block = Block(sealed, tuple(txs))
        res = chain.add_block(block)
        assert res.status.value == "accepted", res.reason
        store.append(block)
        return block

    def test_evicted_filters_rebuild_from_the_store_across_a_reorg(
        self, tmp_path
    ):
        store = ChainStore(tmp_path / "c.dat")
        store.acquire()
        try:
            chain = Chain(1)
            chain.body_source = store
            g = chain.genesis
            # Branch A: two blocks, the second carrying a transfer the
            # filter must commit to.
            a1 = self._extend(
                chain, store, g.block_hash(), 1,
                [Transaction.coinbase(account("alice"), 1)],
                g.header.timestamp + 60,
            )
            tx = stx("alice", "bob", 3, 1, 0, difficulty=1)
            a2 = self._extend(
                chain, store, a1.block_hash(), 2,
                [Transaction.coinbase(account("alice"), 2), tx],
                g.header.timestamp + 120,
            )
            # Fresh ground truth BEFORE any eviction/reorg.
            truth = {
                b.block_hash(): fmod.block_filter(b) for b in (a1, a2)
            }
            # Branch B: three carol blocks from genesis — reorgs A out.
            parent, ts = g.block_hash(), g.header.timestamp + 61
            b_blocks = []
            for h in range(1, 4):
                b = self._extend(
                    chain, store, parent, h,
                    [Transaction.coinbase(account("carol"), h)], ts,
                )
                truth[b.block_hash()] = fmod.block_filter(b)
                parent, ts = b.block_hash(), ts + 60
                b_blocks.append(b)
            assert chain.height == 3  # the reorg landed

            # Now the hostile part: drop every cached filter AND evict
            # bodies so a rebuild must round-trip through the store.
            chain.filter_index = fmod.FilterIndex(max_bytes=16 << 20)
            assert len(chain.filter_index) == 0
            chain.evict_bodies(1)
            assert chain.bodies_evicted > 0

            # New-main-chain filters rebuild byte-identically...
            for h in range(1, 4):
                bhash = chain.main_hash_at(h)
                assert chain.block_filter(bhash) == truth[bhash]
            # ...and so do the ORPHANED branch's (still indexed, still
            # store-resident — a late light client may ask for them).
            assert chain.block_filter(a2.block_hash()) == truth[
                a2.block_hash()
            ]
            assert chain.filter_index.built >= 4  # rebuilt, not cached
            assert chain.body_refetches > 0  # the store path really ran

            # Semantics survived the rebuild: the orphaned block's
            # filter still matches the reorged-out transfer (zero false
            # negatives are per-block, branch or not) while the
            # same-height main-chain block — which never carried it —
            # need not (and its sender set is carol's, not alice's).
            a2f = chain.block_filter(a2.block_hash())
            assert fmod.matches_any(
                a2f, a2.block_hash(), [tx.txid()]
            )
            main2 = chain.main_hash_at(2)
            assert fmod.matches_any(
                chain.block_filter(main2), main2,
                [account("carol").encode()],
            )
            # Unknown hash: not an exception, a None (the serving
            # path's not-found contract).
            assert chain.block_filter(b"\x00" * 32) is None
        finally:
            store.close()

    def test_rebuilt_filters_serve_identical_bytes_to_connect_time(
        self, tmp_path
    ):
        """A store resumed with a bounded body cache must serve the
        exact filter bytes the original node built at connect time —
        the replica/serving plane's cold-history path."""
        chain = build_chain(8, difficulty=1, rng=random.Random(7))
        blocks = list(chain.main_chain())
        truth = {
            b.block_hash(): chain.block_filter(b.block_hash())
            for b in blocks[1:]
        }
        store = ChainStore(tmp_path / "r.dat")
        store.acquire()
        try:
            for b in blocks[1:]:
                store.append(b)
            resumed = store.load_chain(1, body_cache=2)
            resumed.body_source = store
            assert resumed.resident_body_bytes < chain.resident_body_bytes
            for bhash, expected in truth.items():
                assert resumed.block_filter(bhash) == expected
        finally:
            store.close()


# -- node-level wire service ----------------------------------------------


class TestNodeQueryPlane:
    def test_filter_scan_finds_every_touching_block(self):
        """The wallet flow end-to-end against a real node: sync by
        filter match and compare against a full-chain scan — superset
        with (almost surely) zero excess at the production FP rate."""

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                await fund(node, "alice", blocks=2)
                tag = node.chain.genesis.block_hash()
                for seq, rcpt in enumerate(("bob", "merchant", "bob")):
                    await node.submit_tx(
                        Transaction.transfer(
                            key_for("alice"),
                            account("bob") if rcpt == "bob" else rcpt,
                            2,
                            1,
                            seq,
                            chain=tag,
                        )
                    )
                await fund(node, "carol", blocks=1)
                watch = [account("bob").encode(), b"merchant"]
                headers, matches = await filter_scan(
                    "127.0.0.1", node.port, watch, DIFF
                )
                assert len(headers) == node.chain.height + 1
                truth = {
                    h
                    for h in range(1, node.chain.height + 1)
                    for tx in node.chain.get(
                        node.chain.main_hash_at(h)
                    ).txs
                    if tx.recipient.encode() in watch
                    or tx.sender.encode() in watch
                }
                got = {h for h, _ in matches}
                assert got == truth, (got, truth)
                # Every matched block's content really touches the watch
                # set (filter_scan drops FPs after inspection).
                for h, block in matches:
                    assert any(
                        tx.recipient.encode() in watch
                        or tx.sender.encode() in watch
                        for tx in block.txs
                    )
            finally:
                await node.stop()

        run(scenario())

    def test_query_counters_in_status_and_wire(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                await fund(node, "alice", blocks=1)
                await get_filters("127.0.0.1", node.port, 1, 10, DIFF)
                txid = node.chain.tip.txs[0].txid()
                await get_proof("127.0.0.1", node.port, txid, DIFF)
                await get_proof("127.0.0.1", node.port, txid, DIFF)
                q = node.status()["queries"]
                assert q["filters_served"] >= 1
                assert q["filter_bytes_served"] > 0
                assert q["proofs_served"] == 2
                assert q["proof_cache"]["hits"] >= 1
                # The wire status probe carries the same block.
                st = await get_status("127.0.0.1", node.port, DIFF)
                assert st["queries"]["proofs_served"] == 2
            finally:
                await node.stop()

        run(scenario())

    def test_getfilters_past_tip_is_empty_not_error(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                entries = await get_filters(
                    "127.0.0.1", node.port, 1000, 5, DIFF
                )
                assert entries == []
            finally:
                await node.stop()

        run(scenario())


# -- the read replica -----------------------------------------------------


class TestReplica:
    def test_replica_takes_no_writer_lock(self, tmp_path):
        """The acceptance property, literally: a replica attaches while
        the NODE holds the exclusive flock (which a second writer cannot
        take), and the node keeps appending underneath it."""

        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_config(store_path=store))
            await node.start()
            try:
                await fund(node, "alice", blocks=2)
                # A second WRITER is refused...
                other = ChainStore(store)
                with pytest.raises(RuntimeError):
                    other.acquire()
                # ...but the replica attaches fine, with the same view.
                view = ReplicaView(store, DIFF)
                try:
                    assert view.tip_height == node.chain.height
                    # And the node's writer lock is still intact after.
                    with pytest.raises(RuntimeError):
                        other.acquire()
                finally:
                    view.close()
            finally:
                await node.stop()

        run(scenario())

    def test_replica_serves_blocks_appended_after_attach(self, tmp_path):
        """Refresh path: blocks the node appends after the replica
        started are served correctly — proofs included — after one
        refresh tick."""

        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_config(store_path=store))
            await node.start()
            try:
                await fund(node, "alice", blocks=2)
                view = ReplicaView(store, DIFF)
                try:
                    h0 = view.tip_height
                    assert h0 == node.chain.height
                    # Append MORE: a transfer plus blocks.
                    tag = node.chain.genesis.block_hash()
                    tx = Transaction.transfer(
                        key_for("alice"), account("bob"), 2, 1, 0, chain=tag
                    )
                    await node.submit_tx(tx)
                    await fund(node, "carol", blocks=2)
                    assert node.chain.height > h0
                    view.refresh()
                    assert view.tip_height == node.chain.height
                    # A proof for the POST-attach transfer, from the
                    # replica, verifies against the chain parameters.
                    payload = view.proof_payload(tx.txid())
                    mtype, proof = protocol.decode(payload)
                    assert mtype is MsgType.PROOF and proof is not None
                    verify_tx_proof(proof, DIFF, tag, txid=tx.txid())
                    assert proof.tip_height == node.chain.height
                    # Headers served raw match the node's objects.
                    assert view.raw_header(proof.height) == (
                        proof.header.serialize()
                    )
                finally:
                    view.close()
            finally:
                await node.stop()

        run(scenario())

    def test_replica_rescans_when_the_inode_is_replaced(self, tmp_path):
        """A compaction/heal replaces the store file wholesale; the
        replica must notice (st_ino) and rebuild instead of serving
        offsets into a dead inode."""
        store = tmp_path / "chain.dat"
        chain = build_chain(4, difficulty=1)
        save_chain(chain, store)
        view = ReplicaView(store, 1)
        try:
            assert view.tip_height == 4
            longer = build_chain(7, difficulty=1)
            save_chain(longer, store)  # unlink + rewrite: new inode
            view.refresh()
            assert view.rescans == 1
            assert view.tip_height == 7
            assert view.raw_header(7) == longer.tip.header.serialize()
        finally:
            view.close()

    def test_replica_server_end_to_end(self, tmp_path):
        """The full client surface against a QueryPlaneServer: headers,
        filters, proofs, status, and the filter_scan wallet flow."""

        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_config(store_path=store))
            await node.start()
            srv = None
            try:
                await fund(node, "alice", blocks=2)
                tag = node.chain.genesis.block_hash()
                tx = Transaction.transfer(
                    key_for("alice"), account("bob"), 2, 1, 0, chain=tag
                )
                await node.submit_tx(tx)
                await fund(node, "alice", blocks=1)
                srv = await serve_replica(
                    store, DIFF, refresh_interval_s=0.05
                )
                headers = await get_headers("127.0.0.1", srv.port, DIFF)
                assert len(headers) == node.chain.height + 1
                proof = await get_proof(
                    "127.0.0.1", srv.port, tx.txid(), DIFF
                )
                verify_tx_proof(proof, DIFF, tag, txid=tx.txid())
                _, matches = await filter_scan(
                    "127.0.0.1", srv.port, [account("bob").encode()], DIFF
                )
                assert any(
                    t.txid() == tx.txid() for _, b in matches for t in b.txs
                )
                st = await get_status("127.0.0.1", srv.port, DIFF)
                assert st["role"] == "replica"
                assert st["height"] == node.chain.height
                assert st["queries"]["total"] >= 3
                # Mine MORE while the server runs; its refresh loop picks
                # the new tip up without a restart.
                await fund(node, "carol", blocks=1)
                assert await wait_until(
                    lambda: srv.view.tip_height == node.chain.height
                )
                proof = await get_proof(
                    "127.0.0.1", srv.port, tx.txid(), DIFF
                )
                assert proof.tip_height == node.chain.height
            finally:
                if srv is not None:
                    await srv.stop()
                await node.stop()

        run(scenario())

    def test_replica_admission_drops_query_floods(self, tmp_path):
        """Governor admission on the replica: a session streaming
        queries past its class budget sees frames dropped (fewer
        replies than requests), not unbounded service."""
        store = tmp_path / "chain.dat"
        save_chain(build_chain(3, difficulty=1), store)

        async def scenario():
            srv = await serve_replica(store, 1, refresh_interval_s=1.0)
            try:
                from p1_tpu.core.genesis import make_genesis
                from p1_tpu.node.protocol import Hello

                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", srv.port
                )
                ghash = make_genesis(1).block_hash()
                await protocol.write_frame(
                    writer, protocol.encode_hello(Hello(ghash, 0, 0, 5))
                )
                mtype, _ = protocol.decode(
                    await protocol.read_frame(reader)
                )
                assert mtype is MsgType.HELLO
                # 600 instant queries vs a 256-token burst at 32/s.
                n = 600
                for _ in range(n):
                    await protocol.write_frame(
                        writer, protocol.encode_getstatus()
                    )
                writer.write_eof()
                replies = 0
                try:
                    while True:
                        mt, _ = protocol.decode(
                            await asyncio.wait_for(
                                protocol.read_frame(reader), timeout=5
                            )
                        )
                        if mt is MsgType.STATUS:
                            replies += 1
                except (
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError,
                    TimeoutError,
                    ConnectionError,
                ):
                    pass
                assert replies < n
                assert srv.admission_dropped >= n - replies - 1
                writer.close()
            finally:
                await srv.stop()

        run(scenario())


# -- the commitment chain on the wire (round 21) --------------------------


def _paid_heights(chain, item: bytes) -> set:
    return {
        h
        for h in range(1, chain.height + 1)
        if any(
            tx.recipient.encode() == item or tx.sender.encode() == item
            for tx in chain.get(chain.main_hash_at(h)).txs
        )
    }


def _watch_target(chain, floor: int = 3):
    """A watched account the fixture pays at height >= ``floor`` (the
    tx mix varies with the hash seed; the tested property must not)."""
    for label in ("bob", "carol", "dave", "alice"):
        item = account(label).encode()
        paid = _paid_heights(chain, item)
        if paid and max(paid) >= floor:
            return item, paid
    raise AssertionError("fixture pays nobody late enough")


class TestCommitmentChainServing:
    def test_served_filter_headers_equal_local_derivation(self):
        """GETFILTERHEADERS against a live node: the served chain is
        exactly H(filter_hash || prev) over the node's own blocks,
        genesis-anchored — and a span past the committed tip is an
        honest refusal (short/empty), never a partial lie."""

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                await fund(node, "alice", blocks=3)
                tip = node.chain.height
                served = await get_filter_headers(
                    "127.0.0.1", node.port, 0, tip + 1, DIFF
                )
                assert len(served) == tip + 1
                prev = fmod.GENESIS_FILTER_HEADER
                for h in range(tip + 1):
                    fbytes = node.chain.block_filter(
                        node.chain.main_hash_at(h)
                    )
                    prev = fmod.next_filter_header(
                        fmod.filter_hash(fbytes), prev
                    )
                    assert served[h] == prev
                # Honest refusal past the tip.
                assert (
                    await get_filter_headers(
                        "127.0.0.1", node.port, tip + 1, 5, DIFF
                    )
                    == []
                )
                # A span only PARTLY committed is refused whole too —
                # all-or-nothing per request, never a partial answer.
                assert (
                    await get_filter_headers(
                        "127.0.0.1", node.port, tip, 2, DIFF
                    )
                    == []
                )
                exact = await get_filter_headers(
                    "127.0.0.1", node.port, tip, 1, DIFF
                )
                assert exact == [served[tip]]
            finally:
                await node.stop()

        run(scenario())

    def test_mid_scan_reorg_drops_stale_matches(self, tmp_path):
        """Satellite: the peer reorgs between the header sync and the
        filter page — served filters above the fork carry block hashes
        the skeleton never pinned.  The scan must stop at the
        divergence and drop the stale tail's matches.  Control arm
        first: the unforged replica DOES serve those tail matches, and
        the forged tail's filters are built to match the watch item —
        so a scan that believed unpinned filters would have reported
        them (the assertion cannot pass vacuously)."""
        store = tmp_path / "chain.dat"
        chain = build_chain(8, difficulty=1)
        item, paid = _watch_target(chain)
        k = max(paid)  # forge from the last paid height up
        save_chain(chain, store)

        async def scenario():
            srv = await serve_replica(store, 1, refresh_interval_s=60.0)
            try:
                _, control = await filter_scan(
                    "127.0.0.1", srv.port, [item], 1, fetch_blocks=False
                )
                control_heights = {h for h, _ in control}
                assert paid <= control_heights  # zero false negatives
                assert k in control_heights  # the tail match exists

                real_range = srv.view.filters_range

                def reorged_range(start, count):
                    out = []
                    for i, (bhash, f) in enumerate(real_range(start, count)):
                        h = start + i
                        if h >= k:
                            fake = bytes([h & 0xFF]) * 32
                            out.append(
                                (fake, fmod.encode_filter(fake, {item}))
                            )
                        else:
                            out.append((bhash, f))
                    return out

                srv.view.filters_range = reorged_range
                headers, matches = await filter_scan(
                    "127.0.0.1", srv.port, [item], 1, fetch_blocks=False
                )
                got = {h for h, _ in matches}
                assert got == {h for h in control_heights if h < k}
                # The pinned prefix is still commitment-verified and the
                # skeleton is intact — a partial answer, not a wreck.
                assert len(headers) == chain.height + 1
            finally:
                await srv.stop()

        run(scenario())

    def test_incoherent_forger_is_caught_by_its_own_commitments(
        self, tmp_path
    ):
        """Forged filters WITHOUT a recomputed commitment chain: the
        scan replays H(filter_hash || prev) over the served stream and
        the peer's own fheaders disprove it — CommitmentViolation with
        no second peer needed."""
        store = tmp_path / "chain.dat"
        chain = build_chain(5, difficulty=1)
        save_chain(chain, store)

        async def scenario():
            srv = await serve_replica(store, 1, refresh_interval_s=60.0)
            try:
                real_range = srv.view.filters_range

                def forged(start, count):
                    return [
                        (bhash, fmod.encode_filter(bhash, {b"swapped"}))
                        if start + i >= 3
                        else (bhash, f)
                        for i, (bhash, f) in enumerate(
                            real_range(start, count)
                        )
                    ]

                srv.view.filters_range = forged
                with pytest.raises(CommitmentViolation):
                    await filter_scan(
                        "127.0.0.1", srv.port, [b"whatever"], 1,
                        fetch_blocks=False,
                    )
            finally:
                await srv.stop()

        run(scenario())

    def test_coherent_forger_demoted_scan_fails_over(self, tmp_path):
        """The stronger liar recomputes its whole commitment chain over
        forged filters (self-consistent, locally unfalsifiable).  With
        one honest fallback the cross-check disagrees, the hash-pinned
        block at the divergence names the liar, and the scan fails over
        — returning every confirmation the liar tried to hide."""
        store = tmp_path / "chain.dat"
        chain = build_chain(8, difficulty=1)
        item, paid = _watch_target(chain)
        k = max(paid)
        save_chain(chain, store)

        async def scenario():
            liar = await serve_replica(store, 1, refresh_interval_s=60.0)
            honest = await serve_replica(store, 1, refresh_interval_s=60.0)
            try:
                # Recompute the liar's committed chain over forged
                # filters from k up — linkage verifies, content lies.
                entries = liar.view.filter_headers._entries
                forged = {}
                prev = entries[k - 1][1]
                for h in range(k, len(entries)):
                    bhash = entries[h][0]
                    fake = fmod.encode_filter(bhash, {b"elsewhere"})
                    forged[h] = fake
                    prev = fmod.next_filter_header(
                        fmod.filter_hash(fake), prev
                    )
                    entries[h] = (bhash, prev)
                real_range = liar.view.filters_range
                liar.view.filters_range = lambda start, count: [
                    (bhash, forged.get(start + i, f))
                    for i, (bhash, f) in enumerate(real_range(start, count))
                ]

                headers, matches = await filter_scan(
                    "127.0.0.1", liar.port, [item], 1,
                    fallback_peers=[("127.0.0.1", honest.port)],
                )
                got = {h for h, _ in matches}
                assert paid <= got  # k's hidden confirmation included
                for h, block in matches:
                    assert block.block_hash() == chain.main_hash_at(h)
            finally:
                await liar.stop()
                await honest.stop()

        run(scenario())


# -- soaks ----------------------------------------------------------------


async def _light_session(port: int, difficulty: int, watch: bytes) -> int:
    """One light client's visit: filters for the first 50 heights (a
    fresh session each time — connect, HELLO, query, disconnect)."""
    entries = await get_filters(
        "127.0.0.1", port, 1, 50, difficulty, timeout=60.0
    )
    return sum(
        1
        for bhash, f in entries
        if fmod.matches_any(f, bhash, [watch])
    )


class TestSoak:
    def test_mini_soak_replica_sessions_while_node_mines(self, tmp_path):
        """Tier-1-sized soak: 60 light-client sessions against a replica
        while the node keeps mining the same store."""

        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_config(store_path=store))
            await node.start()
            srv = None
            try:
                await fund(node, "alice", blocks=2)
                srv = await serve_replica(
                    store, DIFF, refresh_interval_s=0.05
                )
                node.miner_id = account("alice")
                node.start_mining()
                h0 = node.chain.height
                watch = account("alice").encode()
                results = await asyncio.gather(
                    *(
                        _light_session(srv.port, DIFF, watch)
                        for _ in range(60)
                    )
                )
                await node.stop_mining()
                assert len(results) == 60
                assert all(r >= 1 for r in results)  # alice mined: matches
                assert node.chain.height > h0  # mining never starved
                assert srv.sessions_total >= 60
            finally:
                if srv is not None:
                    await srv.stop()
                await node.stop()

        run(scenario())

    @pytest.mark.slow
    def test_light_client_soak_1000_sessions(self, tmp_path):
        """The acceptance soak: ~1000 concurrent light-client sessions
        through governor admission against the serving plane while the
        consensus node keeps mining and connecting blocks on the same
        store.  'Concurrent' is real: sessions launch in waves of 250
        live tasks, far past the node's own MAX_PEERS — the capacity
        the replica tier exists to add."""

        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_config(store_path=store))
            await node.start()
            srv = None
            try:
                await fund(node, "alice", blocks=3)
                srv = await serve_replica(
                    store, DIFF, refresh_interval_s=0.1
                )
                node.miner_id = account("alice")
                node.start_mining()
                h0 = node.chain.height
                watch = account("alice").encode()
                total = 1000
                done = 0
                for wave in range(4):
                    results = await asyncio.gather(
                        *(
                            _light_session(srv.port, DIFF, watch)
                            for _ in range(total // 4)
                        ),
                        return_exceptions=True,
                    )
                    failures = [
                        r for r in results if isinstance(r, BaseException)
                    ]
                    assert not failures, failures[:3]
                    done += len(results)
                await node.stop_mining()
                assert done == total
                # The consensus thread was never starved: the node kept
                # sealing and connecting blocks through the whole flood.
                assert node.chain.height >= h0 + 2
                assert srv.sessions_total >= total
                assert srv.view.tip_height > 0
            finally:
                if srv is not None:
                    await srv.stop()
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=600))


class TestImportHealthExtension:
    def test_new_modules_in_import_walk(self):
        """tier-0 coverage (tests/test_imports.py walks the package
        automatically; this pins the round-9 modules by name so a
        layout change cannot silently drop them)."""
        import importlib

        for name in (
            "p1_tpu.chain.filters",
            "p1_tpu.node.queryplane",
            "p1_tpu.node.subscriptions",
        ):
            importlib.import_module(name)


class TestRefreshLoopSupervision:
    def test_refresh_loop_crash_is_observed_and_respawned(
        self, tmp_path, caplog
    ):
        """Round 13 lost-task audit fix: the refresh loop handles
        expected per-tick faults (OSError/ValueError) itself, but an
        UNEXPECTED exception used to kill the task silently — the
        replica then served an ever-staler tip with no sign of trouble
        until stop().  The done-callback must log the wreck and respawn
        the loop while the server is still running."""
        import logging

        store = tmp_path / "chain.dat"
        save_chain(build_chain(3, difficulty=1), store)

        async def scenario():
            srv = await serve_replica(store, 1, refresh_interval_s=0.01)
            try:
                first = srv._refresh_task
                real_refresh = srv.view.refresh

                def boom():
                    raise RuntimeError("refresh bug")

                srv.view.refresh = boom
                assert await wait_until(lambda: first.done(), timeout=10)
                assert await wait_until(
                    lambda: srv._refresh_task is not None
                    and srv._refresh_task is not first,
                    timeout=10,
                )
                # The respawned loop is live: the next tick calls the
                # (healed) refresh again.
                healed = asyncio.Event()

                def heal():
                    healed.set()
                    return real_refresh()

                srv.view.refresh = heal
                assert await wait_until(healed.is_set, timeout=10)
            finally:
                await srv.stop()

        with caplog.at_level(logging.ERROR, logger="p1_tpu.queryplane"):
            run(scenario())
        assert any(
            "refresh loop died" in rec.getMessage()
            for rec in caplog.records
        ), [rec.getMessage() for rec in caplog.records]


class TestReplicaSegmented:
    """Round 18: replicas over the SEGMENTED store layout — per-segment
    mmaps, manifest-driven rescans, live attach across segment rolls,
    and the single-file -> segmented upgrade under a live view."""

    def test_live_attach_across_segment_rolls(self, tmp_path):
        """A replica attached to a live node's segmented store keeps
        serving through segment rolls: new segments appear via the
        manifest, sealed history is never rescanned wholesale."""

        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(
                _config(store_path=store, store_segment_bytes=600)
            )
            await node.start()
            try:
                await fund(node, "alice", blocks=2)
                view = ReplicaView(store, DIFF)
                try:
                    assert view.tip_height == node.chain.height
                    rescans0 = view.rescans
                    tag = node.chain.genesis.block_hash()
                    tx = Transaction.transfer(
                        key_for("alice"), account("bob"), 2, 1, 0, chain=tag
                    )
                    await node.submit_tx(tx)
                    await fund(node, "carol", blocks=4)
                    # The store really rolled (that's the point).
                    assert len(node.store.segments) > 1
                    view.refresh()
                    assert view.tip_height == node.chain.height
                    # Incremental: rolls are appends, not rescans.
                    assert view.rescans == rescans0
                    # A proof spanning the roll verifies end to end.
                    payload = view.proof_payload(tx.txid())
                    mtype, proof = protocol.decode(payload)
                    assert mtype is MsgType.PROOF and proof is not None
                    verify_tx_proof(proof, DIFF, tag, txid=tx.txid())
                    # Raw headers serve from whichever segment holds
                    # them.
                    for h in range(view.tip_height + 1):
                        assert view.raw_header(h) is not None
                finally:
                    view.close()
            finally:
                await node.stop()

        run(scenario())

    def test_live_upgrade_single_to_segmented(self, tmp_path):
        """The lossless upgrade under a live view: a replica attached
        to a single-file store notices the layout change (the path now
        holds a manifest) and rebuilds cleanly."""

        async def scenario():
            store = str(tmp_path / "chain.dat")
            node = Node(_config(store_path=store))
            await node.start()
            try:
                await fund(node, "alice", blocks=2)
            finally:
                await node.stop()
            view = ReplicaView(store, DIFF)
            try:
                h0 = view.tip_height
                assert h0 >= 2
                # Restart segmented: the writer upgrade hard-links the
                # old records into seg00000 and replaces the path with
                # a manifest.
                node2 = Node(
                    _config(store_path=store, store_segment_bytes=600)
                )
                await node2.start()
                try:
                    await fund(node2, "carol", blocks=2)
                    view.refresh()
                    assert view.rescans >= 1  # layout change detected
                    assert view.tip_height == node2.chain.height
                finally:
                    await node2.stop()
            finally:
                view.close()

        run(scenario())

    def test_pruned_store_refused(self, tmp_path):
        """A replica must not silently serve a store whose deep bodies
        are gone — pruned manifests are refused with a clear error."""
        from p1_tpu.chain import SegmentedStore
        from p1_tpu.node.testing import make_blocks

        path = tmp_path / "chain.dat"
        blocks = make_blocks(6, difficulty=DIFF)
        store = SegmentedStore(path, segment_bytes=600)
        for h, b in enumerate(blocks):
            store.append(b, height=h)
        store.prune_below(store.segments[0].max_height + 1)
        store.close()
        with pytest.raises(ValueError, match="pruned store"):
            ReplicaView(path, DIFF)
