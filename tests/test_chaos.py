"""The chaos plane (node/chaos.py + SimNet crash semantics).

Round 11's acceptance surface:

- crash/recover primitives: abrupt death (severed links, no shutdown
  hooks, torn in-flight append, stale mempool checkpoint) vs graceful
  restart — the equivalence/divergence pair;
- mempool crash-consistency: a crash-restart never resurrects a tx the
  surviving chain mined, including recovery onto a REORGED tip;
- the `_store_recovery_loop` ENOSPC degrade→serve-only→recover e2e on
  SimNet at PRODUCTION backoff deadlines in milliseconds of wall time
  (the socket variant in test_storefault.py stays as slow smoke);
- determinism: one seed ⇒ byte-identical chaos trace, including across
  crash/recover cycles (the cross-process half lives in test_cli.py);
- the bounded tier-1 invariant sweep (~30 schedules) and the ≥200
  slow sweep;
- the shrinker proof: a deliberately injected recovery bug minimized
  to ≤5 events, repro artifact round-trip;
- named regressions for the two REAL bugs the first sweeps found:
  the quarantined-log-head recovery brick (store.py ``orphans_ok``)
  and the post-catch-up announce skipping the behind peer (node.py
  ``_announce_tip``).
"""

import asyncio
import json

import pytest

from p1_tpu.chain.store import ChainStore
from p1_tpu.node import chaos
from p1_tpu.node.netsim import SimNet

DIFF = 8


def _tx(net, wallet, payee, node, amount=1, fee=1):
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.tx import Transaction

    acct = wallet.account
    seq = node.mempool.pending_next_seq(acct, node.chain.nonce(acct))
    return Transaction.transfer(
        wallet, payee.account, amount, fee, seq, chain=genesis_hash(DIFF)
    )


def _wallets(seed=0):
    from p1_tpu.core.keys import Keypair

    return (
        Keypair.from_seed_text(f"p1-chaos-test-{seed}"),
        Keypair.from_seed_text(f"p1-chaos-test-{seed}-payee"),
    )


class TestCrashRecover:
    """SimNet.crash_node / recover_node — the crash primitives."""

    def test_crash_tears_append_and_recovery_truncates(self, tmp_path):
        net = SimNet(seed=3, difficulty=DIFF, store_dir=tmp_path)

        async def main():
            a = await net.add_node()
            b = await net.add_node(peers=[net.host_name(0)])
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            await net.mine_on(a, spacing_s=1.0)
            assert await net.run_until(
                lambda: b.chain.height == 1, 30, wall_limit_s=30
            )
            host_b = net.host_name(1)
            dead = await net.crash_node(host_b, torn=37)
            # The torn in-flight record reached the disk: the scan sees
            # a torn tail where a graceful stop would leave none.
            scan = ChainStore.scan(
                (tmp_path / f"{host_b}.dat").read_bytes()
            )
            assert scan.torn_tail is not None
            assert len(scan.spans) == 1  # the acknowledged block survived
            # The wire died too: the survivor's peer session is reaped.
            assert await net.run_until(
                lambda: a.peer_count() == 0, 30, wall_limit_s=30
            )
            await net.mine_on(a, spacing_s=1.0)
            b2 = await net.recover_node(host_b)
            # Same seed-derived identity, resume truncated the tear.
            assert b2.instance_nonce == dead.instance_nonce
            assert b2.store.healed["truncated_bytes"] == 37
            assert b2.chain.height == 1  # the acknowledged block resumed
            assert await net.run_until(
                lambda: b2.chain.height == 2, 60, wall_limit_s=30
            )
            assert net.converged() and net.ledger_conserved()
            await net.stop_all()

        net.run(main())

    def test_restart_vs_crash_mempool_checkpoint_pair(self, tmp_path):
        """The equivalence/divergence pair: a GRACEFUL restart persists
        the pending pool through its shutdown checkpoint; a crash loses
        everything since the last periodic one — and recovery tolerates
        that (chain intact, identity intact, pool empty)."""
        net = SimNet(seed=4, difficulty=DIFF, store_dir=tmp_path)
        wallet, payee = _wallets(4)

        async def main():
            a = await net.add_node(miner_id=wallet.account)
            b = await net.add_node(peers=[net.host_name(0)])
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            for _ in range(2):
                await net.mine_on(a, spacing_s=1.0)
            assert await net.run_until(
                lambda: b.chain.height == 2, 30, wall_limit_s=30
            )
            host_b = net.host_name(1)

            # Graceful: the shutdown save persists an un-checkpointed
            # admission (no 30 s housekeeping tick has run yet).
            tx1 = _tx(net, wallet, payee, b)
            assert b.mempool.add(tx1)
            await net.stop_node(host_b)
            b2 = await net.restart_node(host_b)
            assert tx1.txid() in b2.mempool

            # Crash: the same-shaped admission dies with the process —
            # the checkpoint on disk predates it.
            tx2 = _tx(net, wallet, payee, b2)
            assert b2.mempool.add(tx2)
            await net.crash_node(host_b)
            b3 = await net.recover_node(host_b)
            assert tx2.txid() not in b3.mempool  # lost, tolerated
            assert tx1.txid() in b3.mempool  # checkpointed at stop()
            assert b3.chain.height == 2  # acknowledged blocks survive
            assert b3.instance_nonce == b2.instance_nonce
            await net.stop_all()

        net.run(main())


class TestMempoolCrashConsistency:
    """A crash-restart never resurrects a transaction the surviving
    chain mined — driven through crash_node(), not graceful shutdown."""

    def test_checkpointed_tx_mined_while_down_is_not_resurrected(
        self, tmp_path
    ):
        net = SimNet(seed=5, difficulty=DIFF, store_dir=tmp_path)
        wallet, payee = _wallets(5)

        async def main():
            a = await net.add_node(miner_id=wallet.account)
            b = await net.add_node(peers=[net.host_name(0)])
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            for _ in range(2):
                await net.mine_on(a, spacing_s=1.0)
            assert await net.run_until(
                lambda: b.chain.height == 2, 30, wall_limit_s=30
            )
            tx = _tx(net, wallet, payee, b)
            await b.submit_tx(tx)
            assert await net.run_until(
                lambda: tx.txid() in a.mempool, 30, wall_limit_s=30
            )
            # Let B's periodic housekeeping checkpoint the pool (30
            # virtual seconds), so the on-disk file HOLDS the tx.
            await asyncio.sleep(31.0)
            host_b = net.host_name(1)
            await net.crash_node(host_b)
            # The surviving chain mines the tx while B is down.
            mined = await net.mine_on(a, spacing_s=1.0)
            assert any(t.txid() == tx.txid() for t in mined.txs)
            b2 = await net.recover_node(host_b)
            # Immediately after reboot the restored tx may look valid
            # (B's chain predates the mining block) — the invariant is
            # about the SETTLED state: once B catches up, the mined tx
            # must be gone from its pool.
            assert await net.run_until(
                lambda: b2.chain.height == 3, 60, wall_limit_s=30
            )
            assert tx.txid() in b2.chain._tx_index
            assert tx.txid() not in b2.mempool
            await net.stop_all()

        net.run(main())

    def test_recovery_onto_a_reorged_tip_still_evicts(self, tmp_path):
        """The hard case: B holds the tx MINED (block X); B crashes;
        the rest of the mesh reorgs past X onto a longer branch that
        mined the same tx elsewhere.  B recovers onto its stale chain,
        reloads a checkpoint that still lists the tx, then reorgs — the
        pool must not end up resurrecting it."""
        net = SimNet(seed=6, difficulty=DIFF, store_dir=tmp_path)
        wallet, payee = _wallets(6)
        h = net.host_name

        async def main():
            a = await net.add_node(miner_id=wallet.account)
            b = await net.add_node(peers=[h(0)])
            c = await net.add_node(peers=[h(0), h(1)])
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            for _ in range(2):
                await net.mine_on(a, spacing_s=1.0)
            assert await net.run_until(
                lambda: min(net.heights()) == 2 and net.converged(),
                30,
                wall_limit_s=30,
            )
            tx = _tx(net, wallet, payee, b)
            await b.submit_tx(tx)
            assert await net.run_until(
                lambda: tx.txid() in a.mempool and tx.txid() in c.mempool,
                30,
                wall_limit_s=30,
            )
            await asyncio.sleep(31.0)  # B checkpoints the pending tx
            # Partition C away; A mines block X (contains the tx); B
            # holds X at its tip and crashes there.
            net.net.partition([h(0), h(1)], [h(2)])
            x = await net.mine_on(a, spacing_s=1.0)
            assert any(t.txid() == tx.txid() for t in x.txs)
            assert await net.run_until(
                lambda: b.chain.height == 3, 30, wall_limit_s=30
            )
            await net.crash_node(h(1))
            # C's side mines LONGER — its first block also carries the
            # tx (it was gossiped pre-partition).
            y1 = await net.mine_on(c, spacing_s=1.0)
            assert any(t.txid() == tx.txid() for t in y1.txs)
            await net.mine_on(c, spacing_s=1.0)
            net.net.heal()
            assert await net.run_until(
                lambda: a.chain.tip_hash == c.chain.tip_hash,
                60,
                wall_limit_s=30,
            )  # A reorged off X onto C's branch
            b2 = await net.recover_node(h(1))
            assert b2.chain.height == 3  # rebooted onto the STALE branch
            assert await net.run_until(
                lambda: b2.chain.tip_hash == c.chain.tip_hash,
                90,
                wall_limit_s=30,
            )
            assert tx.txid() in b2.chain._tx_index
            assert tx.txid() not in b2.mempool
            assert net.ledger_conserved()
            await net.stop_all()

        net.run(main())


class TestStoreRecoverySim:
    """The ENOSPC degrade→serve-only→recover e2e from test_storefault,
    on SimNet at PRODUCTION backoff deadlines (0.25 s base, 5 s cap —
    the defaults) in milliseconds of wall time.  The socket original
    stays as slow smoke, same migration pattern as the round-10
    stall-failover port."""

    def test_enospc_degrades_serves_and_recovers_virtual_time(
        self, tmp_path
    ):
        from p1_tpu.chain.testing import StoreFaultPlan
        from p1_tpu.node import protocol
        from p1_tpu.node.protocol import MsgType

        net = SimNet(seed=7, difficulty=DIFF, store_dir=tmp_path)

        async def main():
            a = await net.add_node()
            for _ in range(10):
                await net.mine_on(a)
            # B joins and IBDs from A; write #1 is the magic, so the
            # 4th record append hits persistent ENOSPC mid-sync.
            b = await net.add_node(
                peers=[net.host_name(0)],
                store_plan=StoreFaultPlan(fail_writes_from=5),
            )
            host_b = net.host_name(1)
            assert await net.run_until(
                lambda: b._store_degraded, 60, wall_limit_s=30
            )
            status = b.status()["storage"]
            assert status["degraded"] is True and status["errors"] >= 1
            # The delivering session survives the disk fault.
            assert b.peer_count() >= 1
            frozen = b.chain.height
            assert frozen < 10
            # Serve-only: a light client still gets headers over the
            # sim transport.
            reader, writer = await net.net.host("client").connect(
                host_b, 9444
            )
            await protocol.write_frame(
                writer,
                protocol.encode_hello(
                    protocol.Hello(
                        b.chain.genesis.block_hash(), 0, 0, 0
                    )
                ),
            )
            await protocol.read_frame(reader)  # B's HELLO
            await protocol.write_frame(writer, protocol.encode_getheaders([]))
            while True:
                mtype, body = protocol.decode(
                    await protocol.read_frame(reader)
                )
                if mtype is MsgType.HEADERS:
                    break
            assert len(body) == frozen + 1
            writer.close()
            # Space comes back; the recovery loop (production jittered
            # backoff, virtual time) flushes, recovers, backfills.
            net.stores[host_b].clear_faults()
            assert await net.run_until(
                lambda: not b._store_degraded, 60, wall_limit_s=30
            )
            assert b.metrics.store_recoveries == 1
            assert await net.run_until(
                lambda: b.chain.height == 10, 120, wall_limit_s=30
            )
            await net.stop_all()
            # Everything accepted is durably on disk, in order.
            store = ChainStore(tmp_path / f"{host_b}.dat")
            assert len(store.load_blocks()) == 10

        net.run(main())


class TestDeterminism:
    """One seed ⇒ one byte-identical run, crash/recover included."""

    def test_same_seed_same_report_across_crashes(self):
        # Seed 0's generated schedule carries two crashes (and the
        # epilogue recovers), so the digest covers crash/recover too.
        evs = chaos.generate_schedule(0, 5, 10)
        assert sum(1 for e in evs if e["op"] == "crash") >= 1
        a = chaos.run_chaos(0, nodes=5, n_events=10)
        b = chaos.run_chaos(0, nodes=5, n_events=10)
        a.pop("wall_s")
        b.pop("wall_s")
        assert a["ok"] and a == b

    def test_different_seed_different_trace(self):
        a = chaos.run_chaos(0, nodes=5, n_events=10)
        b = chaos.run_chaos(1, nodes=5, n_events=10)
        assert a["trace_digest"] != b["trace_digest"]

    def test_schedules_are_json_round_trippable(self):
        evs = chaos.generate_schedule(9, 6, 16)
        assert json.loads(json.dumps(evs)) == evs
        assert evs == chaos.generate_schedule(9, 6, 16)


@pytest.mark.chaos
class TestInvariantSweep:
    """The randomized search itself: every seed's schedule must hold
    every invariant.  Tier-1 carries the bounded sweep; the wide one
    rides the slow set (both green is the acceptance bar)."""

    def test_bounded_tier1_sweep_30_schedules(self):
        # Round 19: the tier-1 sweep runs STAGED (lane workers on) —
        # the schedule corpus now carries stage_crash events, and the
        # sweep must prove the pipeline's respawn-and-retry under every
        # other fault family, not just in isolation.  Lane jobs stay
        # synchronous under the virtual loop, so this flips behavior,
        # not determinism (the digest pair test pins that).
        failures = []
        for seed in range(30):
            report = chaos.run_chaos(
                seed, nodes=5, n_events=10, pipeline_workers=1
            )
            if not report["ok"]:
                failures.append((seed, report["violations"]))
        assert not failures, failures

    def test_recon_sweep_holds_every_invariant(self):
        """Round 23: the same schedule corpus with set-reconciliation
        relay ON mesh-wide (recon=True — no deployment table, recon
        from block 0).  Crashes, partitions, and reorgs land on nodes
        whose tx relay is sketch rounds + deferred GETTX fetches, and
        every invariant (convergence, conservation, mempool checkpoint
        consistency) must hold exactly as under flood.  Opt-in kwarg,
        so the seed-stable digest corpus above is untouched."""
        failures = []
        for seed in range(10):
            report = chaos.run_chaos(
                seed, nodes=5, n_events=10, recon=True
            )
            if not report["ok"]:
                failures.append((seed, report["violations"]))
        assert not failures, failures

    @pytest.mark.slow
    def test_wide_sweep_200_schedules(self):
        failures = []
        for seed in range(200):
            report = chaos.run_chaos(seed, nodes=6, n_events=14)
            if not report["ok"]:
                failures.append((seed, report["violations"]))
        assert not failures, failures


@pytest.mark.chaos
class TestShrinker:
    def test_ddmin_minimizes_synthetic_predicate(self):
        # Pure-logic check, no sim: the violation needs events 3 AND 7.
        events = [{"at": float(i), "op": "mine", "node": i} for i in range(10)]

        def fails(subset):
            ids = {e["node"] for e in subset}
            return 3 in ids and 7 in ids

        shrunk, runs = chaos.shrink_schedule(events, fails)
        assert sorted(e["node"] for e in shrunk) == [3, 7]
        assert runs <= 60

    def test_injected_bug_shrinks_to_at_most_5_events_and_reproduces(
        self, tmp_path
    ):
        """The acceptance proof: a deliberately seeded recovery bug
        (test-only flag) is found by the sweep, minimized to ≤5 events,
        and its artifact reproduces through the same replay path
        `p1 chaos --repro` uses."""
        # Sweep-pick the witness seed the way the real pipeline would:
        # the first schedule the injected bug actually violates (having
        # a crash op is necessary but not sufficient — the victim also
        # needs a post-recover append inside the horizon, and the op
        # corpus drifts as fault families are added).
        for seed in range(20):
            events = chaos.generate_schedule(seed, 5, 10)
            report = chaos.run_chaos(
                seed, nodes=5, events=events, inject_bug="relapse-disk"
            )
            if not report["ok"]:
                break
        assert not report["ok"]
        target = report["violations"][0]["invariant"]

        def reproduces(subset):
            rep = chaos.run_chaos(
                seed, nodes=5, events=subset, inject_bug="relapse-disk"
            )
            return any(v["invariant"] == target for v in rep["violations"])

        shrunk, _runs = chaos.shrink_schedule(events, reproduces)
        assert len(shrunk) <= 5
        final = chaos.run_chaos(
            seed, nodes=5, events=shrunk, inject_bug="relapse-disk"
        )
        path = tmp_path / "repro.json"
        chaos.write_repro(
            path,
            final,
            shrunk,
            seed=seed,
            nodes=5,
            difficulty=8,
            inject_bug="relapse-disk",
        )
        rep, artifact = chaos.run_repro(path)
        assert {v["invariant"] for v in rep["violations"]} >= {target}
        assert rep["trace_digest"] == artifact["expected_trace_digest"]

    def test_deaf_recover_bug_isolates_an_undialed_node(self):
        """The second injected bug class: a reboot that loses its peer
        list strands a node nobody dials (the backbone's last host) —
        violated with the bug, clean without it."""
        events = [
            {"at": 2.0, "op": "crash", "node": 4, "torn": 0},
            {"at": 4.0, "op": "mine", "node": 0},
        ]
        bugged = chaos.run_chaos(
            3, nodes=5, events=events, inject_bug="deaf-recover"
        )
        assert any(
            v["invariant"] == "converge" for v in bugged["violations"]
        )
        assert chaos.run_chaos(3, nodes=5, events=events)["ok"]

    def test_repro_rejects_foreign_files(self, tmp_path):
        bad = tmp_path / "nope.json"
        bad.write_text("definitely not json{")
        with pytest.raises(ValueError):
            chaos.run_repro(bad)
        wrong = tmp_path / "wrong.json"
        wrong.write_text(json.dumps({"format": "something-else"}))
        with pytest.raises(ValueError):
            chaos.run_repro(wrong)


#: The literal schedule (seed 30 @ 6 nodes / 14 events as first
#: generated) that exposed the announce-skip liveness bug: node 1
#: crashes and rots, reboots BEHIND both the mesh and node 0 (whose
#: only link node 1 is), syncs through two interleaved episodes, and
#: the one-shot post-catch-up tip announce — consumed on the BEHIND
#: peer's quiesce — used to skip exactly that peer.  Pinned literally
#: so generator changes can never un-pin the regression.
REGRESSION_ANNOUNCE_SKIP = [
    {"at": 2.345, "op": "tx", "amount": 4, "fee": 1},
    {"at": 3.007, "op": "mine", "node": 5},
    {"at": 3.271, "op": "mine", "node": 5},
    {"at": 5.218, "op": "crash", "node": 1, "torn": 2459},
    {"at": 6.237, "op": "disk_fail", "node": 1, "errno": 28},
    {"at": 7.404, "op": "tx", "amount": 3, "fee": 0},
    {"at": 13.286, "op": "mine", "node": 5},
    {"at": 13.494, "op": "mine", "node": 2},
    {"at": 16.254, "op": "mine", "node": 4},
    {"at": 19.382, "op": "disk_fail", "node": 2, "errno": 5},
    {"at": 20.045, "op": "hostile", "node": 3, "fault": "swallow", "height": 6},
    {"at": 21.597, "op": "corrupt", "node": 1, "offset": 383762},
    {"at": 25.709, "op": "partition", "frac": 0.7},
    {"at": 28.808, "op": "disk_fail", "node": 0, "errno": 5},
]


@pytest.mark.chaos
class TestRegressions:
    """Named regression schedules for the REAL bugs the first chaos
    sweeps surfaced (both fixed this round)."""

    def test_announce_skip_schedule_seed30(self):
        """node.py: the post-catch-up tip announce must not skip the
        quiescing peer — with interleaved sync episodes it can be the
        one node that still needs the push (details on the pinned
        constant above)."""
        report = chaos.run_chaos(
            30, nodes=6, events=REGRESSION_ANNOUNCE_SKIP
        )
        assert report["ok"], report["violations"]

    def test_quarantined_log_head_does_not_brick_recovery(self, tmp_path):
        """store.py orphans_ok: a crashed node whose heal quarantines
        the FIRST record used to refuse to boot ("records do not
        connect to genesis") even though the whole suffix was intact
        and the mesh held the missing block — recovery must boot, park
        the survivors as orphans, and resync."""
        net = SimNet(seed=11, difficulty=DIFF, store_dir=tmp_path)

        async def main():
            a = await net.add_node()
            b = await net.add_node(peers=[net.host_name(0)])
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            for _ in range(3):
                await net.mine_on(a, spacing_s=1.0)
            assert await net.run_until(
                lambda: b.chain.height == 3, 30, wall_limit_s=30
            )
            host_b = net.host_name(1)
            await net.crash_node(host_b)
            # Rot one byte inside the FIRST record's payload.
            path = tmp_path / f"{host_b}.dat"
            data = bytearray(path.read_bytes())
            off, _n = ChainStore.scan(bytes(data)).spans[0]
            data[off + 2] ^= 0x40
            path.write_bytes(bytes(data))
            b2 = await net.recover_node(host_b)  # used to raise here
            assert b2.store.healed["quarantined_records"] == 1
            assert b2.chain.height == 0  # nothing connects... yet
            await net.mine_on(a, spacing_s=1.0)
            assert await net.run_until(
                lambda: b2.chain.height == 4, 90, wall_limit_s=30
            )
            assert net.converged() and net.ledger_conserved()
            await net.stop_all()

        net.run(main())


class TestLongevitySoak:
    """Round-17 tentpole (c): multi-day virtual-time soaks with leak
    invariants at quiesce.  Tier-1 carries a quarter-day soak (the
    whole machinery: soak schedule shape, probes, leak checks, RSS
    gauge); the slow set carries the ≥1-virtual-week acceptance run."""

    def test_quarter_day_soak_green_with_probes(self):
        r = chaos.longevity_soak(seed=0, nodes=4, days=0.25)
        assert r["ok"], r["violations"]
        assert r["scenario"] == "soak"
        assert r["days_virtual"] == pytest.approx(0.25, abs=0.01)
        # Both leak probes fired and their gauges rode into the report.
        assert r["probes"] == 2
        assert r["leak_gauges"]["mid"] and r["leak_gauges"]["end"]
        for gauges in r["leak_gauges"]["end"].values():
            assert {"tasks", "banned", "sig_cache", "retry_counters"} <= set(
                gauges
            )
        # The RSS gauge measured something real.
        assert r["rss_mb"] is not None and r["rss_mb"] > 0
        assert r["repro"] == "p1 sim soak --seed 0"

    def test_soak_is_deterministic(self):
        a = chaos.longevity_soak(seed=2, nodes=4, days=0.2)
        b = chaos.longevity_soak(seed=2, nodes=4, days=0.2)
        assert a["trace_digest"] == b["trace_digest"]
        # rss/wall are the host-side fields; everything else replays.
        for k in a:
            if k not in ("wall_s", "rss_mb", "leak_gauges"):
                assert a[k] == b[k], k

    def test_rss_bound_is_load_bearing(self):
        r = chaos.longevity_soak(
            seed=0, nodes=4, days=0.2, rss_bound_mb=0.001
        )
        assert not r["ok"]
        assert any(v["invariant"] == "rss" for v in r["violations"])

    def test_soak_schedule_pairs_every_fault_with_a_clearer(self):
        events = chaos.generate_soak_schedule(
            seed=5, n_nodes=5, horizon_vs=7 * chaos.DAY_VS,
            fault_clusters=28, blocks=336,
        )
        assert [e["at"] for e in events] == sorted(e["at"] for e in events)
        ops = [e["op"] for e in events]
        # Pairing: nothing disruptive outlives its envelope.
        assert ops.count("crash") == ops.count("recover")
        assert ops.count("partition") == ops.count("heal")
        assert ops.count("disk_fail") == ops.count("disk_heal")
        assert ops.count("slow_link") == ops.count("restore_link")
        assert ops.count("hostile") + ops.count("flood") == ops.count("calm")
        assert ops.count("probe") == 2
        # And the horizon really is the week asked for.
        assert events[-1]["at"] >= 7 * chaos.DAY_VS - 1.0

    @pytest.mark.slow
    def test_one_virtual_week_acceptance_run(self):
        """ISSUE 14 acceptance: ≥1 virtual week green, leak invariants
        (RSS gauge, ban tables, retry counters, cache bounds) asserted
        at quiesce."""
        r = chaos.longevity_soak(seed=0)
        assert r["ok"], r["violations"]
        assert r["days_virtual"] >= 7.0
        assert r["probes"] == 2
        assert r["crashes"] >= 1 and r["recoveries"] == r["crashes"]
        # ~12,000x time compression makes the week a sub-two-minute
        # test; the wall guard is the regression tripwire.
        assert r["wall_s"] < 300.0


@pytest.mark.chaos
class TestMaintenanceChaos:
    """Round-20 fault families: the always-on maintenance plane under
    chaos.  The generated corpus must carry all four ops (so the sweeps
    exercise them organically), a crafted schedule must FIRE all four
    against live invariants, and the kill-9 mid-rebase must reboot as
    an ordinary un-rebased node."""

    FAMILIES = (
        "rebase",
        "seal_sidecar_crash",
        "online_prune",
        "online_compact_crash",
    )

    def test_generated_corpus_carries_all_four_families(self):
        ops: set[str] = set()
        crash_flags: set[bool] = set()
        for seed in range(40):
            for ev in chaos.generate_schedule(seed, 5, 10):
                ops.add(ev["op"])
                if ev["op"] == "rebase":
                    crash_flags.add(ev["crash"])
        for family in self.FAMILIES:
            assert family in ops, f"{family} never generated in 40 seeds"
        # Both rebase variants appear: the clean live re-base and the
        # kill-9 between the store half and the in-RAM half.
        assert crash_flags == {True, False}

    def test_crafted_schedule_fires_all_four_families(self, monkeypatch):
        """A hand-laid schedule where every family FIRES (not degrades
        to a refusal no-op), proven by spying the runner's trace
        records; the run itself must hold every invariant."""
        t = [0.0]

        def ev(**kw):
            t[0] += 0.8
            return {"at": round(t[0], 3), **kw}

        events = (
            # Enough depth for a checkpointed rebase target and a
            # pruneable sealed segment (snapshot cadence is 4).
            [ev(op="mine", node=0) for _ in range(14)]
            + [
                # Forced seal with the .sdx write failing: tolerated,
                # healed, recorded.
                ev(op="seal_sidecar_crash", node=0),
                # Live re-base (rolls + spills sidecars, then advances
                # the in-RAM base) on the mining node.
                ev(op="rebase", node=0, keep=2, crash=False),
            ]
            + [ev(op="mine", node=0) for _ in range(4)]
            + [
                # The rebase's roll sealed everything below the new
                # checkpoint: this prune MUST discard segments.
                ev(op="online_prune", node=0, keep=2),
                # Planner death mid-compaction on a peer that keeps
                # serving.
                ev(op="online_compact_crash", node=1),
            ]
            + [ev(op="mine", node=1) for _ in range(2)]
        )
        recorded: list[tuple] = []
        orig = chaos._ChaosRunner._record

        def spy(self, *fields):
            recorded.append(fields)
            orig(self, *fields)

        monkeypatch.setattr(chaos._ChaosRunner, "_record", spy)
        report = chaos.run_chaos(0, nodes=3, events=events)
        assert report["ok"], report["violations"]
        fired = {r[0] for r in recorded}
        for family in self.FAMILIES:
            assert family in fired, (family, sorted(fired))
        # online_prune only records when segments actually dropped;
        # the count rode into the trace.
        prune = next(r for r in recorded if r[0] == "online_prune")
        assert prune[2] >= 1

    def test_kill9_mid_rebase_reboots_unrebased(self, monkeypatch):
        """The crash contract of leg (a): the durable store half (seal
        + sidecar spill) lands, the process dies before the in-RAM
        rebase — reboot must come back consistent (fsck clean, exact
        prefix), i.e. the kill-9 costs the rebase, never the chain."""
        t = [0.0]

        def ev(**kw):
            t[0] += 0.8
            return {"at": round(t[0], 3), **kw}

        events = (
            [ev(op="mine", node=0) for _ in range(10)]
            + [
                ev(op="rebase", node=0, keep=2, crash=True),
                ev(op="recover", node=0),
            ]
            + [ev(op="mine", node=0) for _ in range(2)]
        )
        recorded: list[tuple] = []
        orig = chaos._ChaosRunner._record

        def spy(self, *fields):
            recorded.append(fields)
            orig(self, *fields)

        monkeypatch.setattr(chaos._ChaosRunner, "_record", spy)
        report = chaos.run_chaos(3, nodes=3, events=events)
        assert report["ok"], report["violations"]
        assert report["crashes"] == 1 and report["recoveries"] == 1
        assert any(r[0] == "rebase_crash" for r in recorded)
        # The rebase itself never happened — no "rebase" record, so the
        # reboot was an ordinary un-rebased node with spare sidecars.
        assert not any(r[0] == "rebase" for r in recorded)

    def test_soak_schedule_carries_maintenance_clusters(self):
        """generate_soak_schedule's `maintenance` cluster kind: a week
        of recurring self-maintenance must appear in the soak corpus —
        sidecar failure at a seal, live re-base, and exactly one prune
        (someone keeps the archive) with compaction faults thereafter."""
        ops: list[str] = []
        for seed in range(8):
            events = chaos.generate_soak_schedule(
                seed=seed, n_nodes=5, horizon_vs=7 * chaos.DAY_VS,
                fault_clusters=28, blocks=336,
            )
            ops.extend(e["op"] for e in events)
            # At most one online_prune per schedule: the archive rule.
            assert ops.count("online_prune") <= len(ops)
            assert (
                sum(1 for e in events if e["op"] == "online_prune") <= 1
            )
        for family in ("seal_sidecar_crash", "rebase", "online_prune",
                       "online_compact_crash"):
            assert family in ops, f"{family} absent from 8 soak seeds"


@pytest.mark.chaos
class TestSubscriptionChaos:
    """Round-21 tentpole (c): the wallet push plane under chaos.  The
    generated corpus must carry the subscription ops (so the sweeps
    exercise watchers organically), crafted schedules must prove the
    two headline behaviors — a watcher rides out the death of its
    serving replica by failing over with a resume cursor, and a
    SUBSCRIBE flood neither wedges the victim nor starves an honest
    watcher — and the mute-push injectable bug proves the push-missed
    invariant has teeth."""

    @staticmethod
    def _ev_clock():
        t = [0.0]

        def ev(**kw):
            t[0] += 0.8
            return {"at": round(t[0], 3), **kw}

        return ev

    def test_generated_corpus_carries_subscription_ops(self):
        ops: set[str] = set()
        for seed in range(40):
            for ev in chaos.generate_schedule(seed, 5, 10):
                ops.add(ev["op"])
        for op in ("watch_start", "watch_stop", "sub_flood"):
            assert op in ops, f"{op} never generated in 40 seeds"

    def test_crafted_watcher_survives_serving_node_crash_mid_push(self):
        """The tentpole failover contract, end to end on SimNet: a
        wallet watches node 1, node 1 dies abruptly mid-stream, blocks
        keep paying the watched account on the survivors — the watch
        must fail over (resume cursor, commitment-verified) and arrive
        at quiesce gap-free, chain-true, and with every payment seen
        (the push-gap/push-chain/push-commit/push-missed suite)."""
        ev = self._ev_clock()
        events = (
            [ev(op="mine", node=0) for _ in range(2)]
            + [ev(op="watch_start", node=1, watcher=0)]
            + [ev(op="tx", amount=2, fee=1), ev(op="mine", node=0)]
            + [ev(op="crash", node=1)]
            + [ev(op="tx", amount=1, fee=1), ev(op="mine", node=0)]
            + [ev(op="tx", amount=3, fee=0), ev(op="mine", node=2)]
            + [ev(op="recover", node=1)]
            + [ev(op="mine", node=0)]
        )
        report = chaos.run_chaos(0, nodes=3, events=events)
        assert report["ok"], report["violations"]
        assert report["watchers"] == 1
        # The watch saw the whole window despite its replica dying:
        # payment block, the two blocks mined while node 1 was down,
        # and the settle block.
        assert report["watch_events"] >= 4

    def test_crafted_sub_flood_is_survived_and_cleared(self):
        """A SUBSCRIBE flood (rotating watch sets + one unverifiable
        resume cursor per frame burst) against the node an honest
        watcher is riding: admission control must shed it without
        wedging the victim or the watcher, and `calm` + quiesce must
        find zero leaked sessions (push-leak)."""
        ev = self._ev_clock()
        events = (
            [ev(op="mine", node=0) for _ in range(2)]
            + [ev(op="watch_start", node=0, watcher=0)]
            + [ev(op="sub_flood", node=0)]
            + [ev(op="tx", amount=2, fee=1), ev(op="mine", node=1)]
            + [ev(op="calm")]
            + [ev(op="tx", amount=1, fee=1), ev(op="mine", node=0)]
        )
        report = chaos.run_chaos(0, nodes=3, events=events)
        assert report["ok"], report["violations"]
        assert report["watch_events"] >= 3

    def test_mute_push_bug_is_caught(self):
        """The watcher invariants have teeth: `mute-push` strips the
        match payload from delivered events (a push plane that
        "notifies" without telling the wallet it was paid) and the
        push-missed invariant must convict; the identical clean run
        must be green."""
        ev = self._ev_clock()
        events = (
            [ev(op="mine", node=0) for _ in range(2)]
            + [ev(op="watch_start", node=1, watcher=0)]
            + [ev(op="tx", amount=2, fee=1), ev(op="mine", node=0)]
            + [ev(op="tx", amount=1, fee=1), ev(op="mine", node=0)]
        )
        bad = chaos.run_chaos(1, nodes=3, events=events,
                              inject_bug="mute-push")
        assert not bad["ok"]
        assert {v["invariant"] for v in bad["violations"]} == {"push-missed"}
        good = chaos.run_chaos(1, nodes=3, events=events)
        assert good["ok"], good["violations"]
        assert good["watch_events"] >= 3

    def test_soak_schedule_carries_subscription_churn(self):
        """generate_soak_schedule's `subs` cluster kind: recurring
        subscribe/push/unsubscribe cycles across a virtual week, every
        watch_start paired with a watch_stop inside its envelope and a
        block inside the window so each cycle carries a real push."""
        total = 0
        for seed in range(8):
            events = chaos.generate_soak_schedule(
                seed=seed, n_nodes=5, horizon_vs=7 * chaos.DAY_VS,
                fault_clusters=28, blocks=336,
            )
            ops = [e["op"] for e in events]
            assert ops.count("watch_start") == ops.count("watch_stop")
            starts = [e for e in events if e["op"] == "watch_start"]
            stops = [e for e in events if e["op"] == "watch_stop"]
            for a, b in zip(starts, stops):
                assert a["at"] < b["at"]
                # The envelope carries at least one block to push.
                assert any(
                    e["op"] == "mine" and a["at"] < e["at"] < b["at"]
                    for e in events
                )
            total += len(starts)
        assert total >= 1, "subs clusters absent from 8 soak seeds"
