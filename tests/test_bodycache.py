"""Memory-bounded operation: body eviction, refetch, streaming resume.

The chain keeps headers + metadata resident forever; block BODIES below
the keep window are evicted once the append-only store can re-serve them
(Chain.evict_bodies + ChainStore.read_body) and refetched on demand.
These tests prove the eviction is a pure memory policy: every query,
sync reply, proof, reorg, and restart behaves byte-identically with and
without it.
"""

import asyncio

import pytest

from p1_tpu.chain import Chain, ChainStore
from p1_tpu.config import NodeConfig
from p1_tpu.node import Node
from p1_tpu.node.testing import make_blocks

DIFF = 8  # a few hashes per block: chains are cheap to mine


def _store_with(tmp_path, blocks, name="chain.dat"):
    store = ChainStore(tmp_path / name)
    store.acquire()
    for b in blocks[1:]:
        store.append(b)
    return store


def _evicted_chain(store, blocks, keep=4) -> Chain:
    chain = Chain(DIFF)
    chain.body_source = store
    for b in blocks[1:]:
        assert chain.add_block(b).status.value == "accepted"
    freed = chain.evict_bodies(keep)
    assert freed > 0 and chain.bodies_evicted > 0
    return chain


class TestEviction:
    def test_evicts_only_below_keep_window_and_only_durable(self, tmp_path):
        blocks = make_blocks(12, difficulty=DIFF)
        store = _store_with(tmp_path, blocks)
        try:
            chain = Chain(DIFF)
            chain.body_source = store
            for b in blocks[1:]:
                chain.add_block(b)
            before = chain.resident_body_bytes
            chain.evict_bodies(4)
            assert chain.bodies_evicted == 12 - 4
            assert 0 < chain.resident_body_bytes < before
            # The hot window (and genesis) still serve without refetch.
            assert chain.body_refetches == 0
            assert chain.tip.block_hash() == blocks[-1].block_hash()
        finally:
            store.close()

    def test_not_durable_means_not_evicted(self, tmp_path):
        blocks = make_blocks(8, difficulty=DIFF)
        store = _store_with(tmp_path, blocks[:5])  # only 4 mined persisted
        try:
            chain = Chain(DIFF)
            chain.body_source = store
            for b in blocks[1:]:
                chain.add_block(b)
            chain.evict_bodies(1)
            # Blocks 5..8 are not in the store: bodies stay resident no
            # matter how deep they sink.
            assert chain.bodies_evicted == 4
            for b in blocks[5:]:
                assert chain._index[b.block_hash()].block is not None
        finally:
            store.close()

    def test_queries_identical_after_eviction(self, tmp_path):
        blocks = make_blocks(16, difficulty=DIFF)
        store = _store_with(tmp_path, blocks)
        try:
            full = Chain(DIFF)
            for b in blocks[1:]:
                full.add_block(b)
            chain = _evicted_chain(store, blocks, keep=3)
            # blocks_after from genesis: the IBD-serving path, straight
            # through the evicted region.
            locator = [blocks[0].block_hash()]
            got = chain.blocks_after(locator, limit=500)
            want = full.blocks_after(locator, limit=500)
            assert [b.serialize() for b in got] == [
                b.serialize() for b in want
            ]
            assert chain.body_refetches > 0
            # get() on an evicted hash returns the exact block.
            deep = blocks[2]
            assert chain.get(deep.block_hash()).serialize() == deep.serialize()
            # header_of never costs a refetch.
            r = chain.body_refetches
            assert chain.header_of(deep.block_hash()) == deep.header
            assert chain.body_refetches == r
            # main_chain() iteration and the ledger views agree.
            assert [b.block_hash() for b in chain.main_chain()] == [
                b.block_hash() for b in full.main_chain()
            ]
            assert chain.balances_snapshot() == full.balances_snapshot()
        finally:
            store.close()

    def test_tx_proof_from_evicted_block(self, tmp_path):
        from p1_tpu.chain.proof import verify_tx_proof
        from p1_tpu.core.genesis import make_genesis

        blocks = make_blocks(10, difficulty=DIFF, miner_id="m")
        store = _store_with(tmp_path, blocks)
        try:
            chain = _evicted_chain(store, blocks, keep=2)
            # The height-1 coinbase lives in an evicted body.
            txid = blocks[1].txs[0].txid()
            proof = chain.tx_proof(txid)
            assert proof is not None
            verify_tx_proof(
                proof,
                DIFF,
                make_genesis(DIFF).block_hash(),
                txid=txid,
            )
        finally:
            store.close()

    def test_reorg_across_evicted_region(self, tmp_path):
        """A deeper fork arriving after eviction: the reorg walk undoes
        evicted main-chain bodies via refetch and lands on the same
        state a fully-resident chain reaches."""
        blocks = make_blocks(6, difficulty=DIFF, miner_id="a")
        # A heavier branch from height 2 (same prefix, different miner).
        from p1_tpu.core.block import Block, merkle_root
        from p1_tpu.core.header import BlockHeader
        from p1_tpu.core.tx import Transaction
        from p1_tpu.hashx import get_backend
        from p1_tpu.miner import Miner

        miner = Miner(backend=get_backend("cpu"))
        branch = list(blocks[:3])  # genesis, b1, b2 shared
        for height in range(3, 9):  # out-works the 6-block main chain
            parent = branch[-1]
            txs = (Transaction.coinbase("b", height),)
            draft = BlockHeader(
                1,
                parent.block_hash(),
                merkle_root([t.txid() for t in txs]),
                parent.header.timestamp + 1,
                DIFF,
                0,
            )
            sealed = miner.search_nonce(draft)
            branch.append(Block(sealed, txs))

        store = _store_with(tmp_path, blocks)
        try:
            chain = _evicted_chain(store, blocks, keep=1)
            full = Chain(DIFF)
            for b in blocks[1:]:
                full.add_block(b)
            for b in branch[3:]:
                res = chain.add_block(b)
                fres = full.add_block(b)
                assert res.status == fres.status
            assert chain.tip_hash == full.tip_hash
            assert chain.height == full.height == 8
            assert chain.balances_snapshot() == full.balances_snapshot()
        finally:
            store.close()

    def test_read_body_detects_span_mismatch(self, tmp_path):
        blocks = make_blocks(3, difficulty=DIFF)
        store = _store_with(tmp_path, blocks)
        try:
            h1, h2 = blocks[1].block_hash(), blocks[2].block_hash()
            store._body_spans[h1] = store._body_spans[h2]  # lie
            with pytest.raises(ValueError):
                store.read_body(h1)
        finally:
            store.close()


class TestStreamingResume:
    def test_body_cache_resume_state_equals_full_resume(self, tmp_path):
        blocks = make_blocks(20, difficulty=DIFF, miner_id="m")
        store = _store_with(tmp_path, blocks)
        try:
            full = store.load_chain(DIFF)
            bounded = store.load_chain(DIFF, body_cache=5)
            assert bounded.tip_hash == full.tip_hash
            assert bounded.height == full.height
            assert bounded.balances_snapshot() == full.balances_snapshot()
            assert bounded.bodies_evicted > 0
            assert bounded.resident_body_bytes < full.resident_body_bytes
            # Trusted fast resume composes with eviction too.
            trusted = store.load_chain(DIFF, trusted=True, body_cache=5)
            assert trusted.tip_hash == full.tip_hash
            assert trusted.balances_snapshot() == full.balances_snapshot()
        finally:
            store.close()

    def test_node_restart_with_body_cache(self, tmp_path):
        """Mine -> stop -> restart with eviction on -> the node resumes,
        serves its full chain, and keeps accepting blocks."""

        async def scenario():
            path = str(tmp_path / "node-chain.dat")
            node = Node(
                NodeConfig(
                    difficulty=DIFF,
                    chunk=1 << 12,
                    store_path=path,
                    miner_id="m",
                )
            )
            await node.start()
            while node.chain.height < 12:
                await asyncio.sleep(0.01)
            await node.stop()
            height = node.chain.height
            tip = node.chain.tip_hash

            node2 = Node(
                NodeConfig(
                    difficulty=DIFF,
                    chunk=1 << 12,
                    mine=False,
                    store_path=path,
                    body_cache_blocks=4,
                )
            )
            await node2.start()
            try:
                assert node2.chain.height == height
                assert node2.chain.tip_hash == tip
                assert node2.chain.bodies_evicted > 0
                # It still serves the whole chain from genesis (refetch).
                got = node2.chain.blocks_after(
                    [node2.chain.genesis.block_hash()], limit=500
                )
                assert len(got) == height
                # And still extends: mine a few more on top.
                node2.start_mining()
                while node2.chain.height < height + 2:
                    await asyncio.sleep(0.01)
                await node2.stop_mining()
            finally:
                await node2.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=120))
