"""Storage durability layer: checksummed v3 record framing, corruption
salvage, disk-fault injection (FaultStore), and graceful node degradation
under ENOSPC/EIO.

The acceptance pair (ISSUE r7):

- a single bit flipped in a mid-log LENGTH PREFIX loses zero
  checksum-valid records on restart (pre-v3 framing silently truncated
  everything behind it);
- a flipped BODY byte is detected at resume — quarantined, never trusted
  through the fast-resume path.

Plus the node plane: a store failing with ENOSPC degrades the node into
serve-only mode (peers still get headers/blocks), and persistence resumes
end-to-end once the fault clears.
"""

import errno
import os
import signal
import struct
import subprocess
import sys
import zlib

import pytest

from test_node import DIFF, _config, run, wait_until

from p1_tpu.chain import ChainStore, save_chain
from p1_tpu.chain.chain import Chain
from p1_tpu.chain.store import MAGIC, V2_MAGIC, fsync_dir
from p1_tpu.chain.testing import FaultStore, StoreFaultPlan
from p1_tpu.node import Node
from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

_LEN = struct.Struct(">I")
_CRC = struct.Struct(">I")


@pytest.fixture(scope="module")
def blocks():
    """Genesis + 8 mined blocks at DIFF (shared: mining is the only
    expensive part of these tests)."""
    return make_blocks(8, difficulty=DIFF)


def _fill_store(path, blocks) -> bytes:
    """Append every post-genesis block; return the on-disk bytes."""
    store = ChainStore(path)
    try:
        for block in blocks[1:]:
            store.append(block)
    finally:
        store.close()
    return path.read_bytes()


def _record_frames(data: bytes) -> list[tuple[int, int]]:
    """[(frame start, frame end)] of every record, from the scan spans."""
    return [
        (off - _LEN.size, off + n + _CRC.size)
        for off, n in ChainStore.scan(data).spans
    ]


def _write_v2_store(path, blocks) -> None:
    """Hand-craft a pre-checksum v2 store (the old framing)."""
    parts = [V2_MAGIC]
    for block in blocks[1:]:
        raw = block.serialize()
        parts.append(_LEN.pack(len(raw)))
        parts.append(raw)
    path.write_bytes(b"".join(parts))


class TestV3Framing:
    def test_v3_magic_and_roundtrip(self, blocks, tmp_path):
        path = tmp_path / "chain.dat"
        _fill_store(path, blocks)
        assert path.read_bytes().startswith(MAGIC)
        loaded = ChainStore(path).load_blocks()
        assert [b.block_hash() for b in loaded] == [
            b.block_hash() for b in blocks[1:]
        ]
        chain = ChainStore(path).load_chain(DIFF)
        assert chain.tip_hash == blocks[-1].block_hash()

    def test_flipped_length_prefix_loses_zero_good_records(
        self, blocks, tmp_path
    ):
        """THE headline guarantee: pre-v3 framing read a corrupt mid-log
        length prefix as a truncated tail and permanently truncated the
        entire good remainder at the next startup.  v3 resyncs past the
        one damaged record and keeps every other one."""
        path = tmp_path / "chain.dat"
        data = bytearray(_fill_store(path, blocks))
        frames = _record_frames(bytes(data))
        # Flip one bit in record 3's length prefix (8 records total).
        bad_start, bad_end = frames[2]
        data[bad_start] ^= 0x10
        path.write_bytes(bytes(data))

        # Restart sequence: acquire (heal under the lock) + load.
        store = ChainStore(path)
        store.acquire()
        try:
            loaded = store.load_blocks()
        finally:
            store.close()
        survivors = [b.block_hash() for b in loaded]
        want = [b.block_hash() for b in blocks[1:]]
        assert survivors == want[:2] + want[3:]  # ONLY the hit record gone
        assert len(survivors) == 7
        # The bad span is quarantined, not destroyed: sidecar holds the
        # original bytes (offset u64 + len u32 header per entry).
        q = store.quarantine_path().read_bytes()
        qoff, qlen = struct.unpack_from(">QI", q, 0)
        assert (qoff, qlen) == (bad_start, bad_end - bad_start)
        assert q[12 : 12 + qlen] == bytes(data[bad_start:bad_end])
        assert store.healed["quarantined_records"] == 1
        assert store.healed["quarantined_bytes"] == bad_end - bad_start
        # The healed file re-scans clean and still holds the 7 records.
        rescan = ChainStore.scan(path.read_bytes())
        assert rescan.clean and len(rescan.spans) == 7

    def test_flipped_body_byte_detected_at_resume(self, blocks, tmp_path):
        """Bit-rot inside a record body fails the record CRC: the record
        is quarantined at resume instead of riding through the trusted
        fast-resume path undetected (the pre-v3 docstring's admitted
        hole)."""
        path = tmp_path / "chain.dat"
        data = bytearray(_fill_store(path, blocks))
        frames = _record_frames(bytes(data))
        s, e = frames[4]
        data[(s + e) // 2] ^= 0x01  # mid-payload flip
        path.write_bytes(bytes(data))
        corrupt_hash = blocks[5].block_hash()

        store = ChainStore(path)
        store.acquire()
        try:
            loaded = store.load_blocks()
            chain = store.load_chain(DIFF, loaded, trusted=True)
        finally:
            store.close()
        # Detected: the damaged record never reaches the chain, trusted
        # resume or not.
        assert corrupt_hash not in {b.block_hash() for b in loaded}
        assert corrupt_hash not in chain
        assert store.healed["quarantined_records"] == 1
        # The chain resumes to the last block BEFORE the gap (the later
        # records survive on disk as orphans until a peer fills the gap).
        assert chain.tip_hash == blocks[4].block_hash()

    def test_torn_tail_still_truncates_silently(self, blocks, tmp_path):
        path = tmp_path / "chain.dat"
        data = _fill_store(path, blocks)
        path.write_bytes(data[:-7])  # crash mid-append of the last record
        store = ChainStore(path)
        store.acquire()
        try:
            loaded = store.load_blocks()
            # A crash artifact, not corruption: nothing quarantined.
            assert store.healed["quarantined_records"] == 0
            assert store.healed["truncated_bytes"] > 0
            assert not store.quarantine_path().exists()
            assert len(loaded) == 7
            # And the writer can append cleanly behind the trim.
            store.append(blocks[-1])
        finally:
            store.close()
        assert ChainStore(path).load_chain(DIFF).tip_hash == blocks[
            -1
        ].block_hash()

    def test_trailing_complete_corrupt_record_quarantined(
        self, blocks, tmp_path
    ):
        # The LAST record's bytes are all present but its CRC fails:
        # that is corruption (quarantine), not a torn tail (truncate).
        path = tmp_path / "chain.dat"
        data = bytearray(_fill_store(path, blocks))
        data[-1] ^= 0x01  # flip inside the final CRC trailer
        path.write_bytes(bytes(data))
        store = ChainStore(path)
        store.acquire()
        try:
            assert store.healed["quarantined_records"] == 1
            assert store.quarantine_path().exists()
            assert len(store.load_blocks()) == 7
        finally:
            store.close()

    def test_multiple_corrupt_spans_all_quarantined(self, blocks, tmp_path):
        path = tmp_path / "chain.dat"
        data = bytearray(_fill_store(path, blocks))
        frames = _record_frames(bytes(data))
        for idx in (1, 5):
            s, e = frames[idx]
            data[(s + e) // 2] ^= 0x40
        path.write_bytes(bytes(data))
        store = ChainStore(path)
        store.acquire()
        try:
            assert store.healed["quarantined_records"] == 2
            assert len(store.load_blocks()) == 6
        finally:
            store.close()


class TestV2Compat:
    def test_v2_store_loads_read_only(self, blocks, tmp_path):
        path = tmp_path / "v2.dat"
        _write_v2_store(path, blocks)
        loaded = ChainStore(path).load_blocks()
        assert [b.block_hash() for b in loaded] == [
            b.block_hash() for b in blocks[1:]
        ]
        chain = ChainStore(path).load_chain(DIFF)
        assert chain.tip_hash == blocks[-1].block_hash()
        raw, n = ChainStore(path).packed_headers()
        assert n == len(blocks) - 1

    def test_v2_writer_refused_with_upgrade_hint(self, blocks, tmp_path):
        path = tmp_path / "v2.dat"
        _write_v2_store(path, blocks)
        with pytest.raises(RuntimeError, match="fsck"):
            ChainStore(path).acquire()
        # Maintenance tooling (compact/fsck) may still lock it.
        store = ChainStore(path)
        store.acquire(allow_v2=True)
        store.close()
        assert path.read_bytes().startswith(V2_MAGIC)  # untouched

    def test_v2_append_refused_even_under_allow_v2(self, blocks, tmp_path):
        # allow_v2 admits readers/rewriters; an appended v3 record's CRC
        # trailer would read back as the next record's length prefix and
        # desync the v2 framing, so append must refuse.
        path = tmp_path / "v2.dat"
        _write_v2_store(path, blocks)
        before = path.read_bytes()
        store = ChainStore(path)
        store.acquire(allow_v2=True)
        try:
            with pytest.raises(ValueError, match="v2"):
                store.append(blocks[1])
        finally:
            store.close()
        assert path.read_bytes() == before  # untouched

    def test_v2_torn_tail_truncated_under_allow_v2(self, blocks, tmp_path):
        path = tmp_path / "v2.dat"
        _write_v2_store(path, blocks)
        raw = path.read_bytes()
        path.write_bytes(raw[:-5])
        store = ChainStore(path)
        store.acquire(allow_v2=True)
        store.close()
        assert len(ChainStore(path).load_blocks()) == 7


class TestFaultStore:
    def test_persistent_read_fault_refuses_writer(self, blocks, tmp_path):
        # A bit that re-flips on EVERY read (bad sector / lying medium)
        # survives the quarantine rebuild's re-verify; the writer must be
        # refused, not admitted behind unhealed corruption.
        path = tmp_path / "f.dat"
        data = _fill_store(path, blocks)
        s, e = _record_frames(data)[0]
        store = FaultStore(
            path,
            plan=StoreFaultPlan(flip_read_at=(s + e) // 2, flip_mask=0x10),
        )
        with pytest.raises(RuntimeError, match="persist"):
            store.acquire()

    def test_enospc_on_nth_write(self, blocks, tmp_path):
        # Write #1 is the magic, each append is one write.
        store = FaultStore(
            tmp_path / "f.dat", plan=StoreFaultPlan(fail_write_at=3)
        )
        try:
            store.append(blocks[1])
            with pytest.raises(OSError) as exc:
                store.append(blocks[2])
            assert exc.value.errno == errno.ENOSPC
        finally:
            store.close()
        assert len(ChainStore(tmp_path / "f.dat").load_blocks()) == 1

    def test_torn_write_leaves_recoverable_prefix(self, blocks, tmp_path):
        path = tmp_path / "f.dat"
        store = FaultStore(
            path, plan=StoreFaultPlan(fail_write_at=3, torn_bytes=10)
        )
        try:
            store.append(blocks[1])
            with pytest.raises(OSError):
                store.append(blocks[2])
        finally:
            store.close()
        # 10 bytes of record 2 landed: a torn tail the next writer trims.
        fresh = ChainStore(path)
        fresh.acquire()
        try:
            assert fresh.healed["truncated_bytes"] == 10
            fresh.append(blocks[2])
        finally:
            fresh.close()
        assert len(ChainStore(path).load_blocks()) == 2

    def test_fsync_failure_surfaces_as_oserror(self, blocks, tmp_path):
        store = FaultStore(
            tmp_path / "f.dat", plan=StoreFaultPlan(fail_fsync_at=1)
        )
        try:
            with pytest.raises(OSError) as exc:
                store.append(blocks[1])
            assert exc.value.errno == errno.EIO
        finally:
            store.close()

    def test_bitflip_on_read_detected_without_touching_disk(
        self, blocks, tmp_path
    ):
        path = tmp_path / "f.dat"
        pristine = _fill_store(path, blocks)
        frames = _record_frames(pristine)
        s, e = frames[3]
        flipped = FaultStore(
            path, plan=StoreFaultPlan(flip_read_at=(s + e) // 2)
        )
        assert len(flipped.load_blocks()) == 7  # bad read: record dropped
        assert path.read_bytes() == pristine  # platter bytes intact
        assert len(ChainStore(path).load_blocks()) == 8  # clean reader

    def test_save_chain_fsyncs_data_then_directory(self, blocks, tmp_path):
        chain = Chain(DIFF, genesis=blocks[0])
        for block in blocks[1:]:
            chain.add_block(block)
        created = []

        def factory(p, fsync=True):
            s = FaultStore(p, fsync=fsync)
            created.append(s)
            return s

        save_chain(chain, tmp_path / "snap.dat", store_cls=factory)
        (store,) = created
        # The snapshot's one data fsync lands BEFORE the directory fsync
        # (dir-entry durability is meaningless for still-dirty data).
        assert store.events[-2:] == ["fsync", "dir_fsync"]
        assert store.fsyncs == 1 and store.dir_fsyncs == 1
        # A failing directory fsync is a real error, not best-effort.
        with pytest.raises(OSError):
            save_chain(
                chain,
                tmp_path / "snap2.dat",
                store_cls=lambda p, fsync=True: FaultStore(
                    p, plan=StoreFaultPlan(fail_dir_fsync_at=1), fsync=fsync
                ),
            )


class TestCrashSoak:
    def test_truncation_at_every_offset_recovers_prefix(
        self, blocks, tmp_path
    ):
        """Deterministic tier-1 crash soak: a store cut at ANY byte
        offset (kill-9 / power-cut shapes) must reopen to an exact
        prefix of the appended chain — never an exception, never a
        record past the cut, never a misparse."""
        path = tmp_path / "soak.dat"
        data = _fill_store(path, blocks)
        frames = _record_frames(data)
        want = [b.block_hash() for b in blocks[1:]]
        for cut in range(len(MAGIC), len(data), 3):
            path.write_bytes(data[:cut])
            store = ChainStore(path)
            store.acquire()
            try:
                got = [b.block_hash() for b in store.load_blocks()]
            finally:
                store.close()
            whole = sum(1 for _, end in frames if end <= cut)
            assert got == want[:whole], f"cut at {cut}"

    def test_bitflip_at_sampled_offsets_never_loses_other_records(
        self, blocks, tmp_path
    ):
        """Every single-bit flip past the magic costs AT MOST the one
        record it hits — the containment bound the checksums buy."""
        path = tmp_path / "flip.dat"
        data = _fill_store(path, blocks)
        frames = _record_frames(data)
        want = [b.block_hash() for b in blocks[1:]]
        for off in range(len(MAGIC), len(data), 17):
            buf = bytearray(data)
            buf[off] ^= 0x08
            path.write_bytes(bytes(buf))
            store = ChainStore(path)
            store.acquire()
            try:
                got = [b.block_hash() for b in store.load_blocks()]
            finally:
                store.close()
            hit = [
                i for i, (s, e) in enumerate(frames) if s <= off < e
            ]
            expect = [h for i, h in enumerate(want) if i not in hit]
            assert got == expect, f"flip at {off}"

    @pytest.mark.slow
    def test_kill9_at_random_offset_soak(self, tmp_path):
        """The real thing: SIGKILL a subprocess mid-append at random
        moments, reopen, assert the surviving store is an exact prefix
        of the deterministic chain, then relaunch on the SAME store to
        keep appending — every round exercises heal + resume + append
        continuation."""
        import random
        import time

        path = tmp_path / "kill.dat"
        n_blocks, diff, delay = 24, 10, 0.08
        from p1_tpu.node.testing import make_blocks as mk

        want = [b.block_hash() for b in mk(n_blocks, difficulty=diff)]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        rng = random.Random(7)
        rounds = intermediates = 0
        complete = False
        while rounds < 20 and not complete:
            proc = subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "p1_tpu.chain.testing",
                    str(path),
                    str(n_blocks),
                    str(diff),
                    str(delay),
                ],
                env=env,
                cwd="/root/repo",
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            try:
                time.sleep(rng.uniform(0.5, 2.2))
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=30)
            finally:
                if proc.poll() is None:
                    proc.kill()
            rounds += 1
            if not path.exists():
                continue  # killed before the store was even created
            store = ChainStore(path)
            store.acquire()  # the restart heal path
            try:
                got = [b.block_hash() for b in store.load_blocks()]
            finally:
                store.close()
            assert got == want[: len(got)], f"round {rounds}"
            complete = len(got) >= n_blocks + 1
            if got and not complete:
                intermediates += 1
        assert complete, f"never finished in {rounds} rounds"
        # The soak must have actually observed kill-mid-append states —
        # a run whose every kill landed after completion proves nothing.
        assert intermediates >= 1, "no mid-append kill ever observed"


class TestNodeDegradation:
    @pytest.mark.slow
    def test_enospc_degrades_serves_and_recovers(self, tmp_path):
        """End-to-end acceptance: a node whose disk fills mid-sync (a)
        enters degraded serve-only mode without dropping the peer
        connection, (b) still answers headers queries, and (c) resumes
        persisting + catches back up once space returns.

        Slow smoke since round 11: the tier-1 copy of this e2e runs on
        SimNet at PRODUCTION backoff deadlines in milliseconds of wall
        time (tests/test_chaos.py TestStoreRecoverySim) — this socket
        variant keeps the real-kernel path covered, same migration
        pattern as the round-10 stall-failover port."""

        async def scenario():
            chain_blocks = make_blocks(10, difficulty=DIFF)
            peer = HostilePeer(chain_blocks, plan=FaultPlan(batch_limit=2))
            await peer.start()
            # Write #1 = magic, writes #2..4 = records: the 4th record
            # append hits persistent ENOSPC mid-IBD.
            store = FaultStore(
                tmp_path / "victim.dat",
                plan=StoreFaultPlan(fail_writes_from=5),
            )
            node = Node(
                _config(
                    peers=[f"127.0.0.1:{peer.port}"],
                    store_path=str(tmp_path / "victim.dat"),
                    sync_stall_timeout_s=0.5,
                    sync_backoff_base_s=0.05,
                    sync_backoff_max_s=0.2,
                ),
                store=store,
            )
            await node.start()
            try:
                # (a) the store fails on record 4; the node degrades.
                assert await wait_until(lambda: node._store_degraded)
                status = node.status()["storage"]
                assert status["degraded"] is True
                assert status["errors"] >= 1
                assert node.metrics.store_errors >= 1
                # The connection that delivered the fatal block is NOT
                # unwound: the peer session survives the disk fault.
                assert node.peer_count() == 1
                height_frozen = node.chain.height
                assert height_frozen < 10
                # (b) serve-only: a light client still gets our headers.
                from p1_tpu.node.client import get_headers

                headers = await get_headers(
                    "127.0.0.1", node.port, DIFF, timeout=10.0
                )
                assert len(headers) == height_frozen + 1
                # Blocks pushed while degraded are deferred, not taken.
                assert node.chain.height == height_frozen
                # (c) space returns: the recovery loop flushes pending
                # records, clears the flag, and backfills to the full
                # advertised chain.
                store.clear_faults()
                assert await wait_until(
                    lambda: not node._store_degraded, timeout=10.0
                )
                assert node.metrics.store_recoveries == 1
                assert await wait_until(
                    lambda: node.chain.height == 10, timeout=20.0
                )
                # Everything accepted is durably on disk, in order.
                assert await wait_until(
                    lambda: len(ChainStore(store.path).load_blocks()) == 10
                )
                assert node.status()["storage"]["pending_records"] == 0
            finally:
                await node.stop()
                await peer.stop()
            # Restart on the recovered store: full resume, nothing torn.
            revived = Node(
                _config(store_path=str(tmp_path / "victim.dat"))
            )
            await revived.start()
            try:
                assert revived.chain.height == 10
            finally:
                await revived.stop()

        run(scenario())

    def test_store_degraded_exit_signals_fatal(self, tmp_path):
        """The --store-degraded-exit escape hatch: the node signals the
        CLI (store_fatal) instead of entering degraded mode."""

        async def scenario():
            chain_blocks = make_blocks(3, difficulty=DIFF)
            peer = HostilePeer(chain_blocks)
            await peer.start()
            store = FaultStore(
                tmp_path / "fatal.dat",
                plan=StoreFaultPlan(fail_write_at=2),  # first record
            )
            node = Node(
                _config(
                    peers=[f"127.0.0.1:{peer.port}"],
                    store_path=str(tmp_path / "fatal.dat"),
                    store_degraded_exit=True,
                ),
                store=store,
            )
            await node.start()
            try:
                assert await wait_until(lambda: node.store_fatal.is_set())
                assert node.status()["storage"]["degraded"] is True
            finally:
                await node.stop()
                await peer.stop()

        run(scenario())

    def test_mining_pauses_while_degraded(self, tmp_path):
        """A degraded miner stops sealing blocks (they could never be
        persisted or honestly gossiped) and resumes after recovery."""

        async def scenario():
            store = FaultStore(
                tmp_path / "miner.dat",
                plan=StoreFaultPlan(fail_writes_from=4),  # after 2 blocks
            )
            node = Node(
                _config(
                    mine=True,
                    store_path=str(tmp_path / "miner.dat"),
                    sync_backoff_base_s=0.05,
                    sync_backoff_max_s=0.2,
                ),
                store=store,
            )
            await node.start()
            try:
                assert await wait_until(lambda: node._store_degraded)
                frozen = node.chain.height
                import asyncio

                await asyncio.sleep(0.8)
                assert node.chain.height == frozen  # no sealing while down
                store.clear_faults()
                assert await wait_until(
                    lambda: not node._store_degraded, timeout=10.0
                )
                assert await wait_until(
                    lambda: node.chain.height > frozen, timeout=20.0
                )
            finally:
                await node.stop()

        run(scenario())


class TestCompactWriteFailure:
    """Satellite (round 18): compaction ENOSPC mid-rewrite must leave
    the original store byte-identical AND release the writer flock —
    the whole-file os.replace path was untested under tmp-write
    failure."""

    def test_enospc_mid_rewrite_original_untouched_lock_released(
        self, tmp_path, blocks
    ):
        import functools

        from p1_tpu.chain.tooling import run_compact

        path = tmp_path / "chain.dat"
        _fill_store(path, blocks)
        before = path.read_bytes()
        store_cls = functools.partial(
            FaultStore,
            plan=StoreFaultPlan(fail_write_at=3, write_errno=errno.ENOSPC),
        )
        rc = run_compact(str(path), None, store_cls=store_cls)
        assert rc == 2
        # The original store was never touched...
        assert path.read_bytes() == before
        # ...the partial tmp was removed...
        assert not list(tmp_path.glob("*.compact.*"))
        # ...and the writer flock was released: a fresh writer works.
        st = ChainStore(path)
        st.acquire()  # would raise "locked by another process" on a leak
        st.close()

    def test_enospc_with_out_flag_leaves_both_paths(self, tmp_path, blocks):
        import functools

        from p1_tpu.chain.tooling import run_compact

        path = tmp_path / "chain.dat"
        _fill_store(path, blocks)
        before = path.read_bytes()
        out = tmp_path / "out.dat"
        store_cls = functools.partial(
            FaultStore,
            plan=StoreFaultPlan(fail_write_at=2, write_errno=errno.ENOSPC),
        )
        rc = run_compact(str(path), str(out), store_cls=store_cls)
        assert rc == 2
        assert path.read_bytes() == before
        # The destination acquired its magic but no record ever landed.
        assert ChainStore(out).load_blocks() == []
