"""Pallas kernel: interpret-mode parity vs the cpu/numpy oracles.

The compiled Mosaic path needs real TPU hardware; these tests run the
IDENTICAL kernel through the Pallas interpreter on the CPU test platform
(pallas_backend auto-selects interpret mode off-TPU), so every lane of the
round math, the target compare, and the first-hit min-reduction is checked
without a chip.  Throughput of the compiled kernel is bench.py's job.
"""

import random

import pytest

from p1_tpu.core import BlockHeader, meets_target
from p1_tpu.hashx import get_backend

pytest.importorskip("jax.experimental.pallas")

DIFF = 8
BATCH = 1 << 12  # small steps: the interpreter is slow


def _prefix(seed: int) -> bytes:
    rng = random.Random(seed)
    return BlockHeader(
        1, rng.randbytes(32), rng.randbytes(32), 1735689700, DIFF, 0
    ).mining_prefix()


@pytest.fixture(scope="module")
def tpu_backend():
    be = get_backend("tpu", batch=BATCH, sub=8)
    assert be.interpret, "off-TPU the backend must auto-select interpret mode"
    return be


class TestPallasParity:
    def test_registered_as_tpu(self, tpu_backend):
        assert tpu_backend.name == "tpu"

    @pytest.mark.parametrize("seed", [0, 7])
    def test_first_hit_matches_cpu(self, tpu_backend, seed):
        prefix = _prefix(seed)
        got = tpu_backend.search(prefix, 0, BATCH, DIFF)
        want = get_backend("cpu").search(prefix, 0, BATCH, DIFF)
        assert got.nonce == want.nonce
        if want.nonce is not None:
            assert got.hashes_done == want.hashes_done
            sealed = prefix + int(got.nonce).to_bytes(4, "big")
            from p1_tpu.hashx.sha256_ref import sha256d

            assert meets_target(sha256d(sealed), DIFF)

    def test_every_nonce_hits_at_difficulty_zero(self, tpu_backend):
        # Tie-break: difficulty 0 makes every lane a hit; the kernel's
        # min-reduction must still return the earliest (the range start).
        res = tpu_backend.search(_prefix(1), 0, BATCH, 0)
        assert res.nonce == 0 and res.hashes_done == 1

    def test_nonce_start_offset(self, tpu_backend):
        prefix = _prefix(2)
        base = 0x1000
        res = tpu_backend.search(prefix, base, BATCH, 0)
        assert res.nonce == base

    def test_partial_final_step_masked(self, tpu_backend):
        # count smaller than the kernel batch: a hit reported beyond the
        # valid range must be discarded by the host-side mask.
        prefix = _prefix(3)
        full = get_backend("cpu").search(prefix, 0, BATCH, DIFF)
        if full.nonce is None:
            pytest.skip("no hit in range for this seed")
        short = tpu_backend.search(prefix, 0, full.nonce, DIFF)
        assert short.nonce is None
        exact = tpu_backend.search(prefix, 0, full.nonce + 1, DIFF)
        assert exact.nonce == full.nonce

    def test_batch_must_tile(self):
        with pytest.raises(ValueError, match="multiple"):
            get_backend("tpu", batch=1000, sub=8)

    def test_batch_int32_bound(self):
        with pytest.raises(ValueError, match="2\\*\\*31"):
            get_backend("tpu", batch=1 << 31, sub=8)

    def test_odd_tile_disables_ramp(self):
        # sub=20 -> block 2560 doesn't divide the 2^22 ramp floor; the
        # backend must opt out of the opening ramp rather than crash on a
        # fresh low-difficulty search.
        be = get_backend("tpu", batch=2560 * 4, sub=20)
        assert be.ramp_floor is None
        res = be.search(_prefix(4), 0, 2560, 0)
        assert res.nonce == 0
