"""Tier-0 import health: every p1_tpu module must import, period.

The seed round's entire test suite silently collapsed to ZERO collected
tests because one module (core/keys.py) hard-imported an optional wheel
(``cryptography``) at module scope — every test module importing the
core package died at collection, and nothing failed loudly enough to
say why.  This file makes that class of regression impossible to miss:
each module is a separate parametrized case, so the report names the
exact module that stopped importing, and a collection-killing import
shows up as a failing TEST rather than a mysteriously smaller suite.

Optional dependencies must be guarded (lazy import, try/except, vendored
fallback) — see core/keys.py's cryptography/_ed25519 split for the
house pattern.
"""

from __future__ import annotations

import importlib
import pkgutil

import pytest

import p1_tpu


def _all_modules() -> list[str]:
    names = ["p1_tpu"]
    for mod in pkgutil.walk_packages(p1_tpu.__path__, prefix="p1_tpu."):
        if mod.name.endswith("__main__"):
            continue  # entry point: importing it RUNS the CLI
        names.append(mod.name)
    return sorted(names)


@pytest.mark.parametrize("name", _all_modules())
def test_module_imports(name):
    importlib.import_module(name)


def test_walk_found_the_tree():
    # Guard the guard: if the walk itself breaks (layout change, namespace
    # confusion), an empty parametrization would vacuously "pass".
    names = _all_modules()
    assert len(names) > 30, names
    for expected in (
        "p1_tpu.analysis.engine",
        "p1_tpu.analysis.callgraph",
        "p1_tpu.analysis.rules.wallclock",
        "p1_tpu.analysis.rules.awaitstate",
        "p1_tpu.analysis.rules.transblock",
        "p1_tpu.analysis.rules.escstate",
        "p1_tpu.analysis.rules.wirecontract",
        "p1_tpu.core.keys",
        "p1_tpu.core._ed25519",
        "p1_tpu.core._ed25519_native",
        "p1_tpu.core.sigcache",
        "p1_tpu.hashx.ed25519_msm",
        "p1_tpu.chain.replay",
        "p1_tpu.chain.filters",
        "p1_tpu.node.node",
        "p1_tpu.node.queryplane",
        "p1_tpu.node.transport",
        "p1_tpu.node.netsim",
        "p1_tpu.node.scenarios",
        "p1_tpu.hashx.pallas_backend",
    ):
        assert expected in names
