"""SPV transaction-inclusion proofs: merkle branches, the chain's txid
index, client-side verification, and the GETPROOF/PROOF wire round.

The adversarial cases matter most: a lying peer must not be able to serve
a proof that verifies for a transaction the chain never confirmed, for a
relocated index, or for a tampered transaction.
"""

import asyncio
import random

import pytest

from txutil import account, stx

from test_consensus import DIFF, _funded_chain, _mine_child

from p1_tpu.chain import AddStatus, Chain, SPVError, TxProof, verify_tx_proof
from p1_tpu.core import (
    Transaction,
    make_genesis,
    merkle_branch,
    merkle_root,
    verify_merkle_branch,
)
from p1_tpu.core.genesis import genesis_hash
from p1_tpu.core.hashutil import sha256d
from p1_tpu.node import protocol
from p1_tpu.node.protocol import MsgType


def _txids(n: int, rng: random.Random) -> list[bytes]:
    return [rng.randbytes(32) for _ in range(n)]


class TestMerkleBranch:
    def test_every_index_round_trips(self):
        rng = random.Random(42)
        for n in range(1, 26):
            txids = _txids(n, rng)
            root = merkle_root(txids)
            for i in range(n):
                branch = merkle_branch(txids, i)
                assert verify_merkle_branch(txids[i], i, branch, root), (n, i)

    def test_single_tx_has_empty_branch(self):
        txid = b"\x07" * 32
        assert merkle_branch([txid], 0) == ()
        assert verify_merkle_branch(txid, 0, (), merkle_root([txid]))

    def test_wrong_anything_fails(self):
        rng = random.Random(7)
        txids = _txids(5, rng)
        root = merkle_root(txids)
        branch = merkle_branch(txids, 2)
        assert not verify_merkle_branch(txids[3], 2, branch, root)  # wrong leaf
        assert not verify_merkle_branch(txids[2], 3, branch, root)  # wrong index
        assert not verify_merkle_branch(txids[2], 2, branch, b"\x00" * 32)
        bad = (branch[0], sha256d(b"evil"), *branch[2:])  # tampered sibling
        assert not verify_merkle_branch(txids[2], 2, bad, root)
        assert not verify_merkle_branch(txids[2], 2, branch[:-1], root)

    def test_index_beyond_tree_depth_rejected(self):
        # An index >= 2**len(branch) cannot name a leaf: a prover must not
        # be able to "relocate" a transaction by inflating the index.
        txids = _txids(4, random.Random(1))
        root = merkle_root(txids)
        branch = merkle_branch(txids, 1)
        assert not verify_merkle_branch(txids[1], 1 + 4, branch, root)
        assert not verify_merkle_branch(txids[1], -1, branch, root)

    def test_out_of_range_branch_request(self):
        with pytest.raises(ValueError):
            merkle_branch([b"\x01" * 32], 1)
        with pytest.raises(ValueError):
            merkle_branch([], 0)


class TestChainTxProof:
    def test_confirmed_tx_proves_and_verifies(self):
        chain, b1 = _funded_chain("alice")
        spend = stx("alice", account("bob"), 10, 1, 0)
        b2 = _mine_child(
            b1, txs=(Transaction.coinbase("m", 2), spend)
        )
        assert chain.add_block(b2).status is AddStatus.ACCEPTED
        proof = chain.tx_proof(spend.txid())
        assert proof is not None
        assert proof.height == 2 and proof.index == 1
        assert proof.confirmations == 1
        verify_tx_proof(proof, DIFF, genesis_hash(DIFF), txid=spend.txid())
        # The coinbase is provable too.
        cb_proof = chain.tx_proof(b2.txs[0].txid())
        assert cb_proof is not None and cb_proof.index == 0
        verify_tx_proof(cb_proof, DIFF, genesis_hash(DIFF))

    def test_unknown_txid_returns_none(self):
        chain, _ = _funded_chain("alice")
        assert chain.tx_proof(b"\x99" * 32) is None

    def test_reorg_repoints_or_evicts_the_index(self):
        # A tx confirmed on the losing branch must stop being provable;
        # one confirmed on both branches must point at the WINNING block.
        chain, b1 = _funded_chain("alice")
        spend = stx("alice", account("bob"), 10, 1, 0)
        only_a = stx("alice", account("carol"), 5, 1, 1)
        a2 = _mine_child(b1, txs=(Transaction.coinbase("ma", 2), spend, only_a))
        assert chain.add_block(a2).status is AddStatus.ACCEPTED
        assert chain.tx_proof(only_a.txid()) is not None
        # Competing branch from b1 confirms `spend` only, and grows heavier.
        b2 = _mine_child(b1, txs=(Transaction.coinbase("mb", 2), spend), ts_offset=2)
        b3 = _mine_child(b2, txs=(Transaction.coinbase("mb", 3),))
        chain.add_block(b2)
        res = chain.add_block(b3)
        assert res.status is AddStatus.ACCEPTED and res.removed
        assert chain.tx_proof(only_a.txid()) is None  # evicted with branch A
        proof = chain.tx_proof(spend.txid())
        assert proof is not None
        assert proof.header.block_hash() == b2.block_hash()  # repointed
        verify_tx_proof(proof, DIFF, genesis_hash(DIFF), txid=spend.txid())

    def test_lying_peer_cannot_forge(self):
        import dataclasses

        chain, b1 = _funded_chain("alice")
        spend = stx("alice", account("bob"), 10, 1, 0)
        b2 = _mine_child(b1, txs=(Transaction.coinbase("m", 2), spend))
        assert chain.add_block(b2).status is AddStatus.ACCEPTED
        proof = chain.tx_proof(spend.txid())
        # A proof for a different txid than asked.
        with pytest.raises(SPVError, match="different transaction"):
            verify_tx_proof(proof, DIFF, genesis_hash(DIFF), txid=b"\x01" * 32)
        # Tampered transaction (amount inflated): merkle check must fail.
        fake_tx = dataclasses.replace(proof.tx, amount=10_000)
        with pytest.raises(SPVError):
            verify_tx_proof(
                dataclasses.replace(proof, tx=fake_tx),
                DIFF,
                genesis_hash(DIFF),
            )
        # Relocated index.
        with pytest.raises(SPVError, match="merkle"):
            verify_tx_proof(
                dataclasses.replace(proof, index=0), DIFF, genesis_hash(DIFF)
            )
        # Header without the claimed work (wrong difficulty claim).
        with pytest.raises(SPVError, match="difficulty"):
            verify_tx_proof(proof, DIFF + 1, genesis_hash(DIFF + 1))
        # A fabricated height-0 header that is not this chain's genesis.
        with pytest.raises(SPVError, match="genesis"):
            verify_tx_proof(
                dataclasses.replace(proof, height=0), DIFF, genesis_hash(DIFF)
            )
        # Internally inconsistent peer claims: tip below confirming height
        # would hand wallet scripts negative confirmations.
        with pytest.raises(SPVError, match="tip height"):
            verify_tx_proof(
                dataclasses.replace(proof, tip_height=proof.height - 1),
                DIFF,
                genesis_hash(DIFF),
            )

    def test_headerless_work_fails(self):
        # A header that never met the target cannot anchor a proof even if
        # the merkle branch is internally consistent.
        genesis = make_genesis(DIFF)
        chain = Chain(DIFF, genesis=genesis)
        cb = Transaction.coinbase("m", 1)
        from p1_tpu.core import BlockHeader

        header = BlockHeader(
            version=1,
            prev_hash=genesis.block_hash(),
            merkle_root=merkle_root([cb.txid()]),
            timestamp=genesis.header.timestamp + 1,
            difficulty=DIFF,
            nonce=0,
        )
        # Find a nonce that does NOT meet the target (almost any does).
        from p1_tpu.core.header import meets_target

        nonce = 0
        while meets_target(header.with_nonce(nonce).block_hash(), DIFF):
            nonce += 1
        bad = TxProof(cb, header.with_nonce(nonce), 1, 1, 0, ())
        with pytest.raises(SPVError, match="proof-of-work"):
            verify_tx_proof(bad, DIFF, genesis_hash(DIFF))


class TestProofWire:
    def test_getproof_round_trip(self):
        txid = b"\xab" * 32
        mtype, got = protocol.decode(protocol.encode_getproof(txid))
        assert mtype is MsgType.GETPROOF and got == txid

    def test_proof_round_trip(self):
        chain, b1 = _funded_chain("alice")
        spend = stx("alice", account("bob"), 10, 1, 0)
        b2 = _mine_child(b1, txs=(Transaction.coinbase("m", 2), spend))
        chain.add_block(b2)
        proof = chain.tx_proof(spend.txid())
        mtype, got = protocol.decode(protocol.encode_proof(proof))
        assert mtype is MsgType.PROOF and got == proof
        mtype, got = protocol.decode(protocol.encode_proof(None))
        assert mtype is MsgType.PROOF and got is None

    @pytest.mark.parametrize(
        "payload",
        [
            bytes([MsgType.GETPROOF]),  # no txid
            bytes([MsgType.GETPROOF]) + b"\x00" * 31,  # short txid
            bytes([MsgType.GETPROOF]) + b"\x00" * 33,  # long txid
            bytes([MsgType.PROOF]),  # no flag
            bytes([MsgType.PROOF, 0, 0]),  # trailing after not-found
            bytes([MsgType.PROOF, 2]),  # bad flag
            bytes([MsgType.PROOF, 1]) + b"\x00" * 10,  # truncated body
            bytes([MsgType.PROOF, 1]) + b"\x00" * 94 + b"\x00\x05",  # branch lies
        ],
    )
    def test_malformed_rejected(self, payload):
        with pytest.raises(ValueError):
            protocol.decode(payload)


class TestProofOverWire:
    def test_node_serves_verifiable_proof(self):
        from test_node import _config, wait_until

        from p1_tpu.node import Node
        from p1_tpu.node.client import get_proof, send_tx

        NODE_DIFF = 12

        async def scenario():
            node = Node(_config(difficulty=NODE_DIFF))
            await node.start()
            try:
                # Earn a balance, then confirm a spend of it.
                from test_node import fund

                await fund(node, "alice", blocks=1)
                spend = stx(
                    "alice", account("bob"), 10, 1, 0, difficulty=NODE_DIFF
                )
                await send_tx("127.0.0.1", node.port, spend, NODE_DIFF)
                await wait_until(lambda: len(node.mempool) == 1)
                start = node.chain.height
                node.start_mining()
                assert await wait_until(
                    lambda: node.chain.tx_proof(spend.txid()) is not None
                )
                await node.stop_mining()
                proof = await get_proof(
                    "127.0.0.1", node.port, spend.txid(), NODE_DIFF
                )
                assert proof is not None
                verify_tx_proof(
                    proof,
                    NODE_DIFF,
                    genesis_hash(NODE_DIFF),
                    txid=spend.txid(),
                )
                # Unconfirmed txid: clean not-found.
                missing = await get_proof(
                    "127.0.0.1", node.port, b"\x42" * 32, NODE_DIFF
                )
                assert missing is None
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))


class TestRetargetScheduleFloor:
    """ADVICE r4: on retargeting chains, verification runs at the header's
    CLAIMED difficulty — without a floor, ~2 hashes forge "evidence".
    The schedule floor prices forgery at what the retarget rule could
    legitimately have reached by the claimed height."""

    def _mined_header(self, txid: bytes, difficulty: int):
        from p1_tpu.core import BlockHeader
        from p1_tpu.core.header import meets_target

        header = BlockHeader(
            version=1,
            prev_hash=b"\x55" * 32,
            merkle_root=merkle_root([txid]),
            timestamp=1_000,
            difficulty=difficulty,
            nonce=0,
        )
        nonce = 0
        while not meets_target(header.with_nonce(nonce).block_hash(), difficulty):
            nonce += 1
        return header.with_nonce(nonce)

    def test_cheap_forgery_below_floor_rejected(self):
        from p1_tpu.core.retarget import RetargetRule

        rule = RetargetRule(window=50, spacing=5)  # max_adjust = 2
        cb = Transaction.coinbase("m", 7)
        # Difficulty-1 "work" (~2 hashes) at height 7: zero completed
        # windows, so the floor is the full base difficulty.
        forged = TxProof(cb, self._mined_header(cb.txid(), 1), 7, 7, 0, ())
        with pytest.raises(SPVError, match="schedule floor"):
            verify_tx_proof(
                forged, DIFF, genesis_hash(DIFF, rule), retarget=rule
            )

    def test_floor_tracks_claimed_height(self):
        from p1_tpu.core.retarget import RetargetRule

        rule = RetargetRule(window=50, spacing=5)
        cb = Transaction.coinbase("m", 100)
        # Two completed windows at height 100: the rule could have moved
        # at most 2*2 bits, so DIFF-4 evidence is plausible and accepted…
        ok = TxProof(
            cb, self._mined_header(cb.txid(), DIFF - 4), 100, 120, 0, ()
        )
        verify_tx_proof(ok, DIFF, genesis_hash(DIFF, rule), retarget=rule)
        # …but one bit below the reachable floor is not.
        cheap = TxProof(
            cb, self._mined_header(cb.txid(), DIFF - 5), 100, 120, 0, ()
        )
        with pytest.raises(SPVError, match="schedule floor"):
            verify_tx_proof(
                cheap, DIFF, genesis_hash(DIFF, rule), retarget=rule
            )
