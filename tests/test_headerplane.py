"""The spillable header plane: per-segment .hdrx indexes and the
archive serve-only boot (chain/headerplane.py, round 18).

What must hold: the plane is a pure cache of the segment bytes (byte-
identical headers, correct hash/txid lookups), and an ``ArchiveChain``
anchored on a snapshot serves header/balance/proof queries with only
the hot window materialized — including proofs for COLD transactions,
read back one record at a time from their segment.
"""

import pytest

from test_node import DIFF

from p1_tpu.chain import ChainStore, SegmentedStore, snapshot as snapmod
from p1_tpu.chain.headerplane import (
    ArchiveChain,
    HeaderPlane,
    SegmentIndex,
    write_segment_index,
)
from p1_tpu.chain.proof import verify_tx_proof
from p1_tpu.core.hashutil import sha256d
from p1_tpu.node.testing import make_blocks

SEG_BYTES = 600


@pytest.fixture(scope="module")
def blocks():
    return make_blocks(10, difficulty=DIFF)


def _linear_store(path, blocks, segment_bytes=SEG_BYTES):
    """A LINEAR segmented store: genesis at record 0, ordinal == height
    (the archive-serving shape)."""
    store = SegmentedStore(path, segment_bytes=segment_bytes)
    for h, block in enumerate(blocks):
        store.append(block, height=h)
    store.close()
    return store


def _snapshot_at(blocks, height, path):
    """A PR 9 snapshot file for the chain at ``height``."""
    from p1_tpu.chain.chain import Chain

    chain = Chain(DIFF)
    chain.checkpoint_interval = height
    for b in blocks[1:]:
        chain.add_block(b)
    h, block, balances, nonces, root = chain.snapshot_state()
    assert h == height
    manifest, chunks = snapmod.build_records(h, block, balances, nonces)
    snapmod.write_snapshot(path, manifest, chunks)
    return chain


class TestSegmentIndex:
    def test_round_trip(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        _linear_store(path, blocks)
        seg_dir = path.with_name(path.name + ".d")
        seg0 = seg_dir / "seg00000.p1s"
        data = seg0.read_bytes()
        hx = tmp_path / "seg0.hdrx"
        n = write_segment_index(data, hx)
        idx = SegmentIndex(hx)
        assert idx.count == n > 0
        spans = ChainStore.scan(data).spans
        for ordinal, (off, length) in enumerate(spans):
            hdr = data[off : off + 80]
            assert idx.header_at(ordinal) == hdr
            assert idx.find_hash(sha256d(hdr)) == ordinal
            assert idx.record_span(ordinal) == (off, length)
        # Coinbase txids resolve to their record (genesis carries no
        # transactions — nothing of it lands in the txid index).
        for ordinal in range(n):
            block = blocks[ordinal]
            if block.txs:
                assert idx.find_txid(block.txs[0].txid()) == ordinal
        assert idx.find_hash(b"\x00" * 32) is None
        assert idx.find_txid(b"\xff" * 32) is None
        idx.close()

    def test_corrupt_index_refused(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        _linear_store(path, blocks)
        seg0 = path.with_name(path.name + ".d") / "seg00000.p1s"
        hx = tmp_path / "bad.hdrx"
        write_segment_index(seg0.read_bytes(), hx)
        data = bytearray(hx.read_bytes())
        data[20] ^= 0x01
        hx.write_bytes(bytes(data))
        with pytest.raises(ValueError, match="CRC mismatch"):
            SegmentIndex(hx)


class TestHeaderPlane:
    def test_cumulative_ordinals(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        store = _linear_store(path, blocks)
        seg_dir = path.with_name(path.name + ".d")
        indexes = []
        for seg in store.segments:
            hx = seg_dir / f"seg{seg.seg_id:05d}.hdrx"
            write_segment_index((seg_dir / seg.name).read_bytes(), hx)
            indexes.append(SegmentIndex(hx))
        plane = HeaderPlane(indexes)
        assert plane.count == len(blocks)
        for h, block in enumerate(blocks):
            assert plane.header_at(h) == block.header.serialize()
            assert plane.hash_at(h) == block.block_hash()
        assert plane.header_at(len(blocks)) is None
        hit = plane.find_txid(blocks[3].txs[0].txid())
        assert hit is not None and hit[0] == 3
        plane.close()


class TestArchiveChain:
    def test_boot_and_serve(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        _linear_store(path, blocks)
        snap_path = tmp_path / "snap.p1s"
        full = _snapshot_at(blocks, 8, snap_path)
        arch = ArchiveChain(path, snap_path, DIFF)
        try:
            assert arch.base_height == 8
            assert arch.height == len(blocks) - 1
            # Headers: hot window above the base, plane below it.
            for h, block in enumerate(blocks):
                assert arch.header_bytes_at(h) == block.header.serialize()
                assert arch.hash_at(h) == block.block_hash()
            # Balances match the fully-replayed chain's ledger.
            for acct in full.balances_snapshot():
                assert arch.balance(acct) == full.balance(acct)
            # A COLD proof (below the base) is served from the plane +
            # one record read, and verifies end to end.
            cold_txid = blocks[2].txs[0].txid()
            proof = arch.tx_proof(cold_txid)
            assert proof is not None and proof.height == 2
            verify_tx_proof(
                proof, DIFF, blocks[0].block_hash(), txid=cold_txid
            )
            # A hot proof comes from the chain window.
            hot_txid = blocks[-1].txs[0].txid()
            hot = arch.tx_proof(hot_txid)
            assert hot is not None and hot.height == len(blocks) - 1
            assert arch.tx_proof(b"\x00" * 32) is None
            # The whole-archive PoW replay holds.
            report, count = arch.verify_headers()
            assert count == len(blocks) and report.valid
        finally:
            arch.close()

    def test_wrong_snapshot_refused(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        _linear_store(path, blocks)
        other = make_blocks(9, difficulty=DIFF, miner_id="someone-else")
        snap_path = tmp_path / "wrong.p1s"
        _snapshot_at(other, 8, snap_path)
        with pytest.raises(ValueError, match="does not match"):
            ArchiveChain(path, snap_path, DIFF)

    def test_nonlinear_store_refused(self, tmp_path, blocks):
        """A node-style log (no genesis record) fails the linearity
        gate instead of serving wrong heights."""
        path = tmp_path / "chain.dat"
        store = SegmentedStore(path, segment_bytes=SEG_BYTES)
        # Records out of line: skip genesis AND drop a middle block.
        for h, block in enumerate(blocks[2:], start=2):
            store.append(block, height=h)
        store.close()
        snap_path = tmp_path / "snap.p1s"
        _snapshot_at(blocks, 8, snap_path)
        with pytest.raises(ValueError):
            ArchiveChain(path, snap_path, DIFF)

    def test_pruned_cold_bodies_refuse_proofs_keep_headers(
        self, tmp_path, blocks
    ):
        path = tmp_path / "chain.dat"
        _linear_store(path, blocks)
        snap_path = tmp_path / "snap.p1s"
        _snapshot_at(blocks, 8, snap_path)
        store = SegmentedStore(path)
        store.acquire()
        first = store.segments[0]
        assert store.prune_below(first.max_height + 1) >= 1
        store.close()
        arch = ArchiveChain(path, snap_path, DIFF)
        try:
            # Headers below the pruned floor still serve (the plane
            # survives the bodies)...
            for h in range(first.max_height + 1):
                assert arch.header_bytes_at(h) == blocks[h].header.serialize()
            # ...but proofs there honestly refuse.
            assert arch.tx_proof(blocks[1].txs[0].txid()) is None
        finally:
            arch.close()


@pytest.mark.slow
def test_archive_scale_acceptance_1m(tmp_path):
    """The acceptance property at tier-1-adjacent scale: a synthetic
    1M-block segmented archive boots in a FRESH process and serves
    header/balance/proof queries with peak RSS far under the 1 GB bar
    (the 10M shape runs in bench.py behind P1_BENCH_ARCHIVE — same
    code path, same flat-RSS mechanism)."""
    from benchmarks.archive_scale import bench_archive

    out = bench_archive(1_000_000, keep=str(tmp_path / "arch"))
    assert out["height"] == 999_999
    assert out["archive_boot_rss_mb"] < 1024, out
    assert out["archive_query_qps"] > 1_000
    assert out["archive_proof_qps"] > 100
    assert out["archive_resume_bps"] > 10_000
