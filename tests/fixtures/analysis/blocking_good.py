"""GOOD fixture: blocking work dispatched OFF the loop.

The sync helper may open/fsync freely — it runs in a worker via
``asyncio.to_thread`` (node.py's ``_checkpoint_mempool`` house
pattern).  A nested sync ``def`` resets the async context: its body
runs wherever it is CALLED, which for these helpers is off-loop.
"""

import asyncio
import os
import time


def sync_append(path, payload) -> None:
    with open(path, "ab") as fh:
        fh.write(payload)
        os.fsync(fh.fileno())


async def checkpoint(path, payload) -> None:
    await asyncio.to_thread(sync_append, path, payload)


async def pace() -> None:
    await asyncio.sleep(0.01)  # the loop-relative sleep spelling


def bench() -> None:
    time.sleep(0.01)  # sync context: no loop to stall
