"""BAD fixture: synchronous stalls on the event loop.

Each construct here stalls frame reads, ping deadlines, the governor
tick, and mining for its full duration — and the simulator cannot see
it (the virtual clock does not advance during host-side blocking), so
soaks meet it only as unexplained tail latency.  The grants this rule
forces in product code are ROADMAP item 5's work list.
"""

import os
import subprocess
import time


async def handler(path):
    time.sleep(0.1)  # LINT
    fh = open(path, "rb")  # LINT
    data = fh.read()
    os.fsync(fh.fileno())  # LINT
    subprocess.run(["sync"])  # LINT
    return data
