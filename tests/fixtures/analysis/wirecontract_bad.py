"""BAD fixture: a wire surface with classification holes.

The incident shape: a frame type lands with its encoder, decoder, and
dispatch arm (it WORKS, so review moves on) but misses one registry —
and an unclassified frame silently rides the most permissive default
(uncharged by admission, never shed, no version row).  Three holes,
one per member, each anchored at the member's enum line:

- BLOCK is in neither ``_SHED_DROPS`` nor ``_SHED_KEEPS`` — the
  negative control the acceptance criteria name (key ``BLOCK:shed``);
- TX has no ``_dispatch`` arm (key ``TX:dispatch``);
- STATUS has no ``MSG_SINCE`` version row (key ``STATUS:version``);
- HELLO has no ``_RELAY_ACCOUNTING`` family (key ``HELLO:relay``) —
  round 23's seventh aspect: unaccounted egress is bandwidth the
  propagation budget can't see.
"""

import enum

PROTOCOL_VERSION = 9


class MsgType(enum.IntEnum):
    HELLO = 1  # LINT
    BLOCK = 2  # LINT
    TX = 3  # LINT
    STATUS = 4  # LINT


def encode_hello(h):
    return bytes([MsgType.HELLO]) + h


def encode_block(b):
    return bytes([MsgType.BLOCK]) + b


def encode_tx(t):
    return bytes([MsgType.TX]) + t


def encode_status(s):
    return bytes([MsgType.STATUS]) + s


def _decode(payload):
    mtype = MsgType(payload[0])
    if mtype is MsgType.HELLO:
        return mtype, payload[1:]
    if mtype is MsgType.BLOCK:
        return mtype, payload[1:]
    if mtype is MsgType.TX:
        return mtype, payload[1:]
    if mtype is MsgType.STATUS:
        return mtype, payload[1:]
    raise ValueError("unknown message type")


_MSG_CLASS = {
    MsgType.BLOCK: "blocks",
    MsgType.TX: "txs",
}

_ADMISSION_EXEMPT = frozenset({MsgType.HELLO, MsgType.STATUS})

_SHED_DROPS = frozenset({MsgType.TX})

_SHED_KEEPS = frozenset({MsgType.HELLO, MsgType.STATUS})

_RELAY_ACCOUNTING = {
    MsgType.BLOCK: "block",
    MsgType.TX: "tx",
    MsgType.STATUS: "control",
}

MSG_SINCE = {
    MsgType.HELLO: 1,
    MsgType.BLOCK: 1,
    MsgType.TX: 2,
}


class Node:
    async def _dispatch(self, peer, payload):
        mtype, body = _decode(payload)
        if mtype is MsgType.BLOCK:
            await self.handle_block(body)
        elif mtype is MsgType.STATUS:
            await self.handle_status(body)
        elif mtype is MsgType.HELLO:
            raise ValueError("unexpected HELLO")
