"""BAD fixture: consensus state read, awaited past, written stale.

The shape the chaos plane hunts dynamically: every ``await`` is a
scheduling point where a frame handler can accept a block, the miner
can seal one, or a crash callback can fire — the value read before
the await describes a world that may no longer exist by the write.
"""


class Node:
    async def resume(self):
        chain = self.chain
        blocks = await self.load_store()
        self.chain = self.rebuild(chain, blocks)  # LINT

    async def swap_pool(self):
        rows = self.mempool.snapshot()
        packed = await self.encode(rows)
        self.mempool = self.unpack(packed)  # LINT
