"""BAD fixture: the loop stalled through sync helpers.

The incident shape the lexical ``blocking-in-async`` rule cannot see:
the fsync lives one (or three) sync helpers below a spotless-looking
``async def`` — the store-append chain every round-3/round-8 outage
postmortem walked by hand.  The call graph resolves ``self.store``
through the class's constructor binding and follows the chain to the
primitive.
"""

import os
import time


def _write_record(fh, data):
    fh.write(data)
    os.fsync(fh.fileno())


def _persist(path, data):
    fh = open(path, "wb")
    _write_record(fh, data)


class Store:
    def append(self, data):
        _persist("chain.dat", data)


def _sleep_helper():
    time.sleep(1.0)


class Node:
    def __init__(self):
        self.store = Store()

    async def handle_block(self, block):
        self.store.append(block)  # LINT

    async def pause(self):
        _sleep_helper()  # LINT
