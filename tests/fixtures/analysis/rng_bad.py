"""BAD fixture: unseeded/ambient randomness (sim traces diverge)."""

import random


def jitter() -> float:
    rng = random.Random()  # LINT
    return rng.random()


def pick(items):
    return random.choice(items)  # LINT


def roll() -> float:
    return random.random()  # LINT


def shuffle_peers(peers) -> None:
    random.shuffle(peers)  # LINT
