"""GOOD fixture: the same mini wire surface, exhaustively classified.

Every member threads all six registries — encoder, ``_decode`` arm,
``_dispatch`` arm, exactly one admission classification, exactly one
SHED classification, and a ``MSG_SINCE`` row at or below
``PROTOCOL_VERSION``.
"""

import enum

PROTOCOL_VERSION = 9


class MsgType(enum.IntEnum):
    HELLO = 1
    BLOCK = 2
    TX = 3
    STATUS = 4


def encode_hello(h):
    return bytes([MsgType.HELLO]) + h


def encode_block(b):
    return bytes([MsgType.BLOCK]) + b


def encode_tx(t):
    return bytes([MsgType.TX]) + t


def encode_status(s):
    return bytes([MsgType.STATUS]) + s


def _decode(payload):
    mtype = MsgType(payload[0])
    if mtype is MsgType.HELLO:
        return mtype, payload[1:]
    if mtype is MsgType.BLOCK:
        return mtype, payload[1:]
    if mtype is MsgType.TX:
        return mtype, payload[1:]
    if mtype is MsgType.STATUS:
        return mtype, payload[1:]
    raise ValueError("unknown message type")


_MSG_CLASS = {
    MsgType.BLOCK: "blocks",
    MsgType.TX: "txs",
}

_ADMISSION_EXEMPT = frozenset({MsgType.HELLO, MsgType.STATUS})

_SHED_DROPS = frozenset({MsgType.TX})

_SHED_KEEPS = frozenset({MsgType.HELLO, MsgType.BLOCK, MsgType.STATUS})

MSG_SINCE = {
    MsgType.HELLO: 1,
    MsgType.BLOCK: 1,
    MsgType.TX: 2,
    MsgType.STATUS: 9,
}


class Node:
    async def _dispatch(self, peer, payload):
        mtype, body = _decode(payload)
        if mtype is MsgType.BLOCK:
            await self.handle_block(body)
        elif mtype is MsgType.TX:
            await self.handle_tx(body)
        elif mtype is MsgType.STATUS:
            await self.handle_status(body)
        elif mtype is MsgType.HELLO:
            raise ValueError("unexpected HELLO")
