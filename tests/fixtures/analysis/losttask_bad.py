"""BAD fixture: spawned task handles nobody holds.

``_store_fail`` reproduces the round-3 historical bug (then in
node/node.py): the store-recovery loop was spawned fire-and-forget,
died of an unhandled exception, and the node sat degraded serve-only
forever with nothing logged — nobody held the handle, so nobody
observed the death.  The fix is the ``_spawn_store_recovery`` +
``_store_recovery_done`` pattern: track, log, respawn.
"""

import asyncio


class Node:
    async def _store_fail(self) -> None:
        asyncio.create_task(self._store_recovery_loop())  # LINT

    async def _dial(self, addr) -> None:
        task = asyncio.create_task(self._dial_once(addr))  # LINT

    async def _legacy_spawn(self) -> None:
        asyncio.ensure_future(self._dial_once(None))  # LINT

    async def _store_recovery_loop(self) -> None: ...

    async def _dial_once(self, addr) -> None: ...
