"""BAD fixture: consensus read/write routed through helpers across an
await.

The shape ``await-state`` concedes in docs/LINT.md: hide either
endpoint one method call down and the lexical rule goes blind, though
the interleaving hazard is identical — the helper just holds the
stale value one frame lower.  Reproduces the helper-routed chain
write the snapshot-adoption path made real in round 12.
"""


class Node:
    def _read_tip(self):
        return self.chain

    def _install(self, chain):
        self.chain = chain

    def _pool_rows(self):
        return self.mempool.snapshot()

    async def resume(self):
        tip = self._read_tip()
        blocks = await self.load(tip)
        self._install(blocks)  # LINT

    async def swap_pool(self):
        rows = self._pool_rows()
        packed = await self.encode(rows)
        self.mempool = self.unpack(packed)  # LINT
