"""BAD fixture: every `# LINT` line must be flagged by wall-clock.

``encode_block`` reproduces the round-11 historical bug (then in
node/protocol.py): a codec DEFAULT stamping send time from the host
clock put nondeterminism inside frame bytes, so simulated flood traces
diverged run-to-run.  The fix encoded 0.0 = "no stamp" and moved
stamping to callers' transport clocks.
"""

import asyncio
import time
from datetime import datetime


def encode_block(payload: bytes, when=None) -> bytes:
    stamp = when if when is not None else time.time()  # LINT
    return payload + repr(stamp).encode()


def deadline(budget_s: float) -> float:
    return time.monotonic() + budget_s  # LINT


def bench() -> float:
    return time.perf_counter()  # LINT


def log_stamp() -> str:
    return datetime.now().isoformat()  # LINT


async def pace() -> None:
    await asyncio.sleep(0.1)  # LINT
