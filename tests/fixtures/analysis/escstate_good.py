"""GOOD fixture: helpers touch consensus state, but never with an
await inside the read→write window.

Same helpers as the bad fixture — the rule flags the INTERLEAVING,
not the helpers: reads re-taken after the scheduling point and
write-before-await windows are the safe shapes the grant text points
fixes at.
"""


class Node:
    def _read_tip(self):
        return self.chain

    def _install(self, chain):
        self.chain = chain

    async def resume(self):
        blocks = await self.load()
        tip = self._read_tip()  # re-read AFTER the await: fresh world
        self._install(self.merge(tip, blocks))

    async def rebuild(self):
        tip = self._read_tip()
        self._install(tip)  # same tick as the read — no window
        await self.announce()
