"""GOOD fixture: every handle is stored, awaited, or callback'd."""

import asyncio


class Node:
    def __init__(self):
        self._sessions = {}
        self._task = None

    async def spawn_stored(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def spawn_tracked(self) -> None:
        # the node's session pattern: container + done-callback that
        # unregisters AND observes a crash
        task = asyncio.create_task(self._loop())
        self._sessions[task] = None
        task.add_done_callback(self._sessions.pop)

    async def spawn_awaited(self) -> None:
        await asyncio.create_task(self._loop())

    async def spawn_cancelled_later(self) -> None:
        t = asyncio.create_task(self._loop())
        try:
            await asyncio.sleep(1)
        finally:
            t.cancel()

    async def _loop(self) -> None: ...
