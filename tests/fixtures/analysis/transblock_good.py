"""GOOD fixture: blocking chains exist but never run on the loop.

The house off-load pattern: the blocking helper is PASSED to
``asyncio.to_thread`` (no call edge — its body runs off-loop), and
purely-async chains cross as many helpers as they like.  A DIRECT
blocking call inside an ``async def`` is deliberately absent from
this rule's findings too — that is ``blocking-in-async``'s domain
(zero hops); this rule owns the ≥1-hop chains.
"""

import asyncio
import os
import time


def _write_record(fh, data):
    fh.write(data)
    os.fsync(fh.fileno())


def _persist(path, data):
    with open(path, "wb") as fh:
        _write_record(fh, data)


class Node:
    async def checkpoint(self, data):
        await asyncio.to_thread(_persist, "chain.dat", data)

    async def nap(self):
        time.sleep(0.0)  # blocking-in-async's finding, not this rule's

    async def relay(self, frame):
        await self._send(frame)

    async def _send(self, frame):
        await asyncio.sleep(0)
