"""GOOD fixture: no read-await-write window on watched state.

Safe shapes: read-then-await with no write; write whose value came
from the await itself with no prior read; read and write on the same
side of every scheduling point.  (A method that DOES re-validate after
the await still carries the structural window and takes a grant with
the safety argument written down — the rule cannot see guards.)
"""


class Node:
    async def announce_tip(self):
        tip = self.chain
        await self.send(tip)  # read, await, no write: nothing stale

    async def install_fresh(self):
        # the value POSTDATES the scheduling point — nothing was
        # decided from a pre-await read
        self.chain = await self.build_chain()

    async def checkpoint(self):
        rows = self.mempool.rows()
        self.mempool = self.compact(rows)  # read+write BEFORE any await
        await self.flush()
