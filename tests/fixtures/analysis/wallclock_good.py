"""GOOD fixture: clock access routed through the injectable seam.

The bare ``time.monotonic`` default is the house pattern the AST rule
handles structurally: a REFERENCE is not a call, so no grant is needed
(the retired tokenizer scanner got this right only by substring luck).
"""

import time


def deadline(budget_s: float, clock=time.monotonic) -> float:
    return clock() + budget_s


class Node:
    def __init__(self, clock):
        self.clock = clock

    def stamp(self) -> float:
        return self.clock.time()  # the seam's clock, not the host's

    def age(self, since: float) -> float:
        return self.clock.monotonic() - since
