"""BAD fixture: iteration order rides the hash table.

``announce`` reproduces the round-7 historical class (relay/dial order
depended on set order until peer bookkeeping moved to insertion-ordered
dicts); ``probe`` reproduces round 13's chaos.py finding (invariant
probe heights iterated from a set literal, so violation-report order —
and any repro shrunk from it — rode hash order).
"""


def announce(want, have, send):
    for h in set(want) - set(have):  # LINT
        send(h)


def probe(height: int):
    for h in {1, height // 2, height}:  # LINT
        yield h


def unseen(book: dict, seen: dict):
    for key in book.keys() - seen.keys():  # LINT
        yield key


def union_scan(a, b):
    return [x for x in set(a) | set(b)]  # LINT


def trimmed(peers, banned):
    for p in frozenset(peers).difference(banned):  # LINT
        yield p


def gather(peers, extra):
    # the round-16 one-hop upgrade: a LOCAL bound only to set
    # expressions and then iterated — the "through a variable" residue
    # the round-13 docs conceded
    pending = set(peers)
    pending = pending | set(extra)
    for p in pending:  # LINT
        yield p


def spray(book):
    hot = {k for k in book if book[k]}
    return [send(p) for p in hot]  # LINT


def send(p):
    return p
