"""GOOD fixture: every draw descends from a derived seed — or spells
OS entropy explicitly where production randomness is the point."""

import random
import secrets


def jitter(seed: int) -> float:
    rng = random.Random(seed ^ 0x70B0)  # derived: the scenarios.py idiom
    return rng.random()


def draw(rng: random.Random, items):
    return rng.choice(items)  # instance draw, injected by the caller


def identity_nonce() -> int:
    return secrets.randbits(64) | 1  # production identity: entropy intended


def production_rng() -> random.Random:
    # explicit OS-entropy seed: the supervision.py round-13 fix spelling
    return random.Random(secrets.randbits(64))
