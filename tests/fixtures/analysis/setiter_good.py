"""GOOD fixture: dedup without surrendering the iteration order."""


def announce(want, have, send):
    for h in sorted(set(want) - set(have)):  # the sort normalizes
        send(h)


def fanout(peers: dict):
    # dict[key, None] as an insertion-ordered set: the round-7 fix
    for peer in peers:
        yield peer


def probe(height: int):
    for h in sorted({1, height // 2, height}):  # the chaos.py r13 fix
        yield h


def membership(want, have):
    # sets for MEMBERSHIP are fine — only iteration leaks the order
    have_set = set(have)
    return [h for h in want if h not in have_set]


def normalized(peers):
    # a non-set rebinding takes the local out of the set class: the
    # normalize-then-iterate idiom stays clean under the one-hop rule
    pending = set(peers)
    pending = sorted(pending)
    for p in pending:
        yield p


def parameter(pending):
    # parameters are never classified (no structural evidence)
    for p in pending:
        yield p
