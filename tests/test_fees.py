"""Fee estimation: chain-side percentiles, the GETFEES/FEES wire round,
and the wallet query path."""

import asyncio

import pytest

from txutil import account, stx

from test_consensus import DIFF, _funded_chain, _mine_child
from test_node import _config, fund, wait_until

from p1_tpu.chain import AddStatus
from p1_tpu.core import Transaction
from p1_tpu.node import Node, protocol
from p1_tpu.node.client import get_fees
from p1_tpu.node.protocol import FeeStats, MsgType


class TestChainFeeStats:
    def test_empty_chain_suggests_nothing(self):
        chain, _ = _funded_chain("alice")
        stats = chain.fee_stats()
        assert stats["samples"] == 0
        assert stats["p50"] == 0
        assert stats["window_blocks"] >= 1  # blocks seen, no transfers

    def test_percentiles_over_recent_transfers(self):
        chain, b1 = _funded_chain("alice")
        fees = [1, 2, 3, 4, 5, 6, 7, 8]
        tip = b1
        for i, fee in enumerate(fees):
            tip = _mine_child(
                tip,
                txs=(
                    Transaction.coinbase("m", chain.height + 1),
                    stx("alice", account("bob"), 1, fee, i),
                ),
            )
            assert chain.add_block(tip).status is AddStatus.ACCEPTED
        stats = chain.fee_stats(window=100)
        assert stats["samples"] == 8
        assert stats["p25"] == 3 and stats["p50"] == 5 and stats["p75"] == 7
        # A small window samples only the latest blocks.
        assert chain.fee_stats(window=2)["samples"] == 2
        assert chain.fee_stats(window=2)["p50"] == 8

    def test_window_never_includes_genesis(self):
        chain, _ = _funded_chain("alice")
        stats = chain.fee_stats(window=1000)
        assert stats["window_blocks"] == chain.height


class TestWire:
    def test_round_trips(self):
        mtype, got = protocol.decode(protocol.encode_getfees())
        assert mtype is MsgType.GETFEES and got == 0
        mtype, got = protocol.decode(protocol.encode_getfees(64))
        assert got == 64
        stats = FeeStats(32, 100, 1, 2, 3, 999)
        mtype, got = protocol.decode(protocol.encode_fees(stats))
        assert mtype is MsgType.FEES and got == stats

    @pytest.mark.parametrize(
        "payload",
        [
            bytes([MsgType.GETFEES]),  # no window
            bytes([MsgType.GETFEES]) + b"\x00\x00\x00",  # oversized
            bytes([MsgType.FEES]) + b"\x00" * 33,  # short
            bytes([MsgType.FEES]) + b"\x00" * 35,  # long
        ],
    )
    def test_malformed_rejected(self, payload):
        with pytest.raises(ValueError):
            protocol.decode(payload)


class TestWalletQuery:
    def test_live_node_serves_fee_stats(self):
        NODE_DIFF = 12

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                await fund(node, "alice", blocks=1)
                for i, fee in enumerate((2, 4, 6)):
                    await node.submit_tx(
                        stx(
                            "alice",
                            account("bob"),
                            1,
                            fee,
                            i,
                            difficulty=NODE_DIFF,
                        )
                    )
                node.start_mining()
                assert await wait_until(
                    lambda: node.chain.fee_stats()["samples"] >= 3
                )
                await node.stop_mining()
                stats = await get_fees("127.0.0.1", node.port, NODE_DIFF)
                assert stats.samples >= 3
                assert stats.p25 >= 2 and stats.p75 <= 6
                assert stats.tip_height == node.chain.height
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))
