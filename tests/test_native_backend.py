"""Native (C++/ctypes) backend: digest + search parity, both compressions.

Skips cleanly when no C++ toolchain is available; on x86 with SHA-NI both
the hardware path and the portable scalar fallback are exercised via the
force_scalar test hook.
"""

import hashlib
import os
import random

import pytest

from p1_tpu.core import BlockHeader


@pytest.fixture(scope="module")
def native():
    from p1_tpu.hashx.native_build import NativeBuildError

    try:
        from p1_tpu.hashx import get_backend

        be = get_backend("native")
    except NativeBuildError as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    yield be
    be.force_scalar(False)


def _prefix(seed: int) -> bytes:
    rng = random.Random(seed)
    return BlockHeader(
        1, rng.randbytes(32), rng.randbytes(32), 1735689700, 12, 0
    ).mining_prefix()


@pytest.mark.parametrize("scalar", [False, True])
class TestNative:
    def test_sha256d_matches_hashlib(self, native, scalar):
        native.force_scalar(scalar)
        rng = random.Random(42)
        # Lengths straddling every padding boundary.
        for n in (0, 1, 31, 32, 55, 56, 57, 63, 64, 65, 80, 119, 120, 1000):
            data = rng.randbytes(n)
            want = hashlib.sha256(hashlib.sha256(data).digest()).digest()
            assert native.sha256d(data) == want, f"len={n} scalar={scalar}"

    def test_search_parity_with_cpu(self, native, scalar):
        from p1_tpu.hashx import get_backend

        native.force_scalar(scalar)
        for seed in (0, 3):
            prefix = _prefix(seed)
            got = native.search(prefix, 0, 1 << 14, 10)
            want = get_backend("cpu").search(prefix, 0, 1 << 14, 10)
            assert got == want, f"seed={seed} scalar={scalar}"

    def test_nonce_start_and_no_hit(self, native, scalar):
        native.force_scalar(scalar)
        prefix = _prefix(1)
        assert native.search(prefix, 500, 64, 0).nonce == 500
        assert native.search(prefix, 0, 64, 255).nonce is None


def test_env_gate_matches(native):
    # The cross-backend parity suite includes "native" when this env var is
    # set (tests/test_hash_backends.py); make sure the gate stays wired.
    if os.environ.get("P1_TEST_NATIVE"):
        from p1_tpu.hashx import available_backends

        assert "native" in list(available_backends())
