"""Multi-host pod mining: 2 JAX-distributed processes, one miner on the net.

The real thing, no mocks: two ``p1 pod`` subprocesses join one
jax.distributed mesh (Gloo over localhost — the CPU stand-in for a
multi-host TPU pod), mirror the sharded shard_map+pmin search in lockstep,
and the leader gossips the mined blocks to a plain listener node — which
is exactly the north star's "pod presents as a single miner on the gossip
network" (BASELINE.json:5, config 5).
"""

import json
import os
import socket
import subprocess
import sys

import pytest


def _cpu_multiprocess_collectives_available() -> bool:
    """Whether this jax can run cross-process collectives on the CPU
    backend (what every test here needs: the pod is N processes in one
    jax.distributed mesh doing a pmin per search).  The capability
    shipped with the CPU collectives layer (``jax_cpu_collectives`` =
    gloo/mpi); on earlier jax (e.g. the 0.4.x in this image) a CPU mesh
    initializes but wedges or errors on the first collective, so the
    suite would fail for environment reasons, not product ones."""
    import jax

    return hasattr(jax.config, "jax_cpu_collectives")


#: Collection-time gate: an env-limited capability gap is a SKIP with a
#: reason, not 4 standing failures — a green run must mean green (and
#: pytest's lastfailed cache stays empty for `--lf` users).
pytestmark = pytest.mark.skipif(
    not _cpu_multiprocess_collectives_available(),
    reason="jax CPU backend lacks multiprocess collectives "
    "(no jax_cpu_collectives support in this jax build)",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(n_devices: int) -> dict:
    env = dict(os.environ)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_config_mismatch_fails_loudly():
    # chunk differs between processes: the construction-time handshake must
    # turn the would-be silent collective desync into an explicit error.
    coord = _free_port()
    env = _env(2)
    base = [
        sys.executable, "-m", "p1_tpu", "pod",
        "--coordinator", f"127.0.0.1:{coord}",
        "--num-hosts", "2", "--platform", "cpu",
        "--difficulty", "12", "--batch", "256", "--duration", "4",
    ]
    leader = subprocess.Popen(
        [*base, "--host-id", "0", "--chunk", "4096", "--port", "0"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    follower = subprocess.Popen(
        [*base, "--host-id", "1", "--chunk", "8192"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        # Generous timeout: two interpreter+distributed-runtime startups on
        # a loaded 1-vCPU box can take a while before the handshake runs.
        _, err = follower.communicate(timeout=150)
    finally:
        for proc in (leader, follower):
            if proc.poll() is None:
                proc.kill()
    assert follower.returncode != 0
    assert "mismatch" in err, err[-2000:]


def test_two_process_pod_mines_and_gossips():
    coord = _free_port()
    listen_port = _free_port()
    env = _env(4)

    # A plain non-mining node: the gossip network the pod presents to.
    # Test-driven shutdown (--deadline stdin): the listener must outlive
    # the pod BY CONSTRUCTION.  A fixed duration raced the pod's two
    # interpreter+jax.distributed startups — on a loaded 1-vCPU host a
    # 30 s listener died before an 8 s-duration pod finished gossiping
    # (the duration-vs-deadline inconsistency class of VERDICT r5 weak
    # #1), failing the height comparison below for budget reasons.
    listener = subprocess.Popen(
        [
            sys.executable, "-m", "p1_tpu", "node",
            "--port", str(listen_port), "--difficulty", "12",
            "--backend", "cpu", "--no-mine", "--deadline", "stdin",
        ],
        env=env,
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    pod_cmd = [
        sys.executable, "-m", "p1_tpu", "pod",
        "--coordinator", f"127.0.0.1:{coord}",
        "--num-hosts", "2",
        "--platform", "cpu",
        "--difficulty", "12",
        "--chunk", str(1 << 12),
        "--batch", "256",
        "--duration", "8",
    ]
    leader = subprocess.Popen(
        [*pod_cmd, "--host-id", "0", "--port", "0",
         "--peers", f"127.0.0.1:{listen_port}", "--miner-id", "pod"],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    follower = subprocess.Popen(
        [*pod_cmd, "--host-id", "1"],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    try:
        leader_out, _ = leader.communicate(timeout=120)
        follower_out, _ = follower.communicate(timeout=60)
        # The whole pod is down and every block it gossiped is already
        # in flight or landed: NOW the listener may quiesce (it drains
        # its gossip backlog before exiting — cli.py's stability loop).
        import time

        listener.stdin.write(f"{time.time()!r}\n")
        listener.stdin.flush()
        listener_out, _ = listener.communicate(timeout=60)
    finally:
        for proc in (leader, follower, listener):
            if proc.poll() is None:
                proc.kill()

    assert leader.returncode == 0, leader_out[-2000:]
    assert follower.returncode == 0, follower_out[-2000:]
    assert listener.returncode == 0, listener_out[-2000:]

    leader_status = json.loads(leader_out.strip().splitlines()[-1])
    follower_status = json.loads(follower_out.strip().splitlines()[-1])
    listener_status = json.loads(listener_out.strip().splitlines()[-1])

    # The pod mined in lockstep: every leader search was mirrored.
    assert leader_status["height"] > 0
    assert follower_status["role"] == "follower"
    assert follower_status["searches"] > 0
    # ... and the network saw ONE miner: the listener followed the chain.
    assert listener_status["height"] == leader_status["height"]
    assert listener_status["tip"] == leader_status["tip"]
    assert listener_status["blocks_mined"] == 0


def test_leader_survives_follower_sigkill(tmp_path):
    """VERDICT r3 item 8 / SURVEY §5 elastic recovery: SIGKILL a follower
    mid-run -> the leader must NOT go dark.  Its watchdog re-execs it into
    single-process sharded mining against the same store, so the chain
    keeps growing within the grace window."""
    import signal
    import time

    from p1_tpu.chain import ChainStore

    coord = _free_port()
    store = tmp_path / "pod-chain.dat"
    env = _env(4)
    env["P1_POD_GRACE_S"] = "20"  # must still cover the first jit compile
    pod_cmd = [
        sys.executable, "-m", "p1_tpu", "pod",
        "--coordinator", f"127.0.0.1:{coord}",
        "--num-hosts", "2",
        "--platform", "cpu",
        "--difficulty", "12",
        "--chunk", str(1 << 12),
        "--batch", "256",
        # Comfortably above the worst-case sum of the phase budgets below
        # (120 s first-blocks wait + 75 s post-kill growth window): the
        # old 90 s duration could expire INSIDE the post-kill window on a
        # loaded host — mining started at t≈60 left only 30 s of leader
        # life for a 75 s assertion (the VERDICT r5 weak #1 budget-race
        # class).  Teardown kills the processes, so the test never
        # actually waits this long.
        "--duration", "400",
    ]
    log = open(tmp_path / "leader.log", "w")
    leader = subprocess.Popen(
        [*pod_cmd, "--host-id", "0", "--port", "0",
         "--miner-id", "pod", "--store", str(store)],
        env=env, stdout=log, stderr=log,
    )
    follower = subprocess.Popen(
        [*pod_cmd, "--host-id", "1"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )

    def store_blocks() -> int:
        try:
            return len(ChainStore(store).load_blocks())
        except (FileNotFoundError, ValueError):
            return 0

    try:
        # Wait for the pod to actually mine (store grows past genesis).
        deadline = time.monotonic() + 120
        while store_blocks() < 3 and time.monotonic() < deadline:
            time.sleep(0.5)
        pre_kill = store_blocks()
        assert pre_kill >= 3, "pod never started mining"

        follower.send_signal(signal.SIGKILL)
        follower.wait(timeout=10)

        # Within grace (20s) + margin the leader must fail over and keep
        # extending the SAME store — same pid, new process image.
        deadline = time.monotonic() + 75
        grown = False
        while time.monotonic() < deadline:
            if store_blocks() >= pre_kill + 3:
                grown = True
                break
            time.sleep(1.0)
        assert grown, (
            f"chain stuck at {store_blocks()} blocks after follower kill "
            f"(pre-kill {pre_kill}); leader.log tail: "
            + open(tmp_path / "leader.log").read()[-2000:]
        )
    finally:
        for proc in (leader, follower):
            if proc.poll() is None:
                proc.kill()
        log.close()


def test_many_process_pod_with_follower_loss_and_restart(tmp_path):
    """VERDICT r4 weak #4, all three demands in one arc: (a) a
    leader + 3 follower pod mines in lockstep; (b) SIGKILL of one
    follower mid-run -> the leader fails over to single-process mining
    on the SAME store (never goes dark) and the surviving follower's
    watchdog exits 3 — the documented supervisor signal; (c) the
    supervisor recipe end-to-end: relaunch the WHOLE pod on the same
    store and the chain keeps growing from where it stopped.  Follower
    rejoin into a live mesh is not supported — jax.distributed pins
    num_processes at initialize() and a lost process wedges every
    collective — which is exactly why the contract is
    restart-the-whole-pod, and (c) proves that contract works."""
    import signal
    import time

    from p1_tpu.chain import ChainStore

    store = tmp_path / "pod3-chain.dat"
    # 4 processes x 2 local CPU devices = one 8-device global mesh.
    # (Constraints both ways: the sharded backend wants a power-of-two
    # batch split evenly, and jax's multihost broadcast wants UNIFORM
    # per-host device counts — 3x anything can't be a power of two, so
    # the smallest many-follower pod is leader + 3.)
    env = _env(2)
    env["P1_POD_GRACE_S"] = "30"

    def pod_cmd(coord: int) -> list[str]:
        return [
            sys.executable, "-m", "p1_tpu", "pod",
            "--coordinator", f"127.0.0.1:{coord}",
            "--num-hosts", "4",
            "--platform", "cpu",
            "--difficulty", "12",
            "--chunk", str(1 << 12),
            "--batch", "256",
            # Comfortably above the worst-case phase budgets so a slow
            # host can't hit the leader's own deadline mid-test;
            # teardown kills the procs.
            "--duration", "700",
        ]

    logs = []

    def tail() -> str:
        return (tmp_path / "leader.log").read_text()[-2000:]

    def launch(coord: int):
        log = open(tmp_path / "leader.log", "a")
        logs.append(log)
        leader = subprocess.Popen(
            [*pod_cmd(coord), "--host-id", "0", "--port", "0",
             "--miner-id", "pod3", "--store", str(store)],
            env=env, stdout=log, stderr=log,
        )
        followers = [
            subprocess.Popen(
                [*pod_cmd(coord), "--host-id", str(i)],
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            for i in (1, 2, 3)
        ]
        return leader, followers, log

    def store_blocks() -> int:
        try:
            return len(ChainStore(store).load_blocks())
        except (FileNotFoundError, ValueError):
            return 0

    def wait_blocks(target: int, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if store_blocks() >= target:
                return True
            time.sleep(0.5)
        return False

    leader, followers, _ = launch(_free_port())
    procs = [leader, *followers]
    try:
        # (a) the 3-process pod actually mines.
        # Generous: four interpreter+jax.distributed startups on a hot
        # 1-vCPU box (the full suite runs this late) contend hard.
        assert wait_blocks(3, 300), "4-proc pod never started mining"
        pre_kill = store_blocks()

        # (b) lose one follower mid-run.
        followers[0].send_signal(signal.SIGKILL)
        followers[0].wait(timeout=10)
        # The leader must keep the chain growing (failover within grace).
        assert wait_blocks(pre_kill + 3, 120), (
            f"chain stuck at {store_blocks()} after follower kill; "
            "leader.log tail: " + tail()
        )
        # The surviving followers exit 3 for their supervisor.
        assert followers[1].wait(timeout=90) == 3
        assert followers[2].wait(timeout=90) == 3

        # (c) the supervisor recipe: tear down, relaunch the WHOLE pod
        # against the same store, fresh coordinator.
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        pre_restart = store_blocks()
        leader, followers, _ = launch(_free_port())
        procs = [leader, *followers]
        assert wait_blocks(pre_restart + 3, 300), (
            f"restarted pod never extended the chain past {pre_restart}; "
            "leader.log tail: " + tail()
        )
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        for log in logs:
            log.close()
