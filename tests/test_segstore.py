"""Archive-scale durability: the segmented block store (round 18).

The containment pair this round exists for:

- mid-log corruption loses at most ONE SEGMENT's bad span — every
  other segment's bytes are untouched by the heal (the single-file
  heal rewrote the world);
- a crash at ANY segment-roll boundary recovers at the next acquire
  with fsck verdict <= 1 — stray segments adopt, a stale manifest
  rebuilds, and the surviving records are exactly a prefix.

Plus the upgrade (lossless single-file -> segmented, pinned by a
round-trip digest), pruning (bodies discarded below a floor, headers
surviving in the .hdrx plane), and the archive boot (header spill +
snapshot-anchored hot window).
"""

import hashlib
import os
import signal
import subprocess
import sys
import time

import pytest

from test_node import DIFF

from p1_tpu.chain import ChainStore, SegmentedStore, is_segmented, open_store
from p1_tpu.chain.segstore import DEFAULT_SEGMENT_BYTES, SegmentInfo
from p1_tpu.chain.store import MAGIC, V2_MAGIC
from p1_tpu.chain.testing import SegFaultStore, StoreFaultPlan
from p1_tpu.node.testing import make_blocks

#: Small enough that 8 mined blocks span several segments.
SEG_BYTES = 600


@pytest.fixture(scope="module")
def blocks():
    return make_blocks(8, difficulty=DIFF)


def _digest(blocks) -> bytes:
    h = hashlib.sha256()
    for b in blocks:
        h.update(b.serialize())
    return h.digest()


def _fill(path, blocks, segment_bytes=SEG_BYTES, heights=True):
    store = SegmentedStore(path, segment_bytes=segment_bytes)
    try:
        for i, block in enumerate(blocks[1:], start=1):
            store.append(block, height=i if heights else None)
    finally:
        store.close()
    return store


class TestSegmentedCore:
    def test_roll_and_roundtrip(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        store = _fill(path, blocks)
        assert len(store.segments) > 1, "no roll happened — shrink SEG_BYTES"
        assert all(s.sealed for s in store.segments[:-1])
        # Height spans landed in the manifest.
        assert store.segments[0].min_height == 1
        assert store.segments[-1].max_height == len(blocks) - 1
        # The manifest is what the path now holds.
        assert is_segmented(path)
        # Round trip: records come back byte-identical, in order.
        rd = SegmentedStore(path)
        assert _digest(rd.load_blocks()) == _digest(blocks[1:])
        rd.close()

    def test_resume_load_chain(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        _fill(path, blocks)
        store = SegmentedStore(path)
        store.acquire()
        chain = store.load_chain(DIFF, trusted=True)
        assert chain.height == len(blocks) - 1
        assert chain.tip_hash == blocks[-1].block_hash()
        store.close()

    def test_read_body_across_segments(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        _fill(path, blocks)
        store = SegmentedStore(path)
        list(store.iter_blocks())  # registers spans
        for b in blocks[1:]:
            bh = b.block_hash()
            assert store.has_body(bh)
            assert store.read_body(bh).serialize() == b.serialize()
        store.close()

    def test_append_rejects_duplicate_writer(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        a = SegmentedStore(path, segment_bytes=SEG_BYTES)
        a.acquire()
        b = SegmentedStore(path, segment_bytes=SEG_BYTES)
        with pytest.raises(RuntimeError, match="locked by another process"):
            b.acquire()
        a.close()

    def test_open_store_factory(self, tmp_path, blocks):
        seg = tmp_path / "seg.dat"
        _fill(seg, blocks)
        assert isinstance(open_store(seg), SegmentedStore)
        single = tmp_path / "single.dat"
        st = ChainStore(single)
        st.append(blocks[1])
        st.close()
        assert type(open_store(single)) is ChainStore
        assert isinstance(
            open_store(tmp_path / "fresh.dat", segment_bytes=1 << 20),
            SegmentedStore,
        )

    def test_manifest_rebuild_from_directory(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        _fill(path, blocks)
        os.unlink(path)  # the manifest dies; the segments are the data
        store = SegmentedStore(path)
        store.acquire()
        assert _digest(store.load_blocks()) == _digest(blocks[1:])
        # Heights were lost with the manifest: adopted segments are
        # never prunable.
        assert all(s.max_height is None for s in store.segments[:-1])
        store.close()

    def test_stray_segment_adopted(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        store = _fill(path, blocks)
        last = store.segments[-1].seg_id
        # A roll that crashed after creating the file but before the
        # manifest write: bare magic, not in the manifest.
        stray = path.with_name(path.name + ".d") / f"seg{last + 1:05d}.p1s"
        stray.write_bytes(MAGIC)
        rd = SegmentedStore(path)
        rd.acquire()
        assert rd.segments[-1].seg_id == last + 1
        assert _digest(rd.load_blocks()) == _digest(blocks[1:])
        rd.close()


class TestUpgrade:
    def test_single_file_upgrade_lossless(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        st = ChainStore(path)
        for b in blocks[1:]:
            st.append(b)
        st.close()
        before = _digest(ChainStore(path).load_blocks())
        store = SegmentedStore(path, segment_bytes=SEG_BYTES)
        store.acquire()
        # Upgrade happened, and the round-trip digest is identical.
        assert is_segmented(path)
        assert store.segments[0].seg_id == 0
        assert _digest(store.load_blocks()) == before
        # The original records were hard-linked, not copied: seg00000
        # holds the old file's exact bytes.
        store.close()

    def test_upgrade_refuses_v2(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        st = ChainStore(path)
        st.append(blocks[1])
        st.close()
        data = path.read_bytes()
        path.write_bytes(V2_MAGIC + data[len(MAGIC) :])
        store = SegmentedStore(path)
        with pytest.raises(RuntimeError, match="v2 chain store"):
            store.acquire()

    def test_upgrade_excluded_by_legacy_writer(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        legacy = ChainStore(path)
        legacy.append(blocks[1])  # acquires the single-file flock
        store = SegmentedStore(path)
        with pytest.raises(RuntimeError, match="locked by another process"):
            store.acquire()
        legacy.close()

    def test_legacy_writer_refuses_manifest(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        _fill(path, blocks)
        legacy = ChainStore(path)
        with pytest.raises(RuntimeError, match="not a chain store"):
            legacy.acquire()

    def test_single_file_readonly_attach_unchanged(self, tmp_path, blocks):
        """Read paths never upgrade: a single-file store attached
        read-only (no writer acquire) stays a single file."""
        path = tmp_path / "chain.dat"
        st = ChainStore(path)
        for b in blocks[1:]:
            st.append(b)
        st.close()
        rd = ChainStore(path)
        assert len(rd.load_blocks()) == len(blocks) - 1
        assert path.read_bytes().startswith(MAGIC)
        rd.close()


class TestSegmentHeal:
    def _flip_mid_segment(self, path, store):
        """Flip one byte inside a middle SEALED segment's first record
        body; returns (segment path, untouched sibling paths)."""
        segs = store.segments
        victim = segs[len(segs) // 2]
        seg_dir = path.with_name(path.name + ".d")
        vpath = seg_dir / victim.name
        data = bytearray(vpath.read_bytes())
        data[len(MAGIC) + 10] ^= 0x40
        vpath.write_bytes(bytes(data))
        others = [
            seg_dir / s.name for s in segs if s.seg_id != victim.seg_id
        ]
        return vpath, others

    def test_midlog_corruption_contained_to_one_segment(
        self, tmp_path, blocks
    ):
        path = tmp_path / "chain.dat"
        store = _fill(path, blocks)
        n_records = sum(s.records for s in store.segments)
        vpath, others = self._flip_mid_segment(path, store)
        before = {p: p.read_bytes() for p in others}
        healed = SegmentedStore(path)
        healed.acquire()
        # The bad span was quarantined NEXT TO its segment...
        assert vpath.with_name(vpath.name + ".quarantine").exists()
        assert healed.healed["quarantined_records"] == 1
        # ...at most that one record was lost...
        survivors = healed.load_blocks()
        assert len(survivors) >= n_records - 1
        # ...and every OTHER segment's bytes were never rewritten.
        for p, data in before.items():
            assert p.read_bytes() == data, f"{p} was touched by the heal"
        healed.close()

    def test_torn_tail_truncated(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        store = _fill(path, blocks)
        seg_dir = path.with_name(path.name + ".d")
        active = seg_dir / store.segments[-1].name
        data = active.read_bytes()
        os.truncate(active, len(data) - 3)  # crash mid-append shape
        healed = SegmentedStore(path)
        healed.acquire()
        assert healed.healed["truncated_bytes"] > 0
        got = healed.load_blocks()
        assert _digest(got) == _digest(blocks[1 : 1 + len(got)])  # a prefix
        healed.close()


class TestPrune:
    def test_prune_below_keeps_headers(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        store = _fill(path, blocks)
        prunable = store.segments[0]
        floor = prunable.max_height + 1
        store2 = SegmentedStore(path)
        store2.acquire()
        n = store2.prune_below(floor)
        assert n >= 1
        seg_dir = path.with_name(path.name + ".d")
        assert not (seg_dir / prunable.name).exists()
        # The packed-header sidecar survives the body...
        assert (seg_dir / f"seg{prunable.seg_id:05d}.hdrx").exists()
        # ...so the whole-chain header plane is still complete.
        raw, count = store2.packed_headers()
        assert count == len(blocks) - 1
        assert len(raw) == count * 80
        assert store2.first_header() == blocks[1].header
        # Pruned bodies are not refetchable...
        list(store2.iter_blocks())
        assert not store2.has_body(blocks[1].block_hash())
        store2.close()
        # ...and the floor survives a reopen.
        rd = SegmentedStore(path)
        rd.acquire()
        assert rd.pruned_below == floor
        survivors = rd.load_blocks()
        assert survivors[-1].serialize() == blocks[-1].serialize()
        rd.close()

    def test_unknown_heights_never_prune(self, tmp_path, blocks):
        path = tmp_path / "chain.dat"
        _fill(path, blocks, heights=False)
        store = SegmentedStore(path)
        store.acquire()
        assert store.prune_below(10_000) == 0
        store.close()


class TestRollCrashBoundaries:
    """A fault injected at EVERY write/fsync/dir-fsync ordinal through a
    roll-heavy append run, then recovery: fsck verdict <= 1 and the
    survivors are a prefix — the crash-at-every-boundary proof, with
    the kill-9 soak (slow) as the kernel-reality version."""

    def _run_with_fault(self, tmp_path, blocks, plan, tag):
        path = tmp_path / f"crash-{tag}.dat"
        store = SegFaultStore(path, plan=plan, segment_bytes=SEG_BYTES)
        appended = 0
        try:
            for i, b in enumerate(blocks[1:], start=1):
                store.append(b, height=i)
                appended += 1
        except OSError:
            pass
        finally:
            # Abrupt death: no clean close bookkeeping beyond fd close.
            store.close()
        return path, appended

    def _assert_recovers(self, path, blocks):
        rd = SegmentedStore(path)
        rd.acquire()  # must not raise: verdict <= 1 by definition
        for seg, scan in rd.scan_segments():
            assert scan is None or not scan.bad_spans
        got = rd.load_blocks()
        assert _digest(got) == _digest(blocks[1 : 1 + len(got)])
        rd.close()
        return len(got)

    def test_write_fault_at_every_ordinal(self, tmp_path, blocks):
        total_writes = 40  # covers every append + roll magic write
        for n in range(2, total_writes):
            path, _ = self._run_with_fault(
                tmp_path, blocks, StoreFaultPlan(fail_write_at=n), f"w{n}"
            )
            self._assert_recovers(path, blocks)

    def test_torn_write_at_every_ordinal(self, tmp_path, blocks):
        for n in range(2, 30):
            path, _ = self._run_with_fault(
                tmp_path,
                blocks,
                StoreFaultPlan(fail_write_at=n, torn_bytes=3),
                f"t{n}",
            )
            self._assert_recovers(path, blocks)

    def test_fsync_fault_at_every_ordinal(self, tmp_path, blocks):
        for n in range(1, 20):
            path, _ = self._run_with_fault(
                tmp_path, blocks, StoreFaultPlan(fail_fsync_at=n), f"f{n}"
            )
            self._assert_recovers(path, blocks)

    def test_dir_fsync_fault_at_every_ordinal(self, tmp_path, blocks):
        for n in range(1, 12):
            path, _ = self._run_with_fault(
                tmp_path, blocks, StoreFaultPlan(fail_dir_fsync_at=n), f"d{n}"
            )
            self._assert_recovers(path, blocks)

    @pytest.mark.slow
    def test_kill9_segment_roll_soak(self, tmp_path):
        """SIGKILL a real appending process at random moments across a
        segment-rolling run; every recovery must boot with verdict <= 1
        and hold a prefix of the deterministic chain.  Asserts that at
        least one kill landed mid-run (not after completion)."""
        path = tmp_path / "soak.dat"
        n_blocks, mid_kills = 24, 0
        deterministic = make_blocks(n_blocks, difficulty=12)
        for round_i in range(8):
            proc = subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "p1_tpu.chain.testing",
                    str(path),
                    str(n_blocks),
                    "12",
                    "0.01",
                    "400",  # tiny segments: kills land around rolls
                ],
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            time.sleep(0.15 + 0.05 * round_i)
            if proc.poll() is None:
                os.kill(proc.pid, signal.SIGKILL)
                mid_kills += 1
            proc.wait()
            rd = SegmentedStore(path, segment_bytes=400)
            rd.acquire()
            got = rd.load_blocks()
            # The soak child appends from genesis: records are a prefix
            # of the deterministic chain including block 0.
            assert _digest(got) == _digest(deterministic[: len(got)])
            rd.close()
            if len(got) > n_blocks:
                break
        assert mid_kills >= 1, "every run finished before the kill"


class TestSegmentInfoRow:
    def test_manifest_row_round_trip(self):
        row = SegmentInfo(
            seg_id=3, sealed=True, records=7, bytes=1234, max_height=9
        )
        assert SegmentInfo.from_json(row.to_json()) == row
        assert row.name == "seg00003.p1s"

    def test_default_bound_fits_span_packing(self):
        # The packing invariant the module asserts at construction.
        SegmentedStore.__init__  # noqa: B018 (existence)
        assert DEFAULT_SEGMENT_BYTES < (1 << 30)


class TestSegmentEIO:
    def test_segment_eio_degrades_serve_only_and_recovers(self, tmp_path):
        """A segment going EIO under a live body refetch degrades the
        node to serve-only (PR 3 recovery loop) WITHOUT dropping the
        requesting peer; clearing the fault recovers end to end and the
        syncing peer reaches the tip."""
        from p1_tpu.node.netsim import SimNet

        net = SimNet(
            seed=7,
            difficulty=8,
            store_dir=tmp_path,
            segmented_store=True,
            segment_bytes=400,
        )

        async def main():
            v = await net.add_node(body_cache_blocks=2)
            for _ in range(8):
                await net.mine_on(v, spacing_s=0.5)
            v.chain.evict_bodies(2)
            assert v.chain.bodies_evicted > 0
            store = net.stores[net.host_name(0)]
            store.plan = StoreFaultPlan(fail_preads_from=1)
            j = await net.add_node(
                peers=[net.host_name(0)], sync_stall_timeout_s=3.0
            )
            assert await net.run_until(
                lambda: v.status()["storage"]["degraded"],
                60,
                wall_limit_s=60,
            )
            # The failing segment is remembered, the peer session is
            # NOT torn down, and header serving never stopped.
            assert store.read_failed_segments
            assert v.peer_count() >= 1
            assert len(
                v.chain.headers_after([v.chain.genesis.block_hash()])
            ) == v.chain.height
            store.clear_faults()
            assert await net.run_until(
                lambda: not v._store_degraded, 120, wall_limit_s=60
            )
            assert await net.run_until(
                lambda: j.chain.height == v.chain.height,
                120,
                wall_limit_s=60,
            )
            await net.stop_all()

        net.run(main())


class TestSegmentedCompaction:
    def _forked_store(self, path):
        """A segmented store holding a reorged-away side branch: the
        short fork's records are exactly the dirty set."""
        from p1_tpu.chain.tooling import run_compact  # noqa: F401 (used by callers)

        short = make_blocks(3, difficulty=DIFF, miner_id="loser")
        long = make_blocks(5, difficulty=DIFF, miner_id="winner")
        store = SegmentedStore(path, segment_bytes=500)
        for h, b in enumerate(short[1:], start=1):
            store.append(b, height=h)
        for h, b in enumerate(long[1:], start=1):
            store.append(b, height=h)
        store.close()
        return short, long, store

    def test_only_dirty_segments_rewritten(self, tmp_path):
        import json as jsonlib

        from p1_tpu.chain.tooling import run_compact

        path = tmp_path / "chain.dat"
        short, long, store = self._forked_store(path)
        seg_dir = path.with_name(path.name + ".d")
        main_hashes = {b.block_hash() for b in long}
        # Identify which segments are already clean (all-main records).
        clean_before = {}
        rd = SegmentedStore(path)
        for seg, scan in rd.scan_segments():
            data = (seg_dir / seg.name).read_bytes()
            from p1_tpu.core.hashutil import sha256d

            hashes = {sha256d(data[o : o + 80]) for o, _ in scan.spans}
            if hashes and hashes <= main_hashes:
                clean_before[seg.name] = data
        rd.close()
        import contextlib
        import io as iolib

        buf = iolib.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = run_compact(str(path), None)
        assert rc == 0, buf.getvalue()
        report = jsonlib.loads(buf.getvalue().strip())
        assert report["layout"] == "segmented"
        assert report["records_after"] == len(long) - 1
        assert report["segments_rewritten"] >= 1
        # Clean segments were NEVER rewritten — byte-identical.
        for name, data in clean_before.items():
            assert (seg_dir / name).read_bytes() == data, name
        # The compacted store reloads to the winning chain only.
        rd = SegmentedStore(path)
        rd.acquire()
        got = rd.load_blocks()
        assert _digest(got) == _digest(long[1:])
        chain = rd.load_chain(DIFF, got, trusted=True)
        assert chain.height == len(long) - 1
        rd.close()
