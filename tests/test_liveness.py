"""Peer liveness layer: PING/PONG keepalive, handshake + idle deadlines,
slot recovery.

The attack this layer closes (round-4 verdict): a socket that completes
HELLO and then merely keeps reading held one of the MAX_PEERS slots
forever (the only prior eviction path was a *send* timeout, which a
reading-but-silent peer never trips), and a socket that never sent HELLO
grew ``_sessions`` without bound.  These tests drive real Nodes with raw
sockets playing the silent attacker and assert the deadlines actually
fire, the slots actually recover, and honest chatter is never penalized.
"""

import asyncio
import time

import pytest

from test_node import CHUNK, DIFF, run, wait_until

from p1_tpu.config import NodeConfig
from p1_tpu.core.genesis import make_genesis
from p1_tpu.node import Node, protocol
from p1_tpu.node.protocol import Hello, MsgType, ProtocolError


def _config(peers=(), **kw) -> NodeConfig:
    kw.setdefault("difficulty", DIFF)
    kw.setdefault("chunk", CHUNK)
    kw.setdefault("mine", False)
    # Snappy deadlines so the suite doesn't sit through Bitcoin-scale
    # minutes; the production defaults differ only in magnitude.
    kw.setdefault("handshake_timeout_s", 0.3)
    kw.setdefault("ping_interval_s", 0.25)
    kw.setdefault("pong_timeout_s", 0.25)
    return NodeConfig(peers=tuple(peers), **kw)


async def raw_hello(port: int, nonce: int):
    """A bare socket that completes the HELLO exchange like a node and
    then does whatever the test says — the adversary's half of the
    handshake, without any of Node's liveness reflexes."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    genesis_hash = make_genesis(DIFF).block_hash()
    await protocol.write_frame(
        writer, protocol.encode_hello(Hello(genesis_hash, 0, 0, nonce))
    )
    mtype, _ = protocol.decode(await protocol.read_frame(reader))
    assert mtype is MsgType.HELLO
    return reader, writer


async def read_types_until_eof(reader) -> list:
    """Drain frames (the reading-but-silent attacker) until the node
    hangs up; returns the message types seen."""
    types = []
    try:
        while True:
            mtype, _ = protocol.decode(await protocol.read_frame(reader))
            types.append(mtype)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return types


class TestCodec:
    def test_ping_pong_round_trip(self):
        for enc, mtype in (
            (protocol.encode_ping, MsgType.PING),
            (protocol.encode_pong, MsgType.PONG),
        ):
            got_type, got_nonce = protocol.decode(enc(0xDEADBEEF12345678))
            assert got_type is mtype
            assert got_nonce == 0xDEADBEEF12345678

    def test_bad_ping_size_is_violation(self):
        with pytest.raises(ProtocolError):
            protocol.decode(bytes([MsgType.PING]) + b"\x00" * 7)
        with pytest.raises(ProtocolError):
            protocol.decode(bytes([MsgType.PONG]) + b"\x00" * 9)


class TestIdleEviction:
    def test_silent_after_hello_probed_then_evicted(self):
        """The verdict's exact attack: HELLO then silence while reading.
        The node must probe with a PING and, absent any reply, evict
        within ping_interval + pong_timeout — not hold the slot forever."""

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                reader, writer = await raw_hello(node.port, nonce=101)
                assert await wait_until(lambda: node.peer_count() == 1)
                types = await asyncio.wait_for(
                    read_types_until_eof(reader), timeout=30
                )
                # The wall-clock half of this property ("within interval
                # + probe, not forever") lives on the injectable clock
                # now (TestDeliveryBudgetClock) — under full-suite load
                # the only thing a real-time bound here measured was the
                # CI box.  The behavioral half stays: probed first, then
                # evicted, never scored.
                assert MsgType.PING in types  # probed before sentencing
                assert await wait_until(lambda: node.peer_count() == 0)
                assert not node._violations and not node._banned_until
                writer.close()
            finally:
                await node.stop()

        run(scenario())

    def test_any_frame_resets_probe(self):
        """A peer that keeps talking (here: periodic GETADDR) must never
        be evicted, even if it never answers a PING explicitly.

        Deflake note (round 9): the old fixed-cadence sleep loop (0.12 s
        chatter vs a 0.5 s eviction deadline) silently depended on the
        event loop scheduling every iteration on time — under full-suite
        load one 0.5 s stall between writes evicted the peer and failed
        the test for keeping its own promise.  The loop now measures the
        gap it actually achieved and only asserts survival when the
        chatter cadence it was responsible for actually held; a run
        whose own writes stalled past the deadline retries."""

        async def scenario() -> bool:
            node = Node(_config())
            await node.start()
            try:
                reader, writer = await raw_hello(node.port, nonce=102)
                assert await wait_until(lambda: node.peer_count() == 1)
                drainer = asyncio.create_task(read_types_until_eof(reader))
                deadline = 0.25 + 0.25  # ping_interval + pong_timeout
                max_gap, last = 0.0, time.monotonic()
                for _ in range(12):
                    await protocol.write_frame(
                        writer, protocol.encode_getaddr()
                    )
                    now = time.monotonic()
                    max_gap = max(max_gap, now - last)
                    last = now
                    await asyncio.sleep(0.1)
                if max_gap >= deadline * 0.8:
                    return False  # cadence broken by host load: retry
                assert node.peer_count() == 1  # still welcome
                drainer.cancel()
                writer.close()
                return True
            finally:
                await node.stop()

        for _ in range(3):
            if run(scenario()):
                return
        pytest.fail("could not hold chatter cadence in 3 attempts")

    def test_slow_trickle_is_liveness_not_silence(self):
        """A peer delivering ONE frame byte-by-byte, slower than the idle
        interval per byte-gap but inside the frame's delivery budget
        (grace + size/MIN_FRAME_RATE), is alive — byte-level progress must
        reset the probe, and a cancelled mid-frame read must not desync
        the stream into a phantom protocol violation (so: no eviction AND
        no misbehavior score).

        The budget ARITHMETIC is pinned on an injectable clock in
        TestDeliveryBudgetClock; this socket test keeps a wide real-time
        budget (grace ≈ 3.15 s vs ~1 s of trickle) and verifies only the
        wiring, so host load cannot push an honest trickle over the
        deadline it is proving safe."""

        async def scenario() -> bool:
            # grace = 0.15 + 3.0 = 3.15s; the 5-byte frame below arrives
            # over ~0.8s — far inside budget, while every 0.15s idle
            # timeout fires mid-frame and must take the progressed()
            # exemption.
            node = Node(_config(ping_interval_s=0.15, pong_timeout_s=3.0))
            await node.start()
            try:
                reader, writer = await raw_hello(node.port, nonce=103)
                assert await wait_until(lambda: node.peer_count() == 1)
                drainer = asyncio.create_task(read_types_until_eof(reader))
                # One GETADDR frame (4-byte length + 1-byte type), a byte
                # every 0.2s vs the 0.15s probe interval.
                frame = b"\x00\x00\x00\x01" + bytes(
                    [protocol.MsgType.GETADDR]
                )
                t0 = time.monotonic()
                for b in frame:
                    writer.write(bytes([b]))
                    await writer.drain()
                    await asyncio.sleep(0.2)
                if time.monotonic() - t0 >= 3.0:
                    return False  # host load blew the budget: retry
                assert node.peer_count() == 1  # never evicted
                assert not node._violations  # and never scored
                drainer.cancel()
                writer.close()
                return True
            finally:
                await node.stop()

        for _ in range(3):
            if run(scenario()):
                return
        pytest.fail("could not deliver the trickle inside budget")

    def test_endless_trickle_is_bounded(self):
        """The counter-attack to byte-level liveness: a peer promising a
        100-byte body and trickling bytes forever at one per probe
        interval must NOT hold its slot past the frame's delivery budget
        — evicted as a liveness reap, never scored as a violation."""

        async def scenario():
            # Budget: (0.15 + 0.2) grace + 100/10000 ≈ 0.36s; the trickle
            # below would take ~20s to finish the frame.
            node = Node(_config(ping_interval_s=0.15, pong_timeout_s=0.2))
            await node.start()
            try:
                reader, writer = await raw_hello(node.port, nonce=105)
                assert await wait_until(lambda: node.peer_count() == 1)
                drainer = asyncio.create_task(read_types_until_eof(reader))
                writer.write(b"\x00\x00\x00\x64")  # 100-byte body promised
                await writer.drain()
                t0 = time.monotonic()
                evicted = False
                for _ in range(100):
                    try:
                        writer.write(b"\x55")
                        await writer.drain()
                    except (ConnectionError, OSError):
                        evicted = True
                        break
                    if node.peer_count() == 0:
                        evicted = True
                        break
                    await asyncio.sleep(0.14)
                assert evicted
                # Bounded, not the ~20 s the full trickle would take.
                # Wide margin: the precise budget (~0.36 s) is pinned on
                # the injectable clock (TestDeliveryBudgetClock); this
                # bound only distinguishes "reaped" from "waited out".
                assert time.monotonic() - t0 < 12.0
                assert not node._violations and not node._banned_until
                drainer.cancel()
                writer.close()
            finally:
                await node.stop()

        run(scenario())

    def test_midframe_stall_still_evicted_without_ban(self):
        """A length prefix promising a body that never comes: the probe
        must still evict once progress stops — but as a liveness reap,
        never as a scorable protocol violation."""

        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                reader, writer = await raw_hello(node.port, nonce=104)
                assert await wait_until(lambda: node.peer_count() == 1)
                writer.write(b"\x00\x00\x00\x64")  # 100-byte body promised
                await writer.drain()
                types = await asyncio.wait_for(
                    read_types_until_eof(reader), timeout=10
                )
                assert MsgType.PING in types
                assert await wait_until(lambda: node.peer_count() == 0)
                assert not node._violations and not node._banned_until
                writer.close()
            finally:
                await node.stop()

        run(scenario())

    def test_two_real_nodes_keep_each_other_alive(self):
        """Mutual keepalive: two idle nodes with tiny intervals stay
        connected through many probe cycles — the PONG path works."""

        async def scenario():
            a = Node(_config())
            await a.start()
            b = Node(_config(peers=[f"127.0.0.1:{a.port}"]))
            await b.start()
            try:
                assert await wait_until(
                    lambda: a.peer_count() == 1 and b.peer_count() == 1
                )
                await asyncio.sleep(1.5)  # ~6 idle intervals
                assert a.peer_count() == 1 and b.peer_count() == 1
            finally:
                await b.stop()
                await a.stop()

        run(scenario())


class TestHandshakeDeadline:
    def test_never_hello_socket_reaped(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", node.port
                )
                t0 = time.monotonic()
                types = await asyncio.wait_for(
                    read_types_until_eof(reader), timeout=10
                )
                assert types == [MsgType.HELLO]  # their half, then hangup
                # Wide real-time margin (deadline 0.3 s): "reaped, not
                # held forever" — the deadline precision itself is not a
                # wall-clock property this suite can measure under load.
                assert time.monotonic() - t0 < 12.0
                assert await wait_until(lambda: node._handshaking == 0)
                assert node.peer_count() == 0
                writer.close()
            finally:
                await node.stop()

        run(scenario())

    def test_prehandshake_session_cap(self):
        """More simultaneous no-HELLO sockets than MAX_HANDSHAKING: the
        excess is closed on accept (no session task), the rest die at the
        handshake deadline, and the counter returns to zero."""

        async def scenario():
            from p1_tpu.node import node as node_mod

            node = Node(_config(handshake_timeout_s=1.0))
            await node.start()
            try:
                conns = []
                for _ in range(node_mod.MAX_HANDSHAKING + 8):
                    conns.append(
                        await asyncio.open_connection("127.0.0.1", node.port)
                    )
                await asyncio.sleep(0.2)  # let accepts land
                assert node._handshaking <= node_mod.MAX_HANDSHAKING
                # Every socket — capped-out and timed-out alike — sees EOF.
                results = await asyncio.wait_for(
                    asyncio.gather(
                        *(read_types_until_eof(r) for r, _ in conns)
                    ),
                    timeout=15,
                )
                over_cap = sum(1 for t in results if t == [])
                assert over_cap >= 8  # the excess never even got a HELLO
                assert await wait_until(lambda: node._handshaking == 0)
                for _, w in conns:
                    w.close()
            finally:
                await node.stop()

        run(scenario())


class TestSlotRecovery:
    def test_max_peers_slots_recover_after_eviction(self, monkeypatch):
        """Fill MAX_PEERS with silent sockets: a real node is refused;
        after the idle evictions it connects fine — the slots provably
        recycle instead of being pinned by dead weight."""
        from p1_tpu.node import node as node_mod

        monkeypatch.setattr(node_mod, "MAX_PEERS", 2)

        async def scenario():
            victim = Node(_config())
            await victim.start()
            drains = []
            try:
                socks = [
                    await raw_hello(victim.port, nonce=200 + i)
                    for i in range(2)
                ]
                assert await wait_until(lambda: victim.peer_count() == 2)
                # Keep the attackers' read sides flowing (the verdict's
                # reading-but-silent profile) without answering probes.
                drains = [
                    asyncio.create_task(read_types_until_eof(r))
                    for r, _ in socks
                ]
                # A third HELLO is refused at the cap while both slots
                # are held.
                with pytest.raises(
                    (asyncio.IncompleteReadError, ConnectionError)
                ):
                    r3, w3 = await raw_hello(victim.port, nonce=300)
                    await protocol.read_frame(r3)  # node hangs up
                # The idle deadline reaps both attackers...
                assert await wait_until(lambda: victim.peer_count() == 0)
                # ...and a real node then takes a recovered slot.
                joiner = Node(
                    _config(peers=[f"127.0.0.1:{victim.port}"])
                )
                await joiner.start()
                try:
                    assert await wait_until(
                        lambda: victim.peer_count() == 1
                        and joiner.peer_count() == 1
                    )
                finally:
                    await joiner.stop()
                for _, w in socks:
                    w.close()
            finally:
                for d in drains:
                    d.cancel()
                await victim.stop()

        run(scenario())


class TestFrameReaderFuzz:
    """Property test for the cancellation-tolerant reader: any frame
    stream, delivered in any chunking, with reads cancelled at any
    moment, must come out byte-identical — the desync this class exists
    to prevent (a cancelled plain read_frame between length prefix and
    body shifts the stream and fabricates protocol violations)."""

    def test_random_chunking_and_cancellation_never_desyncs(self):
        import random

        async def scenario(seed: int):
            # Separate streams per side: the server and client draw
            # concurrently, and a shared rng would make the run depend
            # on asyncio timing — an unreproducible "seeded" test.
            rng = random.Random(seed)
            srv_rng = random.Random(seed ^ 0x5EED)
            frames = [
                rng.randbytes(rng.choice((0, 1, 4, 17, 200, 5000)))
                for _ in range(40)
            ]
            wire = b"".join(
                len(f).to_bytes(4, "big") + f for f in frames
            )

            async def serve(reader, writer):
                # Trickle the exact byte stream in random chunks with
                # random pauses, then EOF.
                off = 0
                while off < len(wire):
                    n = srv_rng.randrange(1, 64)
                    writer.write(wire[off : off + n])
                    off += n
                    await writer.drain()
                    if srv_rng.random() < 0.3:
                        await asyncio.sleep(0.001)
                writer.close()

            server = await asyncio.start_server(serve, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            frames_out = []
            fr = protocol.FrameReader(reader)
            while len(frames_out) < len(frames):
                # Random aggressive timeouts: most reads get cancelled
                # mid-frame at least once.
                try:
                    payload = await asyncio.wait_for(
                        fr.read(), timeout=rng.choice((0.0005, 0.002, 0.5))
                    )
                except (TimeoutError, asyncio.TimeoutError):
                    # Both spellings: only unified in Python 3.11.
                    continue  # retry exactly as the session loop does
                frames_out.append(payload)
            assert frames_out == frames  # byte-identical, in order
            writer.close()
            server.close()
            await server.wait_closed()

        for seed in range(8):
            run(scenario(seed))


class TestLivenessMetrics:
    def test_probe_and_eviction_counted(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                reader, writer = await raw_hello(node.port, nonce=700)
                assert await wait_until(lambda: node.peer_count() == 1)
                await asyncio.wait_for(
                    read_types_until_eof(reader), timeout=10
                )
                assert node.metrics.pings_sent >= 1
                assert node.metrics.peers_evicted_idle == 1
                assert node.status()["liveness"] == {
                    "pings_sent": node.metrics.pings_sent,
                    "peers_evicted_idle": 1,
                }
                writer.close()
            finally:
                await node.stop()

        run(scenario())


class TestDeliveryBudgetClock:
    """The frame delivery-budget math on an INJECTABLE clock (round-9
    deflake, the ``test_governor.py`` pattern): the socket tests above
    verify the wiring with wide real-time margins; the precise timing
    semantics — what used to be asserted against wall clocks and flaked
    under full-suite load — are pinned here without one real sleep."""

    class _Clock:
        def __init__(self, t: float = 100.0):
            self.t = t

        def __call__(self) -> float:
            return self.t

    def _feed(self, clock):
        sr = asyncio.StreamReader()
        return sr, protocol.FrameReader(sr, clock=clock)

    @staticmethod
    async def _pump(fr):
        """Drive one read attempt: consume whatever bytes are buffered,
        then give up — exactly the cancelled-mid-frame shape the session
        loop's wait_for produces."""
        try:
            return await asyncio.wait_for(fr.read(), timeout=0.02)
        except (TimeoutError, asyncio.TimeoutError):
            return None

    def test_idle_reader_is_never_overdue(self):
        async def scenario():
            clock = self._Clock()
            _, fr = self._feed(clock)
            clock.t += 1e9  # arbitrarily far in the future
            assert not fr.overdue(grace=0.0)  # no frame in progress

        run(scenario())

    def test_budget_scales_with_promised_size(self):
        """budget = grace + promised/MIN_FRAME_RATE, from the first byte
        of the frame — the exact arithmetic the probe loop trusts."""

        async def scenario():
            clock = self._Clock()
            sr, fr = self._feed(clock)
            # Promise a 50_000-byte body; deliver nothing more.
            sr.feed_data((50_000).to_bytes(4, "big"))
            assert await self._pump(fr) is None
            budget = 0.5 + 50_000 / protocol.MIN_FRAME_RATE  # = 5.5s
            clock.t += budget - 0.01
            assert not fr.overdue(grace=0.5)  # inside budget: alive
            clock.t += 0.02
            assert fr.overdue(grace=0.5)  # past it: reap

        run(scenario())

    def test_prefix_only_uses_minimum_budget(self):
        """Before the length prefix completes, the promise is unknown —
        the budget is grace + 4/MIN_FRAME_RATE, nothing more (a peer
        cannot buy time by never finishing the prefix)."""

        async def scenario():
            clock = self._Clock()
            sr, fr = self._feed(clock)
            sr.feed_data(b"\x00\x00")  # half a length prefix
            assert await self._pump(fr) is None
            clock.t += 0.5 + 4 / protocol.MIN_FRAME_RATE + 0.01
            assert fr.overdue(grace=0.5)

        run(scenario())

    def test_completed_frame_clears_the_budget(self):
        async def scenario():
            clock = self._Clock()
            sr, fr = self._feed(clock)
            sr.feed_data(b"\x00\x00\x00\x01")
            assert await self._pump(fr) is None
            sr.feed_data(b"\xaa")
            assert await self._pump(fr) == b"\xaa"
            clock.t += 1e9
            assert not fr.overdue(grace=0.0)  # no frame in progress again

        run(scenario())

    def test_progress_flag_consumed_and_reset_by_completion(self):
        """progressed() reports partial bytes since the last look, is
        consumed by reading it, and a COMPLETED frame does not leave a
        stale progress pass for a later silent interval."""

        async def scenario():
            clock = self._Clock()
            sr, fr = self._feed(clock)
            assert not fr.progressed()  # nothing yet
            sr.feed_data(b"\x00\x00")
            assert await self._pump(fr) is None
            assert fr.progressed()  # bytes arrived mid-frame
            assert not fr.progressed()  # consumed
            sr.feed_data(b"\x00\x01\xbb")
            assert await self._pump(fr) == b"\xbb"
            assert not fr.progressed()  # completion wipes the flag

        run(scenario())

    def test_trickle_inside_budget_survives_forever_on_fake_time(self):
        """The slow-trickle socket test, replayed on the fake clock: a
        byte per probe interval with a small promised frame stays inside
        budget at every observation — the exemption the session loop
        grants is justified at each step, not just on average."""

        async def scenario():
            clock = self._Clock()
            sr, fr = self._feed(clock)
            frame = b"\x00\x00\x00\x01" + bytes([MsgType.GETADDR])
            grace = 0.5
            for b in frame[:-1]:
                sr.feed_data(bytes([b]))
                assert await self._pump(fr) is None
                assert fr.progressed()  # byte-level liveness each step
                assert not fr.overdue(grace)
                clock.t += 0.09  # slower than any probe interval here
            sr.feed_data(frame[-1:])
            assert await self._pump(fr) == bytes([MsgType.GETADDR])

        run(scenario())

    def test_endless_trickle_goes_overdue_on_fake_time(self):
        """The counter-attack, on the fake clock: promising 100 bytes
        and trickling one per 'interval' exceeds the delivery budget
        after grace + 100/MIN_FRAME_RATE — progress alone must not be
        a permanent exemption."""

        async def scenario():
            clock = self._Clock()
            sr, fr = self._feed(clock)
            sr.feed_data((100).to_bytes(4, "big"))
            assert await self._pump(fr) is None
            grace = 0.35
            budget = grace + 100 / protocol.MIN_FRAME_RATE
            fed = 0.0
            while fed <= budget:
                sr.feed_data(b"\x55")
                assert await self._pump(fr) is None
                clock.t += 0.14
                fed += 0.14
            assert fr.progressed()  # still technically progressing...
            assert fr.overdue(grace)  # ...but past its budget: reap

        run(scenario())
