"""Signed-transaction helpers shared by the test suite.

Consensus and the mempool require Ed25519 ownership proofs on every
transfer (round 4), so tests build spends through ``stx`` — a drop-in for
the old raw ``Transaction(sender, ...)`` constructor that derives one
deterministic keypair per sender *label* and signs with it.  Two calls
with the same label spend from the same account (preserving the
(sender, seq) slot semantics the mempool tests rely on).
"""

import functools

from p1_tpu.core.genesis import genesis_hash
from p1_tpu.core.keys import Keypair
from p1_tpu.core.tx import Transaction


@functools.lru_cache(maxsize=None)
def key_for(label: str) -> Keypair:
    """The test suite's deterministic keypair for a human-readable label."""
    return Keypair.from_seed_text(f"p1-test-{label}")


def account(label: str) -> str:
    return key_for(label).account


def stx(
    sender_label: str,
    recipient: str,
    amount: int,
    fee: int,
    seq: int,
    difficulty: int = 8,
) -> Transaction:
    """A signed transfer from the account behind ``sender_label``.

    ``recipient`` may be another label's account (pass ``account(label)``)
    or any free-form id — recipients need no key.  Signatures are
    chain-bound, so pass the ``difficulty`` of the chain the tx targets;
    the default matches the chain-test suites' DIFF=8 (pool-only unit
    tests never check the tag, so any value works there).
    """
    return Transaction.transfer(
        key_for(sender_label),
        recipient,
        amount,
        fee,
        seq,
        chain=genesis_hash(difficulty),
    )
