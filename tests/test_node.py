"""P2P node: handshake, gossip, sync, convergence, faults, restart.

In-process asyncio harness: each test spins real Nodes on ephemeral
localhost ports (the standard localhost form of the reference's 4-peer
distributed config, BASELINE.json:10) and polls for convergence with a
deadline.  Difficulty 12 keeps cpu mining at a few ms/block.
"""

import asyncio
import time

import pytest

from txutil import account, stx

from p1_tpu.config import NodeConfig
from p1_tpu.core import Transaction
from p1_tpu.node import Node

DIFF = 12
CHUNK = 1 << 14  # fine-grained abort so stop() never waits long


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


async def fund(node, label: str, blocks: int = 1) -> None:
    """Mine ``blocks`` block rewards to ``label``'s account on ``node``.

    Consensus rejects overdraws and the pool mirrors the rule, so tests
    that spend must first earn — exactly like a real participant.
    """
    old_id = node.miner_id
    node.miner_id = account(label)
    target = node.chain.height + blocks
    node.start_mining()
    assert await wait_until(lambda: node.chain.height >= target)
    await node.stop_mining()
    node.miner_id = old_id


async def wait_until(cond, timeout=20.0, interval=0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return False


def _config(peers=(), **kw) -> NodeConfig:
    kw.setdefault("difficulty", DIFF)
    kw.setdefault("chunk", CHUNK)
    kw.setdefault("mine", False)
    return NodeConfig(peers=tuple(peers), **kw)


async def start_mesh(n: int, **kw) -> list[Node]:
    """n nodes, each dialing all earlier ones (full mesh)."""
    nodes: list[Node] = []
    for _ in range(n):
        peers = [f"127.0.0.1:{node.port}" for node in nodes]
        node = Node(_config(peers=peers, **kw))
        await node.start()
        nodes.append(node)
    return nodes


async def stop_all(nodes) -> None:
    for node in nodes:
        await node.stop()


class TestHandshake:
    def test_two_nodes_connect(self):
        async def scenario():
            nodes = await start_mesh(2)
            try:
                assert await wait_until(
                    lambda: all(n.peer_count() >= 1 for n in nodes)
                )
            finally:
                await stop_all(nodes)

        run(scenario())

    def test_genesis_mismatch_rejected(self):
        async def scenario():
            a = Node(_config(difficulty=12))
            await a.start()
            b = Node(_config(difficulty=13, peers=[f"127.0.0.1:{a.port}"]))
            await b.start()
            try:
                await asyncio.sleep(0.3)
                assert a.peer_count() == 0
                assert b.peer_count() == 0
            finally:
                await stop_all([a, b])

        run(scenario())


class TestGossip:
    def test_tx_propagates_transitively(self):
        async def scenario():
            # Chain topology a <- b <- c: a tx injected at a must reach c.
            a = Node(_config())
            await a.start()
            b = Node(_config(peers=[f"127.0.0.1:{a.port}"]))
            await b.start()
            c = Node(_config(peers=[f"127.0.0.1:{b.port}"]))
            await c.start()
            try:
                assert await wait_until(
                    lambda: a.peer_count() and c.peer_count()
                )
                # Earn before spending: every pool checks affordability
                # against its own tip, so the funding block must reach c.
                await fund(a, "alice")
                assert await wait_until(lambda: c.chain.height >= 1)
                tx = stx("alice", "bob", 5, 1, 0, difficulty=DIFF)
                await a.submit_tx(tx)
                assert await wait_until(lambda: tx.txid() in c.mempool)
                assert tx.txid() in b.mempool
            finally:
                await stop_all([a, b, c])

        run(scenario())

    def test_mined_blocks_propagate(self):
        async def scenario():
            nodes = await start_mesh(2)
            miner_node = nodes[0]
            try:
                assert await wait_until(lambda: miner_node.peer_count())
                await fund(miner_node, "alice")
                assert await wait_until(lambda: nodes[1].chain.height >= 1)
                tx = stx("alice", "bob", 5, 1, 0, difficulty=DIFF)
                await nodes[1].submit_tx(tx)
                assert await wait_until(
                    lambda: tx.txid() in miner_node.mempool
                )
                miner_node.start_mining()  # mine exactly on node 0
                # The real success condition: the tx gets mined out of the
                # pool (an absolute height target would race fund()'s
                # overshoot — stop_mining can land before the tx's block).
                assert await wait_until(
                    lambda: tx.txid() not in miner_node.mempool
                )
                await miner_node.stop_mining()
                assert await wait_until(
                    lambda: nodes[1].chain.tip_hash == miner_node.chain.tip_hash
                )
                assert nodes[1].chain.height >= 3
                # block acceptance at the peer evicted it there too
                assert tx.txid() not in nodes[1].mempool
                # Propagation timing (SURVEY §5): the receiving node
                # measured send->accept delay for the pushed blocks.
                prop = nodes[1].metrics.propagation_summary()
                assert prop["samples"] >= 1
                assert prop["median_ms"] is not None and prop["median_ms"] >= 0
                assert nodes[1].status()["propagation"] == prop
            finally:
                await stop_all(nodes)

        run(scenario())


class TestTxClient:
    def test_submit_propagates_and_mines(self):
        from p1_tpu.node.client import send_tx

        async def scenario():
            nodes = await start_mesh(2)
            try:
                assert await wait_until(lambda: nodes[1].peer_count())
                await fund(nodes[0], "alice")
                assert await wait_until(lambda: nodes[1].chain.height >= 1)
                tx = stx("alice", "bob", 7, 1, 0, difficulty=DIFF)
                height = await send_tx(
                    "127.0.0.1", nodes[0].port, tx, DIFF
                )
                assert height == nodes[0].chain.height
                # reaches the directly-connected node AND its peer
                assert await wait_until(lambda: tx.txid() in nodes[0].mempool)
                assert await wait_until(lambda: tx.txid() in nodes[1].mempool)
                # ... and ends up in a mined block
                nodes[1].start_mining()
                assert await wait_until(
                    lambda: tx.txid() not in nodes[1].mempool
                )
                await nodes[1].stop_mining()
                mined = [
                    b
                    for b in nodes[1].chain.main_chain()
                    if any(t.txid() == tx.txid() for t in b.txs)
                ]
                assert mined, "submitted tx never mined"
            finally:
                await stop_all(nodes)

        run(scenario())

    def test_wrong_chain_rejected(self):
        from p1_tpu.node.client import send_tx

        async def scenario():
            a = Node(_config())
            await a.start()
            try:
                tx = stx("alice", "bob", 7, 1, 0, difficulty=DIFF)
                with pytest.raises(ValueError, match="genesis mismatch"):
                    await send_tx("127.0.0.1", a.port, tx, DIFF + 1)
                assert tx.txid() not in a.mempool
            finally:
                await a.stop()

        run(scenario())


class TestAccountQuery:
    def test_get_account_reflects_chain_and_pool(self):
        from p1_tpu.node.client import get_account

        async def scenario():
            a = Node(_config())
            await a.start()
            try:
                await fund(a, "alice")
                funded = a.chain.balance(account("alice"))
                state = await get_account(
                    "127.0.0.1", a.port, account("alice"), DIFF
                )
                assert state.balance == funded
                assert state.nonce == 0 and state.next_seq == 0
                # A pending spend advances next_seq but not the nonce.
                await a.submit_tx(stx("alice", "bob", 5, 1, 0, difficulty=DIFF))
                state = await get_account(
                    "127.0.0.1", a.port, account("alice"), DIFF
                )
                assert state.nonce == 0 and state.next_seq == 1
                # Unknown accounts answer zeros, not errors.
                state = await get_account("127.0.0.1", a.port, "nobody", DIFF)
                assert state.balance == 0 and state.next_seq == 0
                # A stray GAPPED pending tx (pinned far-future seq) must
                # not poison auto-seq: next_seq advances contiguously, so
                # the next wallet tx fills the gap instead of extending it.
                await a.submit_tx(
                    stx("alice", "bob", 1, 1, 9, difficulty=DIFF)
                )
                state = await get_account(
                    "127.0.0.1", a.port, account("alice"), DIFF
                )
                assert state.next_seq == 1  # not 10
            finally:
                await a.stop()

        run(scenario())


class TestPeerCap:
    def test_inbound_refused_past_limit(self, monkeypatch):
        from p1_tpu.node import node as node_mod

        monkeypatch.setattr(node_mod, "MAX_PEERS", 1)

        async def scenario():
            hub = Node(_config())
            await hub.start()
            a = Node(_config(peers=[f"127.0.0.1:{hub.port}"]))
            await a.start()
            b = Node(_config(peers=[f"127.0.0.1:{hub.port}"]))
            await b.start()
            try:
                assert await wait_until(lambda: hub.peer_count() == 1)
                await asyncio.sleep(0.5)  # give b's dial loop time to retry
                assert hub.peer_count() == 1  # second connection refused
            finally:
                await stop_all([hub, a, b])

        run(scenario())


class TestMinerIdentity:
    def test_unpeered_miners_diverge(self):
        """Round-2 judge experiment, inverted: two *unconnected* nodes must
        mine different chains (each block carries the miner's coinbase, so
        candidates differ from height 1 on).  Before coinbases every node
        assembled bit-identical blocks and 'convergence' was degenerate."""

        async def scenario():
            a = Node(_config(mine=True))
            b = Node(_config(mine=True))
            await a.start()
            await b.start()
            try:
                assert await wait_until(
                    lambda: a.chain.height >= 3 and b.chain.height >= 3
                )
                await a.stop_mining()
                await b.stop_mining()
                a_hashes = [blk.block_hash() for blk in a.chain.main_chain()]
                b_hashes = [blk.block_hash() for blk in b.chain.main_chain()]
                # Same genesis, nothing else in common.
                assert a_hashes[0] == b_hashes[0]
                overlap = set(a_hashes[1:4]) & set(b_hashes[1:4])
                assert not overlap, f"identical blocks mined: {overlap}"
            finally:
                await stop_all([a, b])

        run(scenario())

    def test_fork_resolves_with_reorg(self):
        """Deterministic network-level reorg: A mines a short private chain,
        B a longer one; when A first hears of B's chain it must abandon its
        own branch (metrics.reorgs goes up) and adopt B's tip."""

        async def scenario():
            a = Node(_config(mine=True, miner_id="alice"))
            b = Node(_config(mine=True, miner_id="bob"))
            await a.start()
            await b.start()
            try:
                assert await wait_until(lambda: a.chain.height >= 2)
                await a.stop_mining()
                height_a = a.chain.height
                assert await wait_until(lambda: b.chain.height >= height_a + 2)
                await b.stop_mining()
                assert a.chain.tip_hash != b.chain.tip_hash
                # Now connect them: a dials b and syncs.
                c = Node(
                    _config(peers=[f"127.0.0.1:{b.port}"]), miner=a.miner
                )
                c.chain = a.chain  # adopt A's private chain wholesale
                await c.start()
                try:
                    assert await wait_until(
                        lambda: c.chain.tip_hash == b.chain.tip_hash
                    )
                    assert c.metrics.reorgs >= 1, "fork resolved without a reorg"
                finally:
                    await c.stop()
            finally:
                await stop_all([a, b])

        run(scenario())


class TestConvergence:
    def test_four_miners_converge(self):
        async def scenario():
            nodes = await start_mesh(4, mine=True)
            try:
                assert await wait_until(
                    lambda: min(n.chain.height for n in nodes) >= 3
                )
                for node in nodes:
                    await node.stop_mining()
                assert await wait_until(
                    lambda: len({n.chain.tip_hash for n in nodes}) == 1,
                    timeout=10,
                ), {n.port: (n.chain.height, n.chain.tip_hash.hex()[:8]) for n in nodes}
                heights = {n.chain.height for n in nodes}
                assert len(heights) == 1 and heights.pop() >= 3
            finally:
                await stop_all(nodes)

        run(scenario())

    def test_late_joiner_learns_large_mempool_in_pages(self, monkeypatch):
        from p1_tpu.node import node as node_mod

        monkeypatch.setattr(node_mod, "MEMPOOL_SYNC_TXS", 3)

        async def scenario():
            a = Node(_config())
            await a.start()
            try:
                await fund(a, "alice", blocks=2)  # 8 txs cost 76 > one reward
                txs = [
                    stx("alice", "bob", 5, f + 1, f, difficulty=DIFF) for f in range(8)
                ]
                for tx in txs:
                    await a.submit_tx(tx)
                b = Node(_config(peers=[f"127.0.0.1:{a.port}"]))
                await b.start()
                try:
                    # 8 txs at 3 per page: continuation must deliver ALL.
                    assert await wait_until(
                        lambda: all(tx.txid() in b.mempool for tx in txs)
                    )
                finally:
                    await b.stop()
            finally:
                await a.stop()

        run(scenario())

    def test_late_joiner_learns_mempool(self):
        async def scenario():
            a = Node(_config())
            await a.start()
            try:
                await fund(a, "alice")
                txs = [stx("alice", "bob", 5, f, 0 + f, difficulty=DIFF) for f in (1, 2, 3)]
                for tx in txs:
                    await a.submit_tx(tx)
                # b joins AFTER the txs exist; block sync alone would leave
                # its pool empty.
                b = Node(_config(peers=[f"127.0.0.1:{a.port}"]))
                await b.start()
                try:
                    assert await wait_until(
                        lambda: all(tx.txid() in b.mempool for tx in txs)
                    )
                finally:
                    await b.stop()
            finally:
                await a.stop()

        run(scenario())

    def test_late_joiner_syncs(self):
        async def scenario():
            a = Node(_config(mine=True))
            await a.start()
            try:
                assert await wait_until(lambda: a.chain.height >= 5)
                await a.stop_mining()
                b = Node(_config(peers=[f"127.0.0.1:{a.port}"]))
                await b.start()
                try:
                    assert await wait_until(
                        lambda: b.chain.tip_hash == a.chain.tip_hash
                    )
                    assert b.chain.height == a.chain.height
                finally:
                    await b.stop()
            finally:
                await a.stop()

        run(scenario())

    def test_deep_sync_spans_many_batches(self):
        """A late joiner pulling a chain much longer than SYNC_BATCH (500)
        must iterate the GETBLOCKS/BLOCKS continuation until caught up —
        exercising the height-indexed blocks_after serving path at depth."""

        async def scenario():
            from p1_tpu.chain import Chain
            from p1_tpu.core import Block, BlockHeader, Transaction, merkle_root
            from p1_tpu.hashx import get_backend
            from p1_tpu.miner import Miner

            diff = 2  # ~4 hashes/block: 1200 blocks stay fast
            miner = Miner(backend=get_backend("cpu"))
            chain = Chain(diff)
            tip = chain.genesis
            for height in range(1, 1201):
                tx = Transaction.coinbase("deep", height)
                header = BlockHeader(
                    1,
                    tip.block_hash(),
                    merkle_root([tx.txid()]),
                    tip.header.timestamp + 1,
                    diff,
                    0,
                )
                sealed = miner.search_nonce(header)
                assert sealed is not None
                block = Block(sealed, (tx,))
                assert chain.add_block(block).tip_changed
                tip = block

            a = Node(_config(difficulty=diff))
            a.chain = chain
            await a.start()
            b = Node(_config(difficulty=diff, peers=[f"127.0.0.1:{a.port}"]))
            await b.start()
            try:
                assert await wait_until(
                    lambda: b.chain.height == 1200, timeout=40
                ), b.chain.height
                assert b.chain.tip_hash == a.chain.tip_hash
            finally:
                await stop_all([a, b])

        run(scenario())

    def test_peer_death_and_recovery(self):
        async def scenario():
            nodes = await start_mesh(3, mine=True)
            victim = nodes[2]
            try:
                assert await wait_until(
                    lambda: min(n.chain.height for n in nodes) >= 2
                )
                await victim.stop()  # kill one peer mid-mine
                survivors = nodes[:2]
                h = max(n.chain.height for n in survivors)
                assert await wait_until(
                    lambda: min(n.chain.height for n in survivors) >= h + 2
                )
                for node in survivors:
                    await node.stop_mining()
                assert await wait_until(
                    lambda: len({n.chain.tip_hash for n in survivors}) == 1
                )
            finally:
                await stop_all(nodes[:2])

        run(scenario())


class TestRestart:
    def test_restart_resumes_and_catches_up(self, tmp_path):
        async def scenario():
            store = tmp_path / "node_a.dat"
            a = Node(_config(mine=True, store_path=str(store)))
            await a.start()
            try:
                assert await wait_until(lambda: a.chain.height >= 3)
            finally:
                await a.stop()
            saved_height, saved_tip = a.chain.height, a.chain.tip_hash

            # Restart from the store: chain state must come back.
            a2 = Node(_config(store_path=str(store)))
            await a2.start()
            try:
                assert a2.chain.height == saved_height
                assert a2.chain.tip_hash == saved_tip
            finally:
                await a2.stop()

        run(scenario())


class TestRestartGuards:
    def test_restart_with_wrong_difficulty_refused(self, tmp_path):
        async def scenario():
            store = tmp_path / "node.dat"
            a = Node(_config(mine=True, store_path=str(store)))
            await a.start()
            try:
                assert await wait_until(lambda: a.chain.height >= 1)
            finally:
                await a.stop()
            # Same store, different chain parameters: must refuse loudly
            # instead of silently interleaving two chains in one log.
            b = Node(_config(difficulty=DIFF + 1, store_path=str(store)))
            with pytest.raises(RuntimeError, match="difficulty"):
                await b.start()
            await b.stop()  # cleanup of whatever start() opened

        run(scenario())

    def test_second_node_same_store_refused(self, tmp_path):
        async def scenario():
            store = tmp_path / "shared.dat"
            a = Node(_config(mine=True, store_path=str(store)))
            await a.start()
            try:
                assert await wait_until(lambda: a.chain.height >= 1)
                b = Node(_config(store_path=str(store)))
                with pytest.raises(RuntimeError, match="locked"):
                    await b.start()
                await b.stop()
            finally:
                await a.stop()

        run(scenario())


class TestMempoolUnit:
    def test_fee_priority_and_dedup(self):
        from p1_tpu.mempool import Mempool

        pool = Mempool()
        cheap = stx("a", "b", 1, 1, 0, difficulty=DIFF)
        rich = stx("c", "d", 1, 9, 0, difficulty=DIFF)
        assert pool.add(cheap) and pool.add(rich)
        assert not pool.add(cheap)  # dedup
        assert pool.select() == [rich, cheap]

    def test_replace_by_fee_on_same_slot(self):
        from p1_tpu.mempool import Mempool

        pool = Mempool()
        cheap = stx("alice", "bob", 5, 1, 7, difficulty=DIFF)
        rich = stx("alice", "carol", 5, 3, 7, difficulty=DIFF)  # same (sender, seq)
        equal = stx("alice", "dave", 5, 3, 7, difficulty=DIFF)
        assert pool.add(cheap)
        assert pool.add(rich)  # outbids -> replaces
        assert cheap.txid() not in pool and rich.txid() in pool
        assert not pool.add(equal)  # must STRICTLY outbid
        assert not pool.add(cheap)  # replay of an outbid tx
        assert len(pool) == 1
        # independent slots coexist
        assert pool.add(stx("alice", "bob", 5, 1, 8, difficulty=DIFF))
        assert len(pool) == 2

    def test_expire_drops_only_stale_and_reopens_state(self):
        import time

        from p1_tpu.mempool import Mempool

        pool = Mempool()
        old = stx("alice", "bob", 5, 1, 0, difficulty=DIFF)
        fresh = stx("carol", "bob", 5, 1, 0, difficulty=DIFF)
        assert pool.add(old)
        assert pool.add(fresh)
        # Backdate `old` past the TTL; `fresh` stays current.
        pool._admitted_at[old.txid()] -= 100.0
        assert pool.expire(10.0) == 1
        assert old.txid() not in pool and fresh.txid() in pool
        assert len(pool) == 1
        # Every index released: the slot reopens (a rebroadcast with the
        # SAME fee re-enters — no RBF bar from a ghost incumbent), the
        # debit is gone, and the sync pager no longer serves it.
        assert pool.add(old)
        page, _ = pool.sync_page(None, 10)
        assert old.txid() in {t.txid() for t in page}
        assert pool.expire(10.0, now=time.monotonic() + 20) == 2
        assert len(pool) == 0 and pool._pending_debit == {}
        assert pool.sync_page(None, 10) == ([], False)

    def test_confirmation_evicts_slot_rivals(self):
        from p1_tpu.core.block import Block, merkle_root
        from p1_tpu.core.header import BlockHeader
        from p1_tpu.mempool import Mempool

        pool = Mempool()
        confirmed = stx("alice", "bob", 5, 1, 7, difficulty=DIFF)
        rival = stx("alice", "carol", 5, 9, 7, difficulty=DIFF)
        assert pool.add(rival)
        # A block confirms the OTHER spend of slot (alice, 7): the pending
        # rival is now a replay and must leave the pool with it.
        header = BlockHeader(
            1, bytes(32), merkle_root([confirmed.txid()]), 1, DIFF, 0
        )
        pool.apply_block_delta((), (Block(header, (confirmed,)),))
        assert rival.txid() not in pool and len(pool) == 0

    def test_sync_page_key_cursor_survives_churn(self):
        from p1_tpu.mempool import Mempool

        pool = Mempool()
        txs = [stx("alice", "bob", 5, 10 - f, f, difficulty=DIFF) for f in range(8)]
        for tx in txs:
            assert pool.add(tx)
        page1, more = pool.sync_page(None, 3)
        assert more and len(page1) == 3
        got = {t.txid() for t in page1}
        # Churn between pages: evict two high-fee txs already delivered.
        for tx in page1[:2]:
            pool._evict(tx)
        last = page1[-1]
        page2, more2 = pool.sync_page((last.fee, last.txid()), 100)
        got |= {t.txid() for t in page2}
        assert not more2
        # A positional cursor would have skipped entries after the
        # eviction shifted ranks; the key cursor delivers every tx.
        assert got == {t.txid() for t in txs}

    def test_rbf_bypasses_full_pool_capacity(self):
        from p1_tpu.mempool import Mempool

        pool = Mempool(max_txs=1)
        assert pool.add(stx("alice", "bob", 5, 1, 7, difficulty=DIFF))
        # Same slot, higher fee: replacement frees the incumbent's
        # capacity, so it is admitted even though the pool is full...
        assert pool.add(stx("alice", "carol", 5, 2, 7, difficulty=DIFF))
        # ...while a NEW slot is refused for capacity.
        assert not pool.add(stx("dave", "erin", 5, 9, 0, difficulty=DIFF))
        assert len(pool) == 1

    def test_confirmed_slot_refuses_late_replay(self):
        from p1_tpu.core.block import Block, merkle_root
        from p1_tpu.core.header import BlockHeader
        from p1_tpu.mempool import Mempool

        pool = Mempool()
        confirmed = stx("alice", "bob", 5, 1, 7, difficulty=DIFF)
        header = BlockHeader(
            1, bytes(32), merkle_root([confirmed.txid()]), 1, DIFF, 0
        )
        block = Block(header, (confirmed,))
        pool.apply_block_delta((), (block,))
        # A spend of the confirmed slot arriving AFTER confirmation (gossip
        # reorder) is refused, whatever its fee.
        late = stx("alice", "mallory", 5, 99, 7, difficulty=DIFF)
        assert not pool.add(late)
        # ... until a reorg rolls the confirmation back.
        pool.apply_block_delta((block,), ())
        assert confirmed.txid() in pool

    def test_full_paged_sync_scales(self, monkeypatch):
        """VERDICT r3 item 9: a late joiner paging a 100k-tx pool must not
        pay O(n) per page.  Signature verification is patched out (the
        pager's complexity is under test, not Ed25519 throughput — 100k
        real signs would dominate the clock and hide a pager regression);
        churn-correctness of the key cursor is covered separately above."""
        import time as time_mod

        from p1_tpu.mempool import Mempool, mempool as mempool_mod

        monkeypatch.setattr(
            mempool_mod.Transaction,
            "verify_signature",
            lambda self, cache=None: True,
        )
        pool = Mempool(max_txs=200_000)
        t0 = time_mod.perf_counter()
        for i in range(100_000):
            assert pool.add(Transaction("s", "r", 1, i % 1000, i))
        build_s = time_mod.perf_counter() - t0
        assert len(pool) == 100_000
        # Full paged sync, 2000/page (the node's MEMPOOL_SYNC_TXS).
        t0 = time_mod.perf_counter()
        cursor, got, more = None, 0, True
        while more:
            page, more = pool.sync_page(cursor, 2000)
            got += len(page)
            last = page[-1]
            cursor = (last.fee, last.txid())
        sync_s = time_mod.perf_counter() - t0
        assert got == 100_000
        # The old filter-everything pager took ~2 min for this loop on
        # this box; the indexed one is sub-second with huge margin even
        # under CI contention.
        assert sync_s < 20, f"paged sync took {sync_s:.1f}s (built in {build_s:.1f}s)"

    def test_coinbase_never_enters_pool(self):
        from p1_tpu.core.block import Block, merkle_root
        from p1_tpu.core.header import BlockHeader
        from p1_tpu.mempool import Mempool

        pool = Mempool()
        cb = Transaction.coinbase("miner-a", 7)
        assert not pool.add(cb)  # gossiped coinbase refused
        # reorg resurrection drops the abandoned branch's reward too
        header = BlockHeader(1, bytes(32), merkle_root([cb.txid()]), 1, DIFF, 0)
        pool.apply_block_delta((Block(header, (cb,)),), ())
        assert cb.txid() not in pool

    def test_block_delta_and_resurrection(self):
        from p1_tpu.core import Block, BlockHeader, merkle_root
        from p1_tpu.mempool import Mempool

        def block_with(txs):
            header = BlockHeader(
                1, bytes(32), merkle_root([t.txid() for t in txs]), 1, DIFF, 0
            )
            return Block(header, tuple(txs))

        pool = Mempool()
        t1 = stx("a", "b", 1, 1, 0, difficulty=DIFF)
        t2 = stx("c", "d", 2, 2, 0, difficulty=DIFF)
        pool.add(t1)
        pool.add(t2)
        pool.apply_block_delta((), (block_with([t1]),))
        assert t1.txid() not in pool and t2.txid() in pool
        # reorg abandons the t1 block: t1 comes back
        pool.apply_block_delta((block_with([t1]),), (block_with([t2]),))
        assert t1.txid() in pool and t2.txid() not in pool


class TestLostTaskObservation:
    """Round 13 lost-task audit fix: fire-and-forget session tasks
    (dials, sync failovers) ride ``_sessions`` + ``_untrack_session``;
    a task dying with an exception must be OBSERVED — logged and
    counted in ``metrics.task_crashes`` — not stranded in the GC's
    "exception was never retrieved" limbo (the round-3
    dead-recovery-loop failure shape the lost-task lint rule pins)."""

    def test_session_task_crash_is_logged_and_counted(self, caplog):
        import logging

        holder = {}

        async def scenario():
            node = Node(_config())
            await node.start()
            holder["node"] = node
            try:

                async def boom():
                    raise RuntimeError("session bug")

                task = asyncio.get_running_loop().create_task(boom())
                node._sessions[task] = None
                task.add_done_callback(node._untrack_session)
                assert await wait_until(
                    lambda: node.metrics.task_crashes == 1
                )
                assert task not in node._sessions
            finally:
                await node.stop()

        with caplog.at_level(logging.ERROR, logger="p1_tpu.node"):
            run(scenario())
        assert holder["node"].metrics.task_crashes == 1
        assert any(
            "died" in rec.getMessage() for rec in caplog.records
        ), [rec.getMessage() for rec in caplog.records]

    def test_cancelled_session_task_is_not_a_crash(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                task = asyncio.get_running_loop().create_task(
                    asyncio.sleep(30)
                )
                node._sessions[task] = None
                task.add_done_callback(node._untrack_session)
                task.cancel()
                assert await wait_until(lambda: task not in node._sessions)
                assert node.metrics.task_crashes == 0
            finally:
                await node.stop()

        run(scenario())
