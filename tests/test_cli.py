"""CLI: each benchmark config shape runs from one command (SURVEY §7.7)."""

import json
import subprocess
import sys

import pytest


def _run(*argv, timeout=110):
    proc = subprocess.run(
        [sys.executable, "-m", "p1_tpu", *argv],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


class TestCli:
    def test_mine_config1(self):
        out = _run("mine", "--difficulty", "10", "--blocks", "3", "--backend", "cpu")
        assert out["blocks"] == 3
        assert out["hashes_per_sec"] > 0
        assert out["time_to_block_s"] >= 0

    def test_sweep_config2(self):
        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "sweep",
                "--difficulties", "8:10", "--blocks", "2", "--backend", "cpu",
            ],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [json.loads(l) for l in proc.stdout.strip().splitlines()]
        assert [l["difficulty"] for l in lines] == [8, 9]
        assert all(l["config"] == "sweep" and l["blocks"] == 2 for l in lines)

    def test_mine_profile_writes_trace(self, tmp_path):
        out = _run(
            "mine", "--difficulty", "8", "--blocks", "1", "--backend", "cpu",
            "--profile", str(tmp_path / "trace"),
        )
        assert out["profile_dir"] == str(tmp_path / "trace")
        files = list((tmp_path / "trace").rglob("*"))
        assert any(f.is_file() for f in files), "no trace files written"

    def test_replay_config3(self):
        out = _run(
            "replay", "--n", "64", "--difficulty", "8", "--method", "host"
        )
        assert out["valid"] and out["n_headers"] == 64

    def test_net_config4_smoke(self):
        out = _run(
            "net",
            "--nodes",
            "2",
            "--difficulty",
            "12",
            "--duration",
            "2",
            "--chunk",
            "16384",
            "--base-port",
            "29444",
        )
        assert out["converged"], out
        assert out["height"] >= 1

    def test_unknown_backend_fails_cleanly(self):
        proc = subprocess.run(
            [sys.executable, "-m", "p1_tpu", "mine", "--backend", "nope"],
            capture_output=True,
            text=True,
            timeout=60,
            cwd="/root/repo",
        )
        assert proc.returncode != 0
        assert "nope" in proc.stderr
