"""CLI: each benchmark config shape runs from one command (SURVEY §7.7)."""

import json
import os
import subprocess
import sys

import pytest


def _run(*argv, timeout=110):
    proc = subprocess.run(
        [sys.executable, "-m", "p1_tpu", *argv],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


class TestCli:
    def test_mine_config1(self):
        out = _run("mine", "--difficulty", "10", "--blocks", "3", "--backend", "cpu")
        assert out["blocks"] == 3
        assert out["hashes_per_sec"] > 0
        assert out["time_to_block_s"] >= 0

    def test_sweep_config2(self):
        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "sweep",
                "--difficulties", "8:10", "--blocks", "2", "--backend", "cpu",
            ],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [json.loads(l) for l in proc.stdout.strip().splitlines()]
        assert [l["difficulty"] for l in lines] == [8, 9]
        assert all(l["config"] == "sweep" and l["blocks"] == 2 for l in lines)

    def test_mine_profile_writes_trace(self, tmp_path):
        out = _run(
            "mine", "--difficulty", "8", "--blocks", "1", "--backend", "cpu",
            "--profile", str(tmp_path / "trace"),
        )
        assert out["profile_dir"] == str(tmp_path / "trace")
        files = list((tmp_path / "trace").rglob("*"))
        assert any(f.is_file() for f in files), "no trace files written"

    def test_replay_config3(self):
        out = _run(
            "replay", "--n", "64", "--difficulty", "8", "--method", "host"
        )
        assert out["valid"] and out["n_headers"] == 64

    def test_net_config4_smoke(self):
        out = _run(
            "net",
            "--nodes",
            "2",
            "--difficulty",
            "12",
            "--duration",
            "2",
            "--chunk",
            "16384",
            "--base-port",
            "29444",
        )
        assert out["converged"], out
        assert out["height"] >= 1

    def test_net_discover_bootstrap(self):
        """Config 4 with the topology assembled by peer discovery: every
        node knows only the seed, and the net must still converge."""
        out = _run(
            "net", "--nodes", "3", "--difficulty", "12", "--duration", "4",
            "--chunk", "16384", "--base-port", "30444", "--discover",
            timeout=200,
        )
        assert out["converged"], out
        assert out["height"] >= 1

    def test_keygen_tx_mine_audit_e2e(self, tmp_path):
        """The full currency drive, CLI only: keygen two identities, mine
        to alice's account, alice pays bob with a SIGNED tx, audit the
        persisted chain — bob got paid, nothing is negative (VERDICT r3
        items 2+3 'live drive' criterion).

        Shutdown is TEST-DRIVEN (`--deadline stdin`): the node stays up
        until this test has finished every client round, then reads its
        stop time from stdin — so the node outlives its clients by
        construction.  The previous fixed `--duration 35` raced the
        clients' own 45 s budget and lost deterministically on a loaded
        1-vCPU host, where ~8 serial interpreter startups alone exceed
        35 s (VERDICT r5 weak #1: the anchored-proof step dialed a dead
        port)."""
        import time

        alice_key = str(tmp_path / "alice.key")
        bob_key = str(tmp_path / "bob.key")
        alice = _run("keygen", "--out", alice_key, "--seed-text", "cli-alice")[
            "account"
        ]
        bob = _run("keygen", "--out", bob_key, "--seed-text", "cli-bob")["account"]

        store = str(tmp_path / "chain.dat")
        # Log to a FILE: the node logs 2 lines per block at ms block
        # times — a stderr PIPE nobody drains fills at 64 KB and
        # deadlocks the node's synchronous logging (and with it the
        # whole event loop).  stdout carries only the ready line and the
        # final status JSON, so reading it directly is safe.
        node_log = open(tmp_path / "node.log", "w")
        node = subprocess.Popen(
            [
                sys.executable, "-m", "p1_tpu", "node",
                "--difficulty", "12", "--backend", "cpu", "--chunk", "16384",
                "--port", "0", "--miner-id", alice, "--store", store,
                "--deadline", "stdin",
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=node_log,
            text=True,
            cwd="/root/repo",
        )
        try:
            port = None
            for line in node.stdout:
                line = line.strip()
                if line.startswith("{"):
                    port = str(json.loads(line)["ready"])
                    break
            assert port, "node never printed its ready line"
            # Submit once alice has earned a balance (admission checks
            # affordability, so a too-early tx is refused silently —
            # retry until the audit can succeed).  The budget below is
            # pure client-side patience: the node no longer has a clock
            # to race.
            deadline = time.monotonic() + 120
            sent = False
            while not sent and time.monotonic() < deadline:
                proc = subprocess.run(
                    [
                        sys.executable, "-m", "p1_tpu", "tx",
                        "--difficulty", "12", "--port", port,
                        "--key", alice_key, "--recipient", bob,
                        "--amount", "7", "--fee", "1",
                    ],
                    capture_output=True, text=True, timeout=30, cwd="/root/repo",
                )
                if proc.returncode == 0:
                    out = json.loads(proc.stdout)
                    sent = out["peer_height"] >= 1  # alice funded from h1 on
                time.sleep(0.3)
            assert sent, "node never became reachable with a funded miner"
            assert out["seq"] == 0  # auto-seq: fresh account starts at 0
            # SPV round: once that spend confirms, `p1 proof` must fetch an
            # inclusion proof AND verify it client-side (exit 3 = not yet
            # mined; block times are ms, so this resolves in a beat).
            txid = out["txid"]
            proved = None
            while proved is None and time.monotonic() < deadline:
                proc = subprocess.run(
                    [
                        sys.executable, "-m", "p1_tpu", "proof",
                        "--difficulty", "12", "--port", port, "--txid", txid,
                    ],
                    capture_output=True, text=True, timeout=30, cwd="/root/repo",
                )
                if proc.returncode == 0:
                    proved = json.loads(proc.stdout)
                else:
                    assert proc.returncode == 3, proc.stderr[-1000:]
                    time.sleep(0.3)  # not mined yet — keep polling
            assert proved is not None, "spend never confirmed with a proof"
            assert proved["verified"] and proved["amount"] == 7
            # Light-client round: sync + locally verify the header chain,
            # then re-fetch the proof anchored against it — height and
            # confirmations now come from OUR verified chain, not the
            # peer's claim.
            hdrs = str(tmp_path / "headers.bin")
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "headers",
                    "--difficulty", "12", "--port", port, "--out", hdrs,
                ],
                capture_output=True, text=True, timeout=30, cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-1000:]
            assert json.loads(proc.stdout)["valid"]
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "proof",
                    "--difficulty", "12", "--port", port, "--txid", txid,
                    "--headers", hdrs,
                ],
                capture_output=True, text=True, timeout=30, cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-1000:]
            anchored = json.loads(proc.stdout)
            assert anchored["anchored"] and anchored["verified"]
            # Second spend, no --seq either: GETACCOUNT must hand back the
            # next usable nonce (1), whether the first tx is still pending
            # or already mined.
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "tx",
                    "--difficulty", "12", "--port", port,
                    "--key", alice_key, "--recipient", bob,
                    "--amount", "5", "--fee", "auto",
                ],
                capture_output=True, text=True, timeout=30, cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-1000:]
            second = json.loads(proc.stdout)
            assert second["seq"] == 1
            # --fee auto priced at the confirmed median (the first spend
            # paid 1, so the sampled median is 1).
            assert second["fee"] == 1
            # Live account query while the node still runs.
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "account",
                    "--difficulty", "12", "--port", port, "--account", bob,
                ],
                capture_output=True, text=True, timeout=30, cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-1000:]
            assert json.loads(proc.stdout)["account"] == bob
        finally:
            # Clients done (or the test failed): NOW the node may stop.
            # "Stop at `now`" starts the quiesce-and-exit path
            # immediately; the generous wait covers quiesce + final
            # store sync on a loaded box.
            try:
                node.stdin.write(f"{time.time()!r}\n")
                node.stdin.flush()
                node.wait(timeout=120)
            except Exception:
                node.kill()
                node.wait(timeout=30)
            node_log.close()
        out = _run(
            "balances", "--store", store, "--difficulty", "12",
            "--account", bob,
        )
        assert out["balance"] == 7 + 5, out
        full = _run("balances", "--store", store, "--difficulty", "12")
        assert full["conserved"]  # offline audit: view==ledger, exact sum
        assert all(v >= 0 for v in full["balances"].values())
        assert full["balances"][alice] >= 50 - 14

    def test_net_with_tx_economy(self):
        """Config 4 carrying a live signed-transfer economy: the net must
        still converge AND every node's ledger must conserve exactly
        (reward x height) — signatures, nonces, overdraw rejection and
        reorg undo all exercised under real concurrent forks."""
        out = _run(
            "net", "--nodes", "2", "--difficulty", "12", "--duration", "5",
            "--chunk", "16384", "--base-port", "29944", "--tx-rate", "3",
            timeout=200,
        )
        assert out["converged"], out
        assert out["economy"]["ledger_conserved"], out["economy"]
        # The audit is vacuous unless transfers actually flowed.
        assert out["economy"]["txs_submitted"] > 0, out["economy"]

    def test_replay_verify_pins_genesis(self, tmp_path):
        # A header file is self-attested evidence; --verify must refuse
        # one that does not start at the selected chain's genesis (a
        # forged trivial-difficulty file would otherwise "verify").
        hdrs = str(tmp_path / "h.bin")
        out = _run(
            "replay", "--n", "8", "--difficulty", "8", "--method", "host",
            "--out", hdrs,
        )
        assert out["valid"]
        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "replay",
                "--verify", hdrs, "--difficulty", "9", "--method", "host",
            ],
            capture_output=True, text=True, timeout=110, cwd="/root/repo",
        )
        assert proc.returncode == 2
        assert "genesis" in proc.stderr

    def test_node_bad_retarget_pair_fails_cleanly(self):
        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "node",
                "--retarget-window", "144", "--port", "0",
            ],
            capture_output=True, text=True, timeout=110, cwd="/root/repo",
        )
        assert proc.returncode != 0
        assert "set together" in proc.stderr
        assert "Traceback" not in proc.stderr

    def test_unknown_backend_fails_cleanly(self):
        proc = subprocess.run(
            [sys.executable, "-m", "p1_tpu", "mine", "--backend", "nope"],
            capture_output=True,
            text=True,
            timeout=60,
            cwd="/root/repo",
        )
        assert proc.returncode != 0
        assert "nope" in proc.stderr


class TestAutoFeeCap:
    """ADVICE r4: `p1 tx --fee auto` signed whatever fee the peer quoted.
    The wallet now refuses quotes above --max-fee before signing."""

    def test_hostile_quote_refused(self, tmp_path, monkeypatch):
        from p1_tpu import cli
        import p1_tpu.node.client as client_mod
        from p1_tpu.node.protocol import FeeStats

        key = str(tmp_path / "k.json")
        assert cli.main(["keygen", "--out", key]) == 0

        called = {}

        async def hostile_fees(*a, **k):
            return FeeStats(32, 5, 10**9, 10**9, 10**9, 10)

        async def never_send(*a, **k):  # pragma: no cover - must not run
            called["sent"] = True
            raise AssertionError("wallet signed a capped fee")

        monkeypatch.setattr(client_mod, "get_fees", hostile_fees)
        monkeypatch.setattr(client_mod, "send_tx", never_send)
        rc = cli.main(
            [
                "tx", "--difficulty", "12", "--key", key,
                "--recipient", "p1deadbeefdeadbeef",
                "--amount", "1", "--fee", "auto",
            ]
        )
        assert rc == 2
        assert "sent" not in called

    def test_quote_within_cap_accepted(self, tmp_path, monkeypatch, capsys):
        import json as _json

        from p1_tpu import cli
        import p1_tpu.node.client as client_mod
        from p1_tpu.node.protocol import AccountState, FeeStats

        key = str(tmp_path / "k.json")
        assert cli.main(["keygen", "--out", key]) == 0
        capsys.readouterr()

        async def fair_fees(*a, **k):
            return FeeStats(32, 5, 2, 3, 4, 10)

        async def fake_account(host, port, account, *a, **k):
            return AccountState(account, 100, 0, 0, 10)

        async def fake_send(*a, **k):
            return 10

        monkeypatch.setattr(client_mod, "get_fees", fair_fees)
        monkeypatch.setattr(client_mod, "get_account", fake_account)
        monkeypatch.setattr(client_mod, "send_tx", fake_send)
        rc = cli.main(
            [
                "tx", "--difficulty", "12", "--key", key,
                "--recipient", "p1deadbeefdeadbeef",
                "--amount", "1", "--fee", "auto",
            ]
        )
        assert rc == 0
        assert _json.loads(capsys.readouterr().out)["fee"] == 3


class TestStatus:
    """`p1 status` renders a running node's full status JSON over the
    wire (GETSTATUS/STATUS v9), the overload block included."""

    def test_status_renders_overload_block(self, tmp_path):
        import time

        node_log = open(tmp_path / "node.log", "w")
        node = subprocess.Popen(
            [
                sys.executable, "-m", "p1_tpu", "node",
                "--difficulty", "12", "--backend", "cpu", "--chunk", "16384",
                "--port", "0", "--no-mine", "--deadline", "stdin",
                "--body-cache", "64", "--mem-watermark-mb", "64",
                "--store", str(tmp_path / "chain.dat"),
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=node_log,
            text=True,
            cwd="/root/repo",
        )
        try:
            port = None
            for line in node.stdout:
                line = line.strip()
                if line.startswith("{"):
                    port = str(json.loads(line)["ready"])
                    break
            assert port, "node never printed its ready line"
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "status",
                    "--difficulty", "12", "--port", port,
                ],
                capture_output=True, text=True, timeout=30, cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            # Pretty-printed (indent=2) — parse the whole document, not
            # a line.
            out = json.loads(proc.stdout)
            overload = out["overload"]
            assert overload["state"] == "normal"
            assert overload["watermark_bytes"] == 64 << 20
            assert overload["body_cache_blocks"] == 64
            assert overload["mining_paused"] is False
            for key in (
                "tracked_bytes",
                "admission_dropped",
                "shed_drops",
                "resident_body_bytes",
                "bodies_evicted",
                "body_refetches",
            ):
                assert key in overload, key
            assert out["height"] == 0 and "storage" in out and "sync" in out
        finally:
            if node.poll() is None:
                node.stdin.write(f"{time.time()!r}\n")
                node.stdin.flush()
                node.stdin.close()
                try:
                    node.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    node.kill()
            node_log.close()


class TestMetricsCLI:
    """`p1 metrics` (GETMETRICS/METRICS v12) and `p1 status --watch N`
    against one running node: the human table, the raw JSON snapshot,
    the Prometheus exposition, and the watch loop's clean-Ctrl-C exit."""

    def test_metrics_renders_and_watch_exits_cleanly(self, tmp_path):
        import signal
        import time

        node_log = open(tmp_path / "node.log", "w")
        node = subprocess.Popen(
            [
                sys.executable, "-m", "p1_tpu", "node",
                "--difficulty", "12", "--backend", "cpu", "--chunk", "16384",
                "--port", "0", "--no-mine", "--deadline", "stdin",
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=node_log,
            text=True,
            cwd="/root/repo",
        )
        try:
            port = None
            for line in node.stdout:
                line = line.strip()
                if line.startswith("{"):
                    port = str(json.loads(line)["ready"])
                    break
            assert port, "node never printed its ready line"

            def metrics(*flags):
                proc = subprocess.run(
                    [
                        sys.executable, "-m", "p1_tpu", "metrics",
                        "--difficulty", "12", "--port", port, *flags,
                    ],
                    capture_output=True, text=True, timeout=30,
                    cwd="/root/repo",
                )
                assert proc.returncode == 0, proc.stderr[-2000:]
                return proc.stdout

            table = metrics()
            assert "role: node" in table and "blocks_accepted" in table
            snap = json.loads(metrics("--json"))
            assert snap["role"] == "node"
            assert "blocks_accepted" in snap["counters"]
            prom = metrics("--prom")
            assert "# TYPE p1_blocks_accepted counter" in prom
            assert "p1_blocks_accepted 0" in prom

            # --watch: two polls land, SIGINT exits 0 (the clean-Ctrl-C
            # contract — a dashboard must not die with a traceback).
            watch = subprocess.Popen(
                [
                    sys.executable, "-m", "p1_tpu", "status",
                    "--difficulty", "12", "--port", port,
                    "--watch", "0.3",
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd="/root/repo",
            )
            try:
                seen = 0
                deadline = time.monotonic() + 30
                while seen < 2 and time.monotonic() < deadline:
                    line = watch.stdout.readline()
                    if line.strip() == "{":
                        seen += 1
                assert seen >= 2, "watch never re-polled"
            finally:
                watch.send_signal(signal.SIGINT)
            rc = watch.wait(timeout=30)
            assert rc == 0, (rc, watch.stderr.read()[-2000:])
        finally:
            if node.poll() is None:
                node.stdin.write(f"{time.time()!r}\n")
                node.stdin.flush()
                node.stdin.close()
                try:
                    node.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    node.kill()
            node_log.close()


class TestByzantineSoak:
    """`p1 net --byzantine N` (VERDICT r4 weak #5): honest nodes keep
    converging and conserving while live attackers throw the whole
    hostile repertoire at them, and the summary asserts containment —
    bans fired, memory bounded — rather than leaving it to the logs."""

    def test_net_with_byzantine_attacker_contained(self):
        out = _run(
            "net",
            "--nodes", "3",
            "--difficulty", "12",
            "--duration", "8",
            "--tx-rate", "2",
            "--byzantine", "1",
            "--chunk", "16384",
            "--base-port", "29844",
        )
        assert out["converged"], out
        byz = out["byzantine"]
        assert byz["contained"], byz
        assert byz["attacks_sent"] > 0
        assert byz["bans_fired"] and byz["refused_connects"] > 0
        assert byz["memory_bounded"]
        # The hostile stream must not have corrupted the economy.
        assert out["economy"]["ledger_conserved"]
        # Several distinct attack categories actually ran.
        assert len(byz["attacks"]) >= 4, byz["attacks"]


class TestRetargetWalletE2E:
    """The round-5 manual drive as a suite test: a live retargeting
    node (schedule actually climbing), funded wallet spend with
    --fee auto, SPV proof verified at the claimed-difficulty bar with
    the unanchored-figures warning on stderr, then headers-first
    anchoring through the native-verified chain."""

    def test_wallet_round_on_retargeting_chain(self, tmp_path):
        import time

        RT = ["--retarget-window", "50", "--target-spacing", "5"]
        key = str(tmp_path / "alice.json")
        out = _run("keygen", "--out", key)
        alice = out["account"]
        node = subprocess.Popen(
            [
                sys.executable, "-m", "p1_tpu", "node",
                "--difficulty", "12", "--port", "0", "--platform", "cpu",
                *RT, "--miner-id", alice, "--deadline", "stdin",
            ],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, cwd="/root/repo",
        )
        try:
            port = None
            for line in node.stdout:
                line = line.strip()
                if line.startswith("{"):
                    port = str(json.loads(line)["ready"])
                    break
            assert port
            time.sleep(2)  # a few blocks of funding
            tx = None
            for _ in range(10):  # tolerate miner-load handshake stalls
                proc = subprocess.run(
                    [
                        sys.executable, "-m", "p1_tpu", "tx",
                        "--difficulty", "12", *RT, "--port", port,
                        "--key", key, "--recipient", "p1deadbeefdeadbeef",
                        "--amount", "3", "--fee", "auto",
                    ],
                    capture_output=True, text=True, timeout=60,
                    cwd="/root/repo",
                )
                if proc.returncode == 0:
                    tx = json.loads(proc.stdout)
                    break
                time.sleep(1)
            assert tx is not None, proc.stderr[-500:]
            txid = tx["txid"]
            proved = None
            for _ in range(60):
                proc = subprocess.run(
                    [
                        sys.executable, "-m", "p1_tpu", "proof",
                        "--difficulty", "12", *RT, "--port", port,
                        "--txid", txid,
                    ],
                    capture_output=True, text=True, timeout=60,
                    cwd="/root/repo",
                )
                if proc.returncode == 0:
                    proved = json.loads(proc.stdout)
                    # Unanchored retarget proofs must shout about it.
                    assert "without --headers" in proc.stderr
                    break
                assert proc.returncode == 3, proc.stderr[-500:]
                time.sleep(0.5)
            assert proved is not None and proved["verified"]
            # Headers-first anchoring (native-verified schedule).
            hdrs = str(tmp_path / "h.bin")
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "headers",
                    "--difficulty", "12", *RT, "--port", port,
                    "--out", hdrs,
                ],
                capture_output=True, text=True, timeout=60, cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-500:]
            synced = json.loads(proc.stdout)
            assert synced["valid"]
            # The schedule actually moved: sub-second real blocks at
            # spacing 5 force the difficulty up past the base.
            assert synced["tip_difficulty"] > 12
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "proof",
                    "--difficulty", "12", *RT, "--port", port,
                    "--txid", txid, "--headers", hdrs,
                ],
                capture_output=True, text=True, timeout=60, cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-500:]
            anchored = json.loads(proc.stdout)
            assert anchored["anchored"] and anchored["verified"]
        finally:
            try:
                node.stdin.write(str(time.time()) + "\n")
                node.stdin.flush()
                node.wait(timeout=60)
            except Exception:
                node.kill()


class TestFsck:
    """`p1 fsck` exit-code contract (ISSUE r7): 0 clean, 1 salvaged,
    2 unrecoverable — plus the v2 upgrade path and a help smoke test."""

    @staticmethod
    def _mk_store(path, n=6, difficulty=12):
        from p1_tpu.chain import ChainStore
        from p1_tpu.node.testing import make_blocks

        blocks = make_blocks(n, difficulty=difficulty)
        store = ChainStore(path)
        try:
            for block in blocks[1:]:
                store.append(block)
        finally:
            store.close()
        return blocks

    @staticmethod
    def _fsck(*argv, timeout=110):
        return subprocess.run(
            [sys.executable, "-m", "p1_tpu", "fsck", *argv],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd="/root/repo",
        )

    def test_clean_store_exit_0(self, tmp_path):
        store = tmp_path / "clean.dat"
        self._mk_store(store)
        proc = self._fsck("--store", str(store))
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip())
        assert out["status"] == "clean"
        assert out["records_valid"] == 6 and out["bad_spans"] == 0

    def test_mid_log_corruption_salvaged_exit_1(self, tmp_path):
        from p1_tpu.chain import ChainStore

        store = tmp_path / "hurt.dat"
        blocks = self._mk_store(store)
        data = bytearray(store.read_bytes())
        # Flip a bit in record 3's length prefix (the headline fault).
        off, _n = ChainStore.scan(bytes(data)).spans[2]
        data[off - 4] ^= 0x10
        store.write_bytes(bytes(data))
        proc = self._fsck("--store", str(store))
        assert proc.returncode == 1, (proc.stdout, proc.stderr[-2000:])
        out = json.loads(proc.stdout.strip())
        assert out["status"] == "salvaged"
        assert out["records_salvaged"] == 5 and out["bad_spans"] == 1
        # The salvaged store is clean v3 holding every good record, and
        # the quarantine sidecar preserves the evidence.
        loaded = ChainStore(store).load_blocks()
        want = [b.block_hash() for b in blocks[1:]]
        assert [b.block_hash() for b in loaded] == want[:2] + want[3:]
        assert (tmp_path / "hurt.dat.quarantine").exists()
        # Second pass over the salvaged store: clean, exit 0.
        assert self._fsck("--store", str(store)).returncode == 0

    def test_garbage_store_exit_2(self, tmp_path):
        junk = tmp_path / "junk.dat"
        junk.write_bytes(b"definitely not a chain store at all")
        proc = self._fsck("--store", str(junk))
        assert proc.returncode == 2
        assert "not a chain store" in proc.stderr
        missing = self._fsck("--store", str(tmp_path / "absent.dat"))
        assert missing.returncode == 2

    def test_v2_store_upgrades_lossless_exit_0(self, tmp_path):
        import struct

        from p1_tpu.chain import ChainStore
        from p1_tpu.chain.store import MAGIC, V2_MAGIC
        from p1_tpu.node.testing import make_blocks

        blocks = make_blocks(4, difficulty=12)
        store = tmp_path / "v2.dat"
        parts = [V2_MAGIC]
        for block in blocks[1:]:
            raw = block.serialize()
            parts.append(struct.pack(">I", len(raw)))
            parts.append(raw)
        store.write_bytes(b"".join(parts))
        proc = self._fsck("--store", str(store))
        assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
        out = json.loads(proc.stdout.strip())
        assert out["status"] == "upgraded" and out["version"] == 2
        assert store.read_bytes().startswith(MAGIC)
        loaded = ChainStore(store).load_blocks()
        assert [b.block_hash() for b in loaded] == [
            b.block_hash() for b in blocks[1:]
        ]
        # A v2 store is also writable again after the upgrade.
        s = ChainStore(store)
        s.acquire()
        s.close()

    def test_help_smoke(self):
        proc = self._fsck("--help")
        assert proc.returncode == 0
        assert "salvage" in proc.stdout and "--store" in proc.stdout


class TestSim:
    """`p1 sim` (round 10): the deterministic network-simulator
    scenarios — list/help smoke plus one subprocess e2e proving the
    JSON report line, the ok exit-code contract, and that the report's
    trace digest is reproducible by seed across PROCESSES (which the
    in-process determinism tests cannot see: it additionally requires
    nothing hash-seed-dependent in the event path)."""

    def test_list_smoke(self):
        proc = subprocess.run(
            [sys.executable, "-m", "p1_tpu", "sim", "--list"],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 0
        for name in ("partition-heal", "flash-crowd", "eclipse", "wan"):
            assert name in proc.stdout

    def test_unknown_scenario_is_a_clean_cli_error(self):
        proc = subprocess.run(
            [sys.executable, "-m", "p1_tpu", "sim", "bogus"],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode != 0
        assert "unknown scenario" in proc.stderr

    def test_sim_e2e_report_and_cross_process_determinism(self):
        def one_run():
            proc = subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "p1_tpu",
                    "sim",
                    "partition-heal",
                    "--nodes",
                    "16",
                    "--seed",
                    "9",
                ],
                capture_output=True,
                text=True,
                timeout=110,
                cwd="/root/repo",
                env={**os.environ, "PYTHONHASHSEED": "0"},
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            return json.loads(proc.stdout.strip().splitlines()[-1])

        a, b = one_run(), one_run()
        assert a["ok"] and a["converged"] and a["ledger_conserved"]
        assert a["nodes"] == 16
        assert a["trace_digest"] == b["trace_digest"]

    @staticmethod
    def _sim(*argv, timeout=180):
        return subprocess.run(
            [sys.executable, "-m", "p1_tpu", "sim", *argv],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd="/root/repo",
            env={**os.environ, "PYTHONHASHSEED": "0"},
        )

    def test_report_repro_stamp_round_trips(self):
        """Round-17 satellite: every report names its one-flag repro
        (`p1 sim <name> --seed N`) and re-running exactly that command
        reproduces the trace digest byte-for-byte, across processes."""
        first = self._sim("retarget-shock", "--nodes", "5", "--seed", "7")
        assert first.returncode == 0, first.stderr[-2000:]
        a = json.loads(first.stdout.strip().splitlines()[-1])
        assert a["seed"] == 7
        assert a["repro"] == "p1 sim retarget-shock --seed 7"
        again = self._sim("retarget-shock", "--nodes", "5", "--seed", "7")
        b = json.loads(again.stdout.strip().splitlines()[-1])
        assert b["trace_digest"] == a["trace_digest"]

    def test_far_field_shard_split_is_digest_stable_cross_process(self):
        """Round-17 acceptance: the far-field merged trace digest does
        not move across the 1→N shard split, with the N shards as REAL
        OS processes over the pipe seam, PYTHONHASHSEED pinned."""
        one = self._sim(
            "far-field", "--nodes", "400", "--seed", "4", "--shards", "1"
        )
        assert one.returncode == 0, one.stderr[-2000:]
        a = json.loads(one.stdout.strip().splitlines()[-1])
        sharded = self._sim(
            "far-field", "--nodes", "400", "--seed", "4", "--shards", "2"
        )
        assert sharded.returncode == 0, sharded.stderr[-2000:]
        b = json.loads(sharded.stdout.strip().splitlines()[-1])
        assert a["ok"] and b["ok"]
        assert b["shard_processes"] and not a["shard_processes"]
        assert a["trace_digest"] == b["trace_digest"]
        assert a["far_trace_digest"] == b["far_trace_digest"]


class TestChaos:
    """`p1 chaos` (round 11): combined-fault schedules over the
    simulated mesh.  Exit-code contract: 0 = all invariants held,
    1 = violation with a (shrunk) repro written — or a --repro replay
    that reproduces — 2 = usage / unreadable artifact.  Plus the
    cross-process determinism half of the acceptance criterion."""

    @staticmethod
    def _chaos(*argv, timeout=240):
        return subprocess.run(
            [sys.executable, "-m", "p1_tpu", "chaos", *argv],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd="/root/repo",
            env={**os.environ, "PYTHONHASHSEED": "0"},
        )

    def test_help_smoke(self):
        proc = self._chaos("--help")
        assert proc.returncode == 0
        for flag in ("--schedules", "--repro", "--seed", "--events"):
            assert flag in proc.stdout

    def test_clean_sweep_exit_0_and_cross_process_determinism(self):
        # A seed whose schedule includes a crash/recover cycle, so the
        # digest equality below covers the reboot path too.
        from p1_tpu.node.chaos import generate_schedule

        seed = next(
            s
            for s in range(20)
            if any(
                e["op"] == "crash" for e in generate_schedule(s, 5, 10)
            )
        )

        def one_run():
            proc = self._chaos(
                "--seed", str(seed), "--schedules", "1", "--nodes", "5",
                "--events", "10",
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            return json.loads(proc.stdout.strip().splitlines()[-1])

        a, b = one_run(), one_run()
        assert a["ok"] and a["trace_digests"] == b["trace_digests"]

    def test_violation_exit_1_writes_repro_that_replays_exit_1(
        self, tmp_path
    ):
        out = tmp_path / "repro.json"
        proc = self._chaos(
            "--seed", "0", "--schedules", "3", "--nodes", "5",
            "--events", "10", "--inject-bug", "relapse-disk",
            "--out", str(out),
        )
        assert proc.returncode == 1, (proc.stdout, proc.stderr[-2000:])
        summary = json.loads(proc.stdout.strip().splitlines()[-1])
        assert summary["violations"] and out.exists()
        # Shrinker acceptance: the minimized schedule is tiny.
        assert summary["shrunk_events"] <= 5
        replay = self._chaos("--repro", str(out))
        assert replay.returncode == 1, replay.stderr[-2000:]
        rep = json.loads(replay.stdout.strip().splitlines()[-1])
        assert rep["reproduced"] and rep["digest_match"]

    def test_unreadable_repro_exit_2(self, tmp_path):
        junk = tmp_path / "junk.json"
        junk.write_text("not a repro")
        assert self._chaos("--repro", str(junk)).returncode == 2
        assert (
            self._chaos("--repro", str(tmp_path / "absent.json")).returncode
            == 2
        )


class TestServe:
    """`p1 serve` (round 9): a read-only replica worker process over a
    chain store — help smoke plus one subprocess e2e proving the JSON
    ready line, real query service, and the --deadline exit."""

    def test_help_smoke(self):
        proc = subprocess.run(
            [sys.executable, "-m", "p1_tpu", "serve", "--help"],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 0
        assert "--store" in proc.stdout and "--workers" in proc.stdout

    def test_worker_count_validation(self, tmp_path):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "p1_tpu",
                "serve",
                "--store",
                str(tmp_path / "x.dat"),
                "--port",
                "0",
                "--workers",
                "2",
            ],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 2
        assert "explicit --port" in proc.stderr

    def test_serve_e2e_queries_then_deadline_exit(self, tmp_path):
        import asyncio

        from p1_tpu.chain import ChainStore
        from p1_tpu.node.client import get_headers, get_status
        from p1_tpu.node.testing import make_blocks

        store = tmp_path / "chain.dat"
        blocks = make_blocks(5, difficulty=12)
        s = ChainStore(store)
        try:
            for block in blocks[1:]:
                s.append(block)
        finally:
            s.close()
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "p1_tpu",
                "serve",
                "--store",
                str(store),
                "--difficulty",
                "12",
                "--port",
                "0",
                "--deadline",
                "30",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            cwd="/root/repo",
        )
        try:
            ready = json.loads(proc.stdout.readline())
            assert ready["config"] == "serve" and ready["height"] == 5

            async def _query():
                headers = await get_headers(
                    "127.0.0.1", ready["port"], 12
                )
                status = await get_status(
                    "127.0.0.1", ready["port"], 12
                )
                return headers, status

            headers, status = asyncio.run(_query())
            assert len(headers) == 6  # genesis + 5
            assert [h.block_hash() for h in headers] == [
                b.block_hash() for b in blocks
            ]
            assert status["role"] == "replica" and status["height"] == 5
        finally:
            proc.terminate()
            proc.wait(timeout=30)


class TestWatchCLI:
    """`p1 watch` — one JSON line per verified push event, deadline and
    max-events as clean exits (0), dead peers as exit 1."""

    def test_help_smoke(self):
        proc = subprocess.run(
            [sys.executable, "-m", "p1_tpu", "watch", "--help"],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 0
        assert "--fallback" in proc.stdout and "--deadline" in proc.stdout

    def test_watch_e2e_mining_node_events_then_exit(self, tmp_path):
        """Submit-free SLO shape over two real processes: a mining node
        pushes events, `p1 watch <miner account>` verifies and prints
        them, then exits 0 at --max-events.  Every line is a matched
        event (each block pays the miner) with contiguous heights."""
        node_log = open(tmp_path / "node.log", "w")
        node = subprocess.Popen(
            [
                sys.executable, "-m", "p1_tpu", "node",
                "--difficulty", "12", "--backend", "cpu",
                "--chunk", "16384", "--port", "0",
                "--miner-id", "watch-cli-acct", "--deadline", "stdin",
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=node_log,
            text=True,
            cwd="/root/repo",
        )
        try:
            port = None
            for line in node.stdout:
                line = line.strip()
                if line.startswith("{"):
                    port = str(json.loads(line)["ready"])
                    break
            assert port, "node never printed its ready line"
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "watch",
                    "watch-cli-acct", "--difficulty", "12",
                    "--port", port, "--deadline", "90",
                    "--max-events", "3",
                ],
                capture_output=True,
                text=True,
                timeout=110,
                cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            lines = [
                json.loads(l) for l in proc.stdout.strip().splitlines()
            ]
            assert len(lines) == 3
            heights = [l["height"] for l in lines]
            assert heights == list(range(heights[0], heights[0] + 3))
            for l in lines:
                assert l["matched"] and l["txids"]
                assert len(l["block"]) == 64  # hex block hash
                assert len(l["filter_header"]) == 64
                assert l["peer"].endswith(f":{port}")
        finally:
            try:
                node.communicate(input="0\n", timeout=30)
            except subprocess.TimeoutExpired:
                node.kill()
            node_log.close()

    def test_watch_deadline_is_a_clean_exit(self, tmp_path):
        """Against a static replica nothing ever connects, so the watch
        idles at its TOFU anchor until --deadline — exit 0, no output
        (the `p1 serve` deadline contract)."""
        from p1_tpu.chain import ChainStore
        from p1_tpu.node.testing import make_blocks

        store = tmp_path / "chain.dat"
        s = ChainStore(store)
        try:
            for block in make_blocks(4, difficulty=12)[1:]:
                s.append(block)
        finally:
            s.close()
        srv = subprocess.Popen(
            [
                sys.executable, "-m", "p1_tpu", "serve",
                "--store", str(store), "--difficulty", "12",
                "--port", "0", "--deadline", "60",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            cwd="/root/repo",
        )
        try:
            ready = json.loads(srv.stdout.readline())
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "watch", "nobody",
                    "--difficulty", "12", "--port", str(ready["port"]),
                    "--deadline", "3",
                ],
                capture_output=True,
                text=True,
                timeout=110,
                cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            assert proc.stdout.strip() == ""
        finally:
            srv.terminate()
            srv.wait(timeout=30)

    def test_watch_dead_peer_exits_1(self):
        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "watch", "nobody",
                "--difficulty", "12", "--port", "1",
                "--max-session-failures", "1",
            ],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 1
        assert "watch failed" in proc.stderr


class TestSnapshotCLI:
    """`p1 snapshot create/verify/info` — the established exit-code
    contract (0 clean / 1 salvageable / 2 unrecoverable) + help smoke."""

    @staticmethod
    def _cli(*argv, timeout=110):
        return subprocess.run(
            [sys.executable, "-m", "p1_tpu", "snapshot", *argv],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd="/root/repo",
        )

    def test_help_smoke(self):
        proc = subprocess.run(
            [sys.executable, "-m", "p1_tpu", "snapshot", "--help"],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 0
        assert "create" in proc.stdout and "verify" in proc.stdout

    def test_create_verify_info_round_trip(self, tmp_path):
        from p1_tpu.chain import ChainStore
        from p1_tpu.node.testing import make_blocks

        store = tmp_path / "store.dat"
        s = ChainStore(store)
        for b in make_blocks(10, 8, miner_id="cli-m")[1:]:
            s.append(b)
        s.close()
        snap = tmp_path / "snap.p1s"
        proc = self._cli(
            "create", "--store", str(store), "--file", str(snap),
            "--interval", "4",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["height"] == 8 and out["accounts"] == 1
        proc = self._cli("verify", "--file", str(snap))
        assert proc.returncode == 0
        assert json.loads(proc.stdout.strip())["status"] == "clean"
        proc = self._cli("info", "--file", str(snap))
        assert proc.returncode == 0
        info = json.loads(proc.stdout.strip())
        assert info["height"] == 8 and "trust" in info

    def test_verify_salvageable_exit_1(self, tmp_path):
        from p1_tpu.chain import ChainStore
        from p1_tpu.node.testing import make_blocks

        store = tmp_path / "store.dat"
        s = ChainStore(store)
        for b in make_blocks(8, 8)[1:]:
            s.append(b)
        s.close()
        snap = tmp_path / "snap.p1s"
        assert (
            self._cli(
                "create", "--store", str(store), "--file", str(snap),
                "--interval", "4",
            ).returncode
            == 0
        )
        with open(snap, "ab") as fh:
            fh.write(b"trailing garbage")
        proc = self._cli("verify", "--file", str(snap))
        assert proc.returncode == 1, (proc.stdout, proc.stderr[-500:])
        assert json.loads(proc.stdout.strip())["status"] == "salvageable"

    def test_unrecoverable_exit_2(self, tmp_path):
        junk = tmp_path / "junk.p1s"
        junk.write_bytes(b"not a snapshot at all")
        assert self._cli("verify", "--file", str(junk)).returncode == 2
        assert (
            self._cli(
                "verify", "--file", str(tmp_path / "absent.p1s")
            ).returncode
            == 2
        )
        # create on a store too short for any checkpoint: unrecoverable.
        from p1_tpu.chain import ChainStore
        from p1_tpu.node.testing import make_blocks

        store = tmp_path / "short.dat"
        s = ChainStore(store)
        for b in make_blocks(2, 8)[1:]:
            s.append(b)
        s.close()
        proc = self._cli(
            "create", "--store", str(store),
            "--file", str(tmp_path / "x.p1s"), "--interval", "4",
        )
        assert proc.returncode == 2
        assert "checkpoint" in proc.stderr


class TestLint:
    """`p1 lint` (round 13): the determinism/async-safety analyzer's
    exit-code contract — 0 = every rule settles clean against the
    allowlist, 1 = violations or stale grants, 2 = usage — plus the
    JSON report shape the round records consume."""

    def _lint(self, *argv):
        return subprocess.run(
            [sys.executable, "-m", "p1_tpu", "lint", *argv],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )

    def test_help_smoke(self):
        proc = self._lint("--help")
        assert proc.returncode == 0
        assert "--json" in proc.stdout and "--rule" in proc.stdout

    def test_clean_tree_exit_0(self):
        proc = self._lint()
        assert proc.returncode == 0, proc.stdout + proc.stderr[-2000:]
        assert "0 violation(s)" in proc.stdout
        assert "0 stale grant(s)" in proc.stdout

    def test_json_report_shape(self):
        proc = self._lint("--json")
        assert proc.returncode == 0
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["clean"] is True
        assert len(out["rules"]) >= 6
        assert out["violations"] == [] and out["stale"] == []
        # granted findings carry the Finding shape the docs promise
        f = out["granted"][0]
        assert set(f) == {"file", "line", "rule", "detail", "key"}

    def test_single_rule_run(self):
        proc = self._lint("--rule", "wall-clock")
        assert proc.returncode == 0, proc.stdout + proc.stderr[-2000:]
        assert "1 rules" in proc.stdout

    def test_unknown_rule_is_usage_error_exit_2(self):
        proc = self._lint("--rule", "no-such-rule")
        assert proc.returncode == 2
        assert "unknown rule" in proc.stderr

    def test_scoped_path_run_exit_0_and_labelled(self):
        """`p1 lint --path` (round 16): scoped pre-commit runs — same
        exit contract, summary names the scope, settlement still
        global (the engine-level guarantees live in
        tests/test_analysis.py::TestScopedRuns)."""
        proc = self._lint("--path", "node/protocol.py", "--path", "analysis")
        assert proc.returncode == 0, proc.stdout + proc.stderr[-2000:]
        assert "scoped to analysis/, node/protocol.py" in proc.stdout

    def test_scoped_json_report_carries_scope_and_callgraph(self):
        proc = self._lint("--path", "node", "--json")
        assert proc.returncode == 0
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["scoped_to"] == ["node/"]
        assert out["clean"] is True
        assert out["callgraph_nodes"] > 0 and out["callgraph_edges"] > 0

    def test_unknown_path_is_usage_error_exit_2(self):
        proc = self._lint("--path", "no/such/file.py")
        assert proc.returncode == 2
        assert "no such path" in proc.stderr

    def test_path_outside_package_is_usage_error_exit_2(self):
        proc = self._lint("--path", "/tmp")
        assert proc.returncode == 2
        assert "outside the analyzed package" in proc.stderr

    def test_bad_flag_is_usage_error_exit_2(self):
        proc = self._lint("--no-such-flag")
        assert proc.returncode == 2


class TestMaintain:
    """`p1 maintain` (round 20, GETMAINTAIN/MAINTAIN v13): the exit-code
    contract — 0 when the node answered ``{"ok": true}``, 1 when it
    refused or the wire failed, 2 on local usage errors — plus one
    subprocess e2e driving a live node through status/rebase/compact
    while it keeps mining."""

    def test_help_smoke(self):
        proc = subprocess.run(
            [sys.executable, "-m", "p1_tpu", "maintain", "--help"],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 0
        assert "rebase" in proc.stdout and "--keep" in proc.stdout

    def test_negative_keep_is_usage_error_exit_2(self):
        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "maintain", "rebase",
                "--difficulty", "12", "--keep", "-1",
            ],
            capture_output=True, text=True, timeout=110, cwd="/root/repo",
        )
        assert proc.returncode == 2
        assert "--keep must be >= 0" in proc.stderr

    def test_keep_with_status_is_usage_error_exit_2(self):
        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "maintain", "status",
                "--difficulty", "12", "--keep", "4",
            ],
            capture_output=True, text=True, timeout=110, cwd="/root/repo",
        )
        assert proc.returncode == 2
        assert "--keep does not apply" in proc.stderr

    def test_connection_failure_exit_1(self):
        # A port nothing listens on: the wire error must land as exit 1
        # with the detail on stderr, not a traceback.
        import socket

        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()
        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "maintain", "status",
                "--difficulty", "12", "--port", str(port),
            ],
            capture_output=True, text=True, timeout=110, cwd="/root/repo",
        )
        assert proc.returncode == 1
        assert "maintain command failed" in proc.stderr

    def test_maintain_e2e_live_rebase_while_mining(self, tmp_path):
        """One mining node, driven across the whole contract: status
        (0), a live rebase that lands (0), a too-deep rebase refused as
        an ANSWER (1, detail on stderr), and an online compact (0) —
        the node never restarts and keeps extending its chain
        throughout."""
        import asyncio
        import time

        from p1_tpu.node.client import get_status

        node_log = open(tmp_path / "node.log", "w")
        node = subprocess.Popen(
            [
                sys.executable, "-m", "p1_tpu", "node",
                "--difficulty", "12", "--backend", "cpu", "--chunk", "16384",
                "--port", "0", "--deadline", "stdin",
                "--miner-id", "alice",
                "--store", str(tmp_path / "chain.dat"),
                "--store-segment-mb", "0.0004",
                "--snapshot-interval", "4",
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=node_log,
            text=True,
            cwd="/root/repo",
        )
        try:
            port = None
            for line in node.stdout:
                line = line.strip()
                if line.startswith("{"):
                    port = str(json.loads(line)["ready"])
                    break
            assert port, "node never printed its ready line"

            def maintain(*argv):
                return subprocess.run(
                    [
                        sys.executable, "-m", "p1_tpu", "maintain", *argv,
                        "--difficulty", "12", "--port", port,
                    ],
                    capture_output=True, text=True, timeout=60,
                    cwd="/root/repo",
                )

            # Let the miner build past two checkpoint boundaries.
            deadline = time.monotonic() + 90
            height = 0
            while height < 9 and time.monotonic() < deadline:
                status = asyncio.run(get_status("127.0.0.1", int(port), 12))
                height = status["height"]
                time.sleep(0.2)
            assert height >= 9, f"miner stalled at height {height}"

            proc = maintain("status")
            assert proc.returncode == 0, proc.stderr[-2000:]
            report = json.loads(proc.stdout)
            assert report["ok"] is True and report["base_height"] == 0
            assert report["versionbits"]["window"] == 8

            proc = maintain("rebase", "--keep", "4")
            assert proc.returncode == 0, proc.stderr[-2000:]
            reply = json.loads(proc.stdout)
            assert reply["ok"] is True and reply["new_base"] >= 4
            assert reply["dropped_blocks"] == reply["new_base"]

            # Refusal contract: a rebase the chain cannot satisfy comes
            # back as an answer (exit 1 + stderr detail), the node keeps
            # serving.
            proc = maintain("rebase", "--keep", "100000")
            assert proc.returncode == 1
            assert "maintain refused" in proc.stderr
            assert json.loads(proc.stdout)["ok"] is False

            proc = maintain("compact")
            assert proc.returncode == 0, proc.stderr[-2000:]
            assert json.loads(proc.stdout)["ok"] is True

            proc = maintain("status")
            report = json.loads(proc.stdout)
            assert report["base_height"] >= 4
            assert report["rebases"] == 1 and report["online_compactions"] == 1

            # The node is still alive and still mining on its rebased
            # chain.
            status = asyncio.run(get_status("127.0.0.1", int(port), 12))
            assert status["height"] >= height
            assert status["maintenance"]["base_height"] >= 4
        finally:
            if node.poll() is None:
                node.stdin.write(f"{time.time()!r}\n")
                node.stdin.flush()
                node.stdin.close()
                try:
                    node.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    node.kill()
            node_log.close()


class TestFsckSegmented:
    """Round 18: `p1 fsck` over segmented stores — per-segment
    scan/salvage with the 0/1/2 exit contract intact — and the
    `--json` machine-readable per-segment report for both layouts."""

    @staticmethod
    def _mk_segmented(path, n=6, difficulty=12, segment_bytes=500):
        from p1_tpu.chain import SegmentedStore
        from p1_tpu.node.testing import make_blocks

        blocks = make_blocks(n, difficulty=difficulty)
        store = SegmentedStore(path, segment_bytes=segment_bytes)
        try:
            for h, block in enumerate(blocks[1:], start=1):
                store.append(block, height=h)
        finally:
            store.close()
        assert len(store.segments) > 1
        return blocks, store

    def test_json_single_file_clean(self, tmp_path):
        store = tmp_path / "clean.dat"
        TestFsck._mk_store(store)
        proc = TestFsck._fsck("--store", str(store), "--json")
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip())
        assert out["layout"] == "single" and out["status"] == "clean"
        (row,) = out["segments"]
        assert row["verdict"] == 0 and row["records_valid"] == 6

    def test_segmented_clean_exit_0(self, tmp_path):
        store = tmp_path / "seg.dat"
        self._mk_segmented(store)
        proc = TestFsck._fsck("--store", str(store))
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip())
        assert out["layout"] == "segmented" and out["status"] == "clean"
        assert all(row["verdict"] == 0 for row in out["segments"])

    def test_segmented_corruption_salvaged_per_segment(self, tmp_path):
        from p1_tpu.chain import ChainStore

        store = tmp_path / "seg.dat"
        blocks, st = self._mk_segmented(store)
        seg_dir = tmp_path / "seg.dat.d"
        victim = seg_dir / st.segments[1].name
        data = bytearray(victim.read_bytes())
        # Flip a record's length prefix inside ONE sealed segment.
        off, _n = ChainStore.scan(bytes(data)).spans[0]
        data[off - 4] ^= 0x10
        victim.write_bytes(bytes(data))
        untouched = {
            s.name: (seg_dir / s.name).read_bytes()
            for s in st.segments
            if s.name != victim.name
        }
        proc = TestFsck._fsck("--store", str(store), "--json")
        assert proc.returncode == 1, (proc.stdout, proc.stderr[-2000:])
        out = json.loads(proc.stdout.strip())
        assert out["status"] == "salvaged"
        by_name = {row["segment"]: row for row in out["segments"]}
        assert by_name[victim.name]["verdict"] == 1
        assert by_name[victim.name]["bad_spans"] == 1
        assert sum(r["verdict"] for r in out["segments"]) == 1
        # Containment: every OTHER segment's bytes untouched, evidence
        # quarantined next to the victim.
        for name, before in untouched.items():
            assert (seg_dir / name).read_bytes() == before, name
        assert (seg_dir / f"{victim.name}.quarantine").exists()
        # Second pass: clean, exit 0.
        assert TestFsck._fsck("--store", str(store)).returncode == 0

    def test_segmented_refuses_out_flag(self, tmp_path):
        store = tmp_path / "seg.dat"
        self._mk_segmented(store)
        proc = TestFsck._fsck(
            "--store", str(store), "--out", str(tmp_path / "x.dat")
        )
        assert proc.returncode == 2
        assert "in place" in proc.stderr

    def test_locked_segmented_store_exit_2(self, tmp_path):
        from p1_tpu.chain import SegmentedStore

        store = tmp_path / "seg.dat"
        self._mk_segmented(store)
        holder = SegmentedStore(store)
        holder.acquire()
        try:
            proc = TestFsck._fsck("--store", str(store))
            assert proc.returncode == 2
            assert "locked" in proc.stderr
        finally:
            holder.close()
