"""The discrete-event network simulator: virtual time, link models,
the transport seam, and the byte-identical-trace determinism contract.

Companion of tests/test_scenarios.py (which runs the corpus): this file
pins the SUBSTRATE — that virtual time costs no wall time, that the
latency/jitter/bandwidth/FIFO/partition link semantics hold, that the
socket transport behind the same seam still moves real bytes, and the
acceptance-criterion determinism proof: two runs of the same scenario
with the same seed produce byte-identical event traces, and the
migrated sync-stall failover case (the round-6 flagship socket test)
reproduces its invariants exactly under the simulator.
"""

import asyncio
import time

import pytest

from p1_tpu.node.netsim import (
    LinkProfile,
    SimLoop,
    SimNet,
    SimTransport,
    VirtualClock,
)
from p1_tpu.node.transport import SocketTransport


def sim_run(coro, clock=None):
    """Run one coroutine on a fresh SimLoop (bare-substrate tests)."""
    loop = SimLoop(clock if clock is not None else VirtualClock())
    asyncio.set_event_loop(loop)
    try:
        return loop.run_until_complete(coro)
    finally:
        asyncio.set_event_loop(None)
        loop.close()


class TestVirtualTime:
    def test_long_sleeps_cost_no_wall_time(self):
        clock = VirtualClock()

        async def main():
            await asyncio.sleep(3600.0)
            return clock.now

        t0 = time.monotonic()
        assert sim_run(main(), clock) == pytest.approx(3600.0)
        assert time.monotonic() - t0 < 2.0  # an hour for (almost) free

    def test_wait_for_times_out_at_the_virtual_deadline(self):
        clock = VirtualClock()

        async def main():
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(asyncio.Event().wait(), timeout=120.0)
            return clock.now

        assert sim_run(main(), clock) == pytest.approx(120.0)

    def test_timers_fire_in_virtual_order(self):
        clock = VirtualClock()
        fired = []

        async def stamp(delay, tag):
            await asyncio.sleep(delay)
            fired.append((tag, clock.now))

        async def main():
            # Scheduled out of order on purpose.
            await asyncio.gather(
                stamp(5.0, "c"), stamp(0.5, "a"), stamp(2.0, "b")
            )

        sim_run(main(), clock)
        assert [t for t, _ in fired] == ["a", "b", "c"]
        assert [round(at, 3) for _, at in fired] == [0.5, 2.0, 5.0]

    def test_virtual_wall_clock_tracks_monotonic(self):
        clock = VirtualClock()
        w0 = clock.wall()

        async def main():
            await asyncio.sleep(7.0)

        sim_run(main(), clock)
        assert clock.wall() - w0 == pytest.approx(7.0)


class _Echo:
    """Tiny accept handler: records payloads, echoes nothing."""

    def __init__(self):
        self.got = []
        self.eof = asyncio.Event()

    async def __call__(self, reader, writer):
        while True:
            data = await reader.read(4096)
            if not data:
                self.eof.set()
                return
            self.got.append((asyncio.get_running_loop().time(), data))


class TestSimLinks:
    def _net(self, **kw):
        clock = VirtualClock()
        return clock, SimTransport(clock, seed=1, **kw)

    def test_latency_delays_delivery(self):
        clock, net = self._net(
            default_profile=LinkProfile(latency_s=0.250)
        )

        async def main():
            sink = _Echo()
            lst = await net.host("b").listen(sink, "b", 0)
            _r, w = await net.host("a").connect("b", lst.port)
            t_send = clock.now
            w.write(b"ping")
            await w.drain()
            await asyncio.sleep(1.0)
            assert [d for _, d in sink.got] == [b"ping"]
            arrival = sink.got[0][0]
            assert arrival - t_send == pytest.approx(0.250)

        sim_run(main(), clock)

    def test_fifo_holds_under_jitter(self):
        clock, net = self._net(
            default_profile=LinkProfile(latency_s=0.01, jitter_s=0.5)
        )

        async def main():
            sink = _Echo()
            lst = await net.host("b").listen(sink, "b", 0)
            _r, w = await net.host("a").connect("b", lst.port)
            for i in range(20):
                w.write(bytes([i]))
            await asyncio.sleep(30.0)
            received = b"".join(d for _, d in sink.got)
            assert received == bytes(range(20))  # jitter never reorders
            stamps = [t for t, _ in sink.got]
            assert stamps == sorted(stamps)

        sim_run(main(), clock)

    def test_bandwidth_shapes_throughput(self):
        # 1 Mb/s: a 1 MB payload needs ~8 virtual seconds on the wire.
        clock, net = self._net(
            default_profile=LinkProfile(latency_s=0.0, bandwidth_bps=1e6)
        )

        async def main():
            sink = _Echo()
            lst = await net.host("b").listen(sink, "b", 0)
            _r, w = await net.host("a").connect("b", lst.port)
            t0 = clock.now
            w.write(bytes(1_000_000))
            await asyncio.sleep(60.0)
            assert sum(len(d) for _, d in sink.got) == 1_000_000
            assert sink.got[-1][0] - t0 == pytest.approx(8.0, rel=0.01)

        sim_run(main(), clock)

    def test_loss_adds_retransmit_delay_but_delivers(self):
        clock, lossy = self._net(
            default_profile=LinkProfile(latency_s=0.05, loss=0.5)
        )

        async def main():
            sink = _Echo()
            lst = await lossy.host("b").listen(sink, "b", 0)
            _r, w = await lossy.host("a").connect("b", lst.port)
            t0 = clock.now
            for _ in range(50):
                w.write(b"x")
            await asyncio.sleep(120.0)
            # Reliable stream: every chunk arrives...
            assert sum(len(d) for _, d in sink.got) == 50
            # ...but the loss model cost real (virtual) tail latency
            # beyond the bare 0.05 s latency floor.
            assert sink.got[-1][0] - t0 > 0.1

        sim_run(main(), clock)

    def test_partition_severs_and_refuses_then_heals(self):
        clock, net = self._net(
            default_profile=LinkProfile(latency_s=0.001)
        )

        async def main():
            sink = _Echo()
            lst = await net.host("b").listen(sink, "b", 0)
            reader, w = await net.host("a").connect("b", lst.port)
            w.write(b"pre")
            await asyncio.sleep(0.1)
            net.partition({"a"}, {"b"})
            # The live connection died: our read side sees EOF...
            assert await asyncio.wait_for(reader.read(100), 1.0) == b""
            await asyncio.sleep(0.01)
            assert sink.eof.is_set()
            # ...and new dials are refused while the cut holds.
            with pytest.raises(ConnectionRefusedError):
                await net.host("a").connect("b", lst.port)
            net.heal()
            _r2, w2 = await net.host("a").connect("b", lst.port)
            w2.write(b"post")
            await asyncio.sleep(0.1)
            assert [d for _, d in sink.got] == [b"pre", b"post"]

        sim_run(main(), clock)

    def test_asymmetric_profiles_apply_per_direction(self):
        clock, net = self._net()
        net.set_profile(
            "a", "b", LinkProfile(latency_s=0.300), symmetric=False
        )
        net.set_profile(
            "b", "a", LinkProfile(latency_s=0.010), symmetric=False
        )

        async def main():
            class EchoBack:
                async def __call__(self, reader, writer):
                    data = await reader.read(4096)
                    writer.write(data)

            lst = await net.host("b").listen(EchoBack(), "b", 0)
            reader, w = await net.host("a").connect("b", lst.port)
            t0 = clock.now
            w.write(b"rt")
            echoed = await reader.read(4096)
            assert echoed == b"rt"
            # One slow leg + one fast leg, not two of either.
            assert clock.now - t0 == pytest.approx(0.310, abs=0.02)

        sim_run(main(), clock)

    def test_write_buffer_gauge_tracks_bytes_in_flight(self):
        clock, net = self._net(
            default_profile=LinkProfile(latency_s=1.0)
        )

        async def main():
            sink = _Echo()
            lst = await net.host("b").listen(sink, "b", 0)
            _r, w = await net.host("a").connect("b", lst.port)
            w.write(bytes(5000))
            assert w.transport.get_write_buffer_size() == 5000
            await asyncio.sleep(2.0)
            assert w.transport.get_write_buffer_size() == 0

        sim_run(main(), clock)


class TestSocketSeam:
    """The default transport still moves real bytes — the seam itself
    must never change socket-path behavior (the whole pre-existing
    node/byzantine/syncfault suites are the deep proof; this is the
    direct one)."""

    def test_listen_connect_roundtrip(self):
        async def main():
            got = asyncio.Queue()

            async def on_conn(reader, writer):
                got.put_nowait(await reader.readexactly(5))
                writer.write(b"world")
                await writer.drain()
                writer.close()

            transport = SocketTransport()
            lst = await transport.listen(on_conn, "127.0.0.1", 0)
            assert lst.port > 0
            reader, writer = await transport.connect("127.0.0.1", lst.port)
            writer.write(b"hello")
            await writer.drain()
            assert await got.get() == b"hello"
            assert await reader.readexactly(5) == b"world"
            writer.close()
            lst.close()
            await lst.wait_closed()

        asyncio.run(asyncio.wait_for(main(), 10))

    def test_clock_is_the_system_clock(self):
        t = SocketTransport()
        assert abs(t.clock.wall() - time.time()) < 1.0
        assert abs(t.clock.monotonic() - time.monotonic()) < 1.0


class TestDeterminism:
    """Acceptance criterion: same seed => byte-identical event trace."""

    @staticmethod
    def _partition_run(seed):
        from p1_tpu.node.scenarios import partition_heal

        report = partition_heal(
            nodes=16, seed=seed, blocks_major=3, blocks_minor=1
        )
        # wall_s is the one legitimately nondeterministic field.
        report.pop("wall_s")
        return report

    def test_same_seed_same_trace_and_report(self):
        a = self._partition_run(11)
        b = self._partition_run(11)
        assert a["ok"] and b["ok"]
        assert a["trace_digest"] == b["trace_digest"]
        assert a == b

    def test_different_seed_different_trace(self):
        a = self._partition_run(11)
        c = self._partition_run(12)
        assert a["trace_digest"] != c["trace_digest"]


class TestStallFailoverSim:
    """The migrated round-6 flagship (tests/test_syncfault.py's
    ``test_stalling_peer_fails_over_mid_ibd``, socket variant now a
    slow smoke): the only-serving peer swallows GETBLOCKS mid-IBD while
    answering PINGs; the victim must detect the stall, demote without
    banning, fail over, and finish IBD from the second peer — here in
    VIRTUAL time (production-scale 10 s deadlines, milliseconds of
    wall), twice, with identical traces."""

    @staticmethod
    def _run(seed):
        import random

        from p1_tpu.node.protocol import MsgType
        from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks

        net = SimNet(seed=seed, difficulty=8)
        chain30 = make_blocks(30, 8)

        async def main():
            staller = HostilePeer(
                chain30,
                plan=FaultPlan(
                    swallow=frozenset({MsgType.GETBLOCKS}),
                    serve_before_fault=1,
                    batch_limit=10,
                ),
                transport=net.net.host("10.8.0.1"),
                host="10.8.0.1",
                rng=random.Random(seed * 3 + 1),
            )
            quiet = HostilePeer(
                chain30,
                plan=FaultPlan(hello_height=0),
                transport=net.net.host("10.8.0.2"),
                host="10.8.0.2",
                rng=random.Random(seed * 3 + 2),
            )
            await staller.start()
            await quiet.start()
            victim = await net.add_node(
                peers=[
                    f"10.8.0.1:{staller.port}",
                    f"10.8.0.2:{quiet.port}",
                ],
                # Production-scale supervision deadlines: virtual time
                # makes them free (the socket variant had to shrink
                # them to keep CI fast — and was flake-prone for it).
                sync_stall_timeout_s=10.0,
            )
            t0 = net.clock.now
            assert await net.run_until(
                lambda: victim.chain.height == 30, 300, wall_limit_s=60
            ), f"IBD pinned at height {victim.chain.height}"
            elapsed_vs = net.clock.now - t0
            m = victim.metrics
            result = {
                "stalls": m.sync_stalls,
                "failovers": m.sync_failovers,
                "demotions": m.sync_demotions,
                "rescued_by_quiet": quiet.requests[MsgType.GETBLOCKS],
                "banned": dict(victim._banned_until),
                "violations": dict(victim._violations),
                "peers": victim.peer_count(),
                "demerited": sum(
                    1
                    for p in victim._peers.values()
                    if p.sync_demerits > 0
                ),
                "elapsed_vs": round(elapsed_vs, 6),
            }
            await net.stop_all()
            await staller.stop()
            await quiet.stop()
            result["digest"] = net.trace_digest()
            return result

        return net.run(main())

    def test_failover_invariants_hold_in_virtual_time(self):
        r = self._run(5)
        assert r["stalls"] >= 1
        assert r["failovers"] >= 1
        assert r["demotions"] >= 1
        assert r["rescued_by_quiet"] >= 1
        # Demoted, never banned.
        assert not r["banned"] and not r["violations"]
        assert r["peers"] == 2
        assert r["demerited"] == 1
        # A stall + jittered backoff + failover at the 10 s production
        # deadline: virtual elapsed must reflect the deadline (no
        # instant magic) yet stay bounded.
        assert 10.0 < r["elapsed_vs"] < 120.0

    def test_failover_run_is_deterministic(self):
        assert self._run(5) == self._run(5)


class TestSimNodeBasics:
    def test_two_sim_nodes_gossip_a_mined_block(self):
        net = SimNet(seed=2, difficulty=8)

        async def main():
            a = await net.add_node()
            b = await net.add_node(peers=[net.host_name(0)])
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            await net.mine_on(a)
            assert await net.run_until(
                lambda: b.chain.height == 1, 30, wall_limit_s=30
            )
            assert net.converged() and net.ledger_conserved()
            # The propagation telemetry rode the virtual wall clock.
            assert b.metrics.propagation_delays_s
            await net.stop_all()

        net.run(main())

    def test_restart_keeps_identity_and_resyncs(self):
        net = SimNet(seed=2, difficulty=8)

        async def main():
            a = await net.add_node()
            b = await net.add_node(peers=[net.host_name(0)])
            assert await net.run_until(net.links_up, 30, wall_limit_s=30)
            nonce_before = b.instance_nonce
            host_b = net.host_name(1)
            await net.stop_node(host_b)
            await net.mine_on(a, spacing_s=1.0)
            b2 = await net.restart_node(host_b)
            assert b2.instance_nonce == nonce_before  # same identity
            assert await net.run_until(
                lambda: b2.chain.height == 1, 60, wall_limit_s=30
            )
            await net.stop_all()

        net.run(main())
