"""Compact block relay: wire codec, mempool reconstruction, the
GETBLOCKTXN round trip, and hostile-input behavior.

The invariant under test everywhere: compact relay is an ENCODING of
gossip, never a consensus change — a reconstructed block goes through
exactly the same ``_handle_block`` path as a full one, and a node that
cannot reconstruct converges anyway (fetch round, or locator sync as the
last resort)."""

import asyncio

import pytest

from txutil import account, stx

from test_node import _config, fund, stop_all, wait_until

from p1_tpu.core import Block, BlockHeader, Transaction, make_genesis
from p1_tpu.node import Node, protocol
from p1_tpu.node.protocol import CompactBlock, MsgType

DIFF = 12


def _block_with_txs(n: int = 3) -> Block:
    txs = (
        Transaction.coinbase("miner", 7),
        *(stx("alice", "bob", 1, f + 1, f) for f in range(n - 1)),
    )
    header = BlockHeader(1, b"\x11" * 32, b"\x22" * 32, 1735689700, DIFF, 9)
    return Block(header, txs)


class TestWire:
    def test_cblock_round_trip(self):
        block = _block_with_txs(4)
        mtype, cb = protocol.decode(protocol.encode_cblock(block, sent_ts=2.5))
        assert mtype is MsgType.CBLOCK
        assert cb.sent_ts == 2.5
        assert cb.header == block.header
        assert cb.ntx == 4
        assert cb.prefilled == ((0, block.txs[0]),)  # coinbase carried whole
        assert cb.txids == tuple(tx.txid() for tx in block.txs[1:])

    def test_cblock_is_much_smaller(self):
        block = _block_with_txs(20)
        full = protocol.encode_block(block)
        compact = protocol.encode_cblock(block)
        # ~32 B/txid vs a few hundred per signed transfer.
        assert len(compact) < len(full) / 4

    def test_cblock_without_coinbase(self):
        block = Block(
            _block_with_txs(2).header, (stx("alice", "bob", 1, 1, 0),)
        )
        mtype, cb = protocol.decode(protocol.encode_cblock(block))
        assert cb.prefilled == () and len(cb.txids) == 1

    def test_getblocktxn_round_trip(self):
        payload = protocol.encode_getblocktxn(b"\xaa" * 32, [1, 3, 7])
        mtype, (bhash, indices) = protocol.decode(payload)
        assert mtype is MsgType.GETBLOCKTXN
        assert bhash == b"\xaa" * 32 and indices == [1, 3, 7]

    def test_blocktxn_round_trip(self):
        txs = [stx("alice", "bob", 1, f + 1, f) for f in range(3)]
        payload = protocol.encode_blocktxn(
            b"\xbb" * 32, [t.serialize() for t in txs]
        )
        mtype, (bhash, got) = protocol.decode(payload)
        assert mtype is MsgType.BLOCKTXN
        assert bhash == b"\xbb" * 32 and got == txs

    @pytest.mark.parametrize(
        "payload",
        [
            bytes([MsgType.CBLOCK]) + b"\x00" * 10,  # truncated
            # prefill count exceeds ntx
            bytes([MsgType.CBLOCK]) + b"\x00" * 8 + b"\x00" * 80 + b"\x00\x01\x00\x02",
            bytes([MsgType.GETBLOCKTXN]) + b"\x00" * 32,  # no count
            bytes([MsgType.GETBLOCKTXN]) + b"\x00" * 32 + b"\x00\x00",  # 0 idx
            # non-ascending indices
            bytes([MsgType.GETBLOCKTXN])
            + b"\x00" * 32
            + b"\x00\x02\x00\x05\x00\x03",
            bytes([MsgType.BLOCKTXN]) + b"\x00" * 5,  # truncated
            bytes([MsgType.BLOCKTXN]) + b"\x00" * 32 + b"\x00\x01",  # count lies
        ],
    )
    def test_malformed_rejected(self, payload):
        with pytest.raises(ValueError):
            protocol.decode(payload)

    def test_cblock_txid_section_must_be_exact(self):
        block = _block_with_txs(3)
        good = protocol.encode_cblock(block)
        with pytest.raises(ValueError):
            protocol.decode(good + b"\x00")
        with pytest.raises(ValueError):
            protocol.decode(good[:-1])


class TestRelay:
    def test_mempool_hit_reconstruction(self):
        """Txs gossiped normally live in every pool; a mined block then
        relays compactly and reconstructs with zero fetch round trips."""

        async def scenario():
            a, b = await self._funded_pair()
            try:
                for i in range(3):
                    await b.submit_tx(
                        stx(
                            "alice",
                            account("bob"),
                            1,
                            1,
                            i,
                            difficulty=DIFF,
                        )
                    )
                assert await wait_until(lambda: len(a.mempool) == 3)
                target = b.chain.height + 1
                b.start_mining()
                assert await wait_until(
                    lambda: a.chain.height >= target
                    and a.chain.tip_hash == b.chain.tip_hash
                )
                await b.stop_mining()
                assert a.metrics.cblocks_received >= 1
                assert a.metrics.cblock_tx_hits >= 3
                assert a.metrics.cblock_tx_fetched == 0
                assert b.metrics.cblocks_sent >= 1
                assert b.metrics.cblock_bytes_saved > 0
                # Wire accounting runs at the send/read choke points.
                assert b.metrics.bytes_sent > 0
                assert a.metrics.bytes_received > 0
                # The confirmed spends actually connected (consensus ran).
                assert a.chain.balance(account("bob")) >= 3
            finally:
                await stop_all((a, b))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_missing_tx_fetch_round_trip(self):
        """A tx slipped straight into the miner's pool (never gossiped)
        forces the receiver through GETBLOCKTXN — and it still converges."""

        async def scenario():
            a, b = await self._funded_pair()
            try:
                sneak = stx(
                    "alice", account("carol"), 2, 1, 0, difficulty=DIFF
                )
                assert b.mempool.add(sneak)  # no gossip: a never sees it
                assert sneak.txid() not in a.mempool
                target = b.chain.height + 1
                b.start_mining()
                assert await wait_until(
                    lambda: a.chain.height >= target
                    and a.chain.tip_hash == b.chain.tip_hash
                )
                await b.stop_mining()
                assert a.metrics.cblock_tx_fetched >= 1
                assert a.chain.balance(account("carol")) >= 2
            finally:
                await stop_all((a, b))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_full_block_nodes_interoperate(self):
        """--no-compact-gossip is a local preference: a full-frame node
        and a compact node still converge both directions."""

        async def scenario():
            a, b = await self._funded_pair(a_kw={"compact_gossip": False})
            try:
                await b.submit_tx(
                    stx("alice", account("bob"), 1, 1, 0, difficulty=DIFF)
                )
                target = b.chain.height + 1
                b.start_mining()
                assert await wait_until(lambda: a.chain.height >= target)
                await b.stop_mining()
                target = a.chain.height + 1
                a.start_mining()
                assert await wait_until(lambda: b.chain.height >= target)
                await a.stop_mining()
                assert await wait_until(
                    lambda: a.chain.tip_hash == b.chain.tip_hash
                )
                assert a.metrics.cblocks_sent == 0  # full frames only
            finally:
                await stop_all((a, b))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    async def _funded_pair(self, a_kw=None):
        """Two connected nodes; alice's account funded on the shared chain."""
        a = Node(_config(**(a_kw or {})))
        await a.start()
        b = Node(_config(peers=(f"127.0.0.1:{a.port}",)))
        await b.start()
        await fund(b, "alice", blocks=2)
        assert await wait_until(
            lambda: a.chain.tip_hash == b.chain.tip_hash
        )
        return a, b


class TestHostileInput:
    def test_workless_cblock_rejected_before_state(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                # A header that never met the target: the handler must
                # refuse before parking anything or asking for txs.
                txs = (
                    Transaction.coinbase("m", 1),
                    stx("alice", "bob", 1, 1, 0, difficulty=DIFF),
                )
                from p1_tpu.core import merkle_root
                from p1_tpu.core.header import meets_target

                header = BlockHeader(
                    1,
                    node.chain.tip_hash,
                    merkle_root([t.txid() for t in txs]),
                    make_genesis(DIFF).header.timestamp + 1,
                    DIFF,
                    0,
                )
                nonce = 0
                while meets_target(header.with_nonce(nonce).block_hash(), DIFF):
                    nonce += 1
                bad = Block(header.with_nonce(nonce), txs)
                _, cb = protocol.decode(protocol.encode_cblock(bad))

                class _FakePeer:
                    label = "test"

                    async def send(self, payload):
                        raise AssertionError(
                            "workless CBLOCK must not trigger any send"
                        )

                before = node.metrics.blocks_rejected
                await node._handle_cblock(cb, _FakePeer())
                assert node.metrics.blocks_rejected == before + 1
                assert not node._pending_cblocks
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_blocktxn_txid_mismatch_dropped(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                from p1_tpu.node.node import _PendingCompact

                header = make_genesis(DIFF).header
                bhash = header.block_hash()
                want_txid = b"\x77" * 32

                class _FakePeer:
                    label = "test"

                asked = _FakePeer()
                node._pending_cblocks[(bhash, asked)] = _PendingCompact(
                    header, [None], {0: want_txid}, 1.0
                )
                wrong = stx("alice", "bob", 9, 9, 3, difficulty=DIFF)
                # An unsolicited reply from a peer we never asked must not
                # touch the in-flight reconstruction (a rival could
                # otherwise destroy it for free)...
                await node._handle_blocktxn((bhash, [wrong]), _FakePeer())
                assert (bhash, asked) in node._pending_cblocks
                # ...while a bad reply from the ASKED peer consumes the
                # entry without accepting anything.
                await node._handle_blocktxn((bhash, [wrong]), asked)
                assert (bhash, asked) not in node._pending_cblocks
                assert node.metrics.blocks_accepted == 0
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_cheap_difficulty_cblock_rejected_on_retarget_chain(self):
        # The flood gate must price a compact push at the EXACT contextual
        # difficulty — a claimed difficulty-1 header (2 hashes of "work")
        # must not create pending state or trigger a fetch round.
        async def scenario():
            node = Node(
                _config(
                    difficulty=10, retarget_window=5, target_spacing=50
                )
            )
            await node.start()
            try:
                from p1_tpu.core import merkle_root
                from p1_tpu.core.header import meets_target

                txs = (
                    Transaction.coinbase("m", 1),
                    stx("alice", "bob", 1, 1, 0, difficulty=DIFF),
                )
                header = BlockHeader(
                    1,
                    node.chain.tip_hash,
                    merkle_root([t.txid() for t in txs]),
                    node.chain.tip.header.timestamp + 1,
                    1,  # claimed difficulty 1: ~2 hashes to satisfy
                    0,
                )
                nonce = 0
                while not meets_target(
                    header.with_nonce(nonce).block_hash(), 1
                ):
                    nonce += 1
                cheap = Block(header.with_nonce(nonce), txs)
                _, cb = protocol.decode(protocol.encode_cblock(cheap))

                class _FakePeer:
                    label = "test"

                    async def send(self, payload):
                        raise AssertionError("cheap CBLOCK triggered a send")

                before = node.metrics.blocks_rejected
                await node._handle_cblock(cb, _FakePeer())
                assert node.metrics.blocks_rejected == before + 1
                assert not node._pending_cblocks
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_unknown_parent_cblock_falls_to_sync(self):
        async def scenario():
            node = Node(_config())
            await node.start()
            try:
                block = _block_with_txs(3)  # prev_hash nobody knows
                _, cb = protocol.decode(protocol.encode_cblock(block))
                sent = []

                class _FakePeer:
                    label = "test"
                    writer = None

                    async def send(self, payload):
                        sent.append(payload)

                await node._handle_cblock(cb, _FakePeer())
                assert not node._pending_cblocks  # nothing parked
                assert len(sent) == 1
                mtype, _ = protocol.decode(sent[0])
                assert mtype is MsgType.GETBLOCKS  # locator sync fallback
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_front_runner_cannot_squat_an_honest_block(self):
        """A tampered-txid CBLOCK from peer B must not stop the honest
        CBLOCK for the SAME block from peer A reconstructing."""

        async def scenario():
            from p1_tpu.hashx import get_backend
            from p1_tpu.miner import Miner
            from p1_tpu.core import merkle_root

            node = Node(_config())
            await node.start()
            try:
                await fund(node, "alice", blocks=1)
                spend = stx(
                    "alice", account("bob"), 1, 1, 0, difficulty=DIFF
                )
                assert node.mempool.add(spend)
                # A real block extending the tip, carrying the spend.
                txs = (
                    Transaction.coinbase("m", node.chain.height + 1),
                    spend,
                )
                draft = BlockHeader(
                    1,
                    node.chain.tip_hash,
                    merkle_root([t.txid() for t in txs]),
                    node.chain.tip.header.timestamp + 1,
                    DIFF,
                    0,
                )
                sealed = Miner(backend=get_backend("cpu")).search_nonce(draft)
                block = Block(sealed, txs)
                bhash = block.block_hash()

                sends = []

                class _FakePeer:
                    writer = None

                    def __init__(self, label):
                        self.label = label

                    async def send(self, payload):
                        sends.append((self.label, payload))

                evil, honest = _FakePeer("evil"), _FakePeer("honest")
                # Front-runner: the real header, garbage txids.
                _, cb = protocol.decode(protocol.encode_cblock(block))
                forged = protocol.CompactBlock(
                    cb.sent_ts, cb.header, cb.ntx, cb.prefilled,
                    (b"\x66" * 32,),
                )
                await node._handle_cblock(forged, evil)
                assert (bhash, evil) in node._pending_cblocks  # stuck ask
                # The honest push reconstructs from the pool and connects.
                await node._handle_cblock(cb, honest)
                assert bhash in node.chain
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))
