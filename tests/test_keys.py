"""Ed25519 account keys: fingerprints, signing, persistence."""

import json

import pytest

from p1_tpu.core import keys
from p1_tpu.core.keys import Keypair


class TestKeypair:
    def test_deterministic_from_seed_text(self):
        a1 = Keypair.from_seed_text("alice")
        a2 = Keypair.from_seed_text("alice")
        b = Keypair.from_seed_text("bob")
        assert a1.account == a2.account and a1.pubkey == a2.pubkey
        assert a1.account != b.account
        assert a1.account.startswith(keys.ACCOUNT_PREFIX)

    def test_sign_verify_round_trip(self):
        kp = Keypair.generate()
        msg = b"spend 5 to bob"
        sig = kp.sign(msg)
        assert keys.verify(kp.pubkey, sig, msg)
        assert not keys.verify(kp.pubkey, sig, msg + b"!")
        assert not keys.verify(Keypair.generate().pubkey, sig, msg)
        assert not keys.verify(b"short", sig, msg)
        assert not keys.verify(kp.pubkey, b"short", msg)

    def test_account_id_or_none(self):
        kp = Keypair.generate()
        assert keys.account_id_or_none(kp.pubkey) == kp.account
        assert keys.account_id_or_none(b"") is None
        assert keys.account_id_or_none(b"x" * 31) is None

    def test_save_load_round_trip(self, tmp_path):
        import os

        kp = Keypair.generate()
        path = tmp_path / "id.key"
        kp.save(str(path))
        assert (os.stat(path).st_mode & 0o777) == 0o600
        loaded = Keypair.load(str(path))
        assert loaded.account == kp.account
        assert loaded.sign(b"m") == kp.sign(b"m")

    def test_save_refuses_overwrite(self, tmp_path):
        # A truncated seed is an unrecoverable loss of funds: clobbering
        # must be an explicit choice.
        path = tmp_path / "id.key"
        old = Keypair.generate()
        old.save(str(path))
        with pytest.raises(FileExistsError):
            Keypair.generate().save(str(path))
        assert Keypair.load(str(path)).account == old.account
        new = Keypair.generate()
        new.save(str(path), overwrite=True)
        assert Keypair.load(str(path)).account == new.account

    def test_load_rejects_tampered_account(self, tmp_path):
        kp = Keypair.generate()
        path = tmp_path / "id.key"
        kp.save(str(path))
        data = json.loads(path.read_text())
        data["account"] = "p1" + "0" * 16
        path.write_text(json.dumps(data))
        with pytest.raises(ValueError, match="derives"):
            Keypair.load(str(path))
