"""Hash layer: FIPS 180-4 vectors, hashlib cross-checks, registry, search parity."""

import hashlib
import os
import random
import struct

import numpy as np
import pytest

from p1_tpu.core import BlockHeader, meets_target
from p1_tpu.hashx import available_backends, get_backend
from p1_tpu.hashx import sha256_ref
from p1_tpu.hashx.numpy_backend import lanes_below_target, sha256d_lanes

# FIPS 180-4 / NIST CAVP known-answer vectors for SHA-256.
FIPS_VECTORS = [
    (b"", "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"),
    (b"abc", "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"),
    (
        b"abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq",
        "248d6a61d20638b8e5c026930c3e6039a33ce45964ff2167f6ecedd419db06c1",
    ),
    (b"a" * 1_000_000, "cdc76e5c9914fb9281a1c7e284d73e67f1809a48a497200e046d39ccc7112cd0"),
]


class TestSha256Ref:
    @pytest.mark.parametrize("msg,hexdigest", FIPS_VECTORS)
    def test_fips_vectors(self, msg, hexdigest):
        assert sha256_ref.sha256(msg).hex() == hexdigest

    def test_random_lengths_match_hashlib(self):
        rng = random.Random(0)
        for n in [1, 55, 56, 63, 64, 65, 119, 120, 127, 128, 200, 1000]:
            data = rng.randbytes(n)
            assert sha256_ref.sha256(data) == hashlib.sha256(data).digest()
            assert (
                sha256_ref.sha256d(data)
                == hashlib.sha256(hashlib.sha256(data).digest()).digest()
            )

    def test_midstate_reconstructs_header_hash(self):
        rng = random.Random(1)
        header = BlockHeader(2, rng.randbytes(32), rng.randbytes(32), 123456, 20, 0)
        prefix = header.mining_prefix()
        midstate = sha256_ref.header_midstate(prefix)
        tail = sha256_ref.header_tail_words(prefix)
        # Manually finish: chunk2 = tail words + nonce + padding for 80 bytes.
        nonce = 0xCAFEBABE
        chunk2 = struct.pack(">4I", *tail, nonce) + sha256_ref.padding(80)[0:48]
        assert len(chunk2) == 64
        state1 = sha256_ref.compress(midstate, chunk2)
        digest1 = struct.pack(">8I", *state1)
        assert digest1 == hashlib.sha256(header.with_nonce(nonce).serialize()).digest()


class TestRegistry:
    def test_known_backends_present(self):
        names = set(available_backends())
        assert {"cpu", "numpy"} <= names

    def test_get_backend_memoizes(self):
        assert get_backend("cpu") is get_backend("cpu")

    def test_unknown_backend_raises(self):
        with pytest.raises(KeyError):
            get_backend("definitely-not-a-backend")

    def test_lazy_resolve_in_fresh_process(self):
        # get_backend must work when the lazy loader (not a direct import)
        # is what registers the backend — regression: @register popping the
        # lazy entry made _resolve's own cleanup KeyError.
        import os
        import subprocess
        import sys

        # The axon sitecustomize overrides JAX_PLATFORMS at interpreter
        # start (see conftest.py) — pin CPU with a config update instead.
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; jax.config.update('jax_platforms', 'cpu');"
                "from p1_tpu.hashx import get_backend;"
                "get_backend('jax'); print('resolved')",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "resolved" in out.stdout

    def test_direct_import_does_not_double_list(self):
        # Importing a lazily-registered backend module directly fulfills the
        # lazy entry; the name must appear exactly once afterwards.
        import p1_tpu.hashx.jax_backend  # noqa: F401

        names = list(available_backends())
        assert names.count("jax") == 1
        assert len(names) == len(set(names))


def _random_prefix(seed: int) -> bytes:
    rng = random.Random(seed)
    header = BlockHeader(1, rng.randbytes(32), rng.randbytes(32), 1735689700, 8, 0)
    return header.mining_prefix()


class TestNumpyLanes:
    def test_lanes_match_reference_digests(self):
        prefix = _random_prefix(2)
        midstate = np.array(sha256_ref.header_midstate(prefix), dtype=np.uint32)
        tail = np.array(sha256_ref.header_tail_words(prefix), dtype=np.uint32)
        nonces = np.array([0, 1, 12345, 0xFFFFFFFF, 0x80000000], dtype=np.uint32)
        words = sha256d_lanes(midstate, tail, nonces)
        for lane, nonce in enumerate(nonces):
            header80 = prefix + struct.pack(">I", int(nonce))
            expect = sha256_ref.sha256d(header80)
            got = struct.pack(">8I", *(int(w[lane]) for w in words))
            assert got == expect, f"lane {lane} nonce {nonce:#x}"

    def test_target_mask_matches_host_check(self):
        prefix = _random_prefix(3)
        midstate = np.array(sha256_ref.header_midstate(prefix), dtype=np.uint32)
        tail = np.array(sha256_ref.header_tail_words(prefix), dtype=np.uint32)
        nonces = np.arange(4096, dtype=np.uint32)
        words = sha256d_lanes(midstate, tail, nonces)
        for difficulty in (4, 8, 12):
            mask = lanes_below_target(words, difficulty)
            for lane in np.flatnonzero(mask)[:4]:
                header80 = prefix + struct.pack(">I", int(nonces[lane]))
                assert meets_target(sha256_ref.sha256d(header80), difficulty)
            # spot-check some negatives too
            for lane in np.flatnonzero(~mask)[:4]:
                header80 = prefix + struct.pack(">I", int(nonces[lane]))
                assert not meets_target(sha256_ref.sha256d(header80), difficulty)


SEARCH_BACKENDS = ["cpu", "numpy"]
if os.environ.get("P1_TEST_NATIVE"):
    SEARCH_BACKENDS.append("native")


class TestSearchParity:
    """All backends agree on earliest-hit semantics."""

    @pytest.mark.parametrize("name", SEARCH_BACKENDS)
    def test_finds_known_hit(self, name):
        backend = get_backend(name)
        prefix = _random_prefix(4)
        # Find ground truth with the cpu reference first at tiny difficulty.
        truth = get_backend("cpu").search(prefix, 0, 4096, 8)
        assert truth.nonce is not None
        got = backend.search(prefix, 0, 4096, 8)
        assert got.nonce == truth.nonce

    @pytest.mark.parametrize("name", SEARCH_BACKENDS)
    def test_no_hit_returns_none(self, name):
        backend = get_backend(name)
        prefix = _random_prefix(5)
        res = backend.search(prefix, 0, 64, 255)
        assert res.nonce is None
        assert res.hashes_done == 64

    @pytest.mark.parametrize("name", SEARCH_BACKENDS)
    def test_respects_nonce_start(self, name):
        backend = get_backend(name)
        prefix = _random_prefix(6)
        truth = get_backend("cpu").search(prefix, 0, 1 << 14, 10)
        assert truth.nonce is not None
        # Start the search just past the first hit; must find a later one or none,
        # never the earlier nonce.
        later = backend.search(prefix, truth.nonce + 1, 1 << 14, 10)
        assert later.nonce is None or later.nonce > truth.nonce

    @pytest.mark.parametrize("name", SEARCH_BACKENDS)
    def test_search_hit_meets_target(self, name):
        backend = get_backend(name)
        prefix = _random_prefix(7)
        res = backend.search(prefix, 0, 1 << 14, 10)
        if res.nonce is not None:
            header80 = prefix + struct.pack(">I", res.nonce)
            assert meets_target(sha256_ref.sha256d(header80), 10)

    @pytest.mark.parametrize("name", SEARCH_BACKENDS)
    def test_arg_validation(self, name):
        backend = get_backend(name)
        with pytest.raises(ValueError):
            backend.search(b"x" * 75, 0, 10, 8)
        with pytest.raises(ValueError):
            backend.search(b"x" * 76, 0xFFFFFFFF, 2, 8)
