"""Fleet provisioning (round 22): snapshot-cold-started replicas,
agreement-aware wallet failover, and the kill-one-replica proof.

Four property families anchor the tier:

- **cold start is verified, resumable, and demotes liars**: a replica
  bootstrapped over GETSNAPSHOT pins the snapshot anchor to a
  PoW-verified header skeleton and adopts the filter-header chain only
  after a genesis recompute (plus a second-peer cross-check when one is
  live); a snapshot server off the verified chain is DEMOTED and the
  next peer tried; a torn ``.bootbase`` restarts the stages cleanly
  while an intact one skips straight to the body fill.
- **ReplicaSet policy is deterministic**: health-scored selection,
  spread under ``spread_key``, shed to the full node ONLY when the
  replica tier is exhausted, permanent demotion of proven liars across
  rebalances.
- **no confirmation is missed across the fleet**: a wallet cursor
  replays gap-free across replica drain, live rebase/compact under the
  store, and the shed to the full node once the store prunes; the
  chaos ``replica_kill``/``replica_join`` family and the
  ``fleet-failover`` scenario prove the same at mesh scale,
  deterministically.
- **`p1 serve --bootstrap` / `p1 watch --fallback` surface it**: the
  bootstrap report, the SIGTERM drain line, and the active-target +
  failover-count fields are real process behavior, not just library
  API.
"""

import asyncio
import json
import subprocess
import sys

import pytest

from test_node import DIFF, fund, run, wait_until
from txutil import account

from p1_tpu.chain import ChainStore
from p1_tpu.config import NodeConfig
from p1_tpu.node import Node
from p1_tpu.node.client import (
    ReplicaSet,
    get_filter_headers,
    get_headers,
    watch,
)
from p1_tpu.node.provision import (
    BootstrapError,
    UpstreamSync,
    bootstrap_store,
    read_bootbase,
    write_bootbase,
)
from p1_tpu.node.queryplane import ReplicaView, serve_replica
from p1_tpu.node.testing import make_blocks


def _config(**kw) -> NodeConfig:
    kw.setdefault("difficulty", DIFF)
    kw.setdefault("mine", False)
    kw.setdefault("peers", ())
    return NodeConfig(**kw)


def _write_store(path, blocks) -> None:
    s = ChainStore(path, fsync=False)
    try:
        for block in blocks[1:]:
            s.append(block)
        s.sync()
    finally:
        s.close()


async def _serving_node(path, n_blocks, miner="fleet-acct", interval=4):
    """A node resumed from a freshly written store; with ``interval``
    set it repopulates state checkpoints during resume replay and
    serves snapshots over GETSNAPSHOT."""
    _write_store(path, make_blocks(n_blocks, DIFF, miner_id=account(miner)))
    node = Node(
        _config(store_path=str(path), snapshot_interval=interval, port=0)
    )
    await node.start()
    return node


# -- the .bootbase sidecar -------------------------------------------------


class TestBootbaseSidecar:
    def _material(self, n=4):
        blocks = make_blocks(n, DIFF)
        headers = [b.header.serialize() for b in blocks[1:]]
        fheaders = [bytes([i]) * 32 for i in range(n + 1)]
        return headers, fheaders

    def test_roundtrip(self, tmp_path):
        store = tmp_path / "c.dat"
        headers, fheaders = self._material()
        path = write_bootbase(store, headers, fheaders)
        assert path.name == "c.dat.bootbase"
        assert read_bootbase(store) == (4, headers, fheaders)

    def test_absent_torn_and_corrupt_all_read_none(self, tmp_path):
        store = tmp_path / "c.dat"
        assert read_bootbase(store) is None
        headers, fheaders = self._material()
        path = write_bootbase(store, headers, fheaders)
        raw = path.read_bytes()
        path.write_bytes(raw[: len(raw) // 2])  # torn
        assert read_bootbase(store) is None
        path.write_bytes(raw[:40] + bytes([raw[40] ^ 1]) + raw[41:])
        assert read_bootbase(store) is None  # digest catches the flip
        path.write_bytes(b"XXXXXXXX" + raw[8:])
        assert read_bootbase(store) is None  # wrong magic
        path.write_bytes(raw)
        assert read_bootbase(store) == (4, headers, fheaders)

    def test_write_checks_filter_header_count(self, tmp_path):
        headers, fheaders = self._material()
        with pytest.raises(ValueError, match="0..base"):
            write_bootbase(tmp_path / "c.dat", headers, fheaders[:-1])


# -- cold start ------------------------------------------------------------


class TestColdStart:
    def test_snapshot_cold_start_then_serve(self, tmp_path):
        """The tentpole happy path: bootstrap from one honest peer,
        land a base at the latest checkpoint plus bodies above it, and
        serve a replica whose filter-header chain matches the node's at
        every height — seconds of work bounded by blocks above the
        base, not an IBD."""

        async def scenario():
            node = await _serving_node(tmp_path / "src.dat", 10)
            srv = None
            try:
                replica = str(tmp_path / "replica.dat")
                report = await bootstrap_store(
                    replica, [("127.0.0.1", node.port)], DIFF
                )
                assert report["base"] == 8 and report["tip"] == 10
                assert report["blocks_fetched"] == 2
                assert not report["resumed"] and not report["demoted"]
                bb = read_bootbase(replica)
                assert bb is not None and bb[0] == 8
                srv = await serve_replica(replica, DIFF)
                assert srv.view.assumed_base == 8
                assert srv.view.tip_height == 10
                # Commitment chain identical to the node's, end to end.
                ours = await get_filter_headers(
                    "127.0.0.1", srv.port, 0, 11, DIFF
                )
                theirs = await get_filter_headers(
                    "127.0.0.1", node.port, 0, 11, DIFF
                )
                assert ours == theirs
                # Adopted heights serve headers (hash-pinned skeleton).
                headers = await get_headers("127.0.0.1", srv.port, DIFF)
                assert [h.block_hash() for h in headers] == [
                    node.chain.main_hash_at(i) for i in range(11)
                ]
            finally:
                if srv is not None:
                    await srv.stop()
                await node.stop()

        run(scenario())

    def test_intact_bootbase_resumes_torn_restarts(self, tmp_path):
        """The crash model: a second bootstrap over an intact sidecar
        skips the snapshot stages (and refetches nothing the store
        already holds); corrupting the sidecar falls back to a clean
        fresh start rather than half-loading."""

        async def scenario():
            node = await _serving_node(tmp_path / "src.dat", 10)
            try:
                replica = str(tmp_path / "replica.dat")
                peers = [("127.0.0.1", node.port)]
                first = await bootstrap_store(replica, peers, DIFF)
                assert not first["resumed"]
                again = await bootstrap_store(replica, peers, DIFF)
                assert again["resumed"] and again["base"] == first["base"]
                assert again["blocks_fetched"] == 0
                # Torn sidecar: restart the snapshot stages cleanly.
                bb = tmp_path / "replica.dat.bootbase"
                bb.write_bytes(bb.read_bytes()[:50])
                third = await bootstrap_store(replica, peers, DIFF)
                assert not third["resumed"]
                assert third["base"] == first["base"]
            finally:
                await node.stop()

        run(scenario())

    def test_lying_snapshot_server_demoted_next_peer_tried(self, tmp_path):
        """A snapshot server whose manifest anchors a block that is NOT
        on the PoW-verified skeleton is demoted (the PR 9 contract) and
        the next peer is tried; a peer serving no snapshot at all is
        honest, just unhelpful."""

        async def scenario():
            # Skeleton source: honest chain, no snapshots configured.
            bare = await _serving_node(
                tmp_path / "bare.dat", 10, interval=0
            )
            # The liar: a VALID node of a different chain — internally
            # consistent snapshot, anchor off our skeleton.
            liar = await _serving_node(
                tmp_path / "liar.dat", 10, miner="liar-acct"
            )
            honest = await _serving_node(tmp_path / "good.dat", 10)
            try:
                report = await bootstrap_store(
                    str(tmp_path / "replica.dat"),
                    [
                        ("127.0.0.1", bare.port),
                        ("127.0.0.1", liar.port),
                        ("127.0.0.1", honest.port),
                    ],
                    DIFF,
                )
                assert report["base"] == 8 and report["tip"] == 10
                assert len(report["demoted"]) == 1
                d = report["demoted"][0]
                assert d["peer"].endswith(f":{liar.port}")
                assert "anchor" in d["why"]
            finally:
                await honest.stop()
                await liar.stop()
                await bare.stop()

        run(scenario())

    def test_no_snapshot_anywhere_degrades_to_full_fill(self, tmp_path):
        async def scenario():
            node = await _serving_node(tmp_path / "src.dat", 6, interval=0)
            try:
                report = await bootstrap_store(
                    str(tmp_path / "replica.dat"),
                    [("127.0.0.1", node.port)],
                    DIFF,
                )
                assert report["base"] == 0
                assert report["blocks_fetched"] == 6  # the IBD fallback
            finally:
                await node.stop()

        run(scenario())

    def test_no_peers_is_loud(self, tmp_path):
        with pytest.raises(BootstrapError, match="at least one peer"):
            run(bootstrap_store(str(tmp_path / "r.dat"), [], DIFF))


# -- serving-time upstream pull --------------------------------------------


class TestUpstreamSync:
    def test_replica_follows_live_mining_gap_free(self, tmp_path):
        """The `p1 serve --bootstrap` steady state: the sync loop pulls
        new PoW-checked blocks into the replica's own store and the
        refresh loop indexes them — the replica tip tracks the node."""

        async def scenario():
            node = await _serving_node(tmp_path / "src.dat", 6)
            srv, store = None, None
            try:
                replica = str(tmp_path / "replica.dat")
                await bootstrap_store(
                    replica, [("127.0.0.1", node.port)], DIFF
                )
                srv = await serve_replica(
                    replica, DIFF, refresh_interval_s=0.02
                )
                store = ChainStore(replica, fsync=False)
                sync = UpstreamSync(
                    store, srv.view, [("127.0.0.1", node.port)], DIFF
                )
                await fund(node, "fleet-acct", blocks=3)

                async def caught_up():
                    while srv.view.tip_height < node.chain.height:
                        await sync.poll_once()
                        await asyncio.sleep(0.02)

                await asyncio.wait_for(caught_up(), 30)
                assert sync.pulled >= 3 and sync.snapshot()["demoted"] == 0
                srv.view.refresh()
                h = node.chain.height
                assert srv.view.hash_at(h) == node.chain.main_hash_at(h)
            finally:
                if store is not None:
                    store.close()
                if srv is not None:
                    await srv.stop()
                await node.stop()

        run(scenario())


# -- wallet-side fleet policy ----------------------------------------------

R0, R1, R2 = ("10.0.0.1", 9), ("10.0.0.2", 9), ("10.0.0.3", 9)
FULL = ("10.0.0.9", 9)


class TestReplicaSetPolicy:
    def test_spread_keys_spread_a_cold_fleet(self):
        picks = {
            ReplicaSet([R0, R1, R2], spread_key=k).pick() for k in range(3)
        }
        assert picks == {R0, R1, R2}

    def test_streak_fails_over_and_an_event_heals(self):
        rs = ReplicaSet([R0, R1])
        assert rs.pick() == R0
        rs.note_stall(R0)
        assert rs.pick() == R1  # mid-outage loses to healthy fast
        rs.note_event(R0)  # streak resets, cumulative stall remains
        rs.note_stall(R1)
        assert rs.pick() == R0

    def test_shed_to_full_node_only_when_replicas_exhausted(self):
        rs = ReplicaSet([R0, R1], full_node=FULL)
        for _ in range(ReplicaSet.SHED_AFTER):
            rs.note_stall(R0)
        assert rs.pick() == R1  # one replica down is not a shed
        for _ in range(ReplicaSet.SHED_AFTER):
            rs.note_stall(R1)
        assert rs.pick() == FULL  # tier exhausted: full node
        rs.note_event(R1)
        assert rs.pick() == R1  # capacity back on the replica tier

    def test_agreement_earns_bounded_preference(self):
        rs = ReplicaSet([R0, R1])
        rs.note_agreement(R1)
        assert rs.pick() == R1
        # Bounded: a stall streak still dislodges a long-lived favorite.
        for _ in range(30):
            rs.note_agreement(R1)
        for _ in range(5):
            rs.note_stall(R1)
        assert rs.pick() == R0

    def test_violation_is_permanent_across_rebalance(self):
        rs = ReplicaSet([R0, R1], full_node=FULL)
        rs.note_violation(R0)
        assert rs.pick() == R1
        rs.update_targets([R1])
        rs.update_targets([R0, R1])  # the liar re-registers
        assert rs.pick() == R1
        rs.note_violation(R1)
        assert rs.pick() == FULL
        rs.note_violation(FULL)
        assert rs.pick() is None  # caller raises, loudly

    def test_rebalance_forgets_leaver_health_clears_active(self):
        rs = ReplicaSet([R0, R1])
        rs.note_stall(R1)
        rs.mark_active(R1)
        joined, left = rs.update_targets([R0, R2])
        assert joined == [R2] and left == [R1]
        assert rs.active is None and rs.rebalances == 1
        # A re-provisioned address starts cold.
        rs.update_targets([R0, R1, R2])
        assert rs._h(R1)["stalls"] == 0

    def test_mark_active_counts_failovers(self):
        rs = ReplicaSet([R0, R1])
        rs.mark_active(R0)
        rs.mark_active(R0)
        assert rs.failovers == 0
        rs.mark_active(R1)
        assert rs.failovers == 1
        snap = rs.snapshot()
        assert snap["active"] == "10.0.0.2:9" and snap["failovers"] == 1


# -- drain, maintenance, and the cursor across all of it -------------------


class TestDrainAndMaintenance:
    def test_drain_pushes_final_cursor_and_closes(self, tmp_path):
        """SIGTERM's library half: drain() stops accepting, hands every
        live subscriber a final resume cursor, and exits clean."""

        async def scenario():
            store = str(tmp_path / "c.dat")
            node = await _serving_node(store, 4, interval=0)
            srv, gen = None, None
            try:
                await node.stop()  # replica owns the read path now
                srv = await serve_replica(store, DIFF)
                gen = watch(
                    "127.0.0.1", srv.port, [account("fleet-acct")], DIFF,
                    max_session_failures=1,
                )
                agen = gen.__aiter__()
                task = asyncio.ensure_future(agen.__anext__())
                assert await wait_until(
                    lambda: srv.subscriptions.snapshot()["live"] == 1
                )
                drained = await srv.drain()
                assert drained == 1
                assert srv.subscriptions.drained_total == 1
                assert srv.subscriptions.snapshot()["live"] == 0
                # The watcher's session died with the drain; its retry
                # budget (1) re-raises the dead-session error loudly.
                with pytest.raises(
                    (ConnectionError, asyncio.IncompleteReadError)
                ):
                    await asyncio.wait_for(task, 30)
            finally:
                if gen is not None:
                    await gen.aclose()
                if srv is not None:
                    await srv.stop()

        run(scenario())

    def test_cursor_gap_free_across_rebase_compact_then_prune_sheds(
        self, tmp_path
    ):
        """Satellite 3, end to end: a wallet cursor replays gap-free
        against a replica refreshed across a live rebase + online
        compaction; once the node PRUNES the store the replica tier is
        honestly gone (a fresh attach refuses) and the ReplicaSet sheds
        the wallet to the full node at the same cursor — heights stay
        contiguous through all of it, zero missed confirmations."""

        async def scenario():
            store = str(tmp_path / "c.dat")
            # Segmented store + checkpoint cadence: the maintenance
            # plane's shape (rebase snaps to a checkpoint, prune drops
            # whole segments).  Mined, not resumed — rebase needs the
            # live checkpoints the mining path records.
            node = Node(
                _config(
                    store_path=store,
                    store_segment_bytes=400,
                    snapshot_interval=4,
                    port=0,
                )
            )
            await node.start()
            await fund(node, "fleet-acct", blocks=8)
            srv, gen = None, None
            try:
                srv = await serve_replica(
                    store, DIFF, refresh_interval_s=0.05
                )
                (fh,) = await get_filter_headers(
                    "127.0.0.1", srv.port, 4, 1, DIFF
                )
                rs = ReplicaSet(
                    [("127.0.0.1", srv.port)],
                    full_node=("127.0.0.1", node.port),
                )
                gen = watch(
                    "127.0.0.1", srv.port, [account("fleet-acct")], DIFF,
                    cursor=(4, fh), replica_set=rs, cross_check_every=0,
                    reconnect_delay_s=0.05, max_session_failures=8,
                )
                agen = gen.__aiter__()
                heights = []

                async def take(n):
                    for _ in range(n):
                        ev = await asyncio.wait_for(agen.__anext__(), 30)
                        assert ev["matched"]
                        heights.append(ev["height"])

                await take(4)  # committed replay 5..8
                # Live maintenance under the replica's mmap.
                assert (await node._maintain({"op": "rebase", "keep": 4}))[
                    "ok"
                ]
                assert (await node._maintain({"op": "compact"}))["ok"]
                await fund(node, "fleet-acct", blocks=2)
                await take(2)  # 9, 10 pushed across the rewrite
                # Prune: the store can no longer back a replica.
                node.store.roll_segment()
                await fund(node, "fleet-acct", blocks=1)
                r = await node._maintain({"op": "prune", "keep": 2})
                assert r["ok"] and r["segments_pruned"] >= 1, r
                with pytest.raises(ValueError, match="pruned"):
                    ReplicaView(store, DIFF)
                # Operator decommissions the replica; the wallet sheds.
                # NOTE: the test miner overshoots its target (it stops
                # only after wait_until sees the height), and the dead
                # replica's last pushes sit in the wallet's socket
                # buffer — so events up to the replica's death-tip can
                # still arrive WITHOUT a failover.  Pin the death-tip,
                # mine past it, and drain until the wallet crosses it:
                # those heights can only come from the full node.
                await srv.stop()
                srv = None
                death_tip = node.chain.height
                await fund(node, "fleet-acct", blocks=2)
                tip = node.chain.height
                while heights[-1] < tip:
                    await take(1)
                assert heights == list(range(5, tip + 1))
                assert rs.active == ("127.0.0.1", node.port)
                assert rs.failovers >= 1
                assert heights[-1] > death_tip
            finally:
                if gen is not None:
                    await gen.aclose()
                if srv is not None:
                    await srv.stop()
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), 120))


# -- fleet at mesh scale ---------------------------------------------------


class TestFleetProof:
    def test_chaos_replica_ops_run_green_and_deterministic(self):
        from p1_tpu.node import chaos

        evs = chaos.generate_schedule(18, 5, 20)
        ops = [e["op"] for e in evs]
        assert "replica_kill" in ops and "replica_join" in ops
        a = chaos.run_chaos(18, nodes=5, n_events=20)
        b = chaos.run_chaos(18, nodes=5, n_events=20)
        assert a["ok"] and not a["violations"]
        a.pop("wall_s")
        b.pop("wall_s")
        assert a == b

    def test_fleet_failover_scenario_zero_missed(self):
        """The kill-one-replica proof as a deterministic scenario: N
        replicas, spread sessions, the most-ridden replica crashed
        mid-push — every stream contiguous and matched."""
        from p1_tpu.node.scenarios import fleet_failover

        r = fleet_failover(seed=0)
        assert r["ok"], r
        assert r["missed_confirmations"] == 0
        assert r["spread"] >= 2 and r["failovers"] >= 1
        again = fleet_failover(seed=0)
        r.pop("wall_s")
        again.pop("wall_s")
        assert r == again


# -- the process surface ---------------------------------------------------


class TestFleetCli:
    def test_serve_bootstrap_then_sigterm_drain(self, tmp_path):
        """`p1 serve --bootstrap <peer>`: the bootstrap report line, a
        ready line carrying the adopted base, real query service, and
        the SIGTERM drain line with a clean exit."""

        async def scenario():
            # The source node must stay LIVE on a running loop while
            # the subprocess bootstraps from it — so all blocking pipe
            # reads go through a worker thread, never the loop thread.
            node = await _serving_node(tmp_path / "src.dat", 14)
            proc = None
            try:
                proc = subprocess.Popen(
                    [
                        sys.executable, "-m", "p1_tpu", "serve",
                        "--store", str(tmp_path / "replica.dat"),
                        "--difficulty", str(DIFF), "--port", "0",
                        "--bootstrap", f"127.0.0.1:{node.port}",
                        "--deadline", "60",
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL,
                    text=True,
                    cwd="/root/repo",
                )

                async def line():
                    return await asyncio.wait_for(
                        asyncio.to_thread(proc.stdout.readline), 60
                    )

                boot = json.loads(await line())
                assert boot["config"] == "bootstrap"
                assert boot["base"] == 12 and boot["tip"] == 14
                assert boot["blocks_fetched"] == 2
                ready = json.loads(await line())
                assert ready["config"] == "serve"
                assert ready["height"] == 14
                assert ready["assumed_base"] == 12

                headers = await get_headers(
                    "127.0.0.1", ready["port"], DIFF
                )
                assert len(headers) == 15
                assert (
                    headers[14].block_hash()
                    == node.chain.main_hash_at(14)
                )

                proc.terminate()  # SIGTERM: graceful drain
                out, _ = await asyncio.wait_for(
                    asyncio.to_thread(proc.communicate), 30
                )
                drain = json.loads(out.strip().splitlines()[-1])
                assert drain["config"] == "drain"
                assert proc.returncode == 0
            finally:
                if proc is not None and proc.poll() is None:
                    proc.kill()
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), 120))

    def test_watch_fallback_file_failover_surfaces_target(self, tmp_path):
        """Satellite 2: a dead primary plus a --fallback-file roster —
        the watch fails over, and every JSON line names the active
        target and the failover count."""
        node_log = open(tmp_path / "node.log", "w")
        node = subprocess.Popen(
            [
                sys.executable, "-m", "p1_tpu", "node",
                "--difficulty", str(DIFF), "--backend", "cpu",
                "--chunk", "16384", "--port", "0",
                "--miner-id", "fleet-cli-acct", "--deadline", "stdin",
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=node_log,
            text=True,
            cwd="/root/repo",
        )
        try:
            port = None
            for line in node.stdout:
                line = line.strip()
                if line.startswith("{"):
                    port = json.loads(line)["ready"]
                    break
            assert port, "node never printed its ready line"
            roster = tmp_path / "fleet.txt"
            roster.write_text(
                f"# fleet roster\n127.0.0.1:{port}\n"
            )
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "watch",
                    "fleet-cli-acct", "--difficulty", str(DIFF),
                    "--port", "1",  # dead primary
                    "--fallback-file", str(roster),
                    "--deadline", "90", "--max-events", "2",
                ],
                capture_output=True,
                text=True,
                timeout=110,
                cwd="/root/repo",
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            lines = [
                json.loads(l) for l in proc.stdout.strip().splitlines()
            ]
            assert len(lines) == 2
            for l in lines:
                assert l["matched"]
                assert l["target"] == f"127.0.0.1:{port}"
                assert l["failovers"] >= 1
        finally:
            try:
                node.communicate(input="0\n", timeout=30)
            except subprocess.TimeoutExpired:
                node.kill()
            node_log.close()
