"""Test environment: force JAX onto CPU with 8 virtual devices.

Multi-chip hardware is unavailable in CI; the sharded nonce-search path
(shard_map + pmin over a Mesh) is exercised on a virtual 8-device CPU mesh
instead (SURVEY.md §7 step 8).

Two traps this file defuses:

- ``XLA_FLAGS`` must be in the environment before the first backend
  initialization, so it is set at import time (conftest imports before any
  test module).
- This VM's axon sitecustomize calls ``jax.config.update("jax_platforms",
  "axon,cpu")`` at interpreter start, which *overrides* any
  ``JAX_PLATFORMS`` env var — forcing CPU requires an explicit config
  update after import, not an env var.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_addoption(parser):
    # Validation fast lane: pin the Ed25519 batch-verification worker
    # count for the whole run.  Default 1 keeps tier-1 deterministic on
    # the 1-vCPU CI host (no thread-pool scheduling in the mix); the
    # slow soak set re-exercises workers>1 explicitly
    # (tests/test_sigbatch.py's pool lifecycle soak).
    parser.addoption(
        "--verify-workers",
        type=int,
        default=int(os.environ.get("P1_VERIFY_WORKERS", "1")),
        help="Ed25519 batch-verification worker threads for this run "
        "(env P1_VERIFY_WORKERS; default 1 for determinism)",
    )


def pytest_configure(config):
    # Tier-1 runs `-m 'not slow'` (ROADMAP.md): the marker must be
    # registered or every slow-marked soak raises an unknown-mark warning.
    config.addinivalue_line(
        "markers",
        "slow: long-running soaks excluded from the tier-1 `-m 'not slow'` run",
    )
    config.addinivalue_line(
        "markers",
        "sim: deterministic network-simulator scenarios (node/netsim.py) "
        "— virtual-time runs selectable with `-m sim`; tier-1 carries "
        "the quick set, the 1000-node acceptance runs are also `slow`",
    )
    config.addinivalue_line(
        "markers",
        "chaos: combined-fault schedules over the simulated mesh "
        "(node/chaos.py) — tier-1 carries the bounded ~30-schedule "
        "sweep, the ≥200-schedule sweep is also `slow`",
    )
    config.addinivalue_line(
        "markers",
        "staged: staged-pipeline coverage (node/pipeline.py, round 19) "
        "— lane offload, ordering/digest equivalence with staging on "
        "vs off, and worker-crash respawn; selectable with `-m staged`",
    )
    from p1_tpu.core import keys

    keys.set_verify_workers(config.getoption("--verify-workers"))


def pytest_sessionstart(session):
    devices = jax.devices()
    assert devices[0].platform == "cpu", f"tests must run on CPU, got {devices}"
    assert len(devices) == 8, f"expected 8 virtual CPU devices, got {len(devices)}"
