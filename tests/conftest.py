"""Test environment: force JAX onto CPU with 8 virtual devices.

Multi-chip hardware is unavailable in CI; the sharded nonce-search path
(shard_map + pmin over a Mesh) is exercised on a virtual 8-device CPU mesh
instead (SURVEY.md §7 step 8).  These env vars must be set before the first
``import jax`` anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
