"""The sharded far-field plane (p1_tpu/node/farfield.py).

Pins the round-17 acceptance contract at the substrate level: the
world is a pure function of the seed (topology, latencies), event time
is integer microseconds, and the merged trace digest is byte-identical
at 1 shard and at N shards — with the shards in one process or spread
across OS processes over the pipe seam.  The scenario-level half (the
composed core+far-field run, its convergence SLO, and the 10k-node
acceptance run) lives in tests/test_scenarios.py; the cross-process
CLI pair lives in tests/test_cli.py.
"""

import pytest

from p1_tpu.node.farfield import (
    LAT_MAX_US,
    LAT_MIN_US,
    FarShard,
    link_latency_us,
    run_far_field,
    shard_bounds,
    topology,
)

pytestmark = pytest.mark.sim


def linear_feed(blocks: int, spacing_s: float = 2.0, tag: str = "b"):
    feed = []
    parent = ""
    for h in range(1, blocks + 1):
        bid = f"{tag}{h:03d}"
        feed.append((spacing_s * h, h, bid, parent))
        parent = bid
    return feed


class TestPureWorld:
    def test_latency_is_deterministic_and_banded(self):
        for src, dst in ((0, 1), (7, 3), (-1, 500), (9999, 0)):
            a = link_latency_us(5, src, dst)
            assert a == link_latency_us(5, src, dst)
            assert LAT_MIN_US <= a < LAT_MAX_US
        # Directional and seed-sensitive: the draw really keys on all
        # of (seed, src, dst).
        assert link_latency_us(5, 0, 1) != link_latency_us(5, 1, 0)
        assert link_latency_us(5, 0, 1) != link_latency_us(6, 0, 1)

    def test_topology_is_symmetric_connected_and_pure(self):
        adj = topology(3, 200, degree=4)
        assert adj == topology(3, 200, degree=4)
        for i, nbrs in enumerate(adj):
            for j in nbrs:
                assert i in adj[j]
        # The i-1 backbone guarantees connectivity.
        for i in range(1, 200):
            assert (i - 1) in adj[i]

    def test_shard_bounds_partition_exactly(self):
        for n, shards in ((10, 1), (10, 3), (10_000, 7), (5, 5)):
            bounds = shard_bounds(n, shards)
            assert bounds[0][0] == 0 and bounds[-1][1] == n
            for (alo, ahi), (blo, _bhi) in zip(bounds, bounds[1:]):
                assert ahi == blo and ahi > alo


class TestShardSemantics:
    def test_orphan_headers_park_until_their_parent_connects(self):
        # One node, headers delivered child-first: the orphan buffer
        # must hold the child and accept it when the parent lands.
        shard = FarShard(seed=0, n=2, lo=0, hi=2, degree=1)
        feed = {"a": (1, ""), "b": (2, "a")}
        shard.push((10_000, 0, -1, 2, "b"))
        shard.push((20_000, 0, -1, 1, "a"))
        shard.process(1_000_000, feed)
        assert shard.tips[0] == (2, "b")
        assert not shard.orphans

    def test_first_seen_wins_height_ties(self):
        shard = FarShard(seed=0, n=2, lo=0, hi=2, degree=1)
        feed = {"a": (1, ""), "a2": (1, "")}
        shard.push((10_000, 0, -1, 1, "a"))
        shard.push((20_000, 0, -1, 1, "a2"))
        shard.process(1_000_000, feed)
        assert shard.tips[0] == (1, "a")


class TestDigestInvariance:
    """THE acceptance pair: same seed ⇒ byte-identical merged digest,
    run to run AND across the 1→N shard split."""

    def test_same_seed_same_run(self):
        import dataclasses

        feed = linear_feed(5)
        a = run_far_field(300, seed=7, feed=feed)
        b = run_far_field(300, seed=7, feed=feed)
        # wall_s is the one legitimately nondeterministic field.
        assert dataclasses.replace(a, wall_s=0) == dataclasses.replace(
            b, wall_s=0
        )
        assert a.converged and a.trace_digest == b.trace_digest

    def test_shard_split_does_not_move_the_digest(self):
        feed = linear_feed(5)
        one = run_far_field(300, seed=7, feed=feed, shards=1)
        three = run_far_field(
            300, seed=7, feed=feed, shards=3, processes=False
        )
        assert one.trace_digest == three.trace_digest
        assert one.deliveries == three.deliveries
        assert one.converged and three.converged

    def test_cross_process_shards_match_in_process(self):
        feed = linear_feed(4)
        one = run_far_field(300, seed=9, feed=feed, shards=1)
        procs = run_far_field(
            300, seed=9, feed=feed, shards=2, processes=True
        )
        assert procs.processes  # really ran one OS process per shard
        assert one.trace_digest == procs.trace_digest

    def test_different_seed_different_digest(self):
        feed = linear_feed(4)
        a = run_far_field(300, seed=1, feed=feed)
        b = run_far_field(300, seed=2, feed=feed)
        assert a.trace_digest != b.trace_digest


class TestConvergence:
    def test_all_nodes_reach_the_final_tip(self):
        feed = linear_feed(6)
        r = run_far_field(800, seed=3, feed=feed, shards=2, processes=False)
        assert r.converged and r.converged_nodes == 800
        assert r.final_tip == (6, "b006")
        # Propagation figures are real: bounded below by one hop,
        # above by the settle time.
        assert r.propagation_p50_ms >= LAT_MIN_US / 1e3
        assert r.propagation_p95_ms <= r.settle_ms
