"""The determinism/async-safety analyzer (p1_tpu/analysis).

Three layers, mirroring the retired wall-clock lint's structure but
generalized over the whole rule registry:

1. **The tier-1 gate**: every registered rule over the whole package —
   zero unallowlisted findings, zero stale grants, zero parse errors.
   This is the test that makes the analyzer ENFORCED rather than
   advisory.
2. **The fixture corpus**: per rule, a known-bad module (every line
   marked ``# LINT`` flagged at exactly that line, nothing else) and a
   known-good module (zero findings).  The bad fixtures include a
   reproduction of each historical bug the rule would have caught
   (round 11 codec stamp, round 3 dead recovery loop, round 7/13 set
   iteration...), so the rules provably cover the incidents that
   motivated them.
3. **The settlement machinery**: grants suppress exactly their
   (rule, file, key); unused grants and grants on vanished files or
   unknown rules surface as stale.
"""

from __future__ import annotations

import ast
from pathlib import Path

import pytest

from p1_tpu.analysis import RULES, run_analysis
from p1_tpu.analysis.engine import PKG_ROOT

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"

#: rule name -> fixture file prefix.
_RULE_FIXTURES = {
    "wall-clock": "wallclock",
    "lost-task": "losttask",
    "unseeded-rng": "rng",
    "set-iteration": "setiter",
    "blocking-in-async": "blocking",
    "await-state": "awaitstate",
}


def _rule_findings(rule_name: str, path: Path):
    """Run ONE rule over a fixture, under a rel path inside every
    rule's scope (the fixture corpus tests rule logic, not scoping)."""
    tree = ast.parse(path.read_bytes(), filename=path.name)
    return list(RULES[rule_name].check(tree, f"node/{path.name}"))


def _marked_lines(path: Path) -> set[int]:
    return {
        i
        for i, line in enumerate(path.read_text().splitlines(), start=1)
        if line.rstrip().endswith("# LINT")
    }


class TestTier1Gate:
    def test_whole_package_settles_clean(self):
        """THE gate: ≥6 rules over every module in p1_tpu, everything
        either fixed or granted with a reason, no grant unused."""
        report = run_analysis()
        assert len(report.rules) >= 6, report.rules
        assert report.files >= 60, report.files  # the walk found the tree
        assert not report.parse_errors, report.parse_errors
        assert not report.violations, "unallowlisted findings:\n  " + "\n  ".join(
            str(f) for f in report.violations
        )
        assert not report.stale, "stale grants:\n  " + "\n  ".join(report.stale)
        assert report.clean

    def test_registry_matches_fixture_corpus(self):
        """Every registered rule carries a bad/good fixture pair — a
        new rule cannot land untested, and a renamed rule cannot orphan
        its fixtures silently."""
        assert set(RULES) == set(_RULE_FIXTURES)
        for prefix in _RULE_FIXTURES.values():
            assert (FIXTURES / f"{prefix}_bad.py").exists(), prefix
            assert (FIXTURES / f"{prefix}_good.py").exists(), prefix

    def test_analyzer_is_fast_enough_for_tier1(self):
        """The whole-package pass must stay interactive (the acceptance
        budget is ~5 s on a 1-vCPU host; the generous bound here exists
        to catch an accidental O(n^2) pass, not to time the machine)."""
        import time

        t0 = time.perf_counter()
        run_analysis()
        assert time.perf_counter() - t0 < 15.0


class TestFixtureCorpus:
    @pytest.mark.parametrize("rule_name,prefix", sorted(_RULE_FIXTURES.items()))
    def test_bad_fixture_flagged_at_exact_lines(self, rule_name, prefix):
        path = FIXTURES / f"{prefix}_bad.py"
        expected = _marked_lines(path)
        assert expected, f"{path.name} carries no # LINT markers"
        got = {f.line for f in _rule_findings(rule_name, path)}
        assert got == expected, (
            f"{rule_name} over {path.name}: flagged {sorted(got)}, "
            f"marked {sorted(expected)}"
        )

    @pytest.mark.parametrize("rule_name,prefix", sorted(_RULE_FIXTURES.items()))
    def test_good_fixture_is_clean(self, rule_name, prefix):
        path = FIXTURES / f"{prefix}_good.py"
        findings = _rule_findings(rule_name, path)
        assert not findings, [str(f) for f in findings]

    def test_findings_carry_file_line_rule_detail(self):
        f = _rule_findings("lost-task", FIXTURES / "losttask_bad.py")[0]
        assert f.file == "node/losttask_bad.py"
        assert f.rule == "lost-task"
        assert f.line > 0 and f.detail
        assert str(f).startswith(f"node/losttask_bad.py:{f.line}: [lost-task]")


class TestHistoricalReproductions:
    """Each rule's bad fixture embeds the incident that motivated it;
    these tests name the incidents so the corpus cannot quietly drop
    one in a refactor."""

    def test_round11_codec_host_stamp_is_caught(self):
        # node/protocol.py's encode_block default put time.time() INSIDE
        # frame bytes — the wall-clock rule flags the reproduction.
        path = FIXTURES / "wallclock_bad.py"
        assert any(
            f.key == "time.time" and "encode_block" in path.read_text()
            for f in _rule_findings("wall-clock", path)
        )

    def test_round3_dead_recovery_loop_is_caught(self):
        # The fire-and-forget store-recovery spawn whose silent death
        # stranded the node degraded forever.
        findings = _rule_findings("lost-task", FIXTURES / "losttask_bad.py")
        assert any(f.key == "_store_fail" for f in findings)

    def test_round7_and_round13_set_iteration_is_caught(self):
        # Relay fan-out over a set difference (r7) and the chaos plane's
        # set-literal probe heights (r13, fixed in this round).
        findings = _rule_findings("set-iteration", FIXTURES / "setiter_bad.py")
        assert len(findings) >= 2


class TestSettlement:
    """The allowlist machinery itself, on a tiny synthetic tree."""

    def _tiny_pkg(self, tmp_path: Path) -> Path:
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "mod.py").write_text(
            "import random\n\n\ndef f():\n    return random.random()\n"
        )
        return root

    def test_ungranted_finding_is_a_violation(self, tmp_path):
        report = run_analysis(
            root=self._tiny_pkg(tmp_path),
            rules=[RULES["unseeded-rng"]],
            grants={},
        )
        assert [f.key for f in report.violations] == ["random.random"]
        assert not report.stale

    def test_grant_suppresses_and_is_consumed(self, tmp_path):
        report = run_analysis(
            root=self._tiny_pkg(tmp_path),
            rules=[RULES["unseeded-rng"]],
            grants={"unseeded-rng": {"mod.py": {"random.random": "test"}}},
        )
        assert not report.violations and not report.stale
        assert [f.key for f in report.granted] == ["random.random"]
        assert report.clean

    def test_unused_grant_goes_stale(self, tmp_path):
        report = run_analysis(
            root=self._tiny_pkg(tmp_path),
            rules=[RULES["unseeded-rng"]],
            grants={
                "unseeded-rng": {
                    "mod.py": {
                        "random.random": "used",
                        "random.shuffle": "nothing emits this",
                    },
                    "gone.py": {"random.random": "file vanished"},
                }
            },
        )
        assert sorted(report.stale) == [
            "unseeded-rng: gone.py: file no longer exists",
            "unseeded-rng: mod.py: grant 'random.shuffle' never used",
        ]
        assert not report.clean

    def test_partial_run_leaves_other_rules_grants_alone(self, tmp_path):
        """`p1 lint --rule X` must not report rule Y's grants stale."""
        report = run_analysis(
            root=self._tiny_pkg(tmp_path),
            rules=[RULES["lost-task"]],
            grants={"unseeded-rng": {"mod.py": {"random.random": "r"}}},
        )
        assert not report.stale and not report.violations

    def test_parse_error_is_reported_not_skipped(self, tmp_path):
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "broken.py").write_text("def f(:\n")
        report = run_analysis(root=root, rules=[RULES["lost-task"]], grants={})
        assert report.parse_errors and not report.clean

    def test_real_package_files_walk(self):
        rels = [rel for rel, _ in __import__(
            "p1_tpu.analysis.engine", fromlist=["package_files"]
        ).package_files(PKG_ROOT)]
        assert "node/node.py" in rels
        assert "analysis/engine.py" in rels  # the analyzer analyzes itself
        assert not any("__pycache__" in r for r in rels)


class TestGrantHygiene:
    def test_grant_under_unknown_rule_is_stale_even_on_partial_runs(
        self, tmp_path
    ):
        """A renamed rule must not orphan its grant table silently."""
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "mod.py").write_text("x = 1\n")
        report = run_analysis(
            root=root,
            rules=[RULES["lost-task"]],
            grants={"no-such-rule": {"mod.py": {"k": "r"}}},
        )
        assert report.stale == ["no-such-rule: no such rule"]

    def test_every_registered_rule_has_an_allowlist_section(self):
        """The allowlist names every rule (even if empty) so a reviewer
        sees the full settlement surface in one file."""
        from p1_tpu.analysis.allowlist import GRANTS

        assert set(GRANTS) == set(RULES)

    def test_every_grant_carries_a_nonempty_reason(self):
        from p1_tpu.analysis.allowlist import GRANTS

        for rule, by_file in GRANTS.items():
            for rel, keys in by_file.items():
                for key, reason in keys.items():
                    assert (
                        isinstance(reason, str) and len(reason) >= 10
                    ), f"{rule}/{rel}/{key}: grant reason too thin"
