"""The determinism/async-safety analyzer (p1_tpu/analysis).

Three layers, mirroring the retired wall-clock lint's structure but
generalized over the whole rule registry:

1. **The tier-1 gate**: every registered rule over the whole package —
   zero unallowlisted findings, zero stale grants, zero parse errors.
   This is the test that makes the analyzer ENFORCED rather than
   advisory.
2. **The fixture corpus**: per rule, a known-bad module (every line
   marked ``# LINT`` flagged at exactly that line, nothing else) and a
   known-good module (zero findings).  The bad fixtures include a
   reproduction of each historical bug the rule would have caught
   (round 11 codec stamp, round 3 dead recovery loop, round 7/13 set
   iteration...), so the rules provably cover the incidents that
   motivated them.
3. **The settlement machinery**: grants suppress exactly their
   (rule, file, key); unused grants and grants on vanished files or
   unknown rules surface as stale.
"""

from __future__ import annotations

import ast
from pathlib import Path

import pytest

from p1_tpu.analysis import RULES, run_analysis
from p1_tpu.analysis.engine import PKG_ROOT, PackageIndex

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"

#: rule name -> fixture file prefix.
_RULE_FIXTURES = {
    "wall-clock": "wallclock",
    "lost-task": "losttask",
    "unseeded-rng": "rng",
    "set-iteration": "setiter",
    "blocking-in-async": "blocking",
    "await-state": "awaitstate",
    "transitive-blocking": "transblock",
    "escaped-state": "escstate",
    "wire-contract": "wirecontract",
}


def _rule_findings(rule_name: str, path: Path):
    """Run ONE rule over a fixture, under a rel path inside every
    rule's scope (the fixture corpus tests rule logic, not scoping).
    Package rules see the fixture as a one-file package index — the
    same interface the engine hands them, so corpus assertions cover
    the real entry point."""
    tree = ast.parse(path.read_bytes(), filename=path.name)
    rule = RULES[rule_name]
    if rule.package_rule:
        return list(rule.check_package(PackageIndex({f"node/{path.name}": tree})))
    return list(rule.check(tree, f"node/{path.name}"))


def _marked_lines(path: Path) -> set[int]:
    return {
        i
        for i, line in enumerate(path.read_text().splitlines(), start=1)
        if line.rstrip().endswith("# LINT")
    }


class TestTier1Gate:
    def test_whole_package_settles_clean(self):
        """THE gate: ≥9 rules over every module in p1_tpu, everything
        either fixed or granted with a reason, no grant unused."""
        report = run_analysis()
        assert len(report.rules) >= 9, report.rules
        assert report.files >= 60, report.files  # the walk found the tree
        assert not report.parse_errors, report.parse_errors
        assert not report.violations, "unallowlisted findings:\n  " + "\n  ".join(
            str(f) for f in report.violations
        )
        assert not report.stale, "stale grants:\n  " + "\n  ".join(report.stale)
        assert report.clean

    def test_registry_matches_fixture_corpus(self):
        """Every registered rule carries a bad/good fixture pair — a
        new rule cannot land untested, and a renamed rule cannot orphan
        its fixtures silently."""
        assert set(RULES) == set(_RULE_FIXTURES)
        for prefix in _RULE_FIXTURES.values():
            assert (FIXTURES / f"{prefix}_bad.py").exists(), prefix
            assert (FIXTURES / f"{prefix}_good.py").exists(), prefix

    def test_analyzer_is_fast_enough_for_tier1(self):
        """The whole-package pass must stay interactive (the acceptance
        budget is ~5 s on a 1-vCPU host; the generous bound here exists
        to catch an accidental O(n^2) pass, not to time the machine)."""
        import time

        t0 = time.perf_counter()
        run_analysis()
        assert time.perf_counter() - t0 < 15.0


class TestFixtureCorpus:
    @pytest.mark.parametrize("rule_name,prefix", sorted(_RULE_FIXTURES.items()))
    def test_bad_fixture_flagged_at_exact_lines(self, rule_name, prefix):
        path = FIXTURES / f"{prefix}_bad.py"
        expected = _marked_lines(path)
        assert expected, f"{path.name} carries no # LINT markers"
        got = {f.line for f in _rule_findings(rule_name, path)}
        assert got == expected, (
            f"{rule_name} over {path.name}: flagged {sorted(got)}, "
            f"marked {sorted(expected)}"
        )

    @pytest.mark.parametrize("rule_name,prefix", sorted(_RULE_FIXTURES.items()))
    def test_good_fixture_is_clean(self, rule_name, prefix):
        path = FIXTURES / f"{prefix}_good.py"
        findings = _rule_findings(rule_name, path)
        assert not findings, [str(f) for f in findings]

    def test_findings_carry_file_line_rule_detail(self):
        f = _rule_findings("lost-task", FIXTURES / "losttask_bad.py")[0]
        assert f.file == "node/losttask_bad.py"
        assert f.rule == "lost-task"
        assert f.line > 0 and f.detail
        assert str(f).startswith(f"node/losttask_bad.py:{f.line}: [lost-task]")


class TestHistoricalReproductions:
    """Each rule's bad fixture embeds the incident that motivated it;
    these tests name the incidents so the corpus cannot quietly drop
    one in a refactor."""

    def test_round11_codec_host_stamp_is_caught(self):
        # node/protocol.py's encode_block default put time.time() INSIDE
        # frame bytes — the wall-clock rule flags the reproduction.
        path = FIXTURES / "wallclock_bad.py"
        assert any(
            f.key == "time.time" and "encode_block" in path.read_text()
            for f in _rule_findings("wall-clock", path)
        )

    def test_round3_dead_recovery_loop_is_caught(self):
        # The fire-and-forget store-recovery spawn whose silent death
        # stranded the node degraded forever.
        findings = _rule_findings("lost-task", FIXTURES / "losttask_bad.py")
        assert any(f.key == "_store_fail" for f in findings)

    def test_round7_and_round13_set_iteration_is_caught(self):
        # Relay fan-out over a set difference (r7) and the chaos plane's
        # set-literal probe heights (r13, fixed in this round).
        findings = _rule_findings("set-iteration", FIXTURES / "setiter_bad.py")
        assert len(findings) >= 2

    def test_set_through_a_variable_is_caught(self):
        # The round-13 docs conceded the "through a variable" residue;
        # round 16's one-dataflow-hop upgrade closes it.
        findings = _rule_findings("set-iteration", FIXTURES / "setiter_bad.py")
        assert any(f.key == "set-local" for f in findings)

    def test_helper_hidden_fsync_is_caught(self):
        # The transitive-blocking incident shape: the fsync lives in a
        # sync helper chain below a clean-looking async def — invisible
        # to the lexical blocking-in-async rule by construction.
        findings = _rule_findings(
            "transitive-blocking", FIXTURES / "transblock_bad.py"
        )
        keys = {f.key for f in findings}
        assert "Node.handle_block->open" in keys, keys
        # the full call path is in the detail — the ROADMAP-2 audit trail
        f = next(f for f in findings if f.key == "Node.handle_block->open")
        assert "Store.append" in f.detail and "_persist" in f.detail

    def test_helper_routed_state_write_across_await_is_caught(self):
        # The escaped-state incident shape: the chain write rides a
        # helper call on the far side of a scheduling point.
        findings = _rule_findings("escaped-state", FIXTURES / "escstate_bad.py")
        assert {f.key for f in findings} == {"chain", "mempool"}

    def test_frame_missing_shed_classification_fails_at_exact_key(self):
        # THE negative control the acceptance criteria name: one frame
        # type (BLOCK) in neither _SHED_DROPS nor _SHED_KEEPS must fail
        # at exactly "BLOCK:shed".
        findings = _rule_findings(
            "wire-contract", FIXTURES / "wirecontract_bad.py"
        )
        keys = {f.key for f in findings}
        assert "BLOCK:shed" in keys, keys
        assert keys == {
            "BLOCK:shed",
            "TX:dispatch",
            "STATUS:version",
            "HELLO:relay",
        }


class TestSettlement:
    """The allowlist machinery itself, on a tiny synthetic tree."""

    def _tiny_pkg(self, tmp_path: Path) -> Path:
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "mod.py").write_text(
            "import random\n\n\ndef f():\n    return random.random()\n"
        )
        return root

    def test_ungranted_finding_is_a_violation(self, tmp_path):
        report = run_analysis(
            root=self._tiny_pkg(tmp_path),
            rules=[RULES["unseeded-rng"]],
            grants={},
        )
        assert [f.key for f in report.violations] == ["random.random"]
        assert not report.stale

    def test_grant_suppresses_and_is_consumed(self, tmp_path):
        report = run_analysis(
            root=self._tiny_pkg(tmp_path),
            rules=[RULES["unseeded-rng"]],
            grants={"unseeded-rng": {"mod.py": {"random.random": "test"}}},
        )
        assert not report.violations and not report.stale
        assert [f.key for f in report.granted] == ["random.random"]
        assert report.clean

    def test_unused_grant_goes_stale(self, tmp_path):
        report = run_analysis(
            root=self._tiny_pkg(tmp_path),
            rules=[RULES["unseeded-rng"]],
            grants={
                "unseeded-rng": {
                    "mod.py": {
                        "random.random": "used",
                        "random.shuffle": "nothing emits this",
                    },
                    "gone.py": {"random.random": "file vanished"},
                }
            },
        )
        assert sorted(report.stale) == [
            "unseeded-rng: gone.py: file no longer exists",
            "unseeded-rng: mod.py: grant 'random.shuffle' never used",
        ]
        assert not report.clean

    def test_partial_run_leaves_other_rules_grants_alone(self, tmp_path):
        """`p1 lint --rule X` must not report rule Y's grants stale."""
        report = run_analysis(
            root=self._tiny_pkg(tmp_path),
            rules=[RULES["lost-task"]],
            grants={"unseeded-rng": {"mod.py": {"random.random": "r"}}},
        )
        assert not report.stale and not report.violations

    def test_parse_error_is_reported_not_skipped(self, tmp_path):
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "broken.py").write_text("def f(:\n")
        report = run_analysis(root=root, rules=[RULES["lost-task"]], grants={})
        assert report.parse_errors and not report.clean

    def test_real_package_files_walk(self):
        rels = [rel for rel, _ in __import__(
            "p1_tpu.analysis.engine", fromlist=["package_files"]
        ).package_files(PKG_ROOT)]
        assert "node/node.py" in rels
        assert "analysis/engine.py" in rels  # the analyzer analyzes itself
        assert not any("__pycache__" in r for r in rels)


class TestInterprocedural:
    """The round-16 call-graph plane: graph construction facts the
    three package rules depend on, and the wire-contract rule proven
    load-bearing against the REAL registries (not just fixtures)."""

    def _package_index(self):
        from p1_tpu.analysis.engine import package_files

        trees = {
            rel: ast.parse(p.read_bytes(), filename=rel)
            for rel, p in package_files(PKG_ROOT)
        }
        return PackageIndex(trees)

    def test_graph_resolves_the_node_consensus_attributes(self):
        """The one-level attribute-type binding that makes the graph
        worth having: self.store/chain/mempool resolve to their real
        classes, so the fsync/validate chains are followable."""
        g = self._package_index().graph
        types = g._attr_types["node/node.py"]["Node"]
        assert types["store"] == ("chain/store.py", "ChainStore")
        assert types["chain"] == ("chain/chain.py", "Chain")
        assert types["mempool"] == ("mempool/mempool.py", "Mempool")

    def test_graph_sees_the_store_append_fsync_chain(self):
        """The headline residue closed: an async def reaching os.fsync
        through ChainStore is in the blocking fixed point."""
        g = self._package_index().graph
        witness = g.blocking_paths()
        assert "chain/store.py::ChainStore.append" in witness
        # and the chain walks down to a real primitive
        chain = g.witness_chain("chain/store.py::ChainStore.append", witness)
        assert chain[-1] in ("open", "os.fsync"), chain

    def test_to_thread_offload_is_not_an_edge(self):
        """The house pattern must stay clean: _checkpoint_mempool
        passes its blocking helper to asyncio.to_thread — no call
        edge, so no transitive-blocking finding against it."""
        g = self._package_index().graph
        witness = g.blocking_paths()
        node = g.nodes.get("node/node.py::Node._checkpoint_mempool")
        assert node is not None and node.is_async
        assert not any(
            c.target in witness
            and not g.nodes[c.target].is_async
            for c in node.calls
            if c.target
        ), [c.dotted for c in node.calls]

    def test_report_carries_callgraph_stats(self):
        report = run_analysis(rules=[RULES["transitive-blocking"]])
        assert report.callgraph_nodes > 500
        assert report.callgraph_edges > 500
        assert report.to_json()["callgraph_nodes"] == report.callgraph_nodes

    def test_wire_contract_is_load_bearing_on_the_real_tree(self):
        """Registry-mutation negative control: drop GETMETRICS from
        node.py's _SHED_DROPS in the PARSED tree and the gate must
        fail at exactly GETMETRICS:shed — proving the rule reads the
        real registries, not a fixture-shaped convention."""
        idx = self._package_index()
        src = (PKG_ROOT / "node" / "node.py").read_text()
        mutated = src.replace("MsgType.GETMETRICS,", "", 1)
        assert mutated != src  # _SHED_DROPS names it exactly once first
        idx.trees["node/node.py"] = ast.parse(mutated, filename="node/node.py")
        findings = list(RULES["wire-contract"].check_package(idx))
        assert [f.key for f in findings] == ["GETMETRICS:shed"], findings

    def test_wire_contract_guards_the_subscription_plane(self):
        """Round-21 mutation controls: the push-plane messages are held
        in the admission and shed registries by the gate, not by
        convention.  Each mutation drops one membership from the PARSED
        node.py and must fail at exactly that member:aspect."""
        src = (PKG_ROOT / "node" / "node.py").read_text()
        cases = [
            # _MSG_CLASS charge entry -> unclassified traffic
            ("MsgType.SUBSCRIBE: CLASS_QUERIES,", "SUBSCRIBE:admission"),
            # _ADMISSION_EXEMPT is the first set-style EVENT mention
            ("MsgType.EVENT,", "EVENT:admission"),
            # _SHED_DROPS is the first set-style SUBSCRIBE mention
            ("MsgType.SUBSCRIBE,", "SUBSCRIBE:shed"),
            # _SHED_KEEPS is the first set-style UNSUBSCRIBE mention
            ("MsgType.UNSUBSCRIBE,", "UNSUBSCRIBE:shed"),
        ]
        for needle, expect in cases:
            idx = self._package_index()
            mutated = src.replace(needle, "", 1)
            assert mutated != src, needle
            idx.trees["node/node.py"] = ast.parse(
                mutated, filename="node/node.py"
            )
            findings = list(RULES["wire-contract"].check_package(idx))
            assert [f.key for f in findings] == [expect], (needle, findings)

    def test_wire_contract_guards_the_relay_accounting_table(self):
        """Round-23 mutation control: every frame type's egress must
        land in a relay.bytes.* family — drop GETTX's row from the
        PARSED node.py and the gate must fail at exactly GETTX:relay
        (the runtime assert beside the table enforces it too; the rule
        fails BEFORE the code ever runs)."""
        src = (PKG_ROOT / "node" / "node.py").read_text()
        idx = self._package_index()
        mutated = src.replace('MsgType.GETTX: "recon",', "", 1)
        assert mutated != src
        idx.trees["node/node.py"] = ast.parse(
            mutated, filename="node/node.py"
        )
        findings = list(RULES["wire-contract"].check_package(idx))
        assert [f.key for f in findings] == ["GETTX:relay"], findings

    def test_transitive_blocking_grants_read_as_the_roadmap2_work_list(self):
        """Acceptance: every transitive-blocking grant names a concrete
        offload decision (a stage or an explicit on/off-loop verdict) —
        the table IS the multi-core split's audited inventory."""
        from p1_tpu.analysis.allowlist import GRANTS

        grants = GRANTS["transitive-blocking"]
        assert grants, "the work list exists"
        for rel, keys in grants.items():
            for key, reason in keys.items():
                assert "->" in key, key  # coroutine->primitive keying
                assert any(
                    tag in reason
                    for tag in ("stage", "startup-only", "shutdown-only",
                                "worker", "offload")
                ), f"{key}: reason names no offload decision: {reason}"

    def test_round19_staging_strictly_shrank_the_node_grant_inventory(self):
        """Round-19 acceptance: the staged pipeline RETIRED grants, it
        did not relabel them.  node/node.py's transitive-blocking table
        held twelve chains at round 16; the two survivors are exactly
        the start/stop boundary cases (no session to stall / pipeline
        drained first), and every validate (ctypes) and store-append
        (open/os.fsync) chain runs on a pipeline lane with NO grant —
        so the count can only have strictly decreased."""
        from p1_tpu.analysis.allowlist import GRANTS

        node_grants = GRANTS["transitive-blocking"]["node/node.py"]
        assert len(node_grants) < 12, "round-16 inventory must shrink"
        assert set(node_grants) == {"Node.start->open", "Node.stop->open"}
        assert not any(
            key.endswith(("ctypes.CDLL", "os.fsync")) for key in node_grants
        ), "validate/store chains must be offloaded, not granted"
        # And the retirement is real, not a lint blind spot: the engine
        # still settles with zero node.py findings against this table.
        report = run_analysis(rules=[RULES["transitive-blocking"]])
        assert not [
            f for f in report.violations if f.file == "node/node.py"
        ], [str(f) for f in report.violations]


class TestScopedRuns:
    """run_analysis(paths=...) — the `p1 lint --path` engine contract:
    findings narrow to the scope, settlement stays global."""

    def _two_file_pkg(self, tmp_path: Path) -> Path:
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "a.py").write_text(
            "import random\n\n\ndef f():\n    return random.random()\n"
        )
        (root / "b.py").write_text(
            "import random\n\n\ndef g():\n    return random.choice([1])\n"
        )
        return root

    def test_scope_filters_reported_violations(self, tmp_path):
        report = run_analysis(
            root=self._two_file_pkg(tmp_path),
            rules=[RULES["unseeded-rng"]],
            grants={},
            paths=["a.py"],
        )
        assert [f.file for f in report.violations] == ["a.py"]
        assert report.scoped_to == ["a.py"]

    def test_directory_scope_matches_prefix(self, tmp_path):
        root = tmp_path / "pkg"
        (root / "sub").mkdir(parents=True)
        (root / "sub" / "mod.py").write_text(
            "import random\nx = random.random()\n"
        )
        (root / "top.py").write_text("import random\ny = random.random()\n")
        report = run_analysis(
            root=root, rules=[RULES["unseeded-rng"]], grants={}, paths=["sub/"]
        )
        assert [f.file for f in report.violations] == ["sub/mod.py"]

    def test_out_of_scope_grant_is_consumed_not_stale(self, tmp_path):
        """Settlement is global: the finding in the out-of-scope file
        still consumes its grant, so the scoped run reports neither a
        violation nor a stale grant for it."""
        report = run_analysis(
            root=self._two_file_pkg(tmp_path),
            rules=[RULES["unseeded-rng"]],
            grants={
                "unseeded-rng": {
                    "a.py": {"random.random": "granted in scope"},
                    "b.py": {"random.choice": "granted out of scope"},
                }
            },
            paths=["a.py"],
        )
        assert not report.violations and not report.stale
        assert [f.file for f in report.granted] == ["a.py"]  # reported in scope

    def test_scoped_run_cannot_hide_a_stale_grant(self, tmp_path):
        """The satellite's headline: a grant NOTHING uses — wherever
        its file lives — still fails a run scoped elsewhere."""
        report = run_analysis(
            root=self._two_file_pkg(tmp_path),
            rules=[RULES["unseeded-rng"]],
            grants={
                "unseeded-rng": {
                    "b.py": {"random.shuffle": "nothing emits this"},
                }
            },
            paths=["a.py"],
        )
        assert "unseeded-rng: b.py: grant 'random.shuffle' never used" in (
            report.stale
        )
        assert not report.clean


class TestGrantHygiene:
    def test_grant_under_unknown_rule_is_stale_even_on_partial_runs(
        self, tmp_path
    ):
        """A renamed rule must not orphan its grant table silently."""
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "mod.py").write_text("x = 1\n")
        report = run_analysis(
            root=root,
            rules=[RULES["lost-task"]],
            grants={"no-such-rule": {"mod.py": {"k": "r"}}},
        )
        assert report.stale == ["no-such-rule: no such rule"]

    def test_every_registered_rule_has_an_allowlist_section(self):
        """The allowlist names every rule (even if empty) so a reviewer
        sees the full settlement surface in one file."""
        from p1_tpu.analysis.allowlist import GRANTS

        assert set(GRANTS) == set(RULES)

    def test_every_grant_carries_a_nonempty_reason(self):
        from p1_tpu.analysis.allowlist import GRANTS

        for rule, by_file in GRANTS.items():
            for rel, keys in by_file.items():
                for key, reason in keys.items():
                    assert (
                        isinstance(reason, str) and len(reason) >= 10
                    ), f"{rule}/{rel}/{key}: grant reason too thin"
