"""The always-on maintenance plane (round 20): live re-basing,
continuous incremental snapshots, online prune/compact while serving,
and version-bits protocol evolution.

Four property families anchor the round:

- **incremental == full**: ``build_records_incremental`` is a cost
  model, never a format — manifest and chunks byte-identical to
  ``build_records`` on every state, with reuse proportional to the
  untouched account mass.
- **sidecar == replay**: a segment's ``.sdx`` state delta applied over
  the pre-segment state equals the live ledger after the segment —
  derived from the ledger's own delta rule, one definition only.
- **maintenance never disconnects**: rebase/prune/compact run on a
  LIVE node — refusals are answers, sessions stay open, a mid-op disk
  fault degrades the store without widening loss (compaction tmps
  self-clean), and a live-attached replica keeps serving across
  compaction and refuses loudly (not wrongly) once the store prunes.
- **activation is a pure header function**: the BIP9-analog ladder
  walks DEFINED → STARTED → LOCKED_IN → ACTIVE on signal counts alone,
  legacy version=1 headers never signal, and an empty deployment table
  is byte-identical to history.
"""

import asyncio

import pytest

from test_node import DIFF, _config, fund, run, wait_until
from txutil import account

from p1_tpu.chain import Chain, statedelta
from p1_tpu.chain import snapshot as snaplib
from p1_tpu.chain.versionbits import (
    TOP_BITS,
    Deployment,
    VBState,
    VersionBits,
    signals,
)
from p1_tpu.core.block import Block, merkle_root
from p1_tpu.core.header import BlockHeader
from p1_tpu.core.tx import BLOCK_REWARD, Transaction
from p1_tpu.hashx import get_backend
from p1_tpu.miner import Miner
from p1_tpu.node import Node, protocol
from p1_tpu.node.client import maintain as client_maintain
from p1_tpu.node.protocol import MsgType
from p1_tpu.node.queryplane import ReplicaView

_MINER = Miner(backend=get_backend("cpu"), chunk=4096)


def _grow(chain: Chain, n: int, label="alice", version=None) -> Chain:
    """Append ``n`` coinbase-only blocks; ``version`` may be an int, a
    callable of the chain (the version-bits miner hook shape), or None
    for the legacy literal 1."""
    for _ in range(n):
        h = chain.height + 1
        txs = (Transaction.coinbase(account(label), h),)
        parent = chain.tip
        v = version(chain) if callable(version) else (version or 1)
        draft = BlockHeader(
            version=v,
            prev_hash=parent.block_hash(),
            merkle_root=merkle_root([t.txid() for t in txs]),
            timestamp=parent.header.timestamp + 60,
            difficulty=chain.difficulty,
            nonce=0,
        )
        sealed = _MINER.search_nonce(draft)
        res = chain.add_block(Block(sealed, txs))
        assert res.status.value == "accepted", res.reason
    return chain


async def _mine(node, n: int, label="alice") -> None:
    """Mine EXACTLY ``n`` blocks on ``node`` through its normal accept
    path — no mining race, so tests can assert exact heights."""
    old = node.miner_id
    node.miner_id = account(label)
    try:
        for _ in range(n):
            candidate = node._assemble()
            sealed = _MINER.search_nonce(candidate.header)
            await node._handle_block(
                Block(sealed, candidate.txs), origin=None
            )
    finally:
        node.miner_id = old


def _mconfig(store, **kw):
    """A maintenance-plane node: segmented store, small segments so a
    handful of blocks spans several, tight checkpoint cadence."""
    kw.setdefault("store_path", store)
    kw.setdefault("store_segment_bytes", 400)
    kw.setdefault("snapshot_interval", 4)
    return _config(**kw)


async def _side_block(node) -> Block:
    """Forge and inject one valid side-branch block (one below the
    tip, so strictly less work) — a dead record for compaction."""
    chain = node.chain
    parent = chain._block_at(chain.main_hash_at(chain.height - 2))
    txs = (Transaction.coinbase(account("mallory"), chain.height - 1),)
    draft = BlockHeader(
        version=1,
        prev_hash=parent.block_hash(),
        merkle_root=merkle_root([t.txid() for t in txs]),
        timestamp=parent.header.timestamp + 61,
        difficulty=chain.difficulty,
        nonce=0,
    )
    sealed = _MINER.search_nonce(draft)
    blk = Block(sealed, txs)
    tip = chain.tip_hash
    await node._handle_block(blk, origin=None)
    assert chain.tip_hash == tip  # stayed a side branch
    assert blk.block_hash() in chain._index
    return blk


# -- version bits ---------------------------------------------------------


class TestVersionBits:
    def _vb(self, start=8, timeout=800):
        return VersionBits(
            (Deployment("feature-x", 0, start, timeout),),
            window=8,
            threshold=6,
        )

    def test_signals_requires_the_top_bits_tag(self):
        # Legacy version=1 has bit 0 SET but never signals: the
        # top-bits convention is what makes mixed meshes safe.
        assert not signals(1, 0)
        assert signals(TOP_BITS | 1, 0)
        assert not signals(TOP_BITS | 1, 1)
        assert not signals(0x60000001, 0)  # top bits 011, not 001
        assert not signals(TOP_BITS, 0)  # tagged but not signaling

    def test_empty_table_mines_literal_legacy_version(self):
        chain = _grow(Chain(1), 2)
        vb = VersionBits((), window=8, threshold=6)
        assert vb.mining_version(chain, chain.tip_hash) == 1
        assert vb.states_report(chain) == {}

    def test_ladder_walks_on_schedule_when_miners_signal(self):
        vb = self._vb()
        dep = vb.deployments[0]
        chain = Chain(1)
        # seen[tip] is the state governing block tip+1 (state_for_next
        # looks FORWARD): block 8 is the first STARTED one, 16 the
        # first LOCKED_IN, 24 the first ACTIVE.
        seen = {}
        for _ in range(33):
            _grow(chain, 1, version=lambda c: vb.mining_version(c, c.tip_hash))
            seen[chain.height] = vb.state_for_next(chain, chain.tip_hash, dep)
        assert seen[6] is VBState.DEFINED
        assert seen[7] is VBState.STARTED
        assert seen[14] is VBState.STARTED
        assert seen[15] is VBState.LOCKED_IN
        assert seen[22] is VBState.LOCKED_IN
        assert seen[23] is VBState.ACTIVE
        assert seen[33] is VBState.ACTIVE
        # The miner hook clears the signal bit once ACTIVE but keeps
        # the top-bits tag (future deployments share the field).
        assert vb.mining_version(chain, chain.tip_hash) == TOP_BITS

    def test_below_threshold_window_does_not_lock_in(self):
        vb = self._vb()
        dep = vb.deployments[0]
        chain = _grow(Chain(1), 7)  # window 0: DEFINED
        # STARTED window [8, 16): only 5 signaling < threshold 6.
        _grow(chain, 5, version=TOP_BITS | 1)
        _grow(chain, 3, version=1)
        assert chain.height == 15
        _grow(chain, 1, version=TOP_BITS | 1)
        assert vb.state_for_next(chain, chain.tip_hash, dep) is VBState.STARTED
        # The NEXT window carries 6: locks in at its boundary.
        _grow(chain, 5, version=TOP_BITS | 1)
        _grow(chain, 2, version=1)
        _grow(chain, 1, version=TOP_BITS | 1)
        assert chain.height == 24
        assert (
            vb.state_for_next(chain, chain.tip_hash, dep) is VBState.LOCKED_IN
        )

    def test_timeout_window_fails_the_deployment_permanently(self):
        vb = self._vb(start=8, timeout=24)
        dep = vb.deployments[0]
        chain = _grow(Chain(1), 22, version=1)  # nobody signals
        assert vb.state_for_next(chain, chain.tip_hash, dep) is VBState.STARTED
        # One more block: the next one (24) starts the timeout window.
        _grow(chain, 1, version=1)
        assert vb.state_for_next(chain, chain.tip_hash, dep) is VBState.FAILED
        # Even unanimous late signaling cannot resurrect it.
        _grow(chain, 16, version=TOP_BITS | 1)
        assert vb.state_for_next(chain, chain.tip_hash, dep) is VBState.FAILED
        assert vb.mining_version(chain, chain.tip_hash) == TOP_BITS

    def test_speedy_trial_threshold_beats_timeout_at_same_boundary(self):
        # A window that both crosses the timeout AND meets the
        # threshold locks in — the speedy-trial evaluation order.
        vb = self._vb(start=8, timeout=16)
        dep = vb.deployments[0]
        chain = _grow(Chain(1), 7)
        _grow(chain, 8, version=TOP_BITS | 1)
        assert chain.height == 15
        _grow(chain, 1, version=TOP_BITS | 1)
        assert (
            vb.state_for_next(chain, chain.tip_hash, dep) is VBState.LOCKED_IN
        )

    def test_states_report_shape(self):
        vb = self._vb()
        chain = _grow(Chain(1), 9, version=TOP_BITS | 1)
        rep = vb.states_report(chain)
        assert rep == {
            "feature-x": {
                "bit": 0,
                "start_height": 8,
                "timeout_height": 800,
                "state": "started",
            }
        }


# -- per-segment state deltas (.sdx) --------------------------------------


class TestStateDelta:
    def test_block_accounts_names_every_touched_account(self, tmp_path):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 2, label="alice")
                cb = node.chain.tip
                assert statedelta.block_accounts(cb) == {account("alice")}
                tag = node.chain.genesis.block_hash()
                from txutil import key_for

                tx = Transaction.transfer(
                    key_for("alice"), account("bob"), 2, 1, 0, chain=tag
                )
                await node.submit_tx(tx)
                await _mine(node, 1, label="carol")
                blk = node.chain.tip
                assert statedelta.block_accounts(blk) >= {
                    account("alice"),
                    account("bob"),
                    account("carol"),
                }
            finally:
                await node.stop()

        run(scenario())

    def test_summed_segment_deltas_equal_the_live_ledger(self, tmp_path):
        """Every segment's delta applied in order from the empty state
        reproduces the chain's exact balances and nonces — the property
        that lets an incremental snapshot build trust the sidecars."""

        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 6, label="alice")
                store = node.store
                store.roll_segment()
                balances: dict[str, int] = {}
                nonces: dict[str, int] = {}
                for seg in store.segments:
                    data = store._seg_path(seg).read_bytes()
                    d = statedelta.segment_delta(data)
                    balances, nonces = d.apply(balances, nonces)
                assert balances == node.chain.balances_snapshot()
            finally:
                await node.stop()

        run(scenario())

    def test_sidecar_roundtrip_and_malformation_tolerance(self, tmp_path):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 4, label="alice")
                node.store.roll_segment()
                seg = node.store.segments[0]
                data = node.store._seg_path(seg).read_bytes()
            finally:
                await node.stop()
            out = tmp_path / "seg0.sdx"
            written = statedelta.write_segment_delta(data, out)
            assert written.records >= 1
            loaded = statedelta.load_segment_delta(out)
            assert loaded == written
            # Malformation never raises — a bad sidecar is just absent
            # (the consumer recomputes from the segment).
            out.write_bytes(b"garbage")
            assert statedelta.load_segment_delta(out) is None
            out.write_bytes(statedelta.SDX_MAGIC + b"\x01")
            assert statedelta.load_segment_delta(out) is None
            assert statedelta.load_segment_delta(tmp_path / "nope.sdx") is None

        run(scenario())


# -- incremental snapshot builds ------------------------------------------


class TestIncrementalSnapshot:
    def _state(self, n=300):
        balances = {f"acct-{i:04d}": 10 + i for i in range(n)}
        nonces = {f"acct-{i:04d}": i % 7 for i in range(n)}
        return balances, nonces

    def test_cold_build_is_byte_identical_to_full(self):
        chain = _grow(Chain(1), 1)
        balances, nonces = self._state()
        full = snaplib.build_records(
            1, chain.tip, balances, nonces, chunk_accounts=16
        )
        m, chunks, inc, reused = snaplib.build_records_incremental(
            None, 1, chain.tip, balances, nonces, set(), chunk_accounts=16
        )
        assert (m, chunks) == full
        assert reused == 0
        assert len(inc.keys) == 300

    def test_warm_build_reuses_untouched_chunks_byte_identically(self):
        chain = _grow(Chain(1), 2)
        balances, nonces = self._state()
        _, _, inc, _ = snaplib.build_records_incremental(
            None, 1, chain.tip, balances, nonces, set(), chunk_accounts=16
        )
        # In-place mutations (no key-shift): exactly two chunks dirty.
        balances["acct-0007"] += 5
        nonces["acct-0200"] += 1
        dirty = {"acct-0007", "acct-0200"}
        full = snaplib.build_records(
            2, chain.tip, balances, nonces, chunk_accounts=16
        )
        m, chunks, inc2, reused = snaplib.build_records_incremental(
            inc, 2, chain.tip, balances, nonces, dirty, chunk_accounts=16
        )
        assert (m, chunks) == full
        assert reused == len(chunks) - 2

        # Create + destroy shift the key order: chunks at and past the
        # shift point re-encode, the result stays byte-identical.
        balances["newcomer"] = 42
        del balances["acct-0100"]
        nonces.pop("acct-0100", None)
        dirty = {"newcomer", "acct-0100"}
        full = snaplib.build_records(
            3, chain.tip, balances, nonces, chunk_accounts=16
        )
        m, chunks, inc3, reused = snaplib.build_records_incremental(
            inc2, 3, chain.tip, balances, nonces, dirty, chunk_accounts=16
        )
        assert (m, chunks) == full
        # Chunks wholly before the deletion point still reuse.
        assert reused >= 1
        assert "acct-0100" not in inc3.entries

    def test_oversized_dirty_set_costs_reuse_never_bytes(self):
        # Every account marked dirty, none actually changed: the build
        # must stay byte-identical, and the value re-check means the
        # too-big set costs per-account encodes, never chunk rebuilds.
        chain = _grow(Chain(1), 1)
        balances, nonces = self._state(50)
        _, _, inc, _ = snaplib.build_records_incremental(
            None, 1, chain.tip, balances, nonces, set(), chunk_accounts=16
        )
        m, chunks, _, reused = snaplib.build_records_incremental(
            inc, 1, chain.tip, balances, nonces,
            set(balances), chunk_accounts=16,
        )
        assert (m, chunks) == snaplib.build_records(
            1, chain.tip, balances, nonces, chunk_accounts=16
        )
        assert reused == len(chunks)
        # And when an oversized set hides ONE real change, exactly that
        # chunk re-encodes.
        balances["acct-0001"] += 7
        m, chunks, _, reused = snaplib.build_records_incremental(
            inc, 1, chain.tip, balances, nonces,
            set(balances), chunk_accounts=16,
        )
        assert (m, chunks) == snaplib.build_records(
            1, chain.tip, balances, nonces, chunk_accounts=16
        )
        assert reused == len(chunks) - 1

    def test_node_publishes_incrementally_and_cross_checks_root(
        self, tmp_path
    ):
        """The node's continuous publication: the second snapshot build
        reuses the first's residue, the published root matches the
        chain's recorded checkpoint root, and the dirty-set plumbing
        (collect + re-seed beyond the checkpoint) keeps it exact."""

        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 5)
                payload, chunks = node._snapshot_records()
                assert (
                    snaplib.parse_manifest(payload).state_root
                    == node.chain.state_checkpoints[4]
                )
                assert node.metrics.snapshot_incremental_builds == 1
                # Cache hit: the checkpoint has not moved.
                assert node._snapshot_records() == (payload, chunks)
                assert node.metrics.snapshot_incremental_builds == 1
                await _mine(node, 4, label="bob")
                payload2, chunks2 = node._snapshot_records()
                assert (
                    snaplib.parse_manifest(payload2).state_root
                    == node.chain.state_checkpoints[8]
                )
                assert node.metrics.snapshot_incremental_builds == 2
                # Byte-identity with a cold full build of the same
                # checkpoint state — incremental is never a format.
                h, block, balances, nonces, _root = (
                    node.chain.snapshot_state()
                )
                assert h == 8
                assert (payload2, chunks2) == snaplib.build_records(
                    h, block, balances, nonces
                )
            finally:
                await node.stop()

        run(scenario())


# -- live re-basing -------------------------------------------------------


class TestChainRebase:
    def _chain(self, blocks=10, interval=4):
        chain = Chain(1)
        chain.checkpoint_interval = interval
        return _grow(chain, blocks)

    def test_rebase_drops_history_keeps_ledger_and_tip(self):
        chain = self._chain(10)
        assert {4, 8} <= set(chain.state_checkpoints)
        tip = chain.tip_hash
        balances = chain.balances_snapshot()
        stats = chain.rebase(8)
        assert stats["old_base"] == 0 and stats["new_base"] == 8
        # Heights 0..7 left the index: genesis + 7 blocks.
        assert stats["dropped_blocks"] == 8
        assert chain.base_height == 8 and chain.height == 10
        assert chain.tip_hash == tip
        assert chain.balances_snapshot() == balances
        assert chain.main_hash_at(9) is not None
        assert chain.main_hash_at(7) is None
        assert min(chain.state_checkpoints) == 8
        # The chain keeps extending and checkpointing past the rebase.
        _grow(chain, 2)
        assert chain.height == 12 and 12 in chain.state_checkpoints

    def test_rebase_target_validation(self):
        chain = self._chain(10)
        with pytest.raises(ValueError, match="cadence"):
            chain.rebase(7)
        with pytest.raises(ValueError, match="outside"):
            chain.rebase(0)
        with pytest.raises(ValueError, match="outside"):
            chain.rebase(12)
        chain.state_checkpoints.pop(4)
        with pytest.raises(ValueError, match="no recorded state root"):
            chain.rebase(4)
        # A failed rebase left the chain untouched.
        assert chain.base_height == 0 and chain.height == 10

    def test_rebase_is_idempotent_about_the_base(self):
        chain = self._chain(10)
        chain.rebase(4)
        stats = chain.rebase(8)
        assert stats["old_base"] == 4 and stats["new_base"] == 8
        with pytest.raises(ValueError, match="outside"):
            chain.rebase(8)


class TestMaintainOps:
    """The node-level plane: every op through the same ``_maintain``
    entry the GETMAINTAIN wire frame and `p1 maintain` drive."""

    def test_live_rebase_then_node_keeps_mining_and_serving(
        self, tmp_path
    ):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 9, label="alice")
                r = await node._maintain({"op": "rebase", "keep": 4})
                assert r["ok"], r
                assert r["old_base"] == 0 and r["new_base"] == 4
                assert r["dropped_blocks"] >= 4
                assert node.chain.base_height == 4
                assert node.metrics.rebases == 1
                # The ledger and tip are untouched; the node mines on.
                assert (
                    node.chain.balance(account("alice")) == 9 * BLOCK_REWARD
                )
                await _mine(node, 3, label="bob")
                assert node.chain.height == 12
                # The spilled sidecar planes back the dropped history.
                sealed = [s for s in node.store.segments if s.sealed]
                assert sealed
                assert all(
                    node.store.hdrx_path(s).exists() for s in sealed
                )
                # status() reports through the maintenance block.
                maint = node.status()["maintenance"]
                assert maint["rebases"] == 1 and maint["base_height"] == 4
                assert maint["busy"] is None
            finally:
                await node.stop()

        run(scenario())

    def test_rebase_refuses_when_nothing_to_do(self, tmp_path):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 9, label="alice")
                assert (await node._maintain({"op": "rebase", "keep": 4}))[
                    "ok"
                ]
                r = await node._maintain({"op": "rebase", "keep": 8})
                assert not r["ok"] and "nothing to rebase" in r["error"]
                assert node.metrics.rebases == 1
            finally:
                await node.stop()

        run(scenario())

    def test_online_prune_discards_and_is_idempotent(self, tmp_path):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 10, label="alice")
                node.store.roll_segment()
                r = await node._maintain({"op": "prune", "keep": 2})
                assert r["ok"], r
                assert r["segments_pruned"] >= 1
                # The reply's floor is the EFFECTIVE one: segments
                # prune wholly, so it lands at or below the requested
                # min(10 - 2, checkpoint 8).
                assert 0 < r["floor"] <= 8
                assert node.chain.prune_floor == r["floor"]
                assert node.store.pruned_below == r["floor"]
                # Again: nothing further below the floor — ok, zero.
                r2 = await node._maintain({"op": "prune", "keep": 2})
                assert r2["ok"] and r2["segments_pruned"] == 0
                assert node.metrics.online_prunes == 2
                # Still serving: headers full-range, tip proofs live.
                locator = [node.chain.genesis.block_hash()]
                assert len(node.chain.headers_after(locator)) == 10
                tip_tx = node.chain.tip.txs[0]
                assert node.chain.tx_proof(tip_tx.txid()) is not None
            finally:
                await node.stop()

        run(scenario())

    def test_online_compact_drops_dead_records_only(self, tmp_path):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 6, label="alice")
                side = await _side_block(node)
                await _mine(node, 2, label="alice")
                # Seal everything so the dead record sits in a sealed
                # segment (compaction only rewrites sealed ones).
                assert (await node._maintain({"op": "rebase", "keep": 2}))[
                    "ok"
                ]
                before = node.chain.height
                r = await node._maintain({"op": "compact"})
                assert r["ok"], r
                assert r["records_dropped"] >= 1
                assert r["segments_compacted"] >= 1
                assert node.metrics.online_compactions == 1
                assert node.metrics.compaction_records_dropped >= 1
                # The node never stopped: chain intact, still mines.
                assert node.chain.height == before
                await _mine(node, 1, label="bob")
                # The dead record is gone from disk; the store reopens
                # clean (fsck finds the exact main-chain records).
            finally:
                await node.stop()
            reopened = Node(_mconfig(str(tmp_path / "c.dat")))
            await reopened.start()
            try:
                assert reopened.chain.height == 9
                assert (
                    reopened.chain.main_hash_at(reopened.chain.height)
                    is not None
                )
                assert side.block_hash() not in reopened.chain._index
            finally:
                await reopened.stop()

        run(scenario())

    def test_compact_without_dead_records_is_a_clean_noop(self, tmp_path):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 5, label="alice")
                r = await node._maintain({"op": "compact"})
                assert r["ok"] and r["segments_compacted"] == 0
                assert r["records_dropped"] == 0
            finally:
                await node.stop()

        run(scenario())

    def test_refusals_are_answers_never_disconnects(self, tmp_path):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 2, label="alice")
                cases = [
                    (["not", "a", "dict"], "must be an object"),
                    ({"op": "frobnicate"}, "unknown maintenance op"),
                    ({"op": None}, "unknown maintenance op"),
                    ({"op": "rebase", "keep": -1}, "non-negative"),
                    ({"op": "rebase", "keep": True}, "non-negative"),
                    ({"op": "prune", "keep": "4"}, "non-negative"),
                ]
                for command, needle in cases:
                    r = await node._maintain(command)
                    assert not r["ok"] and needle in r["error"], (
                        command,
                        r,
                    )
                # One op at a time: a busy plane refuses the second.
                node._maintenance_busy = "compact"
                r = await node._maintain({"op": "rebase", "keep": 0})
                assert not r["ok"] and "busy" in r["error"]
                node._maintenance_busy = None
                # status is always served, busy or not.
                node._maintenance_busy = "rebase"
                r = await node._maintain({"op": "status"})
                assert r["ok"] and r["busy"] == "rebase"
                node._maintenance_busy = None
                # No refusal cost the node its counters or its chain.
                assert node.metrics.rebases == 0
                assert node.chain.height == 2
            finally:
                await node.stop()

        run(scenario())

    def test_status_op_serves_the_full_report(self, tmp_path):
        async def scenario():
            node = Node(
                _mconfig(
                    str(tmp_path / "c.dat"),
                    deployments=(("feature-x", 0, 8, 800),),
                    vb_window=8,
                    vb_threshold=6,
                )
            )
            await node.start()
            try:
                await _mine(node, 1, label="alice")
                r = await node._maintain({"op": "status"})
                assert r["ok"] and r["busy"] is None
                vb = r["versionbits"]
                assert vb["window"] == 8 and vb["threshold"] == 6
                assert vb["deployments"]["feature-x"]["state"] == "defined"
                # And the mined header already carries the tagged
                # version (the deployment table changes what we mine).
                assert node.chain.tip.header.version == TOP_BITS
            finally:
                await node.stop()

        run(scenario())


# -- maintenance under disk faults ----------------------------------------


class TestMaintainFaults:
    def test_compact_planner_fault_degrades_and_self_cleans(
        self, tmp_path
    ):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 6, label="alice")
                await _side_block(node)
                await _mine(node, 2, label="alice")
                assert (await node._maintain({"op": "rebase", "keep": 2}))[
                    "ok"
                ]
                node.store.fail_next_compact = True
                r = await node._maintain({"op": "compact"})
                assert not r["ok"] and "planning failed" in r["error"]
                assert node._store_degraded
                assert node.metrics.online_compactions == 0
                # The partial tmp the fault landed mid-write is gone —
                # a failed compaction must never widen loss.
                seg_dir = node.store.seg_dir
                assert not list(seg_dir.glob("*.tmp"))
                # Degraded store: further maintenance refused, node up.
                r2 = await node._maintain({"op": "rebase", "keep": 2})
                assert not r2["ok"] and "degraded" in r2["error"]
                assert node.chain.height == 8
            finally:
                await node.stop()

        run(scenario())

    def test_sdx_sidecar_fault_is_tolerated_not_fatal(self, tmp_path):
        """A failed ``.sdx`` write at seal is a healed degradation —
        the delta recomputes from the segment — so a live rebase rides
        through it."""

        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 9, label="alice")
                before = node.store.healed["sdx_failures"]
                node.store.fail_next_sidecar = True
                r = await node._maintain({"op": "rebase", "keep": 4})
                assert r["ok"], r
                assert node.store.healed["sdx_failures"] == before + 1
                assert not node._store_degraded
                assert node.chain.base_height == 4
            finally:
                await node.stop()

        run(scenario())


# -- the live-attached replica --------------------------------------------


class TestReplicaAcrossMaintenance:
    def test_replica_serves_across_online_compaction(self, tmp_path):
        """A flock-free replica attached BEFORE an online compaction
        keeps serving after it — the segment files were rewritten
        underneath the mmap and the refresh path must re-pin them."""

        async def scenario():
            store = str(tmp_path / "c.dat")
            node = Node(_mconfig(store))
            await node.start()
            try:
                await _mine(node, 6, label="alice")
                view = ReplicaView(store, DIFF)
                try:
                    assert view.tip_height == 6
                    await _side_block(node)
                    await _mine(node, 2, label="alice")
                    assert (
                        await node._maintain({"op": "rebase", "keep": 2})
                    )["ok"]
                    r = await node._maintain({"op": "compact"})
                    assert r["ok"] and r["records_dropped"] >= 1
                    await _mine(node, 1, label="bob")
                    view.refresh()
                    assert view.tip_height == node.chain.height == 9
                    assert view.raw_header(9) == (
                        node.chain.tip.header.serialize()
                    )
                finally:
                    view.close()
            finally:
                await node.stop()

        run(scenario())

    def test_replica_refuses_loudly_once_the_node_prunes(self, tmp_path):
        """Online pruning under a live replica: the refresh must raise
        the pruned-store refusal — never silently serve a view with
        holes in it."""

        async def scenario():
            store = str(tmp_path / "c.dat")
            node = Node(_mconfig(store))
            await node.start()
            try:
                await _mine(node, 10, label="alice")
                node.store.roll_segment()
                view = ReplicaView(store, DIFF)
                try:
                    assert view.tip_height == 10
                    r = await node._maintain({"op": "prune", "keep": 2})
                    assert r["ok"] and r["segments_pruned"] >= 1
                    with pytest.raises(ValueError, match="pruned"):
                        view.refresh()
                finally:
                    view.close()
                # A FRESH attach refuses the same way.
                with pytest.raises(ValueError, match="pruned"):
                    ReplicaView(store, DIFF)
            finally:
                await node.stop()

        run(scenario())


# -- the wire -------------------------------------------------------------


class TestMaintainWire:
    def test_protocol_roundtrip(self):
        frame = protocol.encode_getmaintain({"op": "rebase", "keep": 4})
        mtype, body = protocol.decode(frame)
        assert mtype is MsgType.GETMAINTAIN
        assert body == {"op": "rebase", "keep": 4}
        frame = protocol.encode_maintain({"ok": True, "new_base": 8})
        mtype, body = protocol.decode(frame)
        assert mtype is MsgType.MAINTAIN
        assert body == {"ok": True, "new_base": 8}

    def test_client_maintain_end_to_end(self, tmp_path):
        async def scenario():
            node = Node(_mconfig(str(tmp_path / "c.dat")))
            await node.start()
            try:
                await _mine(node, 9, label="alice")
                r = await client_maintain(
                    "127.0.0.1", node.port, {"op": "status"}, DIFF
                )
                assert r["ok"] and r["busy"] is None
                assert r["base_height"] == 0
                r = await client_maintain(
                    "127.0.0.1",
                    node.port,
                    {"op": "rebase", "keep": 4},
                    DIFF,
                )
                assert r["ok"] and r["new_base"] == 4
                # A refusal travels the wire as an ANSWER; the session
                # (and the node's serving posture) survives to answer
                # the next query on a fresh connection.
                r = await client_maintain(
                    "127.0.0.1", node.port, {"op": "nope"}, DIFF
                )
                assert not r["ok"] and "unknown" in r["error"]
                r = await client_maintain(
                    "127.0.0.1", node.port, {"op": "status"}, DIFF
                )
                assert r["ok"] and r["base_height"] == 4
            finally:
                await node.stop()

        run(scenario())


class TestCadenceBench:
    """The bench.py maintenance probe (benchmarks/maintenance_cadence.py)
    against its perf_record.py pins: the metric names bench.py wires in
    must exist, and the O(delta) claim must actually show up as a >1
    incremental-over-full speedup even at a toy shape."""

    def test_quick_probe_keys_and_speedup(self):
        from benchmarks.maintenance_cadence import bench_quick

        out = bench_quick(accounts=2_000, delta=16, blocks=48)
        for key in (
            "snapshot_incr_builds_per_sec",
            "snapshot_full_builds_per_sec",
            "snapshot_cadence_speedup",
            "snapshot_chunks_reused",
            "rebase_ms",
            "rebase_dropped_blocks",
            "rebase_freed_bytes",
        ):
            assert key in out, key
        assert out["snapshot_cadence_speedup"] > 1.0
        assert out["rebase_dropped_blocks"] > 0
        assert out["rebase_ms"] < 1_000.0

    def test_pins_exist_and_are_sane(self):
        # The guard constants bench.py divides by: nonzero, right side
        # of the degraded comparison (fraction < 1 for rates, factor > 1
        # for latencies).
        from p1_tpu.hashx import perf_record as pr

        assert pr.RECORDED_SNAPSHOT_CADENCE_BPS > 0
        assert pr.RECORDED_REBASE_MS > 0
        assert 0 < pr.SNAPSHOT_CADENCE_DEGRADED_FRACTION < 1
        assert pr.REBASE_DEGRADED_FACTOR > 1
