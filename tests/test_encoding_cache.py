"""Memoization safety for the cached canonical encodings (PR: host
ingest fast path).

The frozen core types memoize their wire encodings and digests, and
``deserialize`` seeds those caches with the exact arrival bytes.  These
tests pin the three properties the zero-repack pipeline rests on:

1. **Fresh caches on derivation** — ``with_nonce``/``with_timestamp``/
   ``dataclasses.replace`` yield instances whose encodings and hashes
   are recomputed, never inherited (a stale cache here would let a miner
   reuse the parent's hash for a different nonce: consensus corruption).
2. **Round-trip byte identity** — serialize→deserialize→serialize is the
   identity for headers, transactions, and blocks, which is exactly the
   property that makes seeding the cache with wire bytes sound.
3. **Cache == recompute** — every cached encoding and digest is
   byte-identical to a from-scratch computation on an equal instance.
"""

from __future__ import annotations

import dataclasses

from p1_tpu.core.block import Block, merkle_root
from p1_tpu.core.header import BlockHeader
from p1_tpu.core.hashutil import sha256d
from p1_tpu.core.keys import Keypair
from p1_tpu.core.tx import Transaction

ALICE = Keypair.from_seed_text("cache-alice")


def _header(**overrides) -> BlockHeader:
    fields = dict(
        version=1,
        prev_hash=bytes(range(32)),
        merkle_root=bytes(32),
        timestamp=1735689700,
        difficulty=12,
        nonce=777,
    )
    fields.update(overrides)
    return BlockHeader(**fields)


def _signed_tx(seq=0) -> Transaction:
    return Transaction.transfer(ALICE, "bob", 5, 1, seq, chain=b"\x07" * 32)


def _block() -> Block:
    txs = (Transaction.coinbase("miner", 3), _signed_tx(0), _signed_tx(1))
    header = _header(merkle_root=merkle_root([tx.txid() for tx in txs]))
    return Block(header, txs)


class TestFreshCachesOnDerivation:
    def test_with_nonce_recomputes_encoding_and_hash(self):
        h = _header()
        h.serialize(), h.block_hash()  # populate the caches
        h2 = h.with_nonce(h.nonce + 1)
        pristine = _header(nonce=h.nonce + 1)
        assert h2.serialize() == pristine.serialize()
        assert h2.block_hash() == sha256d(pristine.serialize())
        assert h2.block_hash() != h.block_hash()

    def test_with_timestamp_recomputes(self):
        h = _header()
        h.serialize(), h.block_hash()
        h2 = h.with_timestamp(h.timestamp + 60)
        assert h2.serialize() == _header(timestamp=h.timestamp + 60).serialize()
        assert h2.block_hash() == sha256d(h2.serialize())

    def test_replace_never_inherits_cache_slots(self):
        h = _header()
        h.serialize(), h.block_hash()
        h2 = dataclasses.replace(h, difficulty=20)
        # Slotted (no instance dict): an unset cache slot reads as absent.
        assert getattr(h2, "_raw", None) is None
        assert getattr(h2, "_hash", None) is None
        tx = _signed_tx()
        tx.serialize(), tx.txid(), tx.signing_bytes()
        tx2 = dataclasses.replace(tx, fee=tx.fee + 1)
        assert "_raw" not in tx2.__dict__ and "_signing" not in tx2.__dict__
        assert tx2.txid() != tx.txid()

    def test_transfer_signing_path_is_cache_safe(self):
        # Transaction.transfer builds an unsigned tx (whose signing bytes
        # get cached by kp.sign's message computation) and then
        # `replace`s the signature in — the signed result must serialize
        # with the signature, not the unsigned cache.
        tx = _signed_tx()
        assert tx.sig and tx.pubkey
        reparsed = Transaction.deserialize(tx.serialize())
        assert reparsed.sig == tx.sig
        assert reparsed == tx


class TestRoundTripByteIdentity:
    def test_header(self):
        raw = _header().serialize()
        assert BlockHeader.deserialize(raw).serialize() == raw

    def test_transactions(self):
        for tx in (
            Transaction.coinbase("miner-α", 7),  # unicode recipient
            _signed_tx(3),
            Transaction("s", "r", 0, 0, 0),
        ):
            raw = tx.serialize()
            again = Transaction.deserialize(raw)
            assert again.serialize() == raw
            assert again == tx

    def test_block(self):
        raw = _block().serialize()
        assert Block.deserialize(raw).serialize() == raw

    def test_seeded_from_mutable_buffer_is_immutable(self):
        # A bytearray source must not leave the cache aliased to mutable
        # storage.
        raw = bytearray(_block().serialize())
        block = Block.deserialize(bytes(raw))
        before = block.serialize()
        raw[0] ^= 0xFF
        assert block.serialize() == before


class TestCacheMatchesRecompute:
    def test_header_digest(self):
        h = _header()
        raw = h.serialize()
        parsed = BlockHeader.deserialize(raw)
        assert parsed.block_hash() == sha256d(raw)
        assert parsed.block_hash() == _header().block_hash()
        assert parsed == h and hash(parsed) == hash(h)

    def test_txid_and_signing_bytes(self):
        tx = _signed_tx()
        parsed = Transaction.deserialize(tx.serialize())
        # Seeded caches vs fresh construction of an equal instance.
        fresh = Transaction(
            tx.sender,
            tx.recipient,
            tx.amount,
            tx.fee,
            tx.seq,
            tx.pubkey,
            tx.sig,
            tx.chain,
        )
        assert parsed.txid() == fresh.txid() == sha256d(fresh.serialize())
        assert parsed.signing_bytes() == fresh.signing_bytes()
        assert parsed.verify_signature() and fresh.verify_signature()

    def test_block_merkle_and_raw(self):
        block = _block()
        parsed = Block.deserialize(block.serialize())
        assert parsed.compute_merkle_root() == merkle_root(
            [tx.txid() for tx in block.txs]
        )
        assert parsed.serialize() == block.serialize()
        assert parsed.block_hash() == block.block_hash()

    def test_wire_tampering_still_detected(self):
        # The cache must never let a modified frame keep a stale (valid)
        # digest: a tampered byte shows up in the recomputed-from-seed
        # hash because the seed IS the tampered bytes.
        raw = bytearray(_header().serialize())
        raw[79] ^= 0x01  # flip a nonce bit
        tampered = BlockHeader.deserialize(bytes(raw))
        assert tampered.block_hash() == sha256d(bytes(raw))
        assert tampered.block_hash() != _header().block_hash()


class TestFastParseDifferential:
    """The deserialize hot paths build instances directly, trusting what
    the wire format structurally guarantees.  This fuzz pins the trust:
    every mutation either fails with ValueError or yields an instance
    that (a) re-serializes byte-identically and (b) passes the
    dataclass's own full ``__post_init__`` validation."""

    def test_transaction_mutation_fuzz(self):
        import random

        base = _signed_tx(3).serialize()
        rng = random.Random(0)
        parsed = 0
        for _ in range(1500):
            data = bytearray(base)
            for _ in range(rng.randrange(1, 4)):
                op = rng.random()
                if op < 0.4:
                    data[rng.randrange(len(data))] = rng.randrange(256)
                elif op < 0.7 and data:
                    del data[rng.randrange(len(data))]
                else:
                    data.insert(rng.randrange(len(data) + 1), rng.randrange(256))
            raw = bytes(data)
            try:
                tx = Transaction.deserialize(raw)
            except ValueError:
                continue
            parsed += 1
            assert tx.serialize() == raw
            Transaction(  # the validating constructor must agree
                tx.sender,
                tx.recipient,
                tx.amount,
                tx.fee,
                tx.seq,
                tx.pubkey,
                tx.sig,
                tx.chain,
            )
        assert parsed > 50  # the fuzz must actually exercise the accept path

    def test_header_mutation_fuzz(self):
        import random

        base = _header().serialize()
        rng = random.Random(1)
        for _ in range(500):
            data = bytearray(base)
            data[rng.randrange(len(data))] = rng.randrange(256)
            raw = bytes(data)
            try:
                h = BlockHeader.deserialize(raw)
            except ValueError:
                continue
            assert h.serialize() == raw
            BlockHeader(
                h.version,
                h.prev_hash,
                h.merkle_root,
                h.timestamp,
                h.difficulty,
                h.nonce,
            )


class TestPackedPlane:
    def test_pack_parse_round_trip(self):
        from p1_tpu.chain.replay import pack_headers, parse_headers

        headers = [_header(nonce=n) for n in range(5)]
        raw = pack_headers(headers)
        assert raw == b"".join(h.serialize() for h in headers)
        again = parse_headers(raw)
        assert again == headers
        assert pack_headers(again) == raw

    def test_pack_headers_mixed_cold_and_warm(self):
        from p1_tpu.chain.replay import pack_headers

        headers = [_header(nonce=n) for n in range(4)]
        headers[0].serialize()  # warm one, leave the rest cold
        assert pack_headers(headers) == b"".join(
            _header(nonce=n).serialize() for n in range(4)
        )

    def test_store_packed_headers_match_blocks(self, tmp_path):
        from p1_tpu.chain.replay import replay_packed
        from p1_tpu.chain.store import ChainStore, save_chain
        from p1_tpu.chain.chain import Chain
        from p1_tpu.core.genesis import make_genesis
        from p1_tpu.hashx import get_backend
        from p1_tpu.miner import Miner

        chain = Chain(1)
        miner = Miner(backend=get_backend("cpu"))
        for height in range(1, 6):
            parent = chain.tip
            draft = BlockHeader(
                1,
                parent.block_hash(),
                bytes(32),
                parent.header.timestamp + height,
                1,
                0,
            )
            sealed = miner.search_nonce(draft)
            chain.add_block(Block(sealed, ()))
        path = tmp_path / "snap.chain"
        save_chain(chain, path)
        raw, n = ChainStore(path).packed_headers()
        assert n == chain.height + 1
        assert raw == b"".join(
            b.header.serialize() for b in chain.main_chain()
        )
        report = replay_packed(raw)
        assert report.valid, report
        # Corrupt one header byte on disk: the v3 record checksum
        # excludes the damaged record at the framing layer, so the
        # packed buffer shrinks by one instead of carrying a lie.
        pristine = path.read_bytes()
        data = bytearray(pristine)
        # Flip a prev_hash byte of the LAST record (its payload starts
        # 84 bytes before the 4-byte CRC trailer: 80 header + u32 count).
        data[-84] ^= 0x01
        path.write_bytes(bytes(data))
        raw2, n2 = ChainStore(path).packed_headers()
        assert n2 == n - 1 and raw2 == raw[: 80 * (n - 1)]
        # Corruption the checksum CANNOT see (a hostile editor fixes the
        # CRC after flipping): the packed verify still pins it — the
        # layers are complementary, not redundant.  (A prev_hash flip
        # fails linkage deterministically, unlike a nonce flip, which
        # difficulty-1 PoW would often forgive.)
        import struct as _struct
        import zlib as _zlib

        from p1_tpu.chain.store import ChainStore as _CS

        data = bytearray(pristine)
        data[-84] ^= 0x01
        last_off, last_len = _CS.scan(bytes(pristine)).spans[-1]
        frame = bytes(data[last_off - 4 : last_off + last_len])
        data[last_off + last_len :] = _struct.pack(">I", _zlib.crc32(frame))
        path.write_bytes(bytes(data))
        raw3, n3 = ChainStore(path).packed_headers()
        assert n3 == n
        bad = replay_packed(raw3)
        assert not bad.valid and bad.first_invalid == n - 1
