"""Chain: validation, fork choice with reorg, orphans, replay, persistence."""

import pytest

from p1_tpu.chain import (
    AddStatus,
    Chain,
    ChainStore,
    ValidationError,
    check_block,
    generate_headers,
    replay_device,
    replay_host,
    save_chain,
)
from txutil import account, stx

from p1_tpu.core import Block, BlockHeader, Transaction, make_genesis, merkle_root
from p1_tpu.hashx import get_backend
from p1_tpu.miner import Miner

DIFF = 8  # cheap enough to mine dozens of blocks with hashlib
_MINER = Miner(backend=get_backend("cpu"))


def _mine_child(parent: Block, txs=(), ts_offset: int = 1, version: int = 1) -> Block:
    """Seal a valid child block of ``parent``."""
    header = BlockHeader(
        version=version,
        prev_hash=parent.block_hash(),
        merkle_root=merkle_root([tx.txid() for tx in txs]),
        timestamp=parent.header.timestamp + ts_offset,
        difficulty=parent.header.difficulty,
        nonce=0,
    )
    sealed = _MINER.search_nonce(header)
    assert sealed is not None
    return Block(sealed, tuple(txs))


@pytest.fixture(scope="module")
def chain_blocks():
    """Genesis + 3 mined main-chain blocks + a 5-block competing fork off
    genesis (mined once per module; chain state is rebuilt per test)."""
    genesis = make_genesis(DIFF)
    main = [genesis]
    for _ in range(3):
        main.append(_mine_child(main[-1]))
    fork = [genesis]
    for _ in range(5):
        # version=2 differentiates fork headers from main ones at h+1
        fork.append(_mine_child(fork[-1], version=2))
    return main, fork


class TestValidate:
    def test_valid_block_passes(self, chain_blocks):
        main, _ = chain_blocks
        check_block(main[1], DIFF)

    def test_wrong_difficulty(self, chain_blocks):
        main, _ = chain_blocks
        with pytest.raises(ValidationError, match="difficulty"):
            check_block(main[1], DIFF + 1)

    def test_bad_pow(self):
        genesis = make_genesis(DIFF)
        header = BlockHeader(
            1, genesis.block_hash(), bytes(32), genesis.header.timestamp + 1, DIFF, 0
        )
        # nonce 0 is (with overwhelming odds for this fixed header) not a hit
        from p1_tpu.core import meets_target

        assert not meets_target(header.block_hash(), DIFF)
        with pytest.raises(ValidationError, match="proof of work"):
            check_block(Block(header, ()), DIFF)

    def test_merkle_mismatch(self, chain_blocks):
        main, _ = chain_blocks
        tx = Transaction("a", "b", 1, 0, 0)
        forged = Block(main[1].header, (tx,))
        with pytest.raises(ValidationError, match="merkle"):
            check_block(forged, DIFF)

    def test_duplicate_txid_rejected(self):
        # CVE-2012-2459: [t1, t2, t3, t3] shares a merkle root with
        # [t1, t2, t3] (odd tail duplicated) -- the duplicate form must be
        # rejected even though the root matches.
        genesis = make_genesis(DIFF)
        t1 = Transaction("a", "b", 1, 0, 0)
        t2 = Transaction("c", "d", 2, 0, 0)
        t3 = Transaction("e", "f", 3, 0, 0)
        dup = (t1, t2, t3, t3)
        assert merkle_root([t.txid() for t in dup]) == merkle_root(
            [t.txid() for t in (t1, t2, t3)]
        )
        block = _mine_child(genesis, txs=dup)
        with pytest.raises(ValidationError, match="duplicate txid"):
            check_block(block, DIFF)

    def test_genesis_pow_waived(self):
        check_block(make_genesis(DIFF), DIFF, is_genesis=True)

    def test_coinbase_first_ok(self):
        genesis = make_genesis(DIFF)
        cb = Transaction.coinbase("miner-a", 1)
        tx = stx("a", "b", 1, 0, 0)
        check_block(_mine_child(genesis, txs=(cb, tx)), DIFF)

    def test_coinbase_not_first_rejected(self):
        genesis = make_genesis(DIFF)
        cb = Transaction.coinbase("miner-a", 1)
        tx = stx("a", "b", 1, 0, 0)
        block = _mine_child(genesis, txs=(tx, cb))
        with pytest.raises(ValidationError, match="coinbase"):
            check_block(block, DIFF)

    def test_coinbase_wrong_subsidy_rejected(self):
        # ADVICE r3 (medium): a hostile miner must not mint an arbitrary
        # reward — the coinbase amount is consensus-fixed.
        genesis = make_genesis(DIFF)
        cb = Transaction.coinbase("miner-a", 1, reward=10_000)
        block = _mine_child(genesis, txs=(cb,))
        with pytest.raises(ValidationError, match="subsidy"):
            check_block(block, DIFF)

    def test_unsigned_transfer_rejected(self):
        import dataclasses

        from p1_tpu.core.genesis import genesis_hash

        genesis = make_genesis(DIFF)
        # Right chain tag, no proof at all: the signature check must fire.
        naked = dataclasses.replace(
            Transaction("a", "b", 1, 0, 0), chain=genesis_hash(DIFF)
        )
        block = _mine_child(genesis, txs=(naked,))
        with pytest.raises(ValidationError, match="signature"):
            check_block(block, DIFF)

    def test_untagged_transfer_rejected(self):
        # A tx with no chain binding (or any foreign tag) is refused even
        # if its signature is internally valid — cross-chain replays die
        # here.
        from txutil import key_for

        genesis = make_genesis(DIFF)
        untagged = Transaction.transfer(key_for("a"), "b", 1, 0, 0)  # chain=b""
        block = _mine_child(genesis, txs=(untagged,))
        with pytest.raises(ValidationError, match="different chain"):
            check_block(block, DIFF)

    def test_cross_chain_replay_rejected(self):
        # A spend validly signed for the difficulty-12 chain, replayed
        # byte-identically on the difficulty-8 chain: rejected by tag.
        genesis = make_genesis(DIFF)
        foreign = stx("a", "b", 1, 0, 0, difficulty=12)
        assert foreign.verify_signature()  # internally valid...
        block = _mine_child(genesis, txs=(foreign,))
        with pytest.raises(ValidationError, match="different chain"):
            check_block(block, DIFF)  # ...but not for THIS chain

    def test_forged_sender_rejected(self):
        # mallory signs with HER key but claims alice's account as sender:
        # the fingerprint check must catch the mismatch.
        import dataclasses

        from txutil import account, key_for
        from p1_tpu.core.genesis import genesis_hash

        genesis = make_genesis(DIFF)
        mallory = key_for("mallory")
        theft = Transaction(
            account("alice"), mallory.account, 1, 0, 0, chain=genesis_hash(DIFF)
        )
        theft = dataclasses.replace(
            theft, pubkey=mallory.pubkey, sig=mallory.sign(theft.signing_bytes())
        )
        block = _mine_child(genesis, txs=(theft,))
        with pytest.raises(ValidationError, match="signature"):
            check_block(block, DIFF)

    def test_tampered_amount_rejected(self):
        # A validly signed tx whose amount is bumped after signing.
        import dataclasses

        genesis = make_genesis(DIFF)
        tampered = dataclasses.replace(stx("a", "b", 1, 0, 0), amount=40)
        block = _mine_child(genesis, txs=(tampered,))
        with pytest.raises(ValidationError, match="signature"):
            check_block(block, DIFF)

    def test_signed_coinbase_rejected(self):
        # Coinbases are minted by consensus, not spent by an owner — one
        # carrying key material is malformed.
        import dataclasses

        from txutil import key_for

        genesis = make_genesis(DIFF)
        key = key_for("miner")
        cb = Transaction.coinbase("miner-a", 1)
        cb = dataclasses.replace(
            cb, pubkey=key.pubkey, sig=key.sign(cb.signing_bytes())
        )
        block = _mine_child(genesis, txs=(cb,))
        with pytest.raises(ValidationError, match="unsigned"):
            check_block(block, DIFF)

    def test_two_coinbases_rejected(self):
        genesis = make_genesis(DIFF)
        cb1 = Transaction.coinbase("miner-a", 1)
        cb2 = Transaction.coinbase("miner-b", 1)
        block = _mine_child(genesis, txs=(cb1, cb2))
        with pytest.raises(ValidationError, match="coinbase"):
            check_block(block, DIFF)


class TestOrphanPool:
    """A hostile peer flooding unconnectable blocks must not grow memory:
    orphans need their own valid PoW to park, the pool is FIFO-capped, and
    re-received orphans are not double-parked."""

    def test_flood_is_bounded_and_chain_still_extends(self):
        import os

        from p1_tpu.chain.chain import MAX_ORPHANS

        diff = 2  # ~4 hashes per orphan: 10k mined orphans stay cheap
        chain = Chain(diff)
        miner = Miner(backend=get_backend("cpu"))
        for i in range(10_000):
            header = BlockHeader(1, os.urandom(32), bytes(32), i + 1, diff, 0)
            sealed = miner.search_nonce(header)
            assert sealed is not None
            res = chain.add_block(Block(sealed, ()))
            assert res.status is AddStatus.ORPHAN
        assert len(chain._orphan_hashes) <= MAX_ORPHANS
        assert len(chain._orphan_fifo) <= MAX_ORPHANS
        assert sum(len(v) for v in chain._orphans.values()) <= MAX_ORPHANS
        # the chain is unharmed: a legitimate child still connects
        child = _mine_child(chain.genesis)
        assert chain.add_block(child).status is AddStatus.ACCEPTED
        assert chain.height == 1

    def test_orphan_without_pow_rejected_not_parked(self):
        import os

        chain = Chain(20)
        header = BlockHeader(1, os.urandom(32), bytes(32), 1, 20, 0)
        res = chain.add_block(Block(header, ()))  # nonce 0: no PoW at d20
        assert res.status is AddStatus.REJECTED
        assert not chain._orphan_hashes

    def test_reparked_orphan_not_duplicated(self):
        import os

        diff = 2
        chain = Chain(diff)
        miner = Miner(backend=get_backend("cpu"))
        header = BlockHeader(1, os.urandom(32), bytes(32), 1, diff, 0)
        sealed = miner.search_nonce(header)
        orphan = Block(sealed, ())
        assert chain.add_block(orphan).status is AddStatus.ORPHAN
        res = chain.add_block(orphan)
        assert res.status is AddStatus.ORPHAN and res.reason == "already parked"
        assert len(chain._orphan_hashes) == 1
        assert sum(len(v) for v in chain._orphans.values()) == 1


class TestForkChoice:
    def test_linear_growth(self, chain_blocks):
        main, _ = chain_blocks
        chain = Chain(DIFF, genesis=main[0])
        for block in main[1:]:
            res = chain.add_block(block)
            assert res.status is AddStatus.ACCEPTED
            assert res.added == (block,)
            assert res.removed == ()
        assert chain.height == 3
        assert chain.tip == main[3]
        assert list(chain.main_chain()) == main

    def test_duplicate(self, chain_blocks):
        main, _ = chain_blocks
        chain = Chain(DIFF, genesis=main[0])
        chain.add_block(main[1])
        assert chain.add_block(main[1]).status is AddStatus.DUPLICATE

    def test_invalid_rejected(self, chain_blocks):
        main, _ = chain_blocks
        chain = Chain(DIFF, genesis=main[0])
        bad = Block(main[1].header, (Transaction("a", "b", 1, 0, 0),))
        res = chain.add_block(bad)
        assert res.status is AddStatus.REJECTED
        assert "merkle" in res.reason

    def test_shorter_fork_does_not_move_tip(self, chain_blocks):
        main, fork = chain_blocks
        chain = Chain(DIFF, genesis=main[0])
        for block in main[1:]:
            chain.add_block(block)
        res = chain.add_block(fork[1])  # height 1 vs tip height 3
        assert res.status is AddStatus.ACCEPTED
        assert not res.tip_changed
        assert chain.tip == main[3]

    def test_reorg_to_heavier_fork(self, chain_blocks):
        main, fork = chain_blocks
        chain = Chain(DIFF, genesis=main[0])
        for block in main[1:]:
            chain.add_block(block)
        # Strictly lighter fork blocks never move the tip.
        for block in fork[1:3]:
            assert not chain.add_block(block).tip_changed
        # Equal work at height 3: deterministic tie-break by smaller hash.
        chain.add_block(fork[3])
        expected_at_tie = min(main[3], fork[3], key=lambda b: b.block_hash())
        assert chain.tip == expected_at_tie
        # fork[4] is strictly heavier: tip must be fork[4] on every node.
        res = chain.add_block(fork[4])
        assert chain.tip == fork[4]
        assert chain.height == 4
        if expected_at_tie is main[3]:  # the reorg happened just now
            assert res.removed == tuple(reversed(main[1:]))
            assert res.added == tuple(fork[1:5])

    def test_equal_work_tiebreak_is_order_independent(self, chain_blocks):
        # Two nodes seeing the same blocks in different orders must agree.
        main, fork = chain_blocks
        a = Chain(DIFF, genesis=main[0])
        b = Chain(DIFF, genesis=main[0])
        blocks = main[1:4] + fork[1:4]
        for block in blocks:
            a.add_block(block)
        for block in reversed(blocks):
            b.add_block(block)
        assert a.tip_hash == b.tip_hash

    def test_connected_reports_cascaded_orphans(self, chain_blocks):
        # Persistence appends res.connected; it must include orphans the
        # triggering block unblocked, or restarts lose the chain suffix.
        main, _ = chain_blocks
        chain = Chain(DIFF, genesis=main[0])
        chain.add_block(main[2])  # orphan
        chain.add_block(main[3])  # orphan
        res = chain.add_block(main[1])
        assert res.connected == (main[1], main[2], main[3])
        plain = chain.add_block(_mine_child(main[3], ts_offset=99))
        assert len(plain.connected) == 1

    def test_orphan_then_connect(self, chain_blocks):
        main, _ = chain_blocks
        chain = Chain(DIFF, genesis=main[0])
        assert chain.add_block(main[2]).status is AddStatus.ORPHAN
        assert chain.add_block(main[3]).status is AddStatus.ORPHAN
        assert chain.height == 0
        res = chain.add_block(main[1])  # parent arrives: cascade connects
        assert res.status is AddStatus.ACCEPTED
        assert chain.height == 3
        assert res.added == tuple(main[1:])
        assert chain.tip == main[3]

    def test_locator_and_blocks_after(self, chain_blocks):
        main, _ = chain_blocks
        chain = Chain(DIFF, genesis=main[0])
        for block in main[1:]:
            chain.add_block(block)
        loc = chain.locator()
        assert loc[0] == chain.tip_hash
        assert loc[-1] == main[0].block_hash()
        peer = Chain(DIFF, genesis=main[0])
        missing = chain.blocks_after(peer.locator())
        assert missing == main[1:]


class TestReplay:
    @pytest.fixture(scope="class")
    def headers(self):
        return generate_headers(64, DIFF)

    def test_host_replay_valid(self, headers):
        report = replay_host(headers)
        assert report.valid and report.first_invalid is None
        assert report.n_headers == 64

    def test_device_replay_valid(self, headers):
        report = replay_device(headers, segment=16)
        assert report.valid, f"first invalid: {report.first_invalid}"

    def test_native_replay_matches_host(self, headers):
        # The C++ engine (config 3's native tier) agrees with the hashlib
        # oracle on a valid chain AND on the exact first-invalid index.
        from p1_tpu.chain import replay_native

        report = replay_native(headers)
        assert report.valid and report.first_invalid is None
        bad = list(headers)
        bad[37] = bad[37].with_nonce(bad[37].nonce ^ 1)
        host, native = replay_host(bad), replay_native(bad)
        assert not native.valid
        assert native.first_invalid == host.first_invalid == 37
        # Wrong difficulty field mid-chain is also caught (host parity).
        import dataclasses as dc

        bad2 = list(headers)
        bad2[30] = dc.replace(bad2[30], difficulty=DIFF + 1)
        assert (
            replay_native(bad2).first_invalid
            == replay_host(bad2).first_invalid
            == 30
        )

    def test_device_matches_host_on_corruption(self, headers):
        # Corrupt one nonce mid-chain: both paths must flag that index
        # (PoW breaks there, and linkage breaks at the next header).
        bad = list(headers)
        bad[37] = bad[37].with_nonce(bad[37].nonce ^ 1)
        host = replay_host(bad)
        device = replay_device(bad, segment=16)
        assert not host.valid and not device.valid
        assert host.first_invalid == device.first_invalid == 37

    def test_device_flags_broken_link(self, headers):
        bad = list(headers)
        # Re-mine header 21 onto the wrong parent (height 19's hash).
        draft = bad[21].with_nonce(0)
        import dataclasses as dc

        draft = dc.replace(draft, prev_hash=bad[19].block_hash())
        sealed = _MINER.search_nonce(draft)
        bad[21] = sealed
        host = replay_host(bad)
        device = replay_device(bad, segment=16)
        assert host.first_invalid == device.first_invalid == 21

    def test_partial_segment_padding(self, headers):
        # 64 headers with segment 24 -> final segment is 16 real + 8 pad.
        report = replay_device(headers, segment=24)
        assert report.valid

    def test_difficulty_field_corruption_flagged_by_both(self, headers):
        # A difficulty-0 field makes any hash "meet target" -- both paths
        # must still flag it (the declared difficulty is consensus data).
        import dataclasses as dc

        bad = list(headers)
        bad[41] = dc.replace(bad[41], difficulty=0)
        host = replay_host(bad)
        device = replay_device(bad, segment=16)
        assert host.first_invalid == device.first_invalid == 41


class TestPersistence:
    def test_roundtrip(self, chain_blocks, tmp_path):
        main, fork = chain_blocks
        store = ChainStore(tmp_path / "chain.dat")
        chain = Chain(DIFF, genesis=main[0])
        for block in main[1:] + fork[1:]:
            res = chain.add_block(block)
            if res.status is AddStatus.ACCEPTED:
                store.append(block)
        store.close()

        resumed = ChainStore(tmp_path / "chain.dat").load_chain(DIFF)
        assert resumed.tip_hash == chain.tip_hash
        assert resumed.height == chain.height
        assert len(resumed) == len(chain)  # side branches survive too

    def test_truncated_tail_recovers(self, chain_blocks, tmp_path):
        main, _ = chain_blocks
        path = tmp_path / "chain.dat"
        store = ChainStore(path)
        for block in main[1:]:
            store.append(block)
        store.close()
        raw = path.read_bytes()
        path.write_bytes(raw[:-7])  # crash mid-append
        resumed = ChainStore(path).load_chain(DIFF)
        assert resumed.height == 2  # last whole record survives

    def test_append_after_truncated_tail(self, chain_blocks, tmp_path):
        # Appending to a store with a garbage partial tail must first drop
        # the tail, or its stale length prefix poisons every later load.
        main, _ = chain_blocks
        path = tmp_path / "chain.dat"
        store = ChainStore(path)
        store.append(main[1])
        store.append(main[2])
        store.close()
        path.write_bytes(path.read_bytes()[:-7])  # crash mid-append of [2]
        store = ChainStore(path)
        store.append(main[2])
        store.append(main[3])
        store.close()
        resumed = ChainStore(path).load_chain(DIFF)
        assert resumed.height == 3
        assert resumed.tip_hash == main[3].block_hash()

    def test_save_chain_snapshot(self, chain_blocks, tmp_path):
        main, _ = chain_blocks
        chain = Chain(DIFF, genesis=main[0])
        for block in main[1:]:
            chain.add_block(block)
        save_chain(chain, tmp_path / "snap.dat")
        resumed = ChainStore(tmp_path / "snap.dat").load_chain(DIFF)
        assert list(resumed.main_chain()) == main

    def test_wrong_magic(self, tmp_path):
        path = tmp_path / "junk.dat"
        path.write_bytes(b"not a chain store")
        with pytest.raises(ValueError, match="not a chain store"):
            ChainStore(path).load_blocks()

    def test_append_fsyncs_every_block(self, chain_blocks, tmp_path, monkeypatch):
        # Durability contract (VERDICT r3 item 6): an acknowledged append
        # must survive OS crash, so fsync runs once per append — and the
        # fsync=False escape hatch really skips it.
        import os as os_mod

        main, _ = chain_blocks
        calls = []
        real_fsync = os_mod.fsync
        monkeypatch.setattr(
            "p1_tpu.chain.store.os.fsync",
            lambda fd: (calls.append(fd), real_fsync(fd))[1],
        )
        store = ChainStore(tmp_path / "sync.dat")
        store.append(main[1])
        store.append(main[2])
        store.close()
        assert len(calls) == 2
        store = ChainStore(tmp_path / "nosync.dat", fsync=False)
        store.append(main[1])
        store.close()
        assert len(calls) == 2  # unchanged


class TestLedger:
    def test_balances_over_mined_chain(self):
        from p1_tpu.chain import balances

        genesis = make_genesis(DIFF)
        alice, bob = account("alice"), account("bob")
        cb1 = Transaction.coinbase(alice, 1)
        b1 = _mine_child(genesis, txs=(cb1,))
        # alice pays bob 20 (fee 2) in a block mined by carol.
        cb2 = Transaction.coinbase("carol", 2)
        pay = stx("alice", bob, 20, 2, 0)
        b2 = _mine_child(b1, txs=(cb2, pay))
        ledger = balances([genesis, b1, b2])
        assert ledger[alice] == 50 - 20 - 2
        assert ledger[bob] == 20
        assert ledger["carol"] == 50 + 2  # reward + fees
        assert sum(ledger.values()) == 100  # rewards minted, fees conserved
        # The audit view agrees with the consensus ledger on a real chain.
        chain = Chain(DIFF, genesis=genesis)
        assert chain.add_block(b1).status is AddStatus.ACCEPTED
        assert chain.add_block(b2).status is AddStatus.ACCEPTED
        assert chain.balances_snapshot() == {
            a: v for a, v in ledger.items() if v
        }

    def test_coinbase_less_block_burns_fees(self):
        # Pure-view property on a hypothetical block sequence: the view
        # never rejects (consensus would - alice is unfunded), and a
        # coinbase-less block's fees are credited to nobody.
        from p1_tpu.chain import balances

        genesis = make_genesis(DIFF)
        alice, bob = account("alice"), account("bob")
        pay = stx("alice", bob, 5, 3, 0)
        b1 = _mine_child(genesis, txs=(pay,))
        ledger = balances([genesis, b1])
        assert ledger[alice] == -8 and ledger[bob] == 5
        assert sum(ledger.values()) == -3  # the fee is burned

    def test_cli_balances_from_store(self, tmp_path):
        import json as json_mod
        import subprocess
        import sys

        from p1_tpu.chain import Chain, save_chain

        genesis = make_genesis(DIFF)
        chain = Chain(DIFF, genesis=genesis)
        alice = account("alice")
        cb = Transaction.coinbase(alice, 1)
        chain.add_block(_mine_child(genesis, txs=(cb,)))
        store = tmp_path / "chain.dat"
        save_chain(chain, store)
        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "balances",
                "--store", str(store), "--difficulty", str(DIFF),
                "--account", alice,
            ],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json_mod.loads(proc.stdout.strip())
        assert out["balance"] == 50 and out["height"] == 1


class TestCrashRecovery:
    def test_sigkill_mid_mining_then_restart(self, tmp_path):
        """Real fault injection (SURVEY §5): SIGKILL a mining node process
        and restart on the same store — the log must replay to a valid
        chain (possibly minus a torn tail record) and keep growing."""
        import json
        import os
        import signal
        import subprocess
        import sys
        import time

        store = tmp_path / "crash.dat"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        repo_root = str(__import__("pathlib").Path(__file__).resolve().parents[1])
        cmd = [
            sys.executable, "-m", "p1_tpu", "node",
            "--port", "0", "--difficulty", "10", "--backend", "cpu",
            "--store", str(store), "--duration", "60",
        ]
        err_path = tmp_path / "node.err"
        with open(err_path, "w") as err_fh:
            proc = subprocess.Popen(
                cmd, env=env, cwd=repo_root,
                stdout=subprocess.DEVNULL, stderr=err_fh,
            )
            try:
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    if proc.poll() is not None:  # died at startup: fail fast
                        raise AssertionError(
                            f"node exited rc={proc.returncode}: "
                            f"{err_path.read_text()[-2000:]}"
                        )
                    if store.exists() and store.stat().st_size > 2000:
                        break
                    time.sleep(0.1)
                else:
                    raise AssertionError(
                        "node never persisted blocks: "
                        f"{err_path.read_text()[-2000:]}"
                    )
            finally:
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=30)

        # Restart on the possibly-torn store: it must resume and extend.
        out = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "node",
                "--port", "0", "--difficulty", "10", "--backend", "cpu",
                "--store", str(store), "--duration", "2",
            ],
            env=env, cwd=repo_root,
            capture_output=True, text=True, timeout=110,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        status = json.loads(out.stdout.strip().splitlines()[-1])
        assert status["height"] > 0
        # And the final store must audit clean.
        resumed = ChainStore(store).load_chain(10)
        assert resumed.height >= status["height"] - 1

    def test_store_mutation_fuzz_fails_closed(self, chain_blocks, tmp_path):
        """Arbitrary corruption of a store must degrade, not explode, on
        BOTH paths the node restart uses: ``acquire()`` (which converts
        corruption to RuntimeError or truncates the torn tail under the
        lock) and ``load_chain`` (which re-validates every surviving
        record).  Whatever loads must be a prefix-consistent valid chain."""
        import random as rnd

        main, fork = chain_blocks
        path = tmp_path / "fuzz.dat"
        store = ChainStore(path)
        for block in main[1:] + fork[1:]:
            store.append(block)
        store.close()
        seed_bytes = path.read_bytes()
        seed_height = ChainStore(path).load_chain(DIFF).height

        rng = rnd.Random(11)
        for _ in range(300):
            buf = bytearray(seed_bytes)
            op = rng.randrange(3)
            if op == 0:
                buf = buf[: rng.randrange(len(buf))]
            elif op == 1:
                buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            else:
                buf += bytes(rng.randrange(1, 16))
            path.write_bytes(bytes(buf))
            # Path 1: the node's restart sequence (lock + tail-truncate).
            writer = ChainStore(path)
            try:
                writer.acquire()
            except RuntimeError:
                writer.close()
                path.write_bytes(bytes(buf))  # undo any partial truncation
            else:
                writer.close()
            # Path 2: plain read-side load of whatever is on disk now.
            try:
                chain = ChainStore(path).load_chain(DIFF)
            except ValueError:
                continue  # fails closed
            # Whatever loaded must be internally consistent and no taller
            # than the uncorrupted original.
            assert chain.height <= seed_height
            assert len(list(chain.main_chain())) == chain.height + 1


class TestCompact:
    def test_cli_compact_drops_side_branches(self, tmp_path):
        import json as json_mod
        import subprocess
        import sys

        genesis = make_genesis(DIFF)
        main = [genesis]
        for _ in range(4):
            main.append(_mine_child(main[-1]))
        fork = _mine_child(genesis, version=2)  # loses fork choice
        store_path = tmp_path / "chain.dat"
        store = ChainStore(store_path)
        for block in [*main[1:3], fork, *main[3:]]:
            store.append(block)
        store.close()

        proc = subprocess.run(
            [
                sys.executable, "-m", "p1_tpu", "compact",
                "--store", str(store_path),
            ],
            capture_output=True,
            text=True,
            timeout=110,
            cwd="/root/repo",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json_mod.loads(proc.stdout.strip())
        assert out["height"] == 4
        assert out["records_before"] == 5  # 4 main + 1 fork (no genesis rec)
        assert out["records_after"] == 5  # genesis + 4 main
        # The compacted store reloads to the same tip, fork gone.
        reloaded = ChainStore(store_path).load_chain(DIFF)
        assert reloaded.tip_hash == main[-1].block_hash()
        assert len(reloaded) == 5


    def test_compact_refuses_locked_store(self, tmp_path):
        import subprocess
        import sys

        genesis = make_genesis(DIFF)
        store_path = tmp_path / "live.dat"
        writer = ChainStore(store_path)
        writer.append(_mine_child(genesis))  # holds the writer flock
        try:
            proc = subprocess.run(
                [
                    sys.executable, "-m", "p1_tpu", "compact",
                    "--store", str(store_path),
                ],
                capture_output=True,
                text=True,
                timeout=110,
                cwd="/root/repo",
            )
            assert proc.returncode == 2
            assert "locked by another process" in proc.stderr
        finally:
            writer.close()

    def test_second_writer_refused(self, tmp_path):
        genesis = make_genesis(DIFF)
        store_path = tmp_path / "one_writer.dat"
        a = ChainStore(store_path)
        a.append(_mine_child(genesis))
        b = ChainStore(store_path)
        try:
            with pytest.raises(RuntimeError, match="locked"):
                b.append(_mine_child(genesis, ts_offset=2))
        finally:
            a.close()


class TestForkChoiceProperty:
    """Randomized property test (SURVEY §5): for ANY block DAG delivered in
    ANY order, every node converges to the same tip, and that tip is the
    brute-force best (max cumulative work, lexicographically smallest hash
    on ties).  Exercises orphan parking, cascaded connects, and reorgs far
    beyond the hand-written cases."""

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_random_dag_converges_to_brute_force_best(self, seed):
        import random as rnd

        rng = rnd.Random(seed)
        diff = 2
        genesis = make_genesis(diff)
        blocks = [genesis]
        heights = {genesis.block_hash(): 0}
        for i in range(60):
            parent = rng.choice(blocks)
            # Distinct sibling blocks via a unique coinbase-style tx.
            tx = Transaction("coinbase", f"m{seed}", 50, 0, i)
            child = _mine_child(parent, txs=(tx,), ts_offset=rng.randint(1, 9))
            blocks.append(child)
            heights[child.block_hash()] = heights[parent.block_hash()] + 1

        # Brute-force best: max height (fixed difficulty => work ~ height),
        # tie-break smallest hash.
        best_h = max(heights.values())
        expect_tip = min(
            b.block_hash() for b in blocks if heights[b.block_hash()] == best_h
        )

        non_genesis = blocks[1:]
        tips = set()
        for trial in range(3):
            order = non_genesis[:]
            rng.shuffle(order)
            chain = Chain(diff, genesis=genesis)
            for block in order:
                chain.add_block(block)
            assert chain.height == best_h
            # Every block must have connected despite arbitrary order.
            assert len(chain) == len(blocks)
            # The height index must agree with the tip walk.
            main = list(chain.main_chain())
            assert len(main) == best_h + 1
            assert main[-1].block_hash() == chain.tip_hash
            tips.add(chain.tip_hash)
        assert tips == {expect_tip}


class TestTrustedResume:
    """The fast-resume path (VERDICT r4 weak #3): a node reloading its
    OWN flocked store skips the stateless checks it already ran before
    appending; the rebuilt state must be IDENTICAL to a full
    revalidation — tip, every balance, every nonce, side branches."""

    def test_trusted_equals_full_validation(self, tmp_path):
        store_path = tmp_path / "chain.dat"
        chain = Chain(DIFF)
        store = ChainStore(store_path)
        alice = account("alice")
        # A dozen blocks: coinbases to alice, signed spends, one fork.
        for h in range(1, 9):
            tip = chain.tip
            txs = [Transaction.coinbase(alice, h)]
            if h > 2:
                txs.append(
                    stx("alice", account("bob"), 2, 1, h - 3, difficulty=DIFF)
                )
            header = BlockHeader(
                1,
                tip.block_hash(),
                merkle_root([t.txid() for t in txs]),
                tip.header.timestamp + 1,
                DIFF,
                0,
            )
            sealed = _MINER.search_nonce(header)
            res = chain.add_block(Block(sealed, tuple(txs)))
            assert res.status is AddStatus.ACCEPTED
            store.append(chain.tip)
        # A surviving side branch too.
        fork_parent = chain.get(chain.tip.header.prev_hash)
        side = Block(
            _MINER.search_nonce(
                BlockHeader(
                    1,
                    fork_parent.block_hash(),
                    merkle_root([Transaction.coinbase("m2", 8).txid()]),
                    fork_parent.header.timestamp + 2,
                    DIFF,
                    0,
                )
            ),
            (Transaction.coinbase("m2", 8),),
        )
        assert chain.add_block(side).status is AddStatus.ACCEPTED
        store.append(side)
        store.close()

        full = ChainStore(store_path).load_chain(DIFF)
        fast = ChainStore(store_path).load_chain(DIFF, trusted=True)
        assert fast.tip_hash == full.tip_hash == chain.tip_hash
        assert fast.height == full.height
        assert len(fast) == len(full) == len(chain)  # side branch kept
        assert fast.balances_snapshot() == full.balances_snapshot()
        assert fast.nonce(alice) == full.nonce(alice) == chain.nonce(alice)

    def test_trusted_still_enforces_contextual_rules(self, tmp_path):
        """Trust covers only what this node already checked; contextual
        linking still runs, so a record stream from a DIFFERENT chain
        cannot silently graft on (the none-connected guard fires)."""
        other = Chain(DIFF + 1)
        b = Block(
            _MINER.search_nonce(
                BlockHeader(
                    1,
                    other.genesis.block_hash(),
                    merkle_root([Transaction.coinbase("m", 1).txid()]),
                    other.genesis.header.timestamp + 1,
                    DIFF + 1,
                    0,
                )
            ),
            (Transaction.coinbase("m", 1),),
        )
        assert other.add_block(b).status is AddStatus.ACCEPTED
        path = tmp_path / "foreign.dat"
        store = ChainStore(path)
        store.append(b)
        store.close()
        with pytest.raises(ValueError, match="do not connect"):
            ChainStore(path).load_chain(DIFF, trusted=True)
