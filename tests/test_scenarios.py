"""The scenario corpus (p1_tpu/node/scenarios.py) as a test suite.

Tier-1 (``sim`` marker) runs every scenario family at a mesh size real
sockets could never reach on this host — the flagship is a 200-node
partition-heal inside the ordinary timeout budget.  The ``slow`` set
carries the acceptance-scale runs: the 1000-node 600/400 partition-heal
(ISSUE 7's headline criterion) and the 500-joiner flash crowd.

Every scenario's ``ok`` already folds in its own invariants
(convergence, exact ledger conservation, containment metrics); the
tests re-assert the load-bearing ones explicitly so a failure names
what broke instead of just "ok was False".
"""

import pytest

from p1_tpu.node.scenarios import (
    churn_storm,
    eclipse,
    far_field,
    fee_spam,
    flash_crowd,
    partition_heal,
    retarget_shock,
    run_scenario,
    selfish_mining,
    snapshot_cartel,
    wan,
)

pytestmark = pytest.mark.sim


class TestPartitionHeal:
    def test_200_node_mesh_splits_heals_and_converges(self):
        """The tier-1 flagship: a 200-node mesh (≈28x the real-socket
        ceiling) splits 120/80, both sides mine their own chains, the
        cut heals, and every node converges on the majority tip with
        the ledger-sum invariant intact — in bounded VIRTUAL time."""
        r = partition_heal(nodes=200, seed=0)
        assert r["ok"], r
        assert r["tips_diverged"], "partition never actually diverged"
        assert r["converged"] and r["ledger_conserved"]
        assert r["heights"]["min"] == r["final_height"]
        # Every minority node lived on the minority chain and was
        # reorged back — mass fork-choice, not a lucky no-op.
        assert r["minority_nodes_reorged"] >= 0.9 * r["split"][1]
        assert r["heal_virtual_s"] <= 120.0

    @pytest.mark.slow
    def test_1000_node_acceptance_run(self):
        """ISSUE 7 acceptance: 1000 nodes, 600/400 split, heal,
        one tip + conserved ledgers in bounded virtual time, tier-1
        minutes of wall time (measured ~25 s here; the wall guard
        below is the regression tripwire, with wide CI margin)."""
        r = partition_heal(nodes=1000, seed=0)
        assert r["ok"], r
        assert r["split"] == [600, 400]
        assert r["minority_nodes_reorged"] == 400
        assert r["heal_virtual_s"] <= 120.0
        assert r["wall_s"] < 300.0


class TestFlashCrowd:
    def test_80_joiners_storm_one_seed(self):
        # 80 > MAX_PEERS(64): the cap regime, not a comfortable mesh.
        r = flash_crowd(joiners=80, chain_height=12, seed=0)
        assert r["ok"], r
        # The herd exceeded the seed's open slots and synced anyway —
        # through each other, which is the scenario's point.
        assert r["seed_capped"]
        assert r["heights"]["min"] == 12

    @pytest.mark.slow
    def test_500_joiners_acceptance_scale(self):
        r = flash_crowd(joiners=500, chain_height=20, seed=0)
        assert r["ok"], r
        assert r["seed_peer_count"] <= 64  # MAX_PEERS held under the herd
        assert r["heights"]["min"] == 20


class TestChurnStorm:
    def test_waves_of_restarts_still_converge(self):
        r = churn_storm(nodes=30, cycles=4, seed=0)
        assert r["ok"], r
        assert r["restarts"] > 0
        assert r["heights"]["min"] == r["final_height"]


class TestEclipse:
    def test_addr_flood_cannot_eclipse_the_victim(self):
        r = eclipse(honest=16, attackers=6, spam_rounds=20, seed=0)
        assert r["ok"], r
        # The round-4 defenses, named: gossip never reached the tried
        # bucket, the per-host budget clipped the flood to a trickle,
        # the book stayed bounded, and the victim kept following the
        # honest chain.
        assert r["tried_bucket_attacker_entries"] == 0
        assert r["new_bucket_attacker_entries"] < r["spam_addrs_sent"] / 10
        assert r["address_book_bounded"]
        assert r["victim_honest_links"] >= 1
        assert r["victim_followed_honest_tip"]


class TestWan:
    def test_asymmetric_geography_converges_and_is_visible(self):
        r = wan(region_nodes=6, blocks=6, seed=0)
        assert r["ok"], r
        # The latency model is load-bearing: measured propagation shows
        # at least one inter-region one-way latency.
        assert r["propagation_max_p95_ms"] >= r["min_inter_region_latency_ms"]


class TestWanSLOScope:
    """Round-17 satellite: the propagation SLO is never vacuously
    true.  Telemetry off + no bound ⇒ explicitly UNEVALUATED (and
    excluded from ok); telemetry off + an explicit bound ⇒ a loud
    error, because an unmeasurable bound must not pass."""

    def test_no_telemetry_marks_the_slo_unevaluated(self):
        r = wan(region_nodes=3, blocks=2, seed=1, telemetry=False)
        assert r["ok"], r
        assert r["propagation_slo"] == "unevaluated"
        assert r["propagation_bounded"] is None
        assert r["propagation_p95_bound_ms"] is None

    def test_explicit_bound_without_telemetry_fails_loudly(self):
        with pytest.raises(ValueError, match="unmeasurable"):
            wan(
                region_nodes=3,
                blocks=2,
                seed=1,
                telemetry=False,
                propagation_p95_bound_ms=1500.0,
            )

    def test_evaluated_run_names_its_state(self):
        r = wan(region_nodes=3, blocks=2, seed=1)
        assert r["ok"] and r["propagation_slo"] == "evaluated"
        assert r["propagation_bounded"] is True


class TestFarField:
    """Round-17 tentpole (a): the sharded 10k-node plane.  Tier-1
    carries the digest-invariance pairs at a few hundred nodes; the
    slow set carries the 10k acceptance run."""

    def test_shard_split_keeps_the_merged_digest(self):
        one = far_field(nodes=400, full_nodes=8, blocks=4, seed=0, shards=1)
        two = far_field(
            nodes=400, full_nodes=8, blocks=4, seed=0, shards=2,
            processes=False,
        )
        assert one["ok"], one
        assert one["far_converged_nodes"] == 392
        # THE invariance: the merged trace digest does not move with
        # the shard layout — and neither does anything else but wall_s.
        assert one["trace_digest"] == two["trace_digest"]
        for k in one:
            if k not in ("wall_s", "shards", "shard_processes", "repro"):
                assert one[k] == two[k], k

    def test_cross_process_shards_keep_the_merged_digest(self):
        one = far_field(nodes=400, full_nodes=8, blocks=4, seed=3, shards=1)
        procs = far_field(
            nodes=400, full_nodes=8, blocks=4, seed=3, shards=2,
            processes=True,
        )
        assert procs["shard_processes"]
        assert one["trace_digest"] == procs["trace_digest"]

    def test_settle_bound_is_load_bearing(self):
        r = far_field(
            nodes=400, full_nodes=8, blocks=4, seed=0,
            far_settle_bound_ms=0.001,
        )
        assert not r["ok"] and r["far_converged"]

    @pytest.mark.slow
    def test_10k_node_acceptance_run(self):
        """ISSUE 14 acceptance: the 10,000-node scenario completes in
        tier-1-adjacent wall time, and the merged trace digest is
        byte-identical at 1 shard vs N process shards (the in-process
        ×2 pair runs tier-1 above; the cross-process CLI pair under
        PYTHONHASHSEED lives in tests/test_cli.py)."""
        one = far_field(seed=0, shards=1)
        assert one["ok"], {k: one[k] for k in ("ok", "far_converged")}
        assert one["nodes"] == 10_000
        assert one["wall_s"] < 120.0
        sharded = far_field(seed=0, shards=4)
        assert sharded["ok"]
        assert sharded["trace_digest"] == one["trace_digest"]


class TestSelfishMining:
    def test_gamma0_mesh_contains_selfish_revenue(self):
        r = selfish_mining(honest=12, alpha=0.3, finds=80, seed=0)
        assert r["ok"], r
        # The attack really ran: blocks were withheld, overrides
        # reorged honest nodes.
        assert r["withheld_blocks"] > 0 and r["overrides"] >= 1
        assert r["honest_mesh_reorgs"] >= 1
        # Containment: at γ≈0 and α<1/3, selfish mining must not
        # amplify revenue beyond the bound...
        assert r["attacker_revenue_share"] <= r["revenue_share_bound"]
        # ...and on this seed it in fact UNDER-performs honest mining
        # (the Eyal–Sirer sub-threshold loss, realized in the mesh).
        assert r["attacker_revenue_share"] < r["actual_alpha"]

    def test_containment_bound_is_load_bearing(self):
        r = selfish_mining(
            honest=12, alpha=0.3, finds=80, seed=0, margin=-1.0
        )
        assert not r["ok"] and r["withheld_blocks"] > 0


class TestFeeSpam:
    def test_honest_traffic_never_starves_under_spam(self):
        r = fee_spam(nodes=8, spammers=3, honest_txs=12, seed=0, storm_vs=30.0)
        assert r["ok"], r
        # Every honest tx confirmed, inside the bound.
        assert r["honest_confirmed"] == r["honest_submitted"]
        assert r["honest_confirm_blocks_max"] <= r["confirm_bound_blocks"]
        # The flood was real and the layers each did their job: the
        # governor dropped frames at the door and scored the hosts,
        # and the spend limit capped what spam could ever mine.
        assert r["admission_tx_drops"] > 0
        assert r["spammers_scored"] >= 1
        assert r["spam_frames_sent"] > r["spam_budget_txs"]
        assert r["spam_txs_mined"] <= r["spam_budget_txs"]

    def test_confirm_bound_is_load_bearing(self):
        r = fee_spam(
            nodes=8, spammers=3, honest_txs=12, seed=0, storm_vs=30.0,
            confirm_bound_blocks=0,
        )
        assert not r["ok"] and r["honest_confirmed"] > 0


class TestRetargetShock:
    def test_hashrate_step_is_absorbed_within_the_clamp(self):
        r = retarget_shock(nodes=6, seed=0)
        assert r["ok"], r
        # The rule saw the shock and moved...
        assert r["responded"] and r["peak_difficulty"] >= r["base_difficulty"] + 2
        # ...every retarget stayed inside the clamp, at mesh level...
        assert r["retarget_clamp_held"]
        # ...overshoot and undershoot both clamp-bounded...
        assert r["overshoot_bits"] <= r["overshoot_bound_bits"]
        assert r["undershoot_bits"] <= r["max_adjust"]
        # ...and the difficulty returned to base once the shock passed.
        assert r["recovered"]

    def test_overshoot_bound_is_load_bearing(self):
        r = retarget_shock(nodes=6, seed=0, overshoot_bound_bits=-3)
        assert not r["ok"] and r["responded"]


class TestSnapshotCartel:
    def test_cartel_of_lying_servers_is_contained(self):
        r = snapshot_cartel(nodes=10, cartel=3, joiners=2, seed=0)
        assert r["ok"], r
        # Every joiner: lied to, diverged, never flipped, not fooled.
        assert r["divergences"] >= r["joiners"] and r["flips"] == 0
        assert r["fooled"] == 0
        assert r["cartel_servers_scored"] >= 1
        # And the honest mesh never lost its own history.
        assert r["honest_history_kept"]

    def test_capture_detector_is_load_bearing(self):
        # Hand the cartel a HEAVIER fork (majority work, which no
        # snapshot machinery can overrule) and drop the honest
        # response: the mesh is captured and the assertion says so.
        r = snapshot_cartel(
            nodes=10, cartel=3, joiners=2, seed=0,
            liar_height=16, honest_extra_blocks=0,
        )
        assert not r["ok"] and not r["honest_history_kept"]


class TestVersionActivation:
    def test_mixed_version_mesh_activates_without_forking(self):
        from p1_tpu.node.scenarios import version_activation

        r = version_activation(nodes=8, seed=0)
        assert r["ok"], r
        # The ladder walked on schedule: STARTED at the first full
        # window, LOCKED_IN one window later, ACTIVE one after that.
        assert r["ladder_ok"] and r["activation_height"] == 24
        assert r["ladder"]["8"] == "started"
        assert r["ladder"]["16"] == "locked_in"
        assert r["ladder"]["24"] == "active"
        # The mix was real: the straggler mined on BOTH sides of
        # activation with literal version=1 and everyone accepted it —
        # version is not consensus, so zero forks is the bound.
        assert r["straggler_blocks_pre_activation"] > 0
        assert r["straggler_blocks_post_activation"] > 0
        assert r["straggler_versions"] == ["0x00000001"]
        assert r["forks_observed"] == 0 and r["containment_held"]
        # Lock-in was earned, not gifted: the judged window carried
        # exactly threshold signaling headers (the straggler's legacy
        # headers in that window do NOT count — top-bits convention).
        assert r["signal_bit_in_started_window"] == r["vb_threshold"]
        # Post-ACTIVE the signal bit clears but top-bits stay.
        assert "0x20000000" in r["signaling_versions"]
        assert "0x20000001" in r["signaling_versions"]
        # Every signaling node reports active; the straggler has no
        # deployment table at all and agrees on the chain anyway.
        assert r["states_agree"]

    def test_no_fork_bound_is_load_bearing(self):
        from p1_tpu.node.scenarios import version_activation

        r = version_activation(nodes=8, seed=0, margin=-1)
        assert not r["ok"] and not r["containment_held"]
        # The control fails ONLY on the impossible bound — the mesh
        # itself still activated and converged.
        assert r["ladder_ok"] and r["converged"]


class TestRelayBudget:
    """Round 23: the flood-vs-reconciliation A/B over shaped uplinks.
    Tier-1 runs the 10-node quick shape (the same one bench.py pins);
    the slow set carries the 16-node acceptance run with its ≥5x
    budget at full storm scale."""

    def test_recon_beats_flood_on_bytes_and_latency(self):
        r = run_scenario(
            "relay-budget",
            nodes=10, senders=4, txs_per_sender=24, storm_vs=10.0,
            min_reduction=3.0, seed=0,
        )
        assert r["ok"], r
        # Both arms delivered the whole storm to every node.
        assert r["flood"]["delivered"] and r["recon"]["delivered"]
        # The headline pair: fewer bytes AND equal-or-better p95 —
        # efficiency was not bought with latency.
        assert r["reduction"] >= 3.0
        assert (
            r["recon"]["propagation"]["p95_ms"]
            <= r["flood"]["propagation"]["p95_ms"]
        )
        # The histograms are populated on both arms (telemetry is the
        # acceptance instrument, not a side channel).
        for arm in ("flood", "recon"):
            assert r[arm]["propagation"]["count"] == (
                r["total_txs"] * (r["nodes"] - 1)
            )
        # Reconciliation actually carried the recon arm: rounds ran,
        # succeeded, and the flood arm ran zero.
        assert r["recon"]["recon"]["success"] > 0
        assert r["flood"]["recon"]["rounds"] == 0

    def test_impossible_bound_control_fails(self):
        # The A/B must be falsifiable: an absurd reduction floor turns
        # the same healthy run into ok=False.
        r = run_scenario(
            "relay-budget",
            nodes=10, senders=4, txs_per_sender=24, storm_vs=10.0,
            min_reduction=1e9, seed=0,
        )
        assert not r["ok"]
        assert r["flood"]["delivered"] and r["recon"]["delivered"]

    @pytest.mark.slow
    def test_16_node_acceptance_run_holds_the_5x_budget(self):
        r = run_scenario("relay-budget", seed=0)
        assert r["ok"], r
        assert r["reduction"] >= 5.0
        assert (
            r["recon"]["propagation"]["p95_ms"]
            <= r["flood"]["propagation"]["p95_ms"]
        )


class TestReconProtocol:
    def test_over_capacity_burst_falls_back_to_flood(self):
        r = run_scenario("recon-fallback", seed=0)
        assert r["ok"], r
        # The burst overflowed at least one sketch, the fallback
        # flooded it, nobody was demoted for an honest overflow, and
        # the mesh still converged with the ledger conserved.
        assert r["recon_fallbacks"] >= 1
        assert r["recon_demotions"] == 0
        assert r["converged"] and r["ledger_conserved"]

    def test_sketch_poisoner_cannot_stall_honest_relay(self):
        r = run_scenario("recon-poison", seed=0)
        assert r["ok"], r
        # The poisoner got its shots in AND got demoted off the recon
        # plane; honest reconciliation kept succeeding throughout.
        assert r["poisoner_attacks"]["garbage_sketch"] >= 1
        assert r["victim_demotions"] >= 1
        assert r["honest_recon_success"] > 0
        assert r["converged"] and r["ledger_conserved"]

    def test_mixed_version_mesh_floods_until_activation(self):
        r = run_scenario("recon-mixed", seed=0)
        assert r["ok"], r
        # Phase A (pre-activation): flood was the dialect — zero
        # rounds.  Phase B (post-activation): rounds ran and the
        # deployment-less straggler still received everything.
        assert r["recon_rounds_pre_activation"] == 0
        assert r["recon_success_post_activation"] > 0
        assert r["activation_state"] == "active"


class TestRegistry:
    def test_run_scenario_dispatches_and_rejects_unknown(self):
        r = run_scenario("wan", region_nodes=3, blocks=2, seed=1)
        assert r["scenario"] == "wan"
        with pytest.raises(ValueError, match="unknown scenario"):
            run_scenario("nope")

    def test_every_report_is_stamped_for_repro(self):
        # Round-17 satellite: seed + trace digest + the exact repro
        # command, in EVERY scenario report.
        r = run_scenario("retarget-shock", nodes=5, seed=11)
        assert r["seed"] == 11
        assert r["trace_digest"]
        assert r["repro"] == "p1 sim retarget-shock --seed 11"
