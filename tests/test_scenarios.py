"""The scenario corpus (p1_tpu/node/scenarios.py) as a test suite.

Tier-1 (``sim`` marker) runs every scenario family at a mesh size real
sockets could never reach on this host — the flagship is a 200-node
partition-heal inside the ordinary timeout budget.  The ``slow`` set
carries the acceptance-scale runs: the 1000-node 600/400 partition-heal
(ISSUE 7's headline criterion) and the 500-joiner flash crowd.

Every scenario's ``ok`` already folds in its own invariants
(convergence, exact ledger conservation, containment metrics); the
tests re-assert the load-bearing ones explicitly so a failure names
what broke instead of just "ok was False".
"""

import pytest

from p1_tpu.node.scenarios import (
    churn_storm,
    eclipse,
    flash_crowd,
    partition_heal,
    run_scenario,
    wan,
)

pytestmark = pytest.mark.sim


class TestPartitionHeal:
    def test_200_node_mesh_splits_heals_and_converges(self):
        """The tier-1 flagship: a 200-node mesh (≈28x the real-socket
        ceiling) splits 120/80, both sides mine their own chains, the
        cut heals, and every node converges on the majority tip with
        the ledger-sum invariant intact — in bounded VIRTUAL time."""
        r = partition_heal(nodes=200, seed=0)
        assert r["ok"], r
        assert r["tips_diverged"], "partition never actually diverged"
        assert r["converged"] and r["ledger_conserved"]
        assert r["heights"]["min"] == r["final_height"]
        # Every minority node lived on the minority chain and was
        # reorged back — mass fork-choice, not a lucky no-op.
        assert r["minority_nodes_reorged"] >= 0.9 * r["split"][1]
        assert r["heal_virtual_s"] <= 120.0

    @pytest.mark.slow
    def test_1000_node_acceptance_run(self):
        """ISSUE 7 acceptance: 1000 nodes, 600/400 split, heal,
        one tip + conserved ledgers in bounded virtual time, tier-1
        minutes of wall time (measured ~25 s here; the wall guard
        below is the regression tripwire, with wide CI margin)."""
        r = partition_heal(nodes=1000, seed=0)
        assert r["ok"], r
        assert r["split"] == [600, 400]
        assert r["minority_nodes_reorged"] == 400
        assert r["heal_virtual_s"] <= 120.0
        assert r["wall_s"] < 300.0


class TestFlashCrowd:
    def test_80_joiners_storm_one_seed(self):
        # 80 > MAX_PEERS(64): the cap regime, not a comfortable mesh.
        r = flash_crowd(joiners=80, chain_height=12, seed=0)
        assert r["ok"], r
        # The herd exceeded the seed's open slots and synced anyway —
        # through each other, which is the scenario's point.
        assert r["seed_capped"]
        assert r["heights"]["min"] == 12

    @pytest.mark.slow
    def test_500_joiners_acceptance_scale(self):
        r = flash_crowd(joiners=500, chain_height=20, seed=0)
        assert r["ok"], r
        assert r["seed_peer_count"] <= 64  # MAX_PEERS held under the herd
        assert r["heights"]["min"] == 20


class TestChurnStorm:
    def test_waves_of_restarts_still_converge(self):
        r = churn_storm(nodes=30, cycles=4, seed=0)
        assert r["ok"], r
        assert r["restarts"] > 0
        assert r["heights"]["min"] == r["final_height"]


class TestEclipse:
    def test_addr_flood_cannot_eclipse_the_victim(self):
        r = eclipse(honest=16, attackers=6, spam_rounds=20, seed=0)
        assert r["ok"], r
        # The round-4 defenses, named: gossip never reached the tried
        # bucket, the per-host budget clipped the flood to a trickle,
        # the book stayed bounded, and the victim kept following the
        # honest chain.
        assert r["tried_bucket_attacker_entries"] == 0
        assert r["new_bucket_attacker_entries"] < r["spam_addrs_sent"] / 10
        assert r["address_book_bounded"]
        assert r["victim_honest_links"] >= 1
        assert r["victim_followed_honest_tip"]


class TestWan:
    def test_asymmetric_geography_converges_and_is_visible(self):
        r = wan(region_nodes=6, blocks=6, seed=0)
        assert r["ok"], r
        # The latency model is load-bearing: measured propagation shows
        # at least one inter-region one-way latency.
        assert r["propagation_max_p95_ms"] >= r["min_inter_region_latency_ms"]


class TestRegistry:
    def test_run_scenario_dispatches_and_rejects_unknown(self):
        r = run_scenario("wan", region_nodes=3, blocks=2, seed=1)
        assert r["scenario"] == "wan"
        with pytest.raises(ValueError, match="unknown scenario"):
            run_scenario("nope")
