"""Staged pipeline (node/pipeline.py, round 19): lanes, ordering, crashes.

Four planes of proof:

- **Lane mechanics** — inline (workers=0) calls have no awaits and the
  same results as staged calls; depth/byte accounting zeroes out;
  ``offload=True`` keeps a job off-loop even unstaged (the mempool
  checkpoint's historical ``to_thread`` contract).
- **Supervision** — an injected or real worker death respawns the lane,
  counts it, and retries the job once; a second death propagates.
- **Ordering property** — for randomized multi-peer mining
  interleavings (seeded, sim-clock), the victim's block CONNECT order
  is identical with staging on (1 worker) and off.  Under the virtual
  loop lane jobs complete synchronously (``SimLoop.run_in_executor``),
  so this holds by construction — the test pins the construction.
- **Digest contract** — the 200-node partition/heal scenario's trace
  digest is byte-identical with staging on and off, the same observer
  contract the telemetry determinism pair pins.
"""

from __future__ import annotations

import asyncio
import random
import threading

import pytest

from p1_tpu.node.netsim import SimNet
from p1_tpu.node.pipeline import (
    LANE_STAGES,
    STAGES,
    NodePipeline,
    WorkerCrash,
)

pytestmark = pytest.mark.staged


class TestLaneMechanics:
    def test_stage_inventory(self):
        assert STAGES == ("frame", "admission", "validate", "store", "relay")
        assert set(LANE_STAGES) <= set(STAGES)

    def test_inline_mode_runs_synchronously_with_no_awaits(self):
        """workers=0: the coroutine must complete without yielding —
        scheduling-identical to the historical inline node."""
        pipe = NodePipeline(workers=0)
        threads = []
        coro = pipe.run_validate(lambda x: threads.append(
            threading.current_thread().name) or x * 2, 21)
        # Drive the coroutine by hand: inline mode must finish on the
        # FIRST send, proving there is no await on the path.
        try:
            coro.send(None)
        except StopIteration as done:
            result = done.value
        else:  # pragma: no cover - the failure shape
            coro.close()
            pytest.fail("inline run_validate yielded (hidden await)")
        assert result == 42
        assert threads == [threading.current_thread().name]
        assert not pipe.staged and pipe.queued_bytes == 0

    def test_staged_mode_runs_on_the_lane_thread(self):
        pipe = NodePipeline(workers=1)
        try:
            names = {
                lane: asyncio.run(
                    getattr(pipe, f"run_{lane}")(
                        lambda: threading.current_thread().name
                    )
                )
                for lane in LANE_STAGES
            }
            assert names["validate"].startswith("p1-validate")
            assert names["store"].startswith("p1-store")
        finally:
            pipe.drain_and_close()
        assert not pipe.status()["validate_alive"]

    def test_offload_leaves_the_loop_even_unstaged(self):
        """The mempool-checkpoint contract: historically threaded via
        asyncio.to_thread, it must not regress ONTO the loop when
        staging is off."""
        pipe = NodePipeline(workers=0)
        name = asyncio.run(
            pipe.run_store(
                lambda: threading.current_thread().name, offload=True
            )
        )
        assert name != threading.current_thread().name

    def test_depth_and_bytes_account_in_flight_only(self):
        pipe = NodePipeline(workers=1)
        seen = {}

        def probe():
            # Sampled from the worker while the job is in flight.
            seen["depth"] = pipe.depths()["store"]
            seen["bytes"] = pipe.queued_bytes

        try:
            asyncio.run(pipe.run_store(probe, nbytes=4096))
        finally:
            pipe.drain_and_close()
        assert seen == {"depth": 1, "bytes": 4096}
        assert pipe.depths() == {"validate": 0, "store": 0}
        assert pipe.queued_bytes == 0

    def test_status_block_shape(self):
        pipe = NodePipeline(workers=2)
        try:
            status = pipe.status()
        finally:
            pipe.drain_and_close()
        assert status == {
            "workers": 2,
            "validate_depth": 0,
            "store_depth": 0,
            "queued_bytes": 0,
            "validate_alive": True,
            "store_alive": True,
        }


class TestSupervision:
    @pytest.mark.parametrize("workers", [0, 1])
    @pytest.mark.parametrize("stage", LANE_STAGES)
    def test_injected_death_respawns_counts_and_retries(
        self, stage, workers
    ):
        """fail_next fires in BOTH modes (the chaos injector relies on
        it under the inline sim) and the job itself must not be lost."""
        respawned = []
        pipe = NodePipeline(workers=workers, on_respawn=respawned.append)
        pipe.fail_next(stage)
        try:
            result = asyncio.run(
                getattr(pipe, f"run_{stage}")(lambda: "survived")
            )
        finally:
            pipe.drain_and_close()
        assert result == "survived"
        assert respawned == [stage]
        assert pipe._lanes[stage].respawns == 1

    def test_real_pool_death_is_a_worker_crash(self):
        """A lane whose executor died under it (the real-world shape:
        shutdown races, interpreter teardown) respawns and retries."""
        respawned = []
        pipe = NodePipeline(workers=1, on_respawn=respawned.append)
        pipe._lanes["store"].pool.shutdown(wait=True)
        try:
            result = asyncio.run(pipe.run_store(lambda: "persisted"))
        finally:
            pipe.drain_and_close()
        assert result == "persisted"
        assert respawned == ["store"]

    def test_second_consecutive_death_propagates(self):
        """Retry-once, not retry-forever: a job that kills its worker
        every time surfaces to the caller's error path."""
        pipe = NodePipeline(workers=0)

        def poison():
            raise WorkerCrash("again")

        with pytest.raises(WorkerCrash):
            asyncio.run(pipe.run_validate(poison))
        # One respawn happened (first crash), then the retry's crash
        # propagated without a second respawn cycle.
        assert pipe._lanes["validate"].respawns == 1


@pytest.mark.sim
class TestStagedNodeInSim:
    def test_lane_worker_death_mid_mesh_respawns_and_keeps_the_block(
        self, tmp_path
    ):
        """The node-level crash contract: a validate and a store worker
        death during block handling are respawned and counted
        (NodeMetrics.worker_respawns, the task_crashes lineage), and
        the block still connects AND persists."""
        net = SimNet(
            seed=3, difficulty=8, store_dir=tmp_path, pipeline_workers=1
        )

        async def main():
            node = await net.add_node("10.0.0.1")
            pipe = node.pipeline
            pipe.fail_next("validate")
            await net.mine_on(node, spacing_s=1.0)
            pipe.fail_next("store")
            await net.mine_on(node, spacing_s=1.0)
            assert node.chain.height == 2
            status = node.status()["pipeline"]
            assert status["worker_respawns"] == 2
            assert status["validate_alive"] and status["store_alive"]
            assert node.metrics.worker_respawns == 2
            await net.stop_all()

        net.run(main())
        # Both blocks survived the worker deaths onto disk: a fresh
        # resume sees the full chain.
        from p1_tpu.chain.segstore import open_store

        store = open_store(tmp_path / "10.0.0.1.dat", fsync=False)
        try:
            assert store.load_chain(8, trusted=True).height == 2
        finally:
            store.close()

    @staticmethod
    def _connect_order(seed: int, workers: int) -> tuple:
        """One randomized 3-miner interleaving against a victim node;
        returns the victim's exact block CONNECT order."""
        rng = random.Random(seed * 1000 + 17)
        plan = [
            (rng.randrange(3), rng.choice((0.0, 0.05, 0.2, 1.0)))
            for _ in range(12)
        ]
        net = SimNet(seed=seed, difficulty=8, pipeline_workers=workers)
        order: list[bytes] = []

        async def main():
            victim = await net.add_node("10.0.1.0")
            miners = [
                await net.add_node(f"10.0.1.{i + 1}", peers=["10.0.1.0"])
                for i in range(3)
            ]
            assert await net.run_until(net.links_up, 30, wall_limit_s=60)
            inner = victim.chain.add_block

            def spy(block):
                res = inner(block)
                order.extend(b.block_hash() for b in res.connected)
                return res

            victim.chain.add_block = spy
            for miner_idx, spacing in plan:
                await net.mine_on(miners[miner_idx], spacing_s=spacing)
            await net.run_until(lambda: False, 30, wall_limit_s=60)
            await net.stop_all()

        net.run(main())
        assert order, "the interleaving never reached the victim"
        return tuple(order)

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_staged_connect_order_equals_serial_connect_order(self, seed):
        """The ordering property the refactor must preserve: blocks
        from one mesh connect on the victim in IDENTICAL order with
        staging on (1 worker) and off — across randomized multi-peer
        mining interleavings (concurrent forks, reorgs, relay echo)."""
        assert self._connect_order(seed, 1) == self._connect_order(seed, 0)


@pytest.mark.sim
class TestStagingDigestContract:
    """The acceptance pin: the 200-node partition/heal trace digest is
    byte-identical with staging on (1 worker) and off — determinism by
    construction (SimLoop.run_in_executor), proven at mesh scale."""

    def test_200_node_digest_identical_staging_on_off(self):
        from p1_tpu.node.scenarios import partition_heal

        staged = partition_heal(nodes=200, seed=7, pipeline_workers=1)
        inline = partition_heal(nodes=200, seed=7, pipeline_workers=0)
        assert staged["ok"] and inline["ok"]
        assert staged["trace_digest"] == inline["trace_digest"]
