"""Difficulty retargeting: the rule math, genesis commitment, contextual
chain enforcement, persistence, and a live retargeting node.

Fixed difficulty (retarget=None) is the default everywhere and its
behavior is pinned by the rest of the suite; these tests cover the opt-in
rule — including that a chain with a different rule is a *different
chain* (distinct genesis), which is what keeps mixed networks impossible
rather than merely erroring late.
"""

import asyncio
import random

import pytest

from p1_tpu.chain import AddStatus, Chain, ChainStore
from p1_tpu.core import (
    Block,
    BlockHeader,
    RetargetRule,
    Transaction,
    make_genesis,
    merkle_root,
)
from p1_tpu.hashx import get_backend
from p1_tpu.miner import Miner

DIFF = 8
RULE = RetargetRule(window=4, spacing=100)
_MINER = Miner(backend=get_backend("cpu"))


def _child(parent: Block, difficulty: int, ts: int, txs=()) -> Block:
    header = BlockHeader(
        version=1,
        prev_hash=parent.block_hash(),
        merkle_root=merkle_root([tx.txid() for tx in txs]),
        timestamp=ts,
        difficulty=difficulty,
        nonce=0,
    )
    sealed = _MINER.search_nonce(header)
    assert sealed is not None
    return Block(sealed, tuple(txs))


def _extend(chain: Chain, n: int, dt: int) -> None:
    """Mine ``n`` blocks on the tip with ``dt`` seconds between blocks,
    always at the difficulty consensus asks for."""
    for _ in range(n):
        tip = chain.tip
        block = _child(
            tip, chain.next_difficulty(), tip.header.timestamp + dt
        )
        res = chain.add_block(block)
        assert res.status is AddStatus.ACCEPTED, res.reason


class TestRuleMath:
    def test_in_band_span_keeps_difficulty(self):
        # expected span = 100 * 3 = 300
        assert RULE.adjusted(10, 300) == 10
        assert RULE.adjusted(10, 151) == 10  # just above half
        assert RULE.adjusted(10, 599) == 10  # just below double

    def test_fast_blocks_raise_difficulty_bitwise(self):
        assert RULE.adjusted(10, 150) == 11  # span <= expected/2
        assert RULE.adjusted(10, 75) == 12  # span <= expected/4
        assert RULE.adjusted(10, 1) == 12  # clamped at max_adjust=2

    def test_slow_blocks_lower_difficulty_bitwise(self):
        assert RULE.adjusted(10, 600) == 9  # span >= 2x
        assert RULE.adjusted(10, 1200) == 8  # span >= 4x
        assert RULE.adjusted(10, 10_000_000) == 8  # clamped

    def test_range_clamps(self):
        assert RULE.adjusted(1, 10_000_000) == 1  # never below 1
        assert RULE.adjusted(255, 1) == 255  # never above 255
        assert RULE.adjusted(2, 10_000_000) == 1

    def test_parameter_validation(self):
        with pytest.raises(ValueError):
            RetargetRule(window=1, spacing=10)
        with pytest.raises(ValueError):
            RetargetRule(window=4, spacing=0)
        with pytest.raises(ValueError):
            RetargetRule(window=4, spacing=10, max_adjust=0)


class TestClampUnderStepShocks:
    """Round-17 satellite: the unit-level pin the retarget-shock
    scenario's mesh assertion rests on — ``adjusted`` at exact clamp
    boundaries, and the clamp holding through sustained step-shock
    span sequences (every per-window move ≤ max_adjust, convergence to
    the new equilibrium, no runaway in either direction)."""

    def test_exact_upward_boundaries(self):
        # expected span = 300; the rule moves a bit at span*2^k <= 300.
        assert RULE.adjusted(10, 151) == 10  # one over the 1-bit edge
        assert RULE.adjusted(10, 150) == 11  # exactly ON the edge
        assert RULE.adjusted(10, 76) == 11  # one over the 2-bit edge
        assert RULE.adjusted(10, 75) == 12  # exactly ON it
        # Past the max_adjust=2 clamp: 3-bit-deserving spans still get 2.
        assert RULE.adjusted(10, 37) == 12
        assert RULE.adjusted(10, 1) == 12

    def test_exact_downward_boundaries(self):
        assert RULE.adjusted(10, 599) == 10  # one under the 2x edge
        assert RULE.adjusted(10, 600) == 9  # exactly ON it
        assert RULE.adjusted(10, 1199) == 9
        assert RULE.adjusted(10, 1200) == 8  # exactly ON the 4x edge
        # 8x, 16x, ... still clamp to -2.
        assert RULE.adjusted(10, 2400) == 8
        assert RULE.adjusted(10, 1 << 40) == 8

    def test_degenerate_span_floors_at_one_second(self):
        # span <= 0 must not divide-by-zero or sign-flip the rule.
        assert RULE.adjusted(10, 0) == 12
        assert RULE.adjusted(10, -5) == 12

    @staticmethod
    def _drive(rule, d0, hashrate_by_window):
        """Pure-function mesh model: each window's observed span is
        what a steady ``h``-multiple hashrate produces at the window's
        difficulty (span = expected * 2^(d - d0) / h), fed back
        through ``adjusted`` — the scenario's dynamics without the
        mesh."""
        series = [d0]
        for h in hashrate_by_window:
            d = series[-1]
            span = max(1, round(rule.expected_span * (2.0 ** (d - d0)) / h))
            series.append(rule.adjusted(d, span))
        return series

    def test_step_up_shock_converges_within_clamp(self):
        rule = RetargetRule(window=8, spacing=8)  # max_adjust=2
        series = self._drive(rule, 10, [8] * 6)
        # Every per-window move inside the clamp.
        assert all(
            abs(b - a) <= rule.max_adjust
            for a, b in zip(series, series[1:])
        )
        # Converged to the +3-bit equilibrium, no overshoot past it.
        assert series[-1] == 13
        assert max(series) == 13

    def test_step_down_shock_converges_within_clamp(self):
        rule = RetargetRule(window=8, spacing=8)
        series = self._drive(rule, 10, [1 / 8] * 6)
        assert all(
            abs(b - a) <= rule.max_adjust
            for a, b in zip(series, series[1:])
        )
        assert series[-1] == 7 and min(series) == 7

    def test_square_wave_never_escapes_the_band(self):
        # Alternating 8x shocks up and down, many cycles: difficulty
        # must stay within max_adjust of the two equilibria forever —
        # bounded oscillation, not resonance.
        rule = RetargetRule(window=8, spacing=8)
        wave = ([8] * 4 + [1] * 4) * 6
        series = self._drive(rule, 10, wave)
        assert all(
            abs(b - a) <= rule.max_adjust
            for a, b in zip(series, series[1:])
        )
        assert max(series) <= 13 + rule.max_adjust
        assert min(series) >= 10 - rule.max_adjust

    def test_clamp_holds_at_the_difficulty_range_edges(self):
        rule = RetargetRule(window=8, spacing=8)
        # A sustained crash in hashrate walks down 2 bits per window
        # and parks at 1 — never 0 (every hash would be valid).
        series = self._drive(rule, 4, [1 / 1024] * 8)
        assert series[-1] == 1 and min(series) == 1
        # And a sustained boom parks at 255.
        series = self._drive(rule, 252, [1 << 20] * 8)
        assert series[-1] == 255 and max(series) == 255


class TestGenesisCommitment:
    def test_rule_changes_chain_identity(self):
        plain = make_genesis(DIFF)
        ruled = make_genesis(DIFF, RULE)
        other = make_genesis(DIFF, RetargetRule(window=8, spacing=100))
        assert plain.block_hash() != ruled.block_hash()
        assert ruled.block_hash() != other.block_hash()
        # Same parameters -> same chain, deterministically.
        assert ruled.block_hash() == make_genesis(DIFF, RULE).block_hash()

    def test_fixed_difficulty_genesis_unchanged(self):
        # retarget=None must keep every existing chain id stable.
        from p1_tpu.core.block import EMPTY_MERKLE_ROOT

        assert make_genesis(DIFF).header.merkle_root == EMPTY_MERKLE_ROOT


class TestChainEnforcement:
    def test_difficulty_steps_up_at_boundary(self):
        chain = Chain(DIFF, retarget=RULE)
        # Blocks 1..3 at base difficulty; block 4 opens a window.  One
        # second between blocks => span 3 vs expected 300 => +2 bits.
        _extend(chain, 3, dt=1)
        assert chain.next_difficulty() == DIFF + 2
        _extend(chain, 1, dt=1)
        assert chain.tip.header.difficulty == DIFF + 2
        # Mid-window: difficulty sticks to the parent's.
        assert chain.next_difficulty() == DIFF + 2

    def test_difficulty_steps_down_when_slow(self):
        chain = Chain(DIFF + 2, retarget=RULE)
        # span 1200 >= 4x expected (300); dt sits exactly at the
        # per-block forward cap of max_step * spacing = 400 s.
        _extend(chain, 3, dt=400)
        assert chain.next_difficulty() == DIFF

    def test_wrong_difficulty_rejected_contextually(self):
        chain = Chain(DIFF, retarget=RULE)
        _extend(chain, 3, dt=1)
        # Height 4 must carry DIFF+2; a miner claiming DIFF is rejected
        # even though DIFF is the chain's base difficulty.
        tip = chain.tip
        lazy = _child(tip, DIFF, tip.header.timestamp + 1)
        res = chain.add_block(lazy)
        assert res.status is AddStatus.REJECTED
        assert "required" in res.reason

    def test_non_monotonic_timestamp_rejected(self):
        chain = Chain(DIFF, retarget=RULE)
        _extend(chain, 1, dt=5)
        tip = chain.tip
        stale = _child(tip, chain.next_difficulty(), tip.header.timestamp)
        res = chain.add_block(stale)
        assert res.status is AddStatus.REJECTED
        assert "timestamp" in res.reason
        # Fixed-difficulty chains keep their historical tolerance.
        fixed = Chain(DIFF)
        b = _child(fixed.tip, DIFF, fixed.tip.header.timestamp)
        assert fixed.add_block(b).status is AddStatus.ACCEPTED

    def test_work_weighted_fork_choice_across_difficulties(self):
        # After a retarget to DIFF+2, one new-window block (4x work)
        # outweighs two more blocks mined on a stale pre-boundary parent.
        chain = Chain(DIFF, retarget=RULE)
        _extend(chain, 3, dt=1)
        fork_parent = chain.tip  # height 3
        heavy = _child(
            fork_parent, chain.next_difficulty(), fork_parent.header.timestamp + 1
        )
        # Stale branch: same parent, still mid-window difficulties...
        # there is no such thing — height 4 REQUIRES DIFF+2 on every
        # branch (pure function of ancestors).  So build the competing
        # branch from height 2 instead: its height-3 block is mid-window.
        h2 = chain.get(fork_parent.header.prev_hash)
        side3 = _child(h2, DIFF, h2.header.timestamp + 2)
        assert chain.add_block(side3).status is AddStatus.ACCEPTED
        side4 = _child(side3, DIFF + 2, side3.header.timestamp + 1)
        assert chain.add_block(side4).status is AddStatus.ACCEPTED
        res = chain.add_block(heavy)
        assert res.status is AddStatus.ACCEPTED
        # heavy (via fork_parent) and side4 tie on work; the hash
        # tie-break decides — what matters is that BOTH height-4 blocks
        # were forced to DIFF+2 and the index weighed them equally.
        assert chain.height == 4
        assert chain.tip.header.difficulty == DIFF + 2

    def test_difficulty_zero_orphan_rejected(self):
        # On a retargeting chain orphan parking checks PoW at the CLAIMED
        # difficulty; difficulty 0 passes that check vacuously, so it must
        # be refused outright — a free frame must not churn the pool.
        chain = Chain(DIFF, retarget=RULE)
        free = _child(
            make_genesis(99), 0, 1_800_000_000
        )  # unknown parent, d=0
        res = chain.add_block(free)
        assert res.status is AddStatus.REJECTED
        assert "no work" in res.reason

    def test_replay_host_verifies_retargeting_chains(self):
        import dataclasses

        from p1_tpu.chain import generate_headers, replay_host

        fast = RetargetRule(window=4, spacing=100)
        headers = generate_headers(13, DIFF, retarget=fast)
        # +1s spacing vs 100s target: +2 bits at heights 4, 8, 12.
        assert headers[12].difficulty == DIFF + 6
        assert replay_host(headers, retarget=fast).valid
        # The fixed-difficulty check would (wrongly for this chain) fail —
        # which is why `p1 replay` refuses non-host engines with a rule.
        assert replay_host(headers).first_invalid == 4
        # A header claiming the wrong difficulty is caught at its index...
        forged = list(headers)
        forged[9] = dataclasses.replace(headers[9], difficulty=DIFF)
        assert replay_host(forged, retarget=fast).first_invalid == 9
        # ...and so is a non-increasing timestamp.
        stale = list(headers)
        stale[9] = dataclasses.replace(
            headers[9], timestamp=headers[8].timestamp
        )
        assert replay_host(stale, retarget=fast).first_invalid == 9

    def test_store_round_trip_preserves_rule_chain(self):
        import tempfile
        from pathlib import Path

        chain = Chain(DIFF, retarget=RULE)
        _extend(chain, 6, dt=1)  # crosses one boundary
        assert chain.tip.header.difficulty == DIFF + 2
        with tempfile.TemporaryDirectory() as d:
            path = Path(d) / "chain.dat"
            store = ChainStore(path)
            for block in list(chain.main_chain())[1:]:
                store.append(block)
            store.close()
            loaded = ChainStore(path).load_chain(DIFF, retarget=RULE)
            assert loaded.tip_hash == chain.tip_hash
            assert loaded.next_difficulty() == chain.next_difficulty()
            # Without the rule the records are another chain's: nothing
            # connects, and load_chain refuses rather than silently
            # yielding an empty chain (the guard `p1 compact` relies on —
            # it would otherwise rewrite the store as a genesis-only
            # snapshot of the wrong chain).
            with pytest.raises(ValueError, match="do not connect"):
                ChainStore(path).load_chain(DIFF)


class TestRetargetProperty:
    """Randomized DAG property test (extends TestForkChoiceProperty to
    moving difficulty): for ANY block DAG obeying the retarget rule,
    delivered in ANY order, every node converges to the same tip, that
    tip is the brute-force max-cumulative-work block (2^difficulty per
    block — no longer equivalent to height!), and rule-violating blocks
    (wrong difficulty, frozen timestamps) never connect anywhere."""

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_random_dag_with_rule_converges(self, seed):
        import random as rnd

        rng = rnd.Random(seed)
        rule = RetargetRule(window=3, spacing=5, max_adjust=1)
        base = 2
        genesis = make_genesis(base, rule)
        ghash = genesis.block_hash()
        parents = {}
        heights = {ghash: 0}
        diffs = {ghash: base}
        stamps = {ghash: genesis.header.timestamp}
        works = {ghash: 1 << base}
        blocks = {ghash: genesis}

        def required(parent_hash: bytes) -> int:
            h = heights[parent_hash] + 1
            if h % rule.window != 0:
                return diffs[parent_hash]
            anchor = parent_hash
            for _ in range(rule.window - 1):
                anchor = parents[anchor]
            span = stamps[parent_hash] - stamps[anchor]
            return rule.adjusted(diffs[parent_hash], span)

        valid = []
        invalid = []
        for i in range(40):
            parent = rng.choice(list(blocks))
            d = required(parent)
            # Offsets straddle the 5 s target so the walk moves BOTH ways
            # (window span 2..24 vs expected 10: ±1 bit boundaries).
            ts = stamps[parent] + rng.randint(1, 12)
            tx = Transaction("coinbase", f"m{seed}", 50, 0, i)
            child = _child(blocks[parent], d, ts, txs=(tx,))
            ch = child.block_hash()
            parents[ch] = parent
            heights[ch] = heights[parent] + 1
            diffs[ch] = d
            stamps[ch] = ts
            works[ch] = works[parent] + (1 << d)
            blocks[ch] = child
            valid.append(child)
            if i % 9 == 0 and d >= 2:
                # A sibling claiming the WRONG difficulty (one bit easy —
                # only meaningful above the floor, where d-1 != d)...
                invalid.append(
                    _child(blocks[parent], d - 1, ts + 1, txs=(tx,))
                )
            if i % 13 == 0:
                # ...and one freezing its parent's timestamp.
                invalid.append(
                    _child(blocks[parent], d, stamps[parent], txs=(tx,))
                )

        best_work = max(works.values())
        expect_tip = min(
            h for h, w in works.items() if w == best_work
        )
        bad_hashes = {b.block_hash() for b in invalid}

        tips = set()
        for trial in range(3):
            order = valid + invalid
            rng.shuffle(order)
            chain = Chain(base, genesis=genesis, retarget=rule)
            for block in order:
                chain.add_block(block)
            # Every valid block connected; no invalid one ever did.
            assert len(chain) == 1 + len(valid)
            assert not any(h in chain for h in bad_hashes)
            # The tip is the brute-force most-work block, and the chain's
            # own next-difficulty agrees with an independent recomputation.
            tips.add(chain.tip_hash)
            assert chain.next_difficulty() == required(chain.tip_hash)
        assert tips == {expect_tip}


class TestRetargetingNode:
    def test_live_node_climbs_difficulty_and_serves_wallet(self):
        from test_node import _config, wait_until

        from p1_tpu.node import Node
        from p1_tpu.node.client import get_account

        # ms blocks but 50 s/block target => +2 bits every 5-block window.
        rule_kw = dict(retarget_window=5, target_spacing=50)

        async def scenario():
            node = Node(_config(difficulty=10, mine=True, **rule_kw))
            await node.start()
            try:
                assert await wait_until(lambda: node.chain.height >= 12)
                blocks = list(node.chain.main_chain())
                # Window 1 (heights 1-4) mines at the base difficulty; its
                # observed span includes the fixed-2025 genesis timestamp
                # vs. wall clock — enormous — so height 5 deterministically
                # retargets DOWN by the clamp (10 -> 8).  Exactly Bitcoin's
                # first-retarget-after-genesis behavior.
                assert [b.header.difficulty for b in blocks[1:6]] == [
                    10, 10, 10, 10, 8,
                ]
                # Every later block carries precisely what the rule asks
                # of its parent (re-derived from scratch here).
                probe = Chain(10, retarget=RetargetRule(window=5, spacing=50))
                for b in blocks[1:]:
                    assert b.header.difficulty == probe.next_difficulty()
                    assert probe.add_block(b).status is AddStatus.ACCEPTED
                # The wallet path agrees on the chain identity.
                state = await get_account(
                    "127.0.0.1",
                    node.port,
                    node.miner_id,
                    10,
                    retarget=RetargetRule(window=5, spacing=50),
                )
                assert state.balance > 0
                # ...and a fixed-difficulty client is refused outright.
                with pytest.raises(ValueError, match="genesis mismatch"):
                    await get_account(
                        "127.0.0.1", node.port, node.miner_id, 10
                    )
            finally:
                await node.stop()

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_two_retargeting_nodes_converge(self):
        from test_node import _config, stop_all, wait_until

        from p1_tpu.node import Node

        rule_kw = dict(retarget_window=5, target_spacing=50)

        async def scenario():
            a = Node(_config(difficulty=10, mine=True, **rule_kw))
            await a.start()
            b = Node(
                _config(
                    difficulty=10,
                    mine=True,
                    peers=(f"127.0.0.1:{a.port}",),
                    **rule_kw,
                )
            )
            await b.start()
            try:
                assert await wait_until(
                    lambda: a.chain.height >= 11 and b.chain.height >= 11
                )
                for node in (a, b):
                    await node.stop_mining()
                await a.request_sync()
                await b.request_sync()
                assert await wait_until(
                    lambda: a.chain.tip_hash == b.chain.tip_hash
                )
                blocks = list(a.chain.main_chain())
                # Both nodes enforced the genesis-gap retarget at height 5
                # (see the single-node test) while converging under it.
                assert blocks[5].header.difficulty == 8
            finally:
                await stop_all((a, b))

        asyncio.run(asyncio.wait_for(scenario(), timeout=60))

    def test_spv_proof_across_a_retarget_boundary(self):
        # A tx confirmed AFTER a difficulty move must still SPV-verify
        # when the verifier knows the chain retargets (the work bar is
        # the header's claimed difficulty), and must fail the strict
        # fixed-difficulty check — never the other way around.
        from p1_tpu.chain import SPVError, verify_tx_proof

        chain = Chain(DIFF, retarget=RULE)
        cb = None
        for _ in range(5):
            tip = chain.tip
            cb = Transaction.coinbase("miner", chain.height + 1)
            block = _child(
                tip,
                chain.next_difficulty(),
                tip.header.timestamp + 1,
                txs=(cb,),
            )
            assert chain.add_block(block).status is AddStatus.ACCEPTED
        assert chain.tip.header.difficulty == DIFF + 2  # boundary crossed
        proof = chain.tx_proof(cb.txid())
        assert proof is not None
        tag = chain.genesis.block_hash()
        verify_tx_proof(proof, DIFF, tag, retarget=RULE)
        with pytest.raises(SPVError, match="difficulty"):
            verify_tx_proof(proof, DIFF, tag)  # fixed-chain strictness

    def test_coinbase_txs_survive_retarget_boundaries(self):
        # The ledger/conservation machinery must be unaffected by moving
        # difficulty: coinbases at several difficulties, exact sum.
        from p1_tpu.core.tx import BLOCK_REWARD

        chain = Chain(DIFF, retarget=RULE)
        for i in range(9):
            tip = chain.tip
            cb = Transaction.coinbase("miner", chain.height + 1)
            block = _child(
                tip,
                chain.next_difficulty(),
                tip.header.timestamp + 1,
                txs=(cb,),
            )
            assert chain.add_block(block).status is AddStatus.ACCEPTED
        assert sum(chain.balances_snapshot().values()) == 9 * BLOCK_REWARD


class TestForwardDatingBound:
    """The time-warp hardening (VERDICT r4 weak #2): consensus caps the
    per-block timestamp increment at max_step * spacing, so forward-dated
    time must be accumulated block by block instead of claimed in one
    inflated window-closing stamp."""

    def test_height_one_anchors_clock_freely(self):
        """Genesis carries a fixed 2025 timestamp (chain identity), so
        block 1 must be allowed an arbitrary forward jump — it anchors
        the chain clock at the real bootstrap time.  Capped, the clock
        could never catch wall time and difficulty would ratchet to 1
        (observed live before the exemption)."""
        chain = Chain(DIFF, retarget=RULE)
        tip = chain.tip
        year_ahead = tip.header.timestamp + 365 * 86_400
        anchor = _child(tip, chain.next_difficulty(), year_ahead)
        assert chain.add_block(anchor).status is AddStatus.ACCEPTED

    def test_increment_above_cap_rejected_from_height_two(self):
        chain = Chain(DIFF, retarget=RULE)
        _extend(chain, 1, dt=1)  # height 1: the exempt clock anchor
        tip = chain.tip
        cap = RULE.max_increment  # max_step * spacing
        over = _child(tip, chain.next_difficulty(), tip.header.timestamp + cap + 1)
        res = chain.add_block(over)
        assert res.status is AddStatus.REJECTED and "cap" in res.reason
        at_cap = _child(tip, chain.next_difficulty(), tip.header.timestamp + cap)
        assert chain.add_block(at_cap).status is AddStatus.ACCEPTED

    def test_assemble_clamps_to_cap(self, monkeypatch):
        from p1_tpu.config import NodeConfig
        from p1_tpu.node import Node

        node = Node(
            NodeConfig(
                difficulty=DIFF,
                mine=False,
                retarget_window=RULE.window,
                target_spacing=RULE.spacing,
            )
        )
        class _Clock:
            """The node reads wall time ONLY through its clock seam
            (node/transport.py) — a runaway local clock is one field."""

            wall_now = 0.0

            def wall(self):
                return self.wall_now

            monotonic = wall

        clock = _Clock()
        monkeypatch.setattr(node, "clock", clock)
        # Height 1 (tip = genesis): the assembler must NOT clamp — it is
        # the bootstrap anchor that brings the chain clock to wall time.
        far = node.chain.tip.header.timestamp + 10 * RULE.max_increment
        clock.wall_now = far
        anchor = node._assemble()
        assert anchor.header.timestamp == far
        # From height 2 on, a runaway local clock is clamped to the cap.
        _extend(node.chain, 1, dt=1)
        tip_ts = node.chain.tip.header.timestamp
        clock.wall_now = tip_ts + 10 * RULE.max_increment
        block = node._assemble()
        assert block.header.timestamp == tip_ts + RULE.max_increment

    @staticmethod
    def _simulate(alpha: float, capped: bool, windows: int, seed: int,
                  rule: RetargetRule, d0: int) -> list[int]:
        """Difficulty trajectory of a chain under a lone forward-dating
        miner owning fraction ``alpha`` of the hashrate.

        Real block times are exponential with mean spacing * 2^(d - d0)
        (d0 = the difficulty matching the network's real hashrate).
        Honest miners stamp real time clamped into consensus bounds;
        the attacker always stamps the maximum the rules allow —
        parent + cap when capped, enough for a full max_adjust drop at a
        window close when not.  Uses the SAME RetargetRule.adjusted as
        consensus, so the simulation measures the deployed rule.
        """
        rng = random.Random(seed)
        cap = rule.max_increment
        d = d0
        chain_ts = 0.0  # last block's claimed time
        real = 0.0
        out = []
        for _ in range(windows):
            anchor = chain_ts
            for blk in range(rule.window):
                real += rng.expovariate(1.0) * rule.spacing * 2.0 ** (d - d0)
                if rng.random() < alpha:
                    if capped:
                        chain_ts = chain_ts + cap
                    else:
                        # One stamp buys the whole span needed for the
                        # maximum drop (plus slack) — the uncapped abuse.
                        want = anchor + (2 ** rule.max_adjust + 1) * rule.expected_span
                        chain_ts = max(chain_ts + 1, want)
                else:
                    honest = max(chain_ts + 1, real)
                    if capped:
                        honest = min(honest, chain_ts + cap)
                    chain_ts = honest
            span = int(chain_ts - anchor)
            d = rule.adjusted(d, span)
            out.append(d)
        return out

    def test_lone_attacker_bounded_with_cap_collapses_without(self):
        """The documented claims of core/retarget.py, measured: under
        the default cap (max_step=4) a quarter-hashrate forward-dating
        miner cannot hold difficulty below the honest equilibrium, while
        the SAME attacker — even at 10% — with the cap removed ratchets
        the chain to difficulty 1."""
        rule = RetargetRule(window=16, spacing=100)
        d0 = 20
        windows = 400
        for seed in (7, 23):
            # Honest baseline: equilibrium held within one bit.
            honest = self._simulate(0.0, True, windows, seed, rule, d0)
            assert min(honest) >= d0 - 1 and max(honest) <= d0 + 1
            # 25% attacker, capped: time-average within one bit of d0,
            # sustained excursions below d0 - max_adjust essentially
            # absent (random-walk dips only, <= 5% of windows).
            capped = self._simulate(0.25, True, windows, seed, rule, d0)
            assert sum(capped) / len(capped) >= d0 - 1
            below = sum(1 for d in capped if d < d0 - rule.max_adjust)
            assert below / len(capped) <= 0.05
            # 10% attacker, uncapped: total collapse — the attack the
            # cap exists to stop.
            uncapped = self._simulate(0.10, False, windows, seed, rule, d0)
            assert min(uncapped) == 1
            assert sum(uncapped) / len(uncapped) <= 5

    def test_near_majority_attacker_is_the_documented_limit(self):
        """The honest residual, asserted so the docs can't overclaim: a
        ~45% forward-dating miner DOES grind a capped chain down over
        many windows (per-window rate still clamped to max_adjust).
        That is the fundamental limit of wall-clock-free timestamping —
        at near-majority hashrate the chain is reorg-attackable anyway."""
        rule = RetargetRule(window=16, spacing=100)
        d0 = 20
        traj = self._simulate(0.45, True, 400, 11, rule, d0)
        drops = [b - a for a, b in zip(traj, traj[1:])]
        assert min(drops) >= -rule.max_adjust  # rate clamp holds
        assert sum(traj) / len(traj) < d0 - rule.max_adjust  # but it sinks

    def test_replay_host_enforces_forward_cap(self):
        """The light-client verifier applies the same forward-dating cap
        as connect-time consensus — a forward-dated header file must not
        verify for SPV/headers-first clients either."""
        from p1_tpu.chain import replay_host

        g = make_genesis(DIFF, RULE)
        b1 = _child(g, DIFF, g.header.timestamp + 1)
        good = _child(b1, DIFF, b1.header.timestamp + RULE.max_increment)
        bad = _child(b1, DIFF, b1.header.timestamp + RULE.max_increment + 1)
        assert replay_host(
            [g.header, b1.header, good.header], retarget=RULE
        ).valid
        report = replay_host(
            [g.header, b1.header, bad.header], retarget=RULE
        )
        assert not report.valid and report.first_invalid == 2

    def test_hostile_bootstrap_anchor_gets_orphaned_by_policy(self):
        """The height-1 exemption means a hostile first miner CAN stamp
        the far future (consensus accepts it) — the defense is mining
        POLICY: honest miners refuse to extend a tip stamped past their
        wall clock + cap, build from the last sane ancestor, and
        out-work the poisoned suffix."""
        import time as _time

        from p1_tpu.config import NodeConfig
        from p1_tpu.node import Node

        node = Node(
            NodeConfig(
                difficulty=DIFF,
                mine=False,
                retarget_window=RULE.window,
                target_spacing=RULE.spacing,
            )
        )
        g = node.chain.tip
        hostile = _child(
            g,
            node.chain.next_difficulty(),
            # ~70 years ahead: far past any wall clock, within the
            # header's u32 timestamp range.
            g.header.timestamp + 70 * 365 * 86_400,
        )
        assert node.chain.add_block(hostile).status is AddStatus.ACCEPTED
        assert node.chain.tip_hash == hostile.block_hash()
        # Policy: the assembler walks back to genesis, not the poison.
        parent = node._mining_parent()
        assert parent.block_hash() == g.block_hash()
        candidate = node._assemble()
        assert candidate.header.prev_hash == g.block_hash()
        # Its stamp is the real bootstrap anchor (height 1: no cap).
        assert abs(candidate.header.timestamp - int(_time.time())) < 5
        # Seal honest blocks on the sane branch until it out-works the
        # hostile one and the chain reorgs away from the poison.
        for _ in range(2):
            candidate = node._assemble()
            sealed = _MINER.search_nonce(candidate.header)
            assert sealed is not None
            res = node.chain.add_block(Block(sealed, candidate.txs))
            assert res.status is AddStatus.ACCEPTED, res.reason
        assert node.chain.tip_hash != hostile.block_hash()
        assert node.chain.tip.header.timestamp < hostile.header.timestamp
        assert node.chain.height == 2  # the honest branch won


class TestNativeRetargetReplay:
    """The C++ verification engine's retargeting form
    (p1_verify_chain_retarget): rule-for-rule parity with the host
    oracle on clean chains and on every single-field corruption —
    contextual difficulty schedule, PoW at the scheduled bar, linkage,
    and both timestamp rules."""

    def test_parity_with_host_oracle(self):
        import dataclasses

        from p1_tpu.chain import generate_headers, replay_host
        from p1_tpu.chain.replay import replay_native

        fast = RetargetRule(window=4, spacing=100)
        headers = generate_headers(12, DIFF, retarget=fast)
        assert replay_host(headers, retarget=fast).valid
        assert replay_native(headers, retarget=fast).valid
        # Every position x every field corruption: the two engines must
        # agree on the exact first-invalid index.
        for i in range(1, len(headers)):
            for mutate in (
                lambda h: dataclasses.replace(h, nonce=h.nonce ^ 1),
                lambda h: dataclasses.replace(
                    h, difficulty=h.difficulty + 1
                ),
                lambda h: dataclasses.replace(
                    h, timestamp=h.timestamp + 7
                ),
            ):
                mutated = [*headers]
                mutated[i] = mutate(mutated[i])
                host = replay_host(mutated, retarget=fast)
                native = replay_native(mutated, retarget=fast)
                assert not host.valid
                assert native.first_invalid == host.first_invalid, (
                    i,
                    host.first_invalid,
                    native.first_invalid,
                )

    def test_native_enforces_forward_cap_and_backdate(self):
        from p1_tpu.chain.replay import replay_host, replay_native

        g = make_genesis(DIFF, RULE)
        b1 = _child(g, DIFF, g.header.timestamp + 1)
        over = _child(
            b1, DIFF, b1.header.timestamp + RULE.max_increment + 1
        )
        chain_hdrs = [g.header, b1.header, over.header]
        host = replay_host(chain_hdrs, retarget=RULE)
        native = replay_native(chain_hdrs, retarget=RULE)
        assert host.first_invalid == native.first_invalid == 2
        # Backdated (non-increasing) header: same agreement.
        stale = _child(b1, DIFF, b1.header.timestamp)
        chain_hdrs = [g.header, b1.header, stale.header]
        host = replay_host(chain_hdrs, retarget=RULE)
        native = replay_native(chain_hdrs, retarget=RULE)
        assert host.first_invalid == native.first_invalid == 2
        # Height 1 anchor exemption holds natively too.
        far = _child(g, DIFF, g.header.timestamp + 50_000_000)
        ok = [g.header, far.header]
        assert replay_host(ok, retarget=RULE).valid
        assert replay_native(ok, retarget=RULE).valid

    def test_native_retarget_scales(self):
        from p1_tpu.chain import generate_headers
        from p1_tpu.chain.replay import replay_host, replay_native

        fast = RetargetRule(window=64, spacing=1)
        headers = generate_headers(2000, DIFF, retarget=fast)
        native = replay_native(headers, retarget=fast)
        assert native.valid
        # Relative, not wall-clock (a loaded CI box must not flake a
        # perf number): with the schedule on, the C engine still beats
        # the hashlib oracle measured under the same load.
        host = replay_host(headers, retarget=fast)
        assert native.elapsed_s < host.elapsed_s * 1.5, (native, host)

    def test_rule_upper_bounds(self):
        # Native-engine safety bounds (ring allocation, int64 span math).
        with pytest.raises(ValueError):
            RetargetRule(window=2_000_000_000, spacing=1)
        with pytest.raises(ValueError):
            RetargetRule(window=4, spacing=2**31)
