"""Set-reconciliation relay (round 23): codec properties, RECONCILE
wire frames, and the two-node exchange over the simulator.

The codec family is the load-bearing half: PinSketch over GF(2^32)
must round-trip EVERY difference size up to its capacity, DETECT (not
mis-decode) anything beyond it, and be a pure deterministic function
of the set — byte-identical sketches for identical sets is what makes
the XOR-combine algebra sound.  The wire tests pin the four frames'
encode/decode and their hostile-input rejections; the simulator tests
prove a reconciliation round actually moves a transaction between two
nodes with the flood path dark.
"""

import asyncio
import random

import pytest

from p1_tpu.node import protocol, reconcile
from p1_tpu.node.protocol import MsgType
from p1_tpu.node.reconcile import (
    MAX_CAPACITY,
    capacity_of,
    combine,
    decode,
    estimate_capacity,
    pair_salt,
    short_id,
    sketch,
)


def _ids(rng: random.Random, n: int, avoid=()) -> set[int]:
    out: set[int] = set()
    avoid = set(avoid)
    while len(out) < n:
        m = rng.randrange(1, 1 << 32)
        if m not in avoid:
            out.add(m)
    return out


class TestCodecProperties:
    def test_round_trips_every_difference_size_to_capacity(self):
        """For capacities across the range, a difference of EVERY size
        0..capacity decodes exactly — regardless of how much the two
        sets overlap (common elements cancel in the XOR)."""
        rng = random.Random(0xC0DEC)
        for cap in (1, 2, 3, 5, 8):
            for d in range(cap + 1):
                common = _ids(rng, rng.randrange(0, 20))
                diff = _ids(rng, d, avoid=common)
                mine = list(diff)[: d // 2]
                theirs = diff - set(mine)
                a = sketch(common | set(mine), cap)
                b = sketch(common | theirs, cap)
                got = decode(combine(a, b))
                assert got == tuple(sorted(diff)), (cap, d)

    def test_full_capacity_round_trip(self):
        # One full-width decode: 64 elements through a MAX_CAPACITY
        # sketch (the largest field-work a single honest round buys).
        rng = random.Random(0xF011)
        diff = _ids(rng, MAX_CAPACITY)
        got = decode(sketch(diff, MAX_CAPACITY))
        assert got == tuple(sorted(diff))

    def test_over_capacity_is_detected_not_misdecoded(self):
        """THE codec safety property: raw PinSketch hallucinates a
        small set whose syndromes match an over-full sketch; the
        reserved verification syndrome must turn every such case into
        None (the caller's flood-fallback signal), never a wrong set."""
        rng = random.Random(0x0F10)
        for cap in (1, 2, 4, 8):
            for extra in (1, 2, 5, 17):
                diff = _ids(rng, cap + extra)
                assert decode(sketch(diff, cap)) is None, (cap, extra)

    def test_identical_sets_sketch_byte_identical(self):
        rng = random.Random(0x1DE9)
        ids = list(_ids(rng, 12))
        base = sketch(ids, 8)
        for _ in range(3):
            rng.shuffle(ids)
            assert sketch(ids, 8) == base
        # ...and a different set differs (order-free, not content-free).
        other = list(_ids(rng, 12))
        assert sketch(other, 8) != base

    def test_combine_cancels_common_elements(self):
        rng = random.Random(0xCA7)
        common = _ids(rng, 30)
        only = _ids(rng, 2, avoid=common)
        a = sketch(common | only, 4)
        b = sketch(common, 4)
        assert decode(combine(a, b)) == tuple(sorted(only))
        # Identical sets cancel to the empty difference.
        assert decode(combine(a, a)) == ()

    def test_salt_separation(self):
        """Short IDs are salted per peer pair: both ends derive the
        same salt from the two HELLO nonces order-independently, no
        other pair shares it, and a txid maps to UNRELATED ids under
        different salts — a collision precomputed for one link buys
        nothing on any other."""
        assert pair_salt(7, 99) == pair_salt(99, 7)
        assert pair_salt(7, 99) != pair_salt(7, 98)
        txids = [bytes([k]) * 32 for k in range(40)]
        s1, s2 = pair_salt(1, 2), pair_salt(1, 3)
        ids1 = [short_id(s1, t) for t in txids]
        ids2 = [short_id(s2, t) for t in txids]
        assert ids1 != ids2
        assert all(i != 0 for i in ids1 + ids2)  # zero is not an element
        # Same salt, same txid -> same id (both ends must agree).
        assert ids1 == [short_id(s1, t) for t in txids]

    def test_estimate_capacity_is_sum_based_and_clamped(self):
        # Per-link pending queues are mostly DISJOINT (each side queued
        # what the other lacks), so the estimate is ls + rs + slack —
        # NOT Erlay's |ls - rs| overlap heuristic, which under-sized
        # sketches catastrophically here (module docstring).
        assert estimate_capacity(0, 0) == 2
        assert estimate_capacity(3, 4) == 9
        assert estimate_capacity(10, 10) == 22
        assert estimate_capacity(500, 500) == MAX_CAPACITY
        for ls in range(0, 12):
            for rs in range(0, 12):
                c = estimate_capacity(ls, rs)
                assert 1 <= c <= MAX_CAPACITY
                assert c >= min(ls + rs, MAX_CAPACITY)  # never undersized

    def test_sketch_validation(self):
        with pytest.raises(ValueError):
            sketch([1], 0)
        with pytest.raises(ValueError):
            sketch([1], MAX_CAPACITY + 1)
        with pytest.raises(ValueError):
            sketch([0], 4)  # zero is the additive identity
        with pytest.raises(ValueError):
            sketch([1 << 32], 4)  # outside the field
        assert capacity_of(sketch([1, 2], 4)) == 4
        with pytest.raises(ValueError):
            combine(b"\x00" * 8, b"\x00" * 12)  # length mismatch

    def test_decode_rejects_malformed_bytes(self):
        assert decode(b"") is None
        assert decode(b"\x00" * 4) is None  # below minimum (cap 1 = 8)
        assert decode(b"\x00" * 9) is None  # not whole words
        assert decode(b"\x00" * (4 * (MAX_CAPACITY + 2))) is None  # too big
        assert decode(b"\x00" * 8) == ()  # all-zero = empty difference
        # A corrupted sketch fails the re-sketch proof instead of
        # yielding some other plausible set.
        rng = random.Random(0xBAD)
        data = bytearray(sketch(_ids(rng, 3), 4))
        data[5] ^= 0x40
        assert decode(bytes(data)) is None


class TestReconcileFrames:
    def test_reqrecon_round_trip(self):
        mtype, got = protocol.decode(protocol.encode_reqrecon(17))
        assert mtype is MsgType.REQRECON and got == (False, 17)
        mtype, got = protocol.decode(protocol.encode_reqrecon(0, full=True))
        assert mtype is MsgType.REQRECON and got == (True, 0)

    def test_sketch_round_trip_and_bounds(self):
        data = sketch([5, 9], 8)
        mtype, (size, raw) = protocol.decode(protocol.encode_sketch(3, data))
        assert mtype is MsgType.SKETCH and size == 3 and raw == data
        with pytest.raises(ValueError):
            protocol.encode_sketch(3, data[:-1])  # torn word
        with pytest.raises(ValueError):
            protocol.encode_sketch(3, b"\x00" * 4)  # below capacity 1
        with pytest.raises(ValueError):  # over the decode-work clamp
            protocol.encode_sketch(
                3, b"\x00" * (4 * (protocol.MAX_SKETCH_WORDS + 1))
            )

    def test_recondiff_and_gettx_round_trip(self):
        ids = (1, 0xFFFFFFFF, 7)
        mtype, got = protocol.decode(protocol.encode_recondiff(True, ids))
        assert mtype is MsgType.RECONCILDIFF and got == (True, ids)
        mtype, got = protocol.decode(protocol.encode_recondiff(False))
        assert mtype is MsgType.RECONCILDIFF and got == (False, ())
        mtype, got = protocol.decode(protocol.encode_gettx(ids))
        assert mtype is MsgType.GETTX and got == ids

    def test_hostile_shapes_rejected(self):
        with pytest.raises(ValueError):
            protocol.encode_gettx(())  # empty fetch is meaningless
        with pytest.raises(ValueError):
            protocol.encode_gettx(range(1, protocol.MAX_RECON_IDS + 2))
        # Hand-built frames with out-of-contract fields must raise (the
        # peer loop scores them), never mis-parse.
        for payload in (
            bytes([MsgType.REQRECON, 2]) + b"\x00" * 4,  # bad full flag
            bytes([MsgType.REQRECON]) + b"\x00" * 3,  # short
            bytes([MsgType.SKETCH]) + b"\x00\x00\x00\x03\x00\x01" + b"\x00" * 4,
            bytes([MsgType.SKETCH])
            + b"\x00\x00\x00\x03\x04\x00"  # word count over the clamp
            + b"\x00" * 4096,
            bytes([MsgType.RECONCILDIFF, 1, 0x00, 0x02]) + b"\x00" * 4,
            bytes([MsgType.GETTX, 0x00, 0x00]),  # empty GETTX
            bytes([MsgType.GETTX, 0xFF, 0xFF]) + b"\x00" * 8,  # n lies
        ):
            with pytest.raises(ValueError):
                protocol.decode(payload)


@pytest.mark.sim
class TestTwoNodeExchange:
    def test_round_moves_a_tx_without_flooding_it(self):
        """Two reconciling nodes, flood spine off: a submitted tx must
        reach the other node THROUGH a reconciliation round (REQRECON/
        SKETCH/RECONCILDIFF then an explicit GETTX fetch), with the
        recon byte families charged and txs_reconciled counting the one
        serve."""
        from p1_tpu.core.genesis import genesis_hash
        from p1_tpu.core.keys import Keypair
        from p1_tpu.core.tx import Transaction
        from p1_tpu.node.netsim import SimNet

        net = SimNet(seed=11, difficulty=8)

        async def main():
            a = await net.add_node(
                recon_gossip=True,
                recon_interval_s=0.2,
                recon_flood_degree=0,
                miner_id="pool",
            )
            b = await net.add_node(
                peers=[net.host_name(0)],
                recon_gossip=True,
                recon_interval_s=0.2,
                recon_flood_degree=0,
            )
            assert await net.run_until(net.links_up, 30, step=0.1)
            w = Keypair.from_seed_text("p1-recon-pair")
            a.miner_id = w.account
            await net.mine_on(a, spacing_s=1.0)
            assert await net.run_until(
                lambda: b.chain.height == 1, 30, step=0.1
            )
            tx = Transaction.transfer(
                w, "p1-payee", 1, 1, 0, chain=genesis_hash(8)
            )
            await a.submit_tx(tx)
            assert await net.run_until(
                lambda: tx.txid() in b.mempool, 30, step=0.1
            ), "tx never crossed the reconciliation-only link"
            assert a.metrics.recon_success + b.metrics.recon_success >= 1
            assert a.metrics.txs_reconciled == 1  # served exactly once
            relay = a.metrics.relay_bytes()
            assert relay.get("recon", 0) > 0  # the exchange was charged
            assert b.metrics.relay_bytes().get("recon", 0) > 0

        net.run(main())

    def test_flood_stays_the_dialect_when_recon_is_off(self):
        """Negative control: identical pair with recon off moves the
        same tx with ZERO recon rounds and zero recon bytes — the
        pre-round-23 path is untouched."""
        from p1_tpu.core.genesis import genesis_hash
        from p1_tpu.core.keys import Keypair
        from p1_tpu.core.tx import Transaction
        from p1_tpu.node.netsim import SimNet

        net = SimNet(seed=11, difficulty=8)

        async def main():
            a = await net.add_node(miner_id="pool")
            b = await net.add_node(peers=[net.host_name(0)])
            assert await net.run_until(net.links_up, 30, step=0.1)
            w = Keypair.from_seed_text("p1-recon-pair")
            a.miner_id = w.account
            await net.mine_on(a, spacing_s=1.0)
            assert await net.run_until(
                lambda: b.chain.height == 1, 30, step=0.1
            )
            tx = Transaction.transfer(
                w, "p1-payee", 1, 1, 0, chain=genesis_hash(8)
            )
            await a.submit_tx(tx)
            assert await net.run_until(
                lambda: tx.txid() in b.mempool, 30, step=0.1
            )
            for n in (a, b):
                assert n.metrics.recon_rounds == 0
                assert n.metrics.relay_bytes().get("recon", 0) == 0

        net.run(main())
