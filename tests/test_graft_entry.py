"""The driver-graded entry points must work — especially ``dryrun_multichip``.

Round 2's graded run failed (MULTICHIP_r02.json rc=1) because the dryrun
created example arrays on the default axon/TPU platform before falling back
to the CPU mesh, so a transient TPU-client condition killed a CPU-only
check.  The regression test here runs the dryrun in a subprocess with the
TPU platform *deliberately available* (JAX_PLATFORMS scrubbed from the env,
so the axon sitecustomize re-enables it) and asserts it still completes on
CPU without ever touching the TPU.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_entry(code: str, *, scrub_platform_env: bool) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    if scrub_platform_env:
        # Let the interpreter's sitecustomize (axon,cpu on this VM) pick the
        # platform — the dryrun itself must force CPU.
        env.pop("JAX_PLATFORMS", None)
    return subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )


def test_dryrun_multichip_subprocess_with_tpu_available():
    proc = _run_entry(
        "import __graft_entry__; __graft_entry__.dryrun_multichip(8)",
        scrub_platform_env=True,
    )
    assert proc.returncode == 0, (
        f"dryrun_multichip(8) failed:\nstdout={proc.stdout}\nstderr={proc.stderr}"
    )
    assert "dryrun_multichip ok" in proc.stdout
    assert "platform=cpu" in proc.stdout


def test_entry_compiles_in_process():
    # entry() runs on whatever platform the test session uses (CPU here);
    # the driver separately compile-checks it on the real chip.
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__

        fn, args = __graft_entry__.entry()
        out = fn(*args)
        assert int(out) >= 0
    finally:
        sys.path.remove(REPO)
