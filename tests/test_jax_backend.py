"""JAX backend: digest parity vs NumPy oracle, search parity vs cpu backend."""

import random
import struct

import numpy as np
import pytest

from p1_tpu.core import BlockHeader, meets_target, target_from_difficulty, target_to_words
from p1_tpu.hashx import get_backend
from p1_tpu.hashx import sha256_ref

jax = pytest.importorskip("jax")
jnp = jax.numpy

from p1_tpu.hashx import jax_sha256  # noqa: E402

# One shape-specialized compile shared by the digest tests; eager dispatch of
# the unrolled 64-round trace is painfully slow on CPU.
_digest_jit = jax.jit(jax_sha256.sha256d_words)


def _prefix(seed: int) -> bytes:
    rng = random.Random(seed)
    return BlockHeader(
        1, rng.randbytes(32), rng.randbytes(32), 1735689700, 8, 0
    ).mining_prefix()


def _arrays(prefix: bytes, difficulty: int):
    midstate = jnp.array(sha256_ref.header_midstate(prefix), dtype=jnp.uint32)
    tail = jnp.array(sha256_ref.header_tail_words(prefix), dtype=jnp.uint32)
    target = jnp.array(
        target_to_words(target_from_difficulty(difficulty)), dtype=jnp.uint32
    )
    return midstate, tail, target


from p1_tpu.hashx.backend import HashBackend  # noqa: E402
from p1_tpu.hashx.jax_backend import PipelinedSearchMixin  # noqa: E402


class _SpanSpyBackend(PipelinedSearchMixin, HashBackend):
    """Records the span of every device step instead of hashing."""

    def __init__(self, step_span):
        self.step_span = step_span
        self.spans = []

    def _make_step(self, span):
        self.spans.append(span)

        def step(midstate, tail, target, base):
            return jnp.uint32(span)  # never a hit

        return step


class TestOpeningRamp:
    """The adaptive opening ramp (VERDICT r2 #4): fresh low-difficulty scans
    start small and grow; throughput scans skip the ramp entirely."""

    def _scan(self, step_span, count, difficulty, nonce_start=0):
        be = _SpanSpyBackend(step_span)
        prefix = _prefix(0)
        be.search(prefix, nonce_start, count, difficulty)
        return be.spans

    def test_fresh_easy_scan_ramps_geometrically(self):
        from p1_tpu.hashx.jax_backend import _RAMP_FACTOR, _RAMP_FLOOR

        spans = self._scan(1 << 27, 1 << 28, difficulty=20)
        assert spans[0] == _RAMP_FLOOR
        assert spans[1] == _RAMP_FLOOR * _RAMP_FACTOR
        assert max(spans) == 1 << 27  # caps at the full batch
        assert spans == sorted(spans)  # non-decreasing

    def test_hit_inside_opening_step_reported_exactly(self):
        from p1_tpu.hashx.jax_backend import _RAMP_FLOOR

        class _HitAt(_SpanSpyBackend):
            def __init__(self, step_span, hit_offset):
                super().__init__(step_span)
                self.hit_offset = hit_offset

            def _make_step(self, span):
                self.spans.append(span)
                off = self.hit_offset

                def step(midstate, tail, target, base):
                    return jnp.uint32(off if off < span else span)

                return step

        be = _HitAt(1 << 27, hit_offset=1234)
        res = be.search(_prefix(0), 0, 1 << 28, 20)
        # The hit lands inside the FIRST (small) ramp step, and the nonce /
        # hashes_done accounting must reflect the ramped span, not the
        # full batch.
        assert res.nonce == 1234
        assert res.hashes_done == 1235
        assert be.spans[0] == _RAMP_FLOOR

    def test_high_difficulty_scan_skips_ramp(self):
        spans = self._scan(1 << 27, 1 << 28, difficulty=255)
        assert all(s == 1 << 27 for s in spans)

    def test_resumed_range_skips_ramp(self):
        spans = self._scan(1 << 27, 1 << 27, difficulty=20, nonce_start=1 << 27)
        assert all(s == 1 << 27 for s in spans)

    def test_small_backend_never_ramps(self):
        from p1_tpu.hashx.jax_backend import _RAMP_FLOOR

        spans = self._scan(_RAMP_FLOOR // 2, _RAMP_FLOOR, difficulty=20)
        assert all(s == _RAMP_FLOOR // 2 for s in spans)


class TestJaxSha256:
    def test_digest_words_match_reference(self):
        prefix = _prefix(10)
        midstate, tail, _ = _arrays(prefix, 8)
        nonces = jnp.array([0, 1, 99999, 0xFFFFFFFF], dtype=jnp.uint32)
        words = _digest_jit(midstate, tail, nonces)
        for lane, nonce in enumerate([0, 1, 99999, 0xFFFFFFFF]):
            expect = sha256_ref.sha256d(prefix + struct.pack(">I", nonce))
            got = struct.pack(">8I", *(int(w[lane]) for w in words))
            assert got == expect, f"nonce {nonce:#x}"

    def test_search_step_finds_earliest(self):
        prefix = _prefix(11)
        difficulty = 8
        midstate, tail, target = _arrays(prefix, difficulty)
        batch = 1024
        step = jax_sha256.jit_search_step(batch)
        idx = int(step(midstate, tail, target, jnp.uint32(0)))
        truth = get_backend("cpu").search(prefix, 0, batch, difficulty)
        if truth.nonce is None:
            assert idx == batch
        else:
            assert idx == truth.nonce

    def test_search_step_no_hit_returns_batch(self):
        prefix = _prefix(12)
        midstate, tail, target = _arrays(prefix, 255)
        step = jax_sha256.jit_search_step(1024)
        assert int(step(midstate, tail, target, jnp.uint32(0))) == 1024

    def test_nonce_base_wraps_uint32(self):
        prefix = _prefix(13)
        midstate, tail, _ = _arrays(prefix, 8)
        # Lane math at the top of nonce space must wrap mod 2**32 like uint32.
        nonces = jnp.uint32(0xFFFFFFFE) + jnp.arange(4, dtype=jnp.uint32)
        words = _digest_jit(midstate, tail, nonces)
        expect = sha256_ref.sha256d(prefix + struct.pack(">I", 1))
        got = struct.pack(">8I", *(int(w[3]) for w in words))
        assert got == expect


class TestJaxBackend:
    def test_registry_name(self):
        backend = get_backend("jax", batch=4096)
        assert backend.name == "jax"

    def test_search_parity_with_cpu(self):
        backend = get_backend("jax", batch=1024)
        prefix = _prefix(14)
        truth = get_backend("cpu").search(prefix, 0, 1 << 14, 10)
        got = backend.search(prefix, 0, 1 << 14, 10)
        assert got.nonce == truth.nonce
        if got.nonce is not None:
            assert got.hashes_done == truth.hashes_done  # earliest-hit count

    def test_partial_final_batch_masked(self):
        # count smaller than one device batch: hits past count must not report.
        backend = get_backend("jax", batch=4096)
        prefix = _prefix(15)
        truth = get_backend("cpu").search(prefix, 0, 4096, 8)
        assert truth.nonce is not None, "seed must produce a hit in 4096"
        res = backend.search(prefix, 0, truth.nonce, 8)  # exclusive of the hit
        assert res.nonce is None
        res2 = backend.search(prefix, 0, truth.nonce + 1, 8)
        assert res2.nonce == truth.nonce

    def test_hit_meets_target(self):
        backend = get_backend("jax", batch=1024)
        prefix = _prefix(16)
        res = backend.search(prefix, 0, 1 << 13, 9)
        if res.nonce is not None:
            digest = sha256_ref.sha256d(prefix + struct.pack(">I", res.nonce))
            assert meets_target(digest, 9)

    def test_nonzero_start(self):
        backend = get_backend("jax", batch=1024)
        prefix = _prefix(17)
        truth = get_backend("cpu").search(prefix, 5000, 1 << 13, 9)
        got = backend.search(prefix, 5000, 1 << 13, 9)
        assert got.nonce == truth.nonce

    def test_batch_validation(self):
        with pytest.raises(ValueError):
            get_backend("jax", batch=1000)  # not a power of two
