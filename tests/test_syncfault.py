"""Sync-stall failover: request supervision under injected delivery faults.

The attack class (VERDICT r5 Missing #2): locator sync was always
re-requested from the single peer that triggered it, and the liveness
layer's bar is deliberately generous — a peer that answers PINGs, or
trickles bytes, or serves well-formed-but-useless replies stays under it
while pinning a fresh node's catch-up forever.  These tests drive a real
victim ``Node`` against scripted ``HostilePeer`` adversaries
(p1_tpu/node/testing.py) and assert the supervision layer
(p1_tpu/node/supervision.py) actually rescues the sync: the stall is
detected within its progress deadline, the locator fails over to a
different peer, the staller is demoted — never banned — and an honest
slow peer is never falsely demoted (the acceptance pair from VERDICT
next-round item 6).
"""

import asyncio
import time

import pytest

from test_node import CHUNK, DIFF, run, wait_until
from txutil import account, stx

from p1_tpu.config import NodeConfig
from p1_tpu.node import Node, protocol
from p1_tpu.node.protocol import MsgType
from p1_tpu.node.supervision import RequestSupervisor, SyncStalled
from p1_tpu.node.testing import FaultPlan, HostilePeer, make_blocks


def _config(peers=(), **kw) -> NodeConfig:
    kw.setdefault("difficulty", DIFF)
    kw.setdefault("chunk", CHUNK)
    kw.setdefault("mine", False)
    # Snappy supervision so the suite doesn't sit through production-scale
    # deadlines; the defaults differ only in magnitude.  The deadline
    # still leaves ~4 supervision ticks and a wide margin over localhost
    # reply latency, so a loaded CI box can't fire it spuriously.
    kw.setdefault("sync_stall_timeout_s", 0.6)
    kw.setdefault("sync_backoff_base_s", 0.05)
    kw.setdefault("sync_backoff_max_s", 0.2)
    return NodeConfig(peers=tuple(peers), **kw)


# Module-scoped chain: mining 30 blocks once (~100 ms total at DIFF=12)
# instead of per-test keeps the file fast on the 1-vCPU host.
_CHAIN30 = make_blocks(30, DIFF)


class TestSupervisorUnit:
    """The state machine alone, on a fake clock and pinned RNG."""

    def _sup(self, **kw):
        self.now = 0.0
        kw.setdefault("stall_timeout_s", 10.0)
        kw.setdefault("attempts_max", 3)
        import random

        kw.setdefault("rng", random.Random(7))
        return RequestSupervisor(clock=lambda: self.now, **kw)

    def test_deadline_arms_on_begin_and_resets_on_progress(self):
        sup = self._sup()
        assert not sup.active and not sup.stalled()
        sup.begin("peer-a")
        self.now = 9.0
        assert not sup.stalled()
        self.now = 10.5
        assert sup.stalled()
        sup.progress()  # advanced: deadline re-arms from now
        assert not sup.stalled()
        self.now = 20.0
        assert not sup.stalled()
        self.now = 21.0
        assert sup.stalled()

    def test_progress_resets_attempt_budget(self):
        sup = self._sup(attempts_max=2)
        sup.begin("a")
        sup.record_stall()
        sup.begin("b")
        sup.record_stall()
        assert sup.exhausted()
        sup.begin("c")
        sup.progress()  # a live sync is not a failing one
        assert not sup.exhausted()
        assert sup.attempts == 0

    def test_backoff_grows_exponentially_jittered_and_capped(self):
        sup = self._sup(
            attempts_max=10, backoff_base_s=1.0, backoff_max_s=4.0
        )
        delays = []
        for _ in range(6):
            sup.begin("x")
            delays.append(sup.record_stall())
        for i, d in enumerate(delays):
            raw = min(4.0, 1.0 * 2**i)
            assert 0.5 * raw <= d <= 1.5 * raw  # jitter band
        # The cap binds from the third stall on (4 <= 2^i).
        assert all(d <= 1.5 * 4.0 for d in delays[2:])

    def test_ready_gates_on_backoff_and_stall_clears_target(self):
        sup = self._sup(backoff_base_s=1.0)
        sup.begin("x")
        delay = sup.record_stall()
        assert sup.target is None and not sup.active
        assert not sup.ready()
        self.now = delay + 0.01
        assert sup.ready()

    def test_backoff_never_overflows_at_huge_attempt_counts(self):
        # The store recovery loop disables exhaustion (attempts_max is
        # effectively infinite) and stalls forever against a dead disk;
        # 2**attempts must not overflow float conversion, and the delay
        # must stay at the cap.
        sup = self._sup(
            attempts_max=1 << 30, backoff_base_s=0.25, backoff_max_s=5.0
        )
        sup.attempts = 5000  # ~7 hours of stalls at the 5 s cap
        sup.begin("disk")
        d = sup.record_stall()
        assert 0.5 * 5.0 <= d <= 1.5 * 5.0

    def test_idle_without_begin_never_stalls(self):
        sup = self._sup()
        self.now = 1e9
        assert not sup.stalled() and not sup.active


class TestSyncStallFailover:
    """The acceptance pair (VERDICT next-round item 6) plus the other
    fault families, all mid-IBD against a real victim node."""

    @pytest.mark.slow
    def test_stalling_peer_fails_over_mid_ibd(self):
        """The only initially-serving peer serves one batch then swallows
        every further GETBLOCKS (while dutifully answering PINGs — alive
        by the liveness layer's rules).  A second connected peer never
        triggered a sync (it advertised height 0).  The victim must
        detect the stall within its progress deadline, demote the
        staller WITHOUT banning it, fail over, and complete IBD from the
        second peer.

        SLOW since round 10: this exact case migrated onto the network
        simulator (tests/test_netsim.py TestStallFailoverSim) where it
        runs the PRODUCTION 10 s supervision deadlines in milliseconds
        of wall time, deterministically — tier-1 runs that variant; the
        real-socket original stays as a smoke that the seam still
        carries the behavior on actual TCP."""

        async def scenario():
            staller = HostilePeer(
                _CHAIN30,
                plan=FaultPlan(
                    swallow=frozenset({MsgType.GETBLOCKS}),
                    serve_before_fault=1,
                    batch_limit=10,
                ),
            )
            quiet = HostilePeer(_CHAIN30, plan=FaultPlan(hello_height=0))
            await staller.start()
            await quiet.start()
            victim = Node(
                _config(
                    peers=[
                        f"127.0.0.1:{staller.port}",
                        f"127.0.0.1:{quiet.port}",
                    ]
                )
            )
            await victim.start()
            try:
                t0 = time.monotonic()
                assert await wait_until(
                    lambda: victim.chain.height == 30, timeout=20
                ), f"IBD pinned at height {victim.chain.height}"
                elapsed = time.monotonic() - t0
                # Failed over and finished in a few deadline multiples,
                # not by some unrelated slow path (wide CI margin).
                assert elapsed < 15.0
                m = victim.metrics
                assert m.sync_stalls >= 1
                assert m.sync_failovers >= 1
                assert m.sync_demotions >= 1
                # The rescue came from the second peer.
                assert quiet.requests[MsgType.GETBLOCKS] >= 1
                # Demoted, never banned: the staller keeps its connection
                # and clean record.
                assert not victim._banned_until and not victim._violations
                assert victim.peer_count() == 2
                demerited = [
                    p
                    for p in victim._peers.values()
                    if p.sync_demerits > 0
                ]
                assert len(demerited) == 1
                # Counters are surfaced, not just internal.
                s = victim.status()["sync"]
                assert s["stalls"] == m.sync_stalls
                assert s["failovers"] == m.sync_failovers
                assert s["demotions"] == m.sync_demotions
            finally:
                await victim.stop()
                await staller.stop()
                await quiet.stop()

        run(scenario())

    def test_honest_slow_peer_is_never_demoted(self):
        """The false-demotion control: a lone peer serving small batches
        with a per-reply delay well inside the deadline.  Every round
        lands blocks, so the progress deadline keeps re-arming — sync
        completes with zero stalls, zero demotions."""

        async def scenario():
            slow = HostilePeer(
                make_blocks(12, DIFF),
                plan=FaultPlan(batch_limit=3, reply_delay_s=0.3),
            )
            await slow.start()
            victim = Node(
                _config(
                    peers=[f"127.0.0.1:{slow.port}"],
                    sync_stall_timeout_s=2.0,
                )
            )
            await victim.start()
            try:
                assert await wait_until(
                    lambda: victim.chain.height == 12, timeout=20
                )
                m = victim.metrics
                assert m.sync_stalls == 0
                assert m.sync_demotions == 0
                assert m.sync_failovers == 0
                assert all(
                    p.sync_demerits == 0 for p in victim._peers.values()
                )
            finally:
                await victim.stop()
                await slow.stop()

        run(scenario())

    def test_truncated_reply_fails_over_without_misbehavior_score(self):
        """Mid-frame stall: the staller answers GETBLOCKS with HALF a
        frame then wedges.  Byte progress happened (the liveness layer's
        trickle exemption applies) but the chain advances nothing — the
        progress deadline must fire, fail over, and the truncation must
        not be scored as a protocol violation (the FrameReader never
        completed a malformed frame)."""

        async def scenario():
            staller = HostilePeer(
                _CHAIN30,
                plan=FaultPlan(truncate_at=MsgType.GETBLOCKS),
            )
            quiet = HostilePeer(_CHAIN30, plan=FaultPlan(hello_height=0))
            await staller.start()
            await quiet.start()
            victim = Node(
                _config(
                    peers=[
                        f"127.0.0.1:{staller.port}",
                        f"127.0.0.1:{quiet.port}",
                    ]
                )
            )
            await victim.start()
            try:
                assert await wait_until(
                    lambda: victim.chain.height == 30, timeout=20
                )
                assert victim.metrics.sync_failovers >= 1
                assert not victim._violations and not victim._banned_until
            finally:
                await victim.stop()
                await staller.stop()
                await quiet.stop()

        run(scenario())

    def test_dropped_sync_peer_fails_over_without_full_deadline(self):
        """A peer that hangs up the instant it is asked: the supervisor
        sees the target leave the peer set and fails over immediately
        instead of sitting out the whole progress deadline."""

        async def scenario():
            dropper = HostilePeer(
                _CHAIN30,
                plan=FaultPlan(drop_at=MsgType.GETBLOCKS),
            )
            quiet = HostilePeer(_CHAIN30, plan=FaultPlan(hello_height=0))
            await dropper.start()
            await quiet.start()
            victim = Node(
                _config(
                    peers=[
                        f"127.0.0.1:{dropper.port}",
                        f"127.0.0.1:{quiet.port}",
                    ],
                    # A long deadline ON PURPOSE: completion inside the
                    # asserted window proves the disconnected-target
                    # fast path, not deadline expiry.
                    sync_stall_timeout_s=30.0,
                )
            )
            await victim.start()
            try:
                t0 = time.monotonic()
                assert await wait_until(
                    lambda: victim.chain.height == 30, timeout=25
                )
                assert time.monotonic() - t0 < 20.0  # << the 30 s deadline
                assert victim.metrics.sync_failovers >= 1
            finally:
                await victim.stop()
                await dropper.stop()
                await quiet.stop()

        run(scenario())

    def test_chatty_useless_replies_read_as_stall(self):
        """Well-formed empty BLOCKS replies below the advertised height
        are the cheapest stall spelling (no silence anywhere).  The
        quiesce path must not mistake them for a completed sync while
        the peer's own advertised height remains unreached."""

        async def scenario():
            staller = HostilePeer(
                _CHAIN30, plan=FaultPlan(empty_replies=True)
            )
            quiet = HostilePeer(_CHAIN30, plan=FaultPlan(hello_height=0))
            await staller.start()
            await quiet.start()
            victim = Node(
                _config(
                    peers=[
                        f"127.0.0.1:{staller.port}",
                        f"127.0.0.1:{quiet.port}",
                    ]
                )
            )
            await victim.start()
            try:
                assert await wait_until(
                    lambda: victim.chain.height == 30, timeout=20
                )
                assert victim.metrics.sync_stalls >= 1
                assert victim.metrics.sync_failovers >= 1
            finally:
                await victim.stop()
                await staller.stop()
                await quiet.stop()

        run(scenario())

    def test_lone_staller_retries_with_bounded_budget(self):
        """No second peer exists: the supervisor retries the sole source
        with backoff and, after the attempt budget, stops chasing — the
        counters prove both the retries and the bound."""

        async def scenario():
            staller = HostilePeer(
                _CHAIN30,
                plan=FaultPlan(swallow=frozenset({MsgType.GETBLOCKS})),
            )
            await staller.start()
            victim = Node(
                _config(
                    peers=[f"127.0.0.1:{staller.port}"],
                    sync_stall_timeout_s=0.3,
                    sync_attempts_max=2,
                )
            )
            await victim.start()
            try:
                assert await wait_until(
                    lambda: victim.metrics.sync_exhausted >= 1, timeout=20
                )
                assert victim.chain.height == 0  # nothing ever served
                # Retried the lone peer (failovers fired) before giving
                # up within the budget.
                assert 1 <= victim.metrics.sync_failovers <= 4
                assert staller.requests[MsgType.GETBLOCKS] >= 2
                # Still connected, still unbanned: exhaustion parks the
                # episode, it does not punish the peer further.
                assert victim.peer_count() == 1
                assert not victim._banned_until
            finally:
                await victim.stop()
                await staller.stop()

        run(scenario())


class TestCompactFetchSupervision:
    def test_blocktxn_stall_falls_back_to_locator_sync(self):
        """A compact push whose GETBLOCKTXN round is never answered: the
        supervision loop must abandon the reconstruction within the
        deadline, demote the squatter, and recover the block whole via
        locator sync from another peer."""
        alice = account("sf-alice")
        spend = stx(
            "sf-alice", account("sf-bob"), 5, 1, seq=0, difficulty=DIFF
        )
        blocks = make_blocks(
            6, DIFF, miner_id=alice, txs_at={6: (spend,)}
        )

        async def scenario():
            staller = HostilePeer(
                blocks[:-1],  # serves the chain BELOW the compact push
                plan=FaultPlan(
                    swallow=frozenset({MsgType.GETBLOCKTXN}),
                    hello_height=5,
                ),
            )
            full = HostilePeer(blocks, plan=FaultPlan(hello_height=0))
            await staller.start()
            await full.start()
            victim = Node(
                _config(
                    peers=[
                        f"127.0.0.1:{staller.port}",
                        f"127.0.0.1:{full.port}",
                    ]
                )
            )
            await victim.start()
            try:
                assert await wait_until(
                    lambda: victim.chain.height == 5, timeout=20
                )
                # The compact push for the tx-bearing tip block: the
                # victim cannot reconstruct (its pool lacks the spend)
                # and must ask the pusher for the missing transaction.
                await staller.push(protocol.encode_cblock(blocks[-1]))
                assert await wait_until(
                    lambda: victim.chain.height == 6, timeout=20
                ), "block never recovered after the BLOCKTXN stall"
                m = victim.metrics
                assert staller.requests[MsgType.GETBLOCKTXN] >= 1
                assert m.cblock_fetch_stalls >= 1
                assert not victim._pending_cblocks
                assert victim.status()["sync"]["cblock_fetch_stalls"] >= 1
                assert not victim._banned_until
            finally:
                await victim.stop()
                await staller.stop()
                await full.stop()

        run(scenario())


class TestMempoolPageSupervision:
    def test_mempool_page_stall_detected_and_rerouted(self):
        """A peer serving a first mempool page with more=1 and then
        swallowing the continuation: the page deadline must fire, demote
        the staller, and solicit the pool from another connected peer."""
        pool_tx = stx(
            "sf-carol", account("sf-dave"), 3, 1, seq=0, difficulty=DIFF
        )

        async def scenario():
            chain5 = make_blocks(5, DIFF)
            staller = HostilePeer(
                chain5,
                mempool_txs=(pool_tx,),
                plan=FaultPlan(
                    mempool_more=True,
                    swallow=frozenset({MsgType.GETMEMPOOL}),
                    serve_before_fault=1,
                ),
            )
            quiet = HostilePeer(chain5, plan=FaultPlan(hello_height=0))
            await staller.start()
            await quiet.start()
            victim = Node(
                _config(
                    peers=[
                        f"127.0.0.1:{staller.port}",
                        f"127.0.0.1:{quiet.port}",
                    ]
                )
            )
            await victim.start()
            try:
                assert await wait_until(
                    lambda: victim.metrics.mempool_sync_stalls >= 1,
                    timeout=20,
                ), "mempool page stall never detected"
                # Rerouted: the other peer got asked for its pool.
                assert await wait_until(
                    lambda: quiet.requests[MsgType.GETMEMPOOL] >= 1,
                    timeout=10,
                )
                assert victim.status()["sync"]["mempool_stalls"] >= 1
                assert not victim._banned_until
            finally:
                await victim.stop()
                await staller.stop()
                await quiet.stop()

        run(scenario())

    def test_mempool_empty_tail_reads_as_a_stall_not_progress(self):
        """Round 23: a peer answering every GETMEMPOOL with an EMPTY
        page claiming more=True — each page is well-formed and arrives
        on time, so the in-flight deadline never fires, but the pool
        never advances.  Pre-round-23 this silently ENDED the sync (a
        zero-cost park); it must now demote the chatty-useless peer,
        count a mempool_sync_stalls, and re-solicit from the other
        connected peer — without a ban (nothing was malformed)."""

        async def scenario():
            chain5 = make_blocks(5, DIFF)
            parker = HostilePeer(
                chain5, plan=FaultPlan(mempool_empty_tail=True)
            )
            quiet = HostilePeer(chain5, plan=FaultPlan(hello_height=0))
            await parker.start()
            await quiet.start()
            victim = Node(
                _config(
                    peers=[
                        f"127.0.0.1:{parker.port}",
                        f"127.0.0.1:{quiet.port}",
                    ]
                )
            )
            await victim.start()
            try:
                assert await wait_until(
                    lambda: victim.metrics.mempool_sync_stalls >= 1,
                    timeout=20,
                ), "empty-tail pages never read as a stall"
                assert await wait_until(
                    lambda: quiet.requests[MsgType.GETMEMPOOL] >= 1,
                    timeout=10,
                ), "pool sync never rerouted off the parker"
                assert victim.metrics.sync_demotions >= 1
                assert not victim._banned_until
            finally:
                await victim.stop()
                await parker.stop()
                await quiet.stop()

        run(scenario())


class TestHeadersClientFailover:
    """The same supervisor generalized over the light client's headers
    fetch loop (node/client.py get_headers)."""

    def test_get_headers_fails_over_to_fallback_peer(self):
        from p1_tpu.node.client import get_headers

        async def scenario():
            staller = HostilePeer(
                _CHAIN30,
                plan=FaultPlan(
                    swallow=frozenset({MsgType.GETHEADERS}),
                    serve_before_fault=1,
                    batch_limit=8,
                ),
            )
            honest = HostilePeer(_CHAIN30)
            await staller.start()
            await honest.start()
            try:
                headers = await get_headers(
                    "127.0.0.1",
                    staller.port,
                    DIFF,
                    timeout=30.0,
                    stall_timeout_s=0.5,
                    fallback_peers=[("127.0.0.1", honest.port)],
                )
                assert len(headers) == 31  # genesis + 30, rescued
                # Contiguity survived the mid-fetch peer switch.
                for prev, h in zip(headers, headers[1:]):
                    assert h.prev_hash == prev.block_hash()
                assert honest.requests[MsgType.GETHEADERS] >= 1
            finally:
                await staller.stop()
                await honest.stop()

        run(scenario())

    def test_get_headers_rotates_off_half_open_primary(self):
        """A listen backlog with no process behind it (accepts TCP,
        never answers HELLO): the handshake itself must be a supervised
        round — one stall, rotate to the fallback — not a sink for the
        caller's entire overall timeout.  Found live by the round-6
        verify drive."""
        import socket

        from p1_tpu.node.client import get_headers

        async def scenario():
            half_open = socket.socket()
            half_open.bind(("127.0.0.1", 0))
            half_open.listen(1)  # nobody will ever accept/answer
            honest = HostilePeer(_CHAIN30)
            await honest.start()
            try:
                t0 = time.monotonic()
                headers = await get_headers(
                    "127.0.0.1",
                    half_open.getsockname()[1],
                    DIFF,
                    timeout=30.0,
                    stall_timeout_s=0.5,
                    fallback_peers=[("127.0.0.1", honest.port)],
                )
                assert len(headers) == 31
                assert time.monotonic() - t0 < 10.0  # ~one stall, not 30 s
            finally:
                half_open.close()
                await honest.stop()

        run(scenario())

    def test_get_headers_exhaustion_raises_sync_stalled(self):
        from p1_tpu.node.client import get_headers

        async def scenario():
            staller = HostilePeer(
                _CHAIN30,
                plan=FaultPlan(swallow=frozenset({MsgType.GETHEADERS})),
            )
            await staller.start()
            try:
                with pytest.raises(SyncStalled):
                    await get_headers(
                        "127.0.0.1",
                        staller.port,
                        DIFF,
                        timeout=30.0,
                        stall_timeout_s=0.3,
                        attempts_max=2,
                    )
            finally:
                await staller.stop()

        run(scenario())

    def test_get_headers_still_rejects_protocol_violations(self):
        """Supervision retries stalls, never lies: an unlinked HEADERS
        reply must still raise immediately (no silent failover that
        would let a forging peer be laundered by an honest fallback)."""
        from p1_tpu.core.header import BlockHeader
        from p1_tpu.node.client import get_headers

        class _Forger(HostilePeer):
            def _answer(self, mtype, body):
                if mtype is MsgType.GETHEADERS:
                    bogus = BlockHeader(
                        1, bytes(31) + b"\x77", bytes(32), 999, DIFF, 0
                    )
                    return protocol.encode_headers([bogus])
                return super()._answer(mtype, body)

        async def scenario():
            forger = _Forger(_CHAIN30)
            await forger.start()
            try:
                with pytest.raises(ValueError, match="link"):
                    await get_headers(
                        "127.0.0.1",
                        forger.port,
                        DIFF,
                        timeout=20.0,
                        stall_timeout_s=1.0,
                    )
            finally:
                await forger.stop()

        run(scenario())
