"""Native C++ Ed25519 engine: parity with the pure-Python oracle.

The round-15 contract: the native backend (native/ed25519.cpp via
core/_ed25519_native.py) may change WHERE the curve arithmetic runs,
never WHAT is accepted — ``verify`` is bit-identical to the serial
cofactorless ``_ed25519.verify`` on every input (torsion crafts it
tolerates included), and ``verify_batch`` carries the exact subgroup-
gated batch semantics (acceptance implies serial acceptance, False is
not a verdict).  Plus the degradation contract: a missing compiler or
failing build must leave the process on the pure-Python rung with one
log line and zero behavior change.

Build handling: the first ``available()`` call compiles the shared
object into the content-addressed cache (or loads the cached build);
on a toolchain-less image it fails once and every native-only test
here SKIPS cleanly — the fallback-path tests still run.
"""

import random

import pytest

from p1_tpu.core import _ed25519 as py_ed
from p1_tpu.core import _ed25519_native as native
from p1_tpu.core import keys

HAVE_NATIVE = native.available()
needs_native = pytest.mark.skipif(
    not HAVE_NATIVE, reason="no C++ toolchain / native build unavailable"
)


def _triples(n, salt="n"):
    out = []
    for i in range(n):
        seed = bytes([i % 5]) * 31 + bytes([len(salt) % 256])
        msg = b"native-%d-%s" % (i, salt.encode())
        out.append((py_ed.public_key(seed), py_ed.sign(seed, msg), msg))
    return out


def _corrupt(triple, how):
    pubkey, sig, msg = triple
    if how == "sig":
        return (pubkey, sig[:20] + bytes([sig[20] ^ 1]) + sig[21:], msg)
    if how == "msg":
        return (pubkey, sig, msg + b"!")
    if how == "key":
        return (py_ed.public_key(b"\x07" * 32), sig, msg)
    if how == "s_range":  # scalar >= group order: rejected pre-math
        return (pubkey, sig[:32] + py_ed._Q.to_bytes(32, "little"), msg)
    if how == "bad_y":  # non-canonical y >= p: decompression rejects
        return (py_ed._P.to_bytes(32, "little"), sig, msg)
    if how == "short":
        return (pubkey[:31], sig, msg)
    raise AssertionError(how)


def _torsion_triple(*, cancel: bool):
    """A signature carrying small-order torsion (the round-8 fixtures):
    cancel=True is serially VALID (torsion cancels), cancel=False is
    the chain-split craft serial rejects."""
    t_enc = (
        (py_ed._P - 1) if cancel else 0
    ).to_bytes(32, "little")
    a, prefix = py_ed._secret_expand(bytes(32))
    torsion = py_ed._pt_decompress(t_enc)
    a_pt = py_ed._pt_mul(a, py_ed._B)
    pub = py_ed._pt_compress(
        py_ed._pt_add(a_pt, torsion) if cancel else a_pt
    )
    for i in range(200):
        msg = b"native-torsion-%d" % i
        r = int.from_bytes(py_ed._sha512(prefix + msg), "little") % py_ed._Q
        r_enc = py_ed._pt_compress(
            py_ed._pt_add(py_ed._pt_mul(r, py_ed._B), torsion)
        )
        k = (
            int.from_bytes(py_ed._sha512(r_enc + pub + msg), "little")
            % py_ed._Q
        )
        if cancel and k % 2 == 0:
            continue
        return pub, r_enc + ((r + k * a) % py_ed._Q).to_bytes(32, "little"), msg
    raise AssertionError("no usable k in 200 tries")


@needs_native
class TestNativeSerialParity:
    """native.verify == _ed25519.verify, input for input."""

    def test_rfc8032_vector(self):
        seed = bytes.fromhex(
            "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
        )
        pub = py_ed.public_key(seed)
        sig = py_ed.sign(seed, b"")
        assert native.verify(pub, sig, b"")
        assert not native.verify(pub, sig, b"x")

    def test_corruption_matrix_matches_serial(self):
        base = _triples(6, salt="ser")
        for pos, triple in enumerate(base):
            assert native.verify(*triple) and py_ed.verify(*triple)
            for how in ("sig", "msg", "key", "s_range", "bad_y", "short"):
                bad = _corrupt(triple, how)
                assert native.verify(*bad) == py_ed.verify(*bad) == False, (
                    pos,
                    how,
                )

    def test_torsion_crafts_identical_verdicts(self):
        # The serial rule TOLERATES cancelling torsion — the native
        # serial path must accept exactly what pure Python accepts (a
        # gated native serial would silently change consensus).
        acc = _torsion_triple(cancel=True)
        assert py_ed.verify(*acc) and native.verify(*acc)
        rej = _torsion_triple(cancel=False)
        assert not py_ed.verify(*rej) and not native.verify(*rej)

    def test_random_mixes_match(self):
        rng = random.Random(15)
        base = _triples(12, salt="mix")
        for _ in range(8):
            batch = [
                _corrupt(t, rng.choice(("sig", "msg")))
                if rng.random() < 0.3
                else t
                for t in base
            ]
            for t in batch:
                assert native.verify(*t) == py_ed.verify(*t)


@needs_native
class TestNativeBatch:
    """native.verify_batch: the subgroup-gated batch contract."""

    def test_q_constant_pinned(self):
        # The C engine's transcribed gate scalar must BE the group
        # order: B has exact order q, so gate(B) is True iff the
        # constant is exactly q (any other scalar ≤ 2^256 maps B off
        # the identity), and order-2 torsion must gate False.
        assert native.in_subgroup(py_ed._pt_compress(py_ed._B)) is True
        assert (
            native.in_subgroup((py_ed._P - 1).to_bytes(32, "little")) is False
        )

    @pytest.mark.parametrize("n", [0, 1, 2, 7, 8, 9, 33])
    def test_all_valid_accepts(self, n):
        assert native.verify_batch(_triples(n))

    def test_corruption_at_every_position_rejects(self):
        base = _triples(10, salt="pos")
        for pos in range(len(base)):
            for how in ("sig", "msg", "key", "s_range", "bad_y", "short"):
                bad = list(base)
                bad[pos] = _corrupt(bad[pos], how)
                assert not native.verify_batch(bad), (pos, how)

    def test_batch_verdicts_match_fallback(self):
        rng = random.Random(16)
        base = _triples(16, salt="eq")
        for _ in range(6):
            batch = [
                _corrupt(t, rng.choice(("sig", "msg")))
                if rng.random() < 0.2
                else t
                for t in base
            ]
            assert native.verify_batch(batch) == py_ed.verify_batch(batch)

    def test_torsion_gate_rejects_what_serial_tolerates(self):
        # Batch acceptance implies serial acceptance — so the batch
        # must NOT accept the cancelling craft serial tolerates (it is
        # settled by first_invalid's serial confirmation upstream).
        acc = _torsion_triple(cancel=True)
        assert py_ed.verify(*acc)
        assert not native.verify_batch([acc] * 8)
        rej = _torsion_triple(cancel=False)
        assert not native.verify_batch([rej] * 8)
        # parity with the fallback batch on both
        assert not py_ed.verify_batch([acc] * 8)
        assert not py_ed.verify_batch([rej] * 8)

    def test_gate_is_exact_vs_python_oracle(self):
        rng = random.Random(25519)
        t2 = (py_ed._P - 1).to_bytes(32, "little")
        t4 = (0).to_bytes(32, "little")
        cases = [t2, t4, py_ed._pt_compress(py_ed._B)]
        for _ in range(6):
            honest = py_ed._pt_mul(rng.randrange(1, py_ed._Q), py_ed._B)
            cases.append(py_ed._pt_compress(honest))
            for enc in (t2, t4):
                mixed = py_ed._pt_add(honest, py_ed._pt_decompress(enc))
                cases.append(py_ed._pt_compress(mixed))
        for enc in cases:
            pt = py_ed._pt_decompress(enc)
            assert native.in_subgroup(enc) == py_ed._in_prime_subgroup(pt)
        assert native.in_subgroup(py_ed._P.to_bytes(32, "little")) is None

    def test_duplicate_pubkeys_dedup_safely(self):
        # The seam gates each unique pubkey once; many sigs from one
        # key must still verify (and reject) correctly.
        tr = _triples(12, salt="dup")  # 5 unique keys by construction
        assert native.verify_batch(tr)
        bad = list(tr)
        bad[11] = _corrupt(bad[11], "sig")
        assert not native.verify_batch(bad)


class TestBackendLadder:
    """keys.py resolution: wheel > native > pure-python, per-backend
    accounting, and graceful degradation when the build is absent."""

    def teardown_method(self):
        keys.set_sig_backend(None)

    @needs_native
    def test_auto_resolves_native_without_wheel(self):
        if keys.HAVE_CRYPTOGRAPHY:
            pytest.skip("wheel present: auto resolves cryptography")
        keys.set_sig_backend(None)
        assert keys.backend() == "native"

    @needs_native
    def test_native_work_counted_per_backend(self):
        keys.set_sig_backend("native")
        tr = _triples(keys.BATCH_MIN, salt="count")
        keys.STATS.reset()
        assert keys.verify_batch(tr)
        assert keys.STATS.backends["native"] == len(tr)
        keys._neg_cache.clear()
        assert keys.verify(*tr[0])
        assert keys.STATS.backends["native"] == len(tr) + 1

    @needs_native
    def test_first_invalid_serial_contract_on_native(self):
        # first_invalid settles via serial verify — on the native rung
        # that is the native serial path, whose verdicts are pinned
        # identical above, so the left-first contract carries over.
        keys.set_sig_backend("native")
        base = _triples(24, salt="fi")
        tors = _torsion_triple(cancel=True)
        mixed = list(base)
        mixed[2] = tors  # gate-rejected, serially valid
        mixed[20] = _corrupt(mixed[20], "sig")
        assert not keys.verify_batch(mixed)
        assert keys.first_invalid(mixed) == 20
        mixed[20] = base[20]
        assert keys.first_invalid(mixed) is None

    def test_unknown_backend_rejected(self):
        with pytest.raises(ValueError):
            keys.set_sig_backend("sandybridge")

    def test_build_absent_degrades_to_pure_python(self, monkeypatch):
        # The graceful-degradation contract: with the native object
        # unloadable, auto resolution lands on pure-python and every
        # verify path still works — no exception escapes the seam.
        from p1_tpu.hashx import native_build

        monkeypatch.setattr(native, "_LIB", None)
        monkeypatch.setattr(native, "_LOAD_FAILED", False)

        def boom(force=False):
            raise native_build.NativeBuildError("no toolchain (test)")

        monkeypatch.setattr(native_build, "build_lib", boom)
        try:
            assert native.available() is False
            assert native.load() is None  # memoized failure, no retry
            if not keys.HAVE_CRYPTOGRAPHY:
                keys.set_sig_backend(None)
                assert keys.backend() == "pure-python"
            # Forcing the absent rung degrades with a warning, not a crash.
            keys.set_sig_backend("native")
            tr = _triples(keys.BATCH_MIN, salt="absent")
            assert keys.verify_batch(tr)
            assert keys.verify(*tr[0])
        finally:
            keys.set_sig_backend(None)
            monkeypatch.setattr(native, "_LOAD_FAILED", False)
            monkeypatch.setattr(native, "_LIB", None)

    def test_build_smoke_or_clean_skip(self, tmp_path, monkeypatch):
        # The CI smoke: on a toolchain host, a cold cache builds a
        # loadable object; without one, NativeBuildError surfaces and
        # the test SKIPS instead of failing.
        import ctypes

        from p1_tpu.hashx import native_build

        monkeypatch.setenv("P1_NATIVE_CACHE", str(tmp_path))
        try:
            path = native_build.build_lib()
        except native_build.NativeBuildError as exc:
            pytest.skip(f"no C++ toolchain: {exc}")
        lib = ctypes.CDLL(str(path))
        lib.p1_ed25519_impl.restype = ctypes.c_char_p
        assert lib.p1_ed25519_impl()  # both engines in one object
        assert lib.p1_has_shani() in (0, 1)
