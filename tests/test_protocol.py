"""Wire protocol: encode/decode round-trips and malformed-frame rejection.

The node treats any ValueError from decode as a protocol violation and
drops the peer — so every malformed shape must raise, never crash or
mis-parse.  A small mutation fuzz backs the hand-written cases.
"""

import random

import pytest

from p1_tpu.chain.proof import TxProof
from p1_tpu.core import Block, BlockHeader, Transaction, make_genesis
from p1_tpu.node import protocol
from p1_tpu.node.protocol import Hello, MsgType


def _block(n_txs: int = 2) -> Block:
    txs = tuple(Transaction("alice", "bob", 5, f + 1, f) for f in range(n_txs))
    header = BlockHeader(1, b"\x11" * 32, b"\x22" * 32, 1735689700, 12, 7)
    return Block(header, txs)


class TestRoundTrips:
    def test_hello(self):
        h = Hello(b"\xab" * 32, 42, 9444)
        mtype, got = protocol.decode(protocol.encode_hello(h))
        assert mtype is MsgType.HELLO and got == h

    def test_hello_version_mismatch_rejected(self):
        # A peer speaking another protocol version (e.g. round 3's
        # unversioned frames would also fail, by size) must die at the
        # handshake with a clear error, not mis-parse gossip later.
        import struct

        payload = bytes([MsgType.HELLO]) + struct.pack(
            ">B32sIHQ", protocol.PROTOCOL_VERSION + 1, b"\xab" * 32, 1, 1, 7
        )
        with pytest.raises(ValueError, match="protocol version"):
            protocol.decode(payload)

    def test_block(self):
        block = _block()
        # The codec reads no clock of its own (round 11: a host-clock
        # stamp inside the frame bytes made simulated traces
        # nondeterministic): no sent_ts encodes the 0.0 "no stamp"
        # sentinel, which receivers skip for propagation telemetry.
        mtype, (sent_ts, got) = protocol.decode(protocol.encode_block(block))
        assert mtype is MsgType.BLOCK and got == block
        assert sent_ts == 0.0
        # Explicit timestamps survive the round trip exactly (f64).
        _, (ts2, _) = protocol.decode(protocol.encode_block(block, sent_ts=1.5))
        assert ts2 == 1.5

    def test_tx(self):
        tx = Transaction("alice", "bob", 5, 1, 0)
        mtype, got = protocol.decode(protocol.encode_tx(tx))
        assert mtype is MsgType.TX and got == tx

    def test_getblocks(self):
        locator = [bytes([i]) * 32 for i in range(5)]
        mtype, got = protocol.decode(protocol.encode_getblocks(locator))
        assert mtype is MsgType.GETBLOCKS and got == locator

    def test_blocks(self):
        blocks = [_block(0), _block(3), make_genesis(12)]
        mtype, got = protocol.decode(protocol.encode_blocks(blocks))
        assert mtype is MsgType.BLOCKS and got == blocks

    def test_getmempool_start_and_cursor(self):
        mtype, got = protocol.decode(protocol.encode_getmempool())
        assert mtype is MsgType.GETMEMPOOL and got is None
        cursor = (7, b"\xcd" * 32)
        mtype, got = protocol.decode(protocol.encode_getmempool(cursor))
        assert mtype is MsgType.GETMEMPOOL and got == cursor

    def test_account_query_round_trip(self):
        mtype, got = protocol.decode(protocol.encode_getaccount("p1deadbeef"))
        assert mtype is MsgType.GETACCOUNT and got == "p1deadbeef"
        state = protocol.AccountState("p1deadbeef", 123, 4, 7, 99)
        mtype, got = protocol.decode(protocol.encode_account(state))
        assert mtype is MsgType.ACCOUNT and got == state

    def test_filter_headers_round_trip(self):
        mtype, got = protocol.decode(protocol.encode_getfilterheaders(7, 100))
        assert mtype is MsgType.GETFILTERHEADERS and got == (7, 100)
        headers = [bytes([i]) * 32 for i in range(3)]
        mtype, got = protocol.decode(protocol.encode_filterheaders(7, headers))
        assert mtype is MsgType.FILTERHEADERS and got == (7, headers)
        # The clean refusal: empty list survives the trip.
        mtype, got = protocol.decode(protocol.encode_filterheaders(9, []))
        assert mtype is MsgType.FILTERHEADERS and got == (9, [])

    def test_subscribe_round_trip(self):
        items = [b"alice", b"\x01" * 32]
        mtype, got = protocol.decode(protocol.encode_subscribe(items))
        assert mtype is MsgType.SUBSCRIBE and got == (None, items)
        cursor = (12, b"\xfe" * 32)
        mtype, got = protocol.decode(protocol.encode_subscribe(items, cursor))
        assert mtype is MsgType.SUBSCRIBE and got == (cursor, items)
        mtype, got = protocol.decode(protocol.encode_unsubscribe())
        assert mtype is MsgType.UNSUBSCRIBE and got is None

    def test_event_round_trip(self):
        ev = protocol.BlockEvent(
            height=5,
            raw_header=_block().header.serialize(),
            filter_header=b"\xaa" * 32,
            filter=b"\x01\x02\x03",
            matched=True,
            txids=(b"\x0b" * 32, b"\x0c" * 32),
        )
        mtype, got = protocol.decode(protocol.encode_event(ev))
        assert mtype is MsgType.EVENT and got == ev
        # Non-matched events carry no txids (the shared frame).
        plain = protocol.BlockEvent(6, ev.raw_header, ev.filter_header, b"", False, ())
        mtype, got = protocol.decode(protocol.encode_event(plain))
        assert mtype is MsgType.EVENT and got == plain
        mtype, got = protocol.decode(protocol.encode_event_gap(3, 9))
        assert mtype is MsgType.EVENT
        assert got == protocol.GapEvent(3, 9)

    def test_mempool(self):
        txs = [Transaction("a", "b", 1, f, f) for f in range(3)]
        payload = protocol.encode_mempool([t.serialize() for t in txs], more=True)
        mtype, (more, got) = protocol.decode(payload)
        assert mtype is MsgType.MEMPOOL and more and got == txs
        _, (more2, got2) = protocol.decode(protocol.encode_mempool([]))
        assert not more2 and got2 == []


class TestMalformed:
    @pytest.mark.parametrize(
        "payload",
        [
            b"",  # empty frame
            bytes([99]),  # unknown type
            bytes([MsgType.HELLO]) + b"short",
            bytes([MsgType.HELLO]),  # no body
            bytes([MsgType.BLOCK]) + b"\x00" * 10,  # truncated header
            bytes([MsgType.TX]),  # empty tx
            bytes([MsgType.GETBLOCKS]) + b"\x00",  # short count
            bytes([MsgType.GETBLOCKS]) + b"\x00\x02" + b"\x00" * 32,  # count lies
            bytes([MsgType.BLOCKS]) + b"\x00",  # short count
            bytes([MsgType.BLOCKS]) + b"\x00\x01\x00\x00\x00\x05ab",  # truncated
            bytes([MsgType.GETMEMPOOL]) + b"\x00" * 3,  # wrong cursor size
            bytes([MsgType.GETACCOUNT]),  # no length
            bytes([MsgType.GETACCOUNT]) + b"\x05ab",  # length lies
            bytes([MsgType.GETACCOUNT]) + b"\x00",  # empty account
            bytes([MsgType.ACCOUNT]) + b"\x02ab" + b"\x00" * 10,  # short state
            bytes([MsgType.MEMPOOL]) + b"\x00",  # short header
            bytes([MsgType.MEMPOOL]) + b"\x00\x00\x00\x00\x00\x01",  # count lies
            bytes([MsgType.GETFILTERHEADERS]) + b"\x00",  # short range
            bytes([MsgType.GETFILTERHEADERS])
            + b"\x00\x00\x00\x00\x00\x00",  # zero count
            bytes([MsgType.FILTERHEADERS]) + b"\x00" * 3,  # short header
            bytes([MsgType.FILTERHEADERS])
            + b"\x00\x00\x00\x00\x00\x02"
            + b"\x00" * 32,  # count lies
            bytes([MsgType.SUBSCRIBE]),  # no cursor flag
            bytes([MsgType.SUBSCRIBE, 2]),  # unknown cursor flag
            bytes([MsgType.SUBSCRIBE, 0]) + b"\x00\x00",  # zero items
            bytes([MsgType.SUBSCRIBE, 0]) + b"\x00\x01\x00\x05ab",  # len lies
            bytes([MsgType.SUBSCRIBE, 1]) + b"\x00" * 10,  # short cursor
            bytes([MsgType.UNSUBSCRIBE]) + b"\x00",  # trailing byte
            bytes([MsgType.EVENT]),  # no kind
            bytes([MsgType.EVENT, 2]),  # unknown kind
            bytes([MsgType.EVENT, 0]) + b"\x00" * 20,  # truncated block event
            bytes([MsgType.EVENT, 1]) + b"\x00" * 4,  # truncated gap
            bytes([MsgType.EVENT, 1])
            + b"\x00\x00\x00\x05\x00\x00\x00\x03",  # end < start
        ],
    )
    def test_rejected(self, payload):
        with pytest.raises(ValueError):
            protocol.decode(payload)

    def test_trailing_bytes_rejected(self):
        good = protocol.encode_blocks([_block(1)])
        with pytest.raises(ValueError, match="trailing|truncated"):
            protocol.decode(good + b"\x00")

    def test_pure_random_bytes_never_crash(self):
        # Beyond mutations of valid frames: completely arbitrary payloads
        # across every length bucket must decode or raise ValueError.
        rng = random.Random(99)
        for _ in range(3000):
            buf = rng.randbytes(rng.randrange(0, 240))
            try:
                protocol.decode(buf)
            except ValueError:
                pass  # the contract: reject, don't crash

    def test_mutation_fuzz_never_crashes(self):
        # Truncations and byte flips of valid frames must either decode to
        # SOMETHING or raise ValueError -- never any other exception.
        rng = random.Random(7)
        seeds = [
            protocol.encode_hello(Hello(b"\x01" * 32, 3, 1)),
            protocol.encode_block(_block()),
            protocol.encode_tx(Transaction("a", "b", 1, 1, 0)),
            protocol.encode_blocks([_block(0), _block(2)]),
            protocol.encode_mempool(
                [Transaction("a", "b", 1, f, f).serialize() for f in range(2)],
                more=True,
            ),
            protocol.encode_getblocks([b"\x02" * 32]),
            protocol.encode_getmempool((9, b"\x03" * 32)),
            protocol.encode_getaccount("p1deadbeefdeadbeef"),
            protocol.encode_account(
                protocol.AccountState("p1deadbeefdeadbeef", 50, 1, 2, 7)
            ),
            protocol.encode_getproof(b"\x04" * 32),
            protocol.encode_getheaders([b"\x09" * 32]),
            protocol.encode_getaddr(),
            protocol.encode_getfees(16),
            protocol.encode_fees(protocol.FeeStats(32, 9, 1, 2, 3, 44)),
            protocol.encode_addr([("127.0.0.1", 9444), ("h.example", 80)]),
            protocol.encode_headers([_block().header, make_genesis(12).header]),
            protocol.encode_cblock(_block(3)),
            protocol.encode_getblocktxn(b"\x07" * 32, [1, 2, 5]),
            protocol.encode_blocktxn(
                b"\x08" * 32,
                [Transaction("a", "b", 1, f, f).serialize() for f in range(2)],
            ),
            protocol.encode_getfilterheaders(3, 50),
            protocol.encode_filterheaders(3, [bytes([i]) * 32 for i in range(2)]),
            protocol.encode_subscribe([b"alice"], (4, b"\x0d" * 32)),
            protocol.encode_unsubscribe(),
            protocol.encode_event(
                protocol.BlockEvent(
                    5,
                    _block().header.serialize(),
                    b"\x0e" * 32,
                    b"\x01\x02",
                    True,
                    (b"\x0f" * 32,),
                )
            ),
            protocol.encode_event_gap(2, 6),
            protocol.encode_proof(None),
            protocol.encode_proof(
                TxProof(
                    Transaction("a", "b", 1, 1, 0),
                    _block().header,
                    3,
                    9,
                    1,
                    (b"\x05" * 32, b"\x06" * 32),
                )
            ),
        ]
        for seed in seeds:
            for _ in range(200):
                buf = bytearray(seed)
                op = rng.randrange(3)
                if op == 0 and len(buf) > 1:
                    buf = buf[: rng.randrange(1, len(buf))]
                elif op == 1:
                    buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
                else:
                    buf += bytes([rng.randrange(256)])
                try:
                    protocol.decode(bytes(buf))
                except ValueError:
                    pass  # the contract: reject, don't crash
