"""Wall-clock lint headline tests, now riding the AST analyzer.

Round 13 retired this file's tokenizer scanner in favor of
``p1_tpu/analysis`` (rules/wallclock.py): same patterns, same file
coverage (node/, chain/, mempool/), same allowlist CONTENTS (now in
p1_tpu/analysis/allowlist.py with per-grant reasons), but matched
structurally — only real ``ast.Call`` nodes count, so an
injectable-clock default argument (``clock=time.monotonic``) is clean
by construction rather than by token-join accident, and a name merely
*ending* in a pattern can no longer false-positive.

What this file keeps is the HEADLINE guarantees, under their original
names, so the migration provably regressed no coverage:

- every wall-clock construct outside the allowlist fails (the generic
  sweep also runs in tests/test_analysis.py with every other rule);
- the allowlist carries no stale grants;
- chain/snapshot.py is clock-free with ZERO grants;
- node/node.py's consensus core reads no host clock at all.

The full-tree/all-rules gate and the per-rule fixture corpus live in
tests/test_analysis.py.
"""

from p1_tpu.analysis import RULES, run_analysis
from p1_tpu.analysis.allowlist import GRANTS
from p1_tpu.analysis.engine import PKG_ROOT


def _wallclock_report():
    return run_analysis(rules=[RULES["wall-clock"]])


class TestWallClockLint:
    def test_no_direct_wall_clock_outside_the_allowlist(self):
        report = _wallclock_report()
        assert not report.violations, (
            "direct wall-clock/sleep constructs outside the blessed "
            "seams (route them through the node's Clock, or extend "
            "p1_tpu/analysis/allowlist.py with a reason):\n  "
            + "\n  ".join(str(f) for f in report.violations)
        )
        assert not report.parse_errors, report.parse_errors

    def test_allowlist_carries_no_stale_grants(self):
        report = _wallclock_report()
        assert not report.stale, (
            "allowlist grants nothing uses (tighten the list):\n  "
            + "\n  ".join(report.stale)
        )

    def test_snapshot_plane_is_clock_free_from_day_one(self):
        """Round 12's module stays lint-covered and CLEAN: no direct
        wall-clock constructs, no allowlist grant — snapshot integrity
        checking and (de)serialization are pure functions of bytes, and
        granting the module a clock seam it does not need would only
        invite one.  The node-side fetch/revalidation machinery lives
        in node/node.py under ITS existing grant and reads time only
        through ``Node.clock``."""
        report = _wallclock_report()
        assert not any(
            f.file == "chain/snapshot.py" for f in report.findings
        ), [str(f) for f in report.findings if f.file == "chain/snapshot.py"]
        assert "chain/snapshot.py" not in GRANTS["wall-clock"]

    def test_recon_codec_is_clock_free_with_zero_grants(self):
        """Round 23's module ships lint-covered and CLEAN: the sketch
        codec is pure GF(2^32) arithmetic over bytes — no clock, no
        rng, no loop — and every consumer-side timing decision (round
        cadence, stall aging, demotion windows) lives in node/node.py
        under ITS existing grant, reading time through ``Node.clock``."""
        report = _wallclock_report()
        assert not any(
            f.file == "node/reconcile.py" for f in report.findings
        ), [str(f) for f in report.findings if f.file == "node/reconcile.py"]
        assert "node/reconcile.py" not in GRANTS["wall-clock"]

    def test_node_core_is_fully_seam_routed(self):
        """The headline: the node's consensus/session core reads NO
        host clock at all — every deadline, ban window, telemetry stamp
        and mining timestamp goes through ``self.clock``.  Its only
        grant is loop-relative ``asyncio.sleep``."""
        keys = {
            f.key for f in _wallclock_report().findings
            if f.file == "node/node.py"
        }
        assert "time.time" not in keys
        assert "time.monotonic" not in keys
        assert "time.perf_counter" not in keys
        assert set(GRANTS["wall-clock"]["node/node.py"]) == {"asyncio.sleep"}

    def test_telemetry_plane_is_clock_free_with_zero_grants(self):
        """Round 14's module ships lint-covered and CLEAN: the
        telemetry plane's whole contract is that it reads time only
        through its injected clock (the node passes the transport
        clock), so a direct wall-clock call here would break virtual-
        time measurement AND the sim determinism pair at once.  The
        ``time.monotonic`` spellings in the file are injectable default
        arguments — references the AST rule correctly ignores."""
        report = _wallclock_report()
        assert not any(
            f.file == "node/telemetry.py" for f in report.findings
        ), [str(f) for f in report.findings if f.file == "node/telemetry.py"]
        assert "node/telemetry.py" not in GRANTS["wall-clock"]

    def test_default_arg_references_are_structurally_clean(self):
        """What the AST migration BUYS over the tokenizer: the seam
        itself (node/transport.py) holds bare ``time.monotonic``
        references as injectable defaults without calling them, and
        needs no grant — the rule counts calls, not spellings."""
        assert (PKG_ROOT / "node" / "transport.py").exists()
        assert not any(
            f.file == "node/transport.py"
            for f in _wallclock_report().findings
        )
        assert "node/transport.py" not in GRANTS["wall-clock"]
