"""Static wall-clock lint: keep node/ and chain/ simulator-compatible.

The transport seam (node/transport.py) exists so every clock read in
the node goes through an injectable ``Clock`` and every sleep/deadline
through the event loop — which is what lets node/netsim.py virtualize a
thousand nodes deterministically.  One future ``time.time()`` in a
consensus or session path silently re-couples the node to the host
clock: the sim still RUNS, but deadlines stop scaling with virtual time
and same-seed traces drift.  This tier-1 lint greps the product tree
for direct wall-clock constructs outside an explicit allowlist, so the
hole is caught at commit time, not three rounds later in a flaky soak.

``asyncio.sleep`` / ``asyncio.wait_for`` are loop-relative — the
simulator virtualizes the loop itself, so they are sim-compatible BY
CONSTRUCTION and allowed wherever async code runs under the node's
loop.  They are still matched and allowlisted per file: a *new* module
acquiring sleeps is worth a deliberate allowlist edit (is this file
really always run under the virtual loop?), not a silent pass.
"""

import tokenize
from pathlib import Path

PKG = Path(__file__).resolve().parent.parent / "p1_tpu"

#: Constructs that read the HOST clock (or sleep) directly.
_PATTERNS = (
    "time.time(",
    "time.monotonic(",
    "time.perf_counter(",
    "datetime.now(",
    "asyncio.sleep(",
)

#: file (relative to p1_tpu/) -> allowed constructs, each with a reason
#: a reviewer can audit.  Anything NOT listed here must be clock-seam
#: clean; anything listed but unused fails too (stale grants rot).
ALLOWED: dict[str, set[str]] = {
    # (The seam itself — node/transport.py — and the injectable-clock
    # DEFAULT arguments elsewhere hold bare ``time.monotonic``
    # references without calling them; the tokenizer scan below only
    # flags calls, so they need no grants.  node/protocol.py held a
    # ``time.time(`` grant for encode_block's default send stamp until
    # round 11: the codec now encodes 0.0 = "no stamp" and every caller
    # stamps from its own transport clock — the stamp is INSIDE the
    # frame bytes, so a codec-side host-clock read made simulated flood
    # traces nondeterministic.)
    # Async product code running under the (possibly virtual) loop.
    "node/node.py": {"asyncio.sleep("},
    "node/client.py": {"asyncio.sleep("},
    # The simulator itself: asyncio.sleep IS virtual here, and
    # time.monotonic guards REAL wall budgets (SimWallTimeout) plus the
    # scenario reports' wall_s — deliberate host-clock reads.
    "node/netsim.py": {"time.monotonic(", "asyncio.sleep("},
    "node/scenarios.py": {"time.monotonic(", "asyncio.sleep("},
    # The chaos plane: same split as scenarios.py — sleeps are virtual,
    # time.monotonic is the SimWallTimeout budget + report wall_s.
    "node/chaos.py": {"time.monotonic(", "asyncio.sleep("},
    # Harness/tooling that drives REAL processes and sockets on the
    # host clock by design (subprocess meshes, soak drivers, operator
    # runners) — not part of the simulated node.
    "node/runner.py": {"time.time(", "time.monotonic(", "asyncio.sleep("},
    "node/netharness.py": {"time.time(", "asyncio.sleep("},
    "node/byzantine.py": {"asyncio.sleep("},
    "node/testing.py": {"asyncio.sleep("},
    # The read-replica serving plane: a real-socket, separate-process
    # tier (`p1 serve`) that is out of the simulator's scope.
    "node/queryplane.py": {"time.monotonic(", "asyncio.sleep("},
    # Benchmark timing (replay throughput figures), not node behavior.
    "chain/replay.py": {"time.perf_counter("},
}

def _scan(path: Path) -> set[str]:
    """Patterns present as CODE (comments and strings stripped; tokens
    re-joined without whitespace, so ``time.time (...)`` and
    ``time.time(...)`` both read ``time.time(`` while a bare
    ``clock=time.monotonic`` default-argument reference does not)."""
    with open(path, "rb") as fh:
        code = "".join(
            tok.string
            for tok in tokenize.tokenize(fh.readline)
            if tok.type not in (tokenize.COMMENT, tokenize.STRING)
        )
    return {pat for pat in _PATTERNS if pat in code}


def _product_files():
    # mempool/ joined the covered set in round 11: pool admission
    # stamps and TTL ages ride the node's injected clock now, so chaos
    # schedules that crash/recover nodes see deterministic checkpoint
    # ages — and stay that way.
    for sub in ("node", "chain", "mempool"):
        for path in sorted((PKG / sub).glob("*.py")):
            yield f"{sub}/{path.name}", path


class TestWallClockLint:
    def test_no_direct_wall_clock_outside_the_allowlist(self):
        violations = []
        for rel, path in _product_files():
            found = _scan(path)
            extra = found - ALLOWED.get(rel, set())
            if extra:
                violations.append(f"{rel}: {sorted(extra)}")
        assert not violations, (
            "direct wall-clock/sleep constructs outside the blessed "
            "seams (route them through the node's Clock, or extend the "
            "allowlist with a reason):\n  " + "\n  ".join(violations)
        )

    def test_allowlist_carries_no_stale_grants(self):
        stale = []
        files = dict(_product_files())
        for rel, allowed in ALLOWED.items():
            path = files.get(rel)
            if path is None:
                stale.append(f"{rel}: file no longer exists")
                continue
            unused = allowed - _scan(path)
            if unused:
                stale.append(f"{rel}: {sorted(unused)} never occurs")
        assert not stale, (
            "allowlist grants nothing uses (tighten the list):\n  "
            + "\n  ".join(stale)
        )

    def test_snapshot_plane_is_clock_free_from_day_one(self):
        """Round 12's new module enters the lint covered and CLEAN: no
        direct wall-clock constructs, no allowlist grant — snapshot
        integrity checking and (de)serialization are pure functions of
        bytes, and granting the module a clock seam it does not need
        would only invite one.  The node-side fetch/revalidation
        machinery lives in node/node.py under ITS existing grant and
        reads time only through ``Node.clock``."""
        assert _scan(PKG / "chain" / "snapshot.py") == set()
        assert "chain/snapshot.py" not in ALLOWED

    def test_node_core_is_fully_seam_routed(self):
        """The headline: the node's consensus/session core reads NO
        host clock at all — every deadline, ban window, telemetry stamp
        and mining timestamp goes through ``self.clock``."""
        found = _scan(PKG / "node" / "node.py")
        assert "time.time(" not in found
        assert "time.monotonic(" not in found
        assert "time.perf_counter(" not in found
