"""Chaos sweep throughput: schedules/second, wall-vs-virtual ratio.

The question this answers: how fast can the chaos plane SEARCH the
combined-fault space?  Every schedule is a full mesh life cycle —
formation, warmup mining, the fault events (crashes with torn appends,
disk errors, partitions, adversaries), the heal epilogue, settle, and
the invariant suite — so the schedules/s figure is the search budget
`p1 chaos` and the sweeps in tests/test_chaos.py spend from.

The companion ratio (virtual seconds simulated per wall second) says
what the discrete-event substrate buys here: a schedule spans minutes
of virtual time (supervision deadlines, store-recovery backoff, settle
windows all at PRODUCTION values) and costs tens of milliseconds of
wall clock.

The default run feeds ``bench.py``'s ``chaos_rate`` line against the
pinned ``RECORDED_CHAOS_RATE`` (p1_tpu/hashx/perf_record.py).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def bench_chaos(
    schedules: int = 10, nodes: int = 5, events: int = 10, seed: int = 0
) -> dict:
    """Run ``schedules`` consecutive seeds; all must hold their
    invariants (a violation voids the measurement — a failing sweep is
    a bug report, not a benchmark)."""
    from p1_tpu.node.chaos import run_chaos

    wall = virtual = 0.0
    ok = True
    t0 = time.perf_counter()
    for s in range(seed, seed + schedules):
        report = run_chaos(s, nodes=nodes, n_events=events)
        ok &= report["ok"]
        virtual += report["virtual_s"]
    wall = time.perf_counter() - t0
    return {
        "schedules": schedules,
        "nodes": nodes,
        "events": events,
        "ok": ok,
        "wall_s": round(wall, 3),
        "virtual_s": round(virtual, 1),
        "chaos_schedules_per_sec": round(schedules / max(wall, 1e-9), 2),
        "virtual_per_wall": round(virtual / max(wall, 1e-9), 1),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--schedules", type=int, default=10)
    parser.add_argument("--nodes", type=int, default=5)
    parser.add_argument("--events", type=int, default=10)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()
    print(
        json.dumps(
            bench_chaos(args.schedules, args.nodes, args.events, args.seed)
        )
    )


if __name__ == "__main__":
    main()
