"""Signature-verification microbench: serial vs batch vs cached.

The untrusted-path validation fast lane (round 8) rests on three claims:
batched Ed25519 verification beats one-at-a-time calls, the verify-once
cache makes re-checks free, and the pure-Python fallback's batch path —
one multi-scalar multiplication per window — closes a useful fraction of
the gap to the native wheel.  This harness measures all three on THIS
machine, same contract as ``bench.py``: one JSON line, measured, no
estimates.

Rows cover both crypto backends where available: the ACTIVE backend
(whatever ``core/keys.py`` resolved — the wheel when present) and the
pure-Python fallback explicitly, so a wheel-equipped host reports both
and a wheel-less CI image still shows the fallback's serial→batch gain
next to the recorded constants the one-time warning cites
(``_ed25519.RECORDED_SERIAL_MS`` / ``RECORDED_BATCH_MS``).

Optionally (``--store-blocks N``) builds an on-disk store and measures
full untrusted revalidation three ways — serial (fast lane disabled),
batched, and batched+cache-warm — the microscale version of docs/PERF.md
"Untrusted-path validation".
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _make_triples(n: int, keypairs):
    out = []
    for i in range(n):
        kp = keypairs[i % len(keypairs)]
        msg = b"sig-verify-bench-%d" % i
        out.append((kp.pubkey, kp.sign(msg), msg))
    return out


def _rate(fn, payload_sigs: int, repeats: int = 3) -> float:
    """Best-of-N signatures/second for ``fn()`` covering ``payload_sigs``."""
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = max(best, payload_sigs / dt)
    return best


def bench_micro(batch_sizes=(64, 256, 1024, 4096), serial_n=64) -> dict:
    from p1_tpu.core import _ed25519, keys

    keypairs = [keys.Keypair.from_seed_text(f"sigbench-{i}") for i in range(8)]
    out: dict = {"backend": keys.BACKEND, "workers": keys.verify_workers()}

    triples = _make_triples(serial_n, keypairs)
    out["serial_us"] = round(
        1e6 / _rate(lambda: all(keys.verify(*t) for t in triples), serial_n), 1
    )
    if keys.BACKEND != "pure-python":
        out["fallback_serial_us"] = round(
            1e6
            / _rate(
                lambda: all(_ed25519.verify(*t) for t in triples), serial_n
            ),
            1,
        )

    for n in batch_sizes:
        tr = _make_triples(n, keypairs)
        _ed25519._pubkey_point.cache_clear()
        out[f"batch{n}_us"] = round(
            1e6 / _rate(lambda: keys.verify_batch(tr), n), 1
        )
        if keys.BACKEND != "pure-python":
            _ed25519._pubkey_point.cache_clear()
            out[f"fallback_batch{n}_us"] = round(
                1e6 / _rate(lambda: _ed25519.verify_batch(tr), n), 1
            )
    biggest = max(batch_sizes)
    out["batch_speedup"] = round(
        out["serial_us"] / out[f"batch{biggest}_us"], 1
    )

    # Cached path: the verify-once memo a block connect hits for
    # mempool-resident transfers (txid-keyed, core/sigcache.py).
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.sigcache import SignatureCache
    from p1_tpu.core.tx import Transaction

    cache = SignatureCache()
    tag = genesis_hash(8)
    txs = [
        Transaction.transfer(keypairs[0], "r", 1, 0, i, chain=tag)
        for i in range(256)
    ]
    for tx in txs:
        tx.verify_signature(cache=cache)  # populate
    out["cached_us"] = round(
        1e6
        / _rate(
            lambda: all(tx.verify_signature(cache=cache) for tx in txs),
            len(txs),
        ),
        2,
    )
    return out


def bench_revalidate(n_blocks: int, repeats: int = 3) -> dict:
    """Store revalidation three ways (median-of-``repeats`` each)."""
    from benchmarks.host_ingest import build_blocks
    from p1_tpu.chain import validate
    from p1_tpu.chain.store import ChainStore, save_chain
    from p1_tpu.core import keys
    from p1_tpu.core.sigcache import SignatureCache

    chain, _raws = build_blocks(n_blocks, 2, difficulty=1)
    out: dict = {"store_blocks": n_blocks}
    with tempfile.TemporaryDirectory() as td:
        path = Path(td) / "bench.chain"
        save_chain(chain, path)

        def run(serial: bool, warm_cache=None) -> float:
            import p1_tpu.chain.store as store_mod

            store = ChainStore(path)
            times = []
            for _ in range(repeats):
                cache = warm_cache if warm_cache is not None else SignatureCache()
                old_min = keys.BATCH_MIN
                old_pre = validate.preverify_signatures
                old_stream = store_mod._preverify_stream
                if serial:
                    # Disable the fast lane: per-tx backend calls, the
                    # pre-round-8 cost model.
                    keys.BATCH_MIN = 1 << 30
                    validate.preverify_signatures = (
                        lambda txs, tag, sig_cache=None: 0
                    )
                    store_mod._preverify_stream = (
                        lambda blocks, tag, cache: blocks
                    )
                try:
                    t0 = time.perf_counter()
                    store.load_chain(1, trusted=False, sig_cache=cache)
                    times.append(time.perf_counter() - t0)
                finally:
                    keys.BATCH_MIN = old_min
                    validate.preverify_signatures = old_pre
                    store_mod._preverify_stream = old_stream
            store.close()
            return statistics.median(times)

        t_serial = run(serial=True)
        t_batch = run(serial=False)
        warm = SignatureCache()
        run(serial=False, warm_cache=warm)  # populate
        t_cached = run(serial=False, warm_cache=warm)
        t_trusted_store = ChainStore(path)
        t0 = time.perf_counter()
        t_trusted_store.load_chain(1, trusted=True)
        t_trusted = time.perf_counter() - t0
        t_trusted_store.close()
    out["revalidate_serial_s"] = round(t_serial, 3)
    out["revalidate_batch_s"] = round(t_batch, 3)
    out["revalidate_cached_s"] = round(t_cached, 3)
    out["trusted_resume_s"] = round(t_trusted, 3)
    out["revalidate_speedup"] = round(t_serial / t_batch, 2)
    out["revalidate_bps"] = round(n_blocks / t_batch)
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--batch-sizes", type=int, nargs="*", default=[64, 256, 1024, 4096]
    )
    ap.add_argument(
        "--store-blocks",
        type=int,
        default=0,
        help="also build an N-block store (1 signed transfer every other "
        "block) and measure full revalidation serial vs batch vs cached",
    )
    args = ap.parse_args()

    result = bench_micro(tuple(args.batch_sizes))
    if args.store_blocks:
        result.update(bench_revalidate(args.store_blocks))
    try:
        load_1m, load_5m, _ = os.getloadavg()
        result["load_avg_1m"] = round(load_1m, 2)
        result["load_avg_5m"] = round(load_5m, 2)
    except OSError:
        pass
    result["cpu_count"] = os.cpu_count()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
