"""Signature-verification microbench: every backend rung, one harness.

The untrusted-path validation fast lane (rounds 8 and 15) rests on the
claims docs/PERF.md tables carry: batched Ed25519 beats one-at-a-time
calls, the verify-once cache makes re-checks free, the native C++
engine (native/ed25519.cpp) turns the wheel-less gap into a ~20×
win, and the device-sharded JAX MSM scales with mesh size.  This
harness measures all of it on THIS machine, same contract as
``bench.py``: one JSON line, measured, no estimates.

Rows cover every backend rung the host can run (``core/keys.py``
ladder): the ACTIVE backend, the pure-Python fallback explicitly, the
native engine when a toolchain or cached build exists, and — behind
``--device``, because each array shape pays a multi-minute XLA compile
on a small host — the device MSM, including a devices-vs-throughput
scaling row over 1/2/4/8-chip meshes (``device_scaling``).

Optionally (``--store-blocks N``) builds an on-disk store and measures
full untrusted revalidation three ways — serial (fast lane disabled),
batched, and batched+cache-warm — the microscale version of docs/PERF.md
"Untrusted-path validation".
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _make_triples(n: int, keypairs):
    out = []
    for i in range(n):
        kp = keypairs[i % len(keypairs)]
        msg = b"sig-verify-bench-%d" % i
        out.append((kp.pubkey, kp.sign(msg), msg))
    return out


def _rate(fn, payload_sigs: int, repeats: int = 3) -> float:
    """Best-of-N signatures/second for ``fn()`` covering ``payload_sigs``."""
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = max(best, payload_sigs / dt)
    return best


def bench_micro(batch_sizes=(64, 256, 1024, 4096), serial_n=64) -> dict:
    from p1_tpu.core import _ed25519, _ed25519_native, keys

    keypairs = [keys.Keypair.from_seed_text(f"sigbench-{i}") for i in range(8)]
    active = keys.backend()
    out: dict = {"backend": active, "workers": keys.verify_workers()}

    triples = _make_triples(serial_n, keypairs)
    out["serial_us"] = round(
        1e6 / _rate(lambda: all(keys.verify(*t) for t in triples), serial_n), 1
    )
    if active != "pure-python":
        out["fallback_serial_us"] = round(
            1e6
            / _rate(
                lambda: all(_ed25519.verify(*t) for t in triples), serial_n
            ),
            1,
        )
    if _ed25519_native.available():
        out["native_serial_us"] = round(
            1e6
            / _rate(
                lambda: all(_ed25519_native.verify(*t) for t in triples),
                serial_n,
            ),
            1,
        )

    for n in batch_sizes:
        tr = _make_triples(n, keypairs)
        _ed25519._pubkey_point.cache_clear()
        out[f"batch{n}_us"] = round(
            1e6 / _rate(lambda: keys.verify_batch(tr), n), 1
        )
        if active != "pure-python":
            _ed25519._pubkey_point.cache_clear()
            out[f"fallback_batch{n}_us"] = round(
                1e6 / _rate(lambda: _ed25519.verify_batch(tr), n), 1
            )
        if _ed25519_native.available() and active != "native":
            out[f"native_batch{n}_us"] = round(
                1e6 / _rate(lambda: _ed25519_native.verify_batch(tr), n), 1
            )
    biggest = max(batch_sizes)
    out["batch_speedup"] = round(
        out["serial_us"] / out[f"batch{biggest}_us"], 1
    )
    if _ed25519_native.available():
        # The headline the perf_record pin tracks: native ms/sig at the
        # 1024 window, whichever rung is active.
        key = "batch1024_us" if active == "native" else "native_batch1024_us"
        if key in out:
            out["native_batch_ms"] = round(out[key] / 1e3, 4)

    # Cached path: the verify-once memo a block connect hits for
    # mempool-resident transfers (txid-keyed, core/sigcache.py).
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.sigcache import SignatureCache
    from p1_tpu.core.tx import Transaction

    cache = SignatureCache()
    tag = genesis_hash(8)
    txs = [
        Transaction.transfer(keypairs[0], "r", 1, 0, i, chain=tag)
        for i in range(256)
    ]
    for tx in txs:
        tx.verify_signature(cache=cache)  # populate
    out["cached_us"] = round(
        1e6
        / _rate(
            lambda: all(tx.verify_signature(cache=cache) for tx in txs),
            len(txs),
        ),
        2,
    )
    return out


def bench_device(
    batch: int = 512, device_counts=(1, 2, 4, 8), repeats: int = 3
) -> dict:
    """Devices-vs-throughput scaling for the sharded MSM path
    (hashx/ed25519_msm.py): signatures/second through
    ``verify_batch_device`` per mesh size, steady state (the one-time
    XLA compile per mesh is paid by a warmup call and reported
    separately — on real TPU pods it is once per pod lifetime).

    Honesty note baked into the output: on a single-CPU host the mesh
    is VIRTUAL (``--xla_force_host_platform_device_count``), so chips
    share one core and the row measures the sharding seam's overhead,
    not hardware scaling — docs/PERF.md prints it with exactly that
    caveat, and docs/ROUND15.md has the tried/kept ledger.
    """
    import jax

    from p1_tpu.core import keys
    from p1_tpu.hashx import ed25519_msm

    keypairs = [keys.Keypair.from_seed_text(f"sigbench-{i}") for i in range(8)]
    tr = _make_triples(batch, keypairs)
    out: dict = {"device_batch": batch, "device_rows": []}
    avail = jax.device_count()
    for n_dev in device_counts:
        if n_dev > avail:
            continue
        t0 = time.perf_counter()
        assert ed25519_msm.verify_batch_device(tr, n_devices=n_dev)
        compile_s = time.perf_counter() - t0
        rate = _rate(
            lambda: ed25519_msm.verify_batch_device(tr, n_devices=n_dev),
            batch,
            repeats,
        )
        out["device_rows"].append(
            {
                "devices": n_dev,
                "sigs_per_s": round(rate, 1),
                "us_per_sig": round(1e6 / rate, 1),
                "first_call_s": round(compile_s, 1),
            }
        )
    if out["device_rows"]:
        out["device_us_per_sig"] = out["device_rows"][-1]["us_per_sig"]
    return out


def bench_revalidate(n_blocks: int, repeats: int = 3) -> dict:
    """Store revalidation three ways (median-of-``repeats`` each)."""
    from benchmarks.host_ingest import build_blocks
    from p1_tpu.chain import validate
    from p1_tpu.chain.store import ChainStore, save_chain
    from p1_tpu.core import keys
    from p1_tpu.core.sigcache import SignatureCache

    chain, _raws = build_blocks(n_blocks, 2, difficulty=1)
    out: dict = {"store_blocks": n_blocks}
    with tempfile.TemporaryDirectory() as td:
        path = Path(td) / "bench.chain"
        save_chain(chain, path)

        def run(serial: bool, warm_cache=None) -> float:
            import p1_tpu.chain.store as store_mod

            store = ChainStore(path)
            times = []
            for _ in range(repeats):
                cache = warm_cache if warm_cache is not None else SignatureCache()
                old_min = keys.BATCH_MIN
                old_pre = validate.preverify_signatures
                old_stream = store_mod._preverify_stream
                if serial:
                    # Disable the fast lane: per-tx backend calls, the
                    # pre-round-8 cost model.
                    keys.BATCH_MIN = 1 << 30
                    validate.preverify_signatures = (
                        lambda txs, tag, sig_cache=None: 0
                    )
                    store_mod._preverify_stream = (
                        lambda blocks, tag, cache: blocks
                    )
                try:
                    t0 = time.perf_counter()
                    store.load_chain(1, trusted=False, sig_cache=cache)
                    times.append(time.perf_counter() - t0)
                finally:
                    keys.BATCH_MIN = old_min
                    validate.preverify_signatures = old_pre
                    store_mod._preverify_stream = old_stream
            store.close()
            return statistics.median(times)

        t_serial = run(serial=True)
        t_batch = run(serial=False)
        warm = SignatureCache()
        run(serial=False, warm_cache=warm)  # populate
        t_cached = run(serial=False, warm_cache=warm)
        t_trusted_store = ChainStore(path)
        t0 = time.perf_counter()
        t_trusted_store.load_chain(1, trusted=True)
        t_trusted = time.perf_counter() - t0
        t_trusted_store.close()
    out["revalidate_serial_s"] = round(t_serial, 3)
    out["revalidate_batch_s"] = round(t_batch, 3)
    out["revalidate_cached_s"] = round(t_cached, 3)
    out["trusted_resume_s"] = round(t_trusted, 3)
    out["revalidate_speedup"] = round(t_serial / t_batch, 2)
    out["revalidate_bps"] = round(n_blocks / t_batch)
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--batch-sizes", type=int, nargs="*", default=[64, 256, 1024, 4096]
    )
    ap.add_argument(
        "--store-blocks",
        type=int,
        default=0,
        help="also build an N-block store (1 signed transfer every other "
        "block) and measure full revalidation serial vs batch vs cached",
    )
    ap.add_argument(
        "--device",
        action="store_true",
        help="also measure the device-sharded JAX MSM "
        "(hashx/ed25519_msm.py) with a devices-vs-throughput scaling "
        "row — each mesh size pays one multi-minute XLA compile on a "
        "small host, hence opt-in",
    )
    ap.add_argument(
        "--device-batch", type=int, default=512, help="device window size"
    )
    args = ap.parse_args()

    result = bench_micro(tuple(args.batch_sizes))
    if args.store_blocks:
        result.update(bench_revalidate(args.store_blocks))
    if args.device:
        result.update(bench_device(args.device_batch))
    try:
        load_1m, load_5m, _ = os.getloadavg()
        result["load_avg_1m"] = round(load_1m, 2)
        result["load_avg_5m"] = round(load_5m, 2)
    except OSError:
        pass
    result["cpu_count"] = os.cpu_count()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
